"""Concurrency sanitizer tests (ISSUE 11): the static AST lint
(core/analysis/concurrency_lint.py + tools/lint_concurrency.py) over
seeded-defect fixture modules — each rule must fire with the right
file:line — plus the runtime half (core/analysis/lockdep.py): a real
A/B–B/A two-thread deadlock under FLAGS_sanitize_locks=1 must raise a
typed LockOrderError AND land a kind:"stall" all-thread stack dump in
the run log, while FLAGS_sanitize_locks=0 keeps every lock a plain
threading primitive (no lock.* records). Also: the live-tree gate
(lint_concurrency --strict exits 0 on this repo), the
threading.excepthook satellite, and the perf_report "Concurrency"
section.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from paddle_tpu.core import flags as _flags
from paddle_tpu.core import telemetry
from paddle_tpu.core.analysis import concurrency_lint as clint
from paddle_tpu.core.analysis import lockdep

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_source(tmp_path, name, source):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return clint.lint_paths([str(path)]), str(path)


def _by_rule(result, rule):
    return [f for f in result.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# static lint: seeded-defect fixtures (one per rule)
# ---------------------------------------------------------------------------

class TestLockOrderRule:
    def test_inversion_detected_with_lines(self, tmp_path):
        """A/B vs B/A nesting is reported as a cycle, with the inner
        `with` lines of BOTH edges."""
        result, path = _lint_source(tmp_path, "fix_lockorder.py", """\
            import threading


            class Worker:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def forward(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def backward(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
        """)
        findings = _by_rule(result, "lock-order")
        assert len(findings) == 2, [f.format() for f in result.findings]
        assert {f.line for f in findings} == {11, 16}
        assert all(f.severity == "error" for f in findings)
        assert all(f.path == path for f in findings)
        assert "cycle" in findings[0].message

    def test_inversion_through_a_call_is_seen(self, tmp_path):
        """One level of same-class call expansion: m1 holds A and calls
        m2 which takes B; m3 nests B then A — still a cycle."""
        result, _ = _lint_source(tmp_path, "fix_lockorder_call.py", """\
            import threading


            class Worker:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def m1(self):
                    with self._a_lock:
                        self.m2()

                def m2(self):
                    with self._b_lock:
                        pass

                def m3(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
        """)
        assert _by_rule(result, "lock-order"), \
            [f.format() for f in result.findings]

    def test_consistent_order_is_clean(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_lockorder_ok.py", """\
            import threading


            class Worker:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def forward(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def backward(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
        """)
        assert not _by_rule(result, "lock-order")


class TestBlockingUnderLockRule:
    def test_direct_blocking_calls(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_blocking.py", """\
            import subprocess
            import threading
            import time


            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.predictor = None

                def handle(self, sock, q):
                    with self._lock:
                        time.sleep(1.0)
                        sock.recv(1024)
                        q.get()
                        subprocess.run(["ls"])
                        self.predictor.run({})
        """)
        findings = _by_rule(result, "blocking-call-under-lock")
        lines = {f.line for f in findings}
        assert lines == {13, 14, 15, 16, 17}, \
            [f.format() for f in result.findings]
        msgs = " ".join(f.message for f in findings)
        assert "time.sleep" in msgs
        assert ".recv" in msgs
        assert "queue .get() without timeout" in msgs
        assert "subprocess.run" in msgs
        assert "jit/compile entry point" in msgs

    def test_blocking_through_local_call_chain(self, tmp_path):
        """Transitive: the lock holder calls a helper whose body sleeps."""
        result, _ = _lint_source(tmp_path, "fix_blocking_call.py", """\
            import threading
            import time


            class Server:
                def __init__(self):
                    self._lock = threading.Lock()

                def helper(self):
                    time.sleep(0.1)

                def indirect(self):
                    with self._lock:
                        self.helper()
        """)
        findings = _by_rule(result, "blocking-call-under-lock")
        assert len(findings) == 1 and findings[0].line == 14, \
            [f.format() for f in result.findings]
        assert "helper" in findings[0].message

    def test_bounded_waits_are_clean(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_blocking_ok.py", """\
            import threading
            import time


            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition()

                def ok(self, q, event):
                    with self._lock:
                        q.get(timeout=1.0)
                        event.wait(0.5)
                    time.sleep(1.0)
                    with self._cond:
                        self._cond.wait(timeout=2.0)
        """)
        assert not _by_rule(result, "blocking-call-under-lock"), \
            [f.format() for f in result.findings]


class TestUnlockedSharedFieldRule:
    def test_worker_and_main_write_without_lock(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_unlocked.py", """\
            import threading


            class Stats:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0

                def start(self):
                    t = threading.Thread(target=self._worker,
                                         name="pt-fix-worker", daemon=True)
                    t.start()

                def _worker(self):
                    self.count = self.count + 1

                def reset(self):
                    self.count = 0
        """)
        findings = _by_rule(result, "unlocked-shared-field")
        assert {f.line for f in findings} == {15, 18}, \
            [f.format() for f in result.findings]
        assert "self.count" in findings[0].message

    def test_locked_writes_are_clean(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_locked_ok.py", """\
            import threading


            class Stats:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0

                def start(self):
                    t = threading.Thread(target=self._worker,
                                         name="pt-fix-worker", daemon=True)
                    t.start()

                def _worker(self):
                    with self._lock:
                        self.count = self.count + 1

                def reset(self):
                    with self._lock:
                        self.count = 0
        """)
        assert not _by_rule(result, "unlocked-shared-field")


class TestThreadLifecycleRule:
    def test_unnamed_and_unjoined(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_threads.py", """\
            import threading


            def spawn_bad(fn):
                t = threading.Thread(target=fn)
                t.start()
                return t


            def spawn_named_unjoined(fn):
                t = threading.Thread(target=fn, name="pt-fix-loose")
                t.start()


            def spawn_good(fn):
                t = threading.Thread(target=fn, name="pt-fix-d",
                                     daemon=True)
                t.start()


            def spawn_joined(fn):
                t = threading.Thread(target=fn, name="pt-fix-j")
                t.start()
                t.join(timeout=5)
        """)
        unnamed = _by_rule(result, "thread-unnamed")
        unjoined = _by_rule(result, "thread-unjoined")
        assert [f.line for f in unnamed] == [5], \
            [f.format() for f in result.findings]
        assert unnamed[0].severity == "error"
        assert {f.line for f in unjoined} == {5, 11}, \
            [f.format() for f in result.findings]


class TestSuppressions:
    def test_inline_suppression_with_reason(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_suppressed.py", """\
            import threading
            import time


            class Server:
                def __init__(self):
                    self._lock = threading.Lock()

                def handle(self):
                    with self._lock:
                        time.sleep(1.0)  # pt-lint: disable=blocking-call-under-lock(backoff by design (bounded))
        """)
        assert not result.findings
        assert len(result.suppressed) == 1
        assert result.suppressed[0].suppressed == \
            "backoff by design (bounded)"

    def test_suppression_on_preceding_line(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_suppressed2.py", """\
            import threading
            import time


            class Server:
                def __init__(self):
                    self._lock = threading.Lock()

                def handle(self):
                    with self._lock:
                        # pt-lint: disable=blocking-call-under-lock(fine here)
                        time.sleep(1.0)
        """)
        assert not result.findings
        assert len(result.suppressed) == 1

    def test_wrong_rule_does_not_suppress(self, tmp_path):
        result, _ = _lint_source(tmp_path, "fix_suppressed3.py", """\
            import threading
            import time


            class Server:
                def __init__(self):
                    self._lock = threading.Lock()

                def handle(self):
                    with self._lock:
                        time.sleep(1.0)  # pt-lint: disable=lock-order(nope)
        """)
        assert len(result.findings) == 1


# ---------------------------------------------------------------------------
# CLI: exit codes + live-tree gate (ISSUE satellite: CI wiring)
# ---------------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "lint_concurrency.py"), *args],
        capture_output=True, text=True, cwd=REPO)


class TestCLI:
    def test_findings_exit_1(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import threading\n"
                       "t = threading.Thread(target=print)\n"
                       "t.start()\n")
        r = _run_cli(str(bad))
        assert r.returncode == 1, r.stdout + r.stderr
        assert "thread-unnamed" in r.stdout

    def test_clean_exit_0_and_json(self, tmp_path):
        ok = tmp_path / "ok.py"
        ok.write_text("x = 1\n")
        r = _run_cli(str(ok), "--json")
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        assert doc["errors"] == 0 and doc["files"] == 1

    def test_unparseable_exit_2(self, tmp_path):
        broken = tmp_path / "broken.py"
        broken.write_text("def f(:\n")
        r = _run_cli(str(broken))
        assert r.returncode == 2, r.stdout + r.stderr

    def test_warnings_need_strict(self, tmp_path):
        warny = tmp_path / "warny.py"
        warny.write_text(
            "import threading\nimport time\n\n\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n\n"
            "    def m(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)\n")
        assert _run_cli(str(warny)).returncode == 0
        assert _run_cli(str(warny), "--strict").returncode == 1

    def test_live_tree_is_clean_strict(self):
        """Acceptance: zero unsuppressed findings on the merged tree —
        the same invocation the tools smoke path runs."""
        r = _run_cli("--strict")
        assert r.returncode == 0, \
            f"live tree has lint findings:\n{r.stdout}\n{r.stderr}"
        assert "0 error(s), 0 warning(s)" in r.stdout


# ---------------------------------------------------------------------------
# runtime sanitizer (core/analysis/lockdep.py)
# ---------------------------------------------------------------------------

@pytest.fixture
def sanitize(tmp_path):
    """FLAGS_sanitize_locks=1 + a telemetry sink; restores both."""
    log = str(tmp_path / "run.jsonl")
    old = _flags.all_flags()
    _flags.set_flags({"sanitize_locks": True, "lock_stall_s": 0.2})
    telemetry.configure(log)
    try:
        yield log
    finally:
        telemetry.configure(None)
        _flags.set_flags({"sanitize_locks": old["sanitize_locks"],
                          "lock_stall_s": old["lock_stall_s"]})


def _records(log):
    telemetry.flush_sink()
    with open(log) as f:
        return [json.loads(line) for line in f if line.strip()]


class TestLockdepRuntime:
    def test_off_means_plain_primitives_and_no_records(self, tmp_path):
        """Acceptance: FLAGS_sanitize_locks=0 keeps lock overhead at
        parity — factories hand back stock threading objects and no
        lock.* telemetry exists."""
        assert not _flags.flag("sanitize_locks")
        before = {k for k in telemetry.counters() if k.startswith("lock.")}
        lk = lockdep.lock("parity.test")
        assert type(lk) is type(threading.Lock())
        cond = lockdep.condition("parity.cond")
        assert type(cond) is threading.Condition
        with lk:
            pass
        hists = telemetry.snapshot()["hists"]
        assert not any(k.startswith("lock.parity") for k in hists)
        after = {k for k in telemetry.counters() if k.startswith("lock.")}
        assert after == before

    def test_ab_ba_deadlock_detected_and_dumped(self, sanitize):
        """Acceptance: a REAL two-thread A/B–B/A deadlock raises a typed
        LockOrderError in the inverting thread (un-wedging the other)
        and the watchdog lands a kind:"stall" all-thread stack dump."""
        A = lockdep.lock("dl.A")
        B = lockdep.lock("dl.B")
        assert isinstance(A, lockdep.SanitizedLock)
        caught = []

        def t1():
            with A:
                time.sleep(0.15)
                with B:        # blocks on t2 past lock_stall_s=0.2
                    pass

        def t2():
            with B:
                time.sleep(0.7)
                try:
                    with A:    # closes the cycle -> typed error
                        pass
                except lockdep.LockOrderError as e:
                    caught.append(e)

        th2 = threading.Thread(target=t2, name="pt-test-dl2", daemon=True)
        th1 = threading.Thread(target=t1, name="pt-test-dl1", daemon=True)
        th2.start()
        time.sleep(0.05)
        th1.start()
        th1.join(5)
        th2.join(5)
        # the sanitizer must UN-WEDGE the schedule: both threads exit
        assert not th1.is_alive() and not th2.is_alive()
        assert caught, "inverting thread saw no LockOrderError"
        assert "dl.A" in str(caught[0]) and "cycle" in str(caught[0])

        stalls = [r for r in _records(sanitize) if r["kind"] == "stall"]
        assert stalls, "watchdog produced no stall record"
        attrs = stalls[0]["attrs"]
        assert attrs["lock"] == "dl.B"
        assert attrs["thread"] == "pt-test-dl1"
        by_name = {t["name"]: t for t in attrs["threads"]}
        assert by_name["pt-test-dl1"]["held"] == ["dl.A"]
        assert by_name["pt-test-dl1"]["waiting_for"] == "dl.B"
        assert "dl.B" in by_name["pt-test-dl2"]["held"]
        assert "stack" in by_name["pt-test-dl1"]
        assert telemetry.counter_get("lock.stalls") >= 1
        assert telemetry.counter_get("lock.order_violations") >= 1

    def test_same_thread_reentry_raises(self, sanitize):
        L = lockdep.lock("re.L")
        with L:
            with pytest.raises(lockdep.LockOrderError, match="re-entry"):
                with L:
                    pass
        # the lock is released and reusable after the unwind
        with L:
            pass

    def test_rlock_reentry_is_legal(self, sanitize):
        R = lockdep.rlock("re.R")
        with R:
            with R:
                assert R._is_owned()
        assert not R._is_owned()

    def test_condition_wrapper_roundtrip(self, sanitize):
        cond = lockdep.condition("cv.test")
        got = []

        def waiter():
            with cond:
                cond.wait_for(lambda: got, timeout=2)
                got.append("woke")

        w = threading.Thread(target=waiter, name="pt-test-cv",
                             daemon=True)
        w.start()
        time.sleep(0.1)
        with cond:
            got.append(1)
            cond.notify_all()
        w.join(3)
        assert "woke" in got

    def test_contention_and_held_telemetry(self, sanitize):
        L = lockdep.lock("tele.L")
        release = threading.Event()

        def holder():
            with L:
                release.wait(2)

        h = threading.Thread(target=holder, name="pt-test-holder",
                             daemon=True)
        h.start()
        time.sleep(0.05)
        t = threading.Thread(target=lambda: L.acquire() and L.release(),
                             name="pt-test-contender", daemon=True)
        t.start()
        time.sleep(0.05)
        release.set()
        t.join(3)
        h.join(3)
        assert telemetry.counter_get("lock.contentions") >= 1
        hists = telemetry.snapshot()["hists"]
        assert "lock.tele.L.held_ms" in hists
        assert "lock.tele.L.wait_ms" in hists


class TestThreadExcepthook:
    def test_uncaught_exception_is_counted_and_logged(self, tmp_path):
        log = str(tmp_path / "hook.jsonl")
        telemetry.configure(log)
        try:
            before = telemetry.counter_get("threads.uncaught_exceptions")

            def boom():
                raise ValueError("seeded worker crash")

            t = threading.Thread(target=boom, name="pt-test-boom",
                                 daemon=True)
            t.start()
            t.join(3)
            assert telemetry.counter_get(
                "threads.uncaught_exceptions") == before + 1
            recs = _records(log)
            errs = [r for r in recs if r["kind"] == "thread_error"]
            assert errs and errs[-1]["name"] == "pt-test-boom"
            assert errs[-1]["attrs"]["exc"] == "ValueError"
            assert "seeded worker crash" in errs[-1]["attrs"]["traceback"]
        finally:
            telemetry.configure(None)


# ---------------------------------------------------------------------------
# perf_report "Concurrency" section
# ---------------------------------------------------------------------------

class TestPerfReportSection:
    def test_section_renders(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import perf_report
        finally:
            sys.path.pop(0)
        log = tmp_path / "cc.jsonl"
        recs = [
            {"ts": 1.0, "kind": "timer", "name": "lock.engine.infer.held_ms",
             "value": 2.5, "attrs": {}},
            {"ts": 1.1, "kind": "timer", "name": "lock.engine.infer.wait_ms",
             "value": 0.4, "attrs": {}},
            {"ts": 1.2, "kind": "counter", "name": "lock.stalls",
             "value": 1, "attrs": {"delta": 1}},
            {"ts": 1.3, "kind": "stall", "name": "lockdep.stall",
             "value": 0.3,
             "attrs": {"lock": "engine.infer", "thread": "pt-x",
                       "waited_s": 0.3,
                       "threads": [{"name": "pt-x", "held": [],
                                    "stack": "..."}]}},
            {"ts": 1.4, "kind": "thread_error", "name": "pt-dead",
             "value": None, "attrs": {"exc": "ValueError"}},
            {"ts": 2.0, "kind": "snapshot", "name": "telemetry",
             "value": None,
             "attrs": {"counters": {"lock.acquires": 42,
                                    "lock.contentions": 3,
                                    "threads.uncaught_exceptions": 1},
                       "gauges": {}, "hists": {}}},
        ]
        log.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        s = perf_report.summarize_log(*perf_report.load_counted(str(log)))
        cc = s["concurrency"]
        assert cc["acquires"] == 42
        assert cc["contentions"] == 3
        assert cc["stalls"] == 1
        assert cc["uncaught_thread_exceptions"] == 1
        assert "engine.infer" in cc["locks"]
        assert cc["locks"]["engine.infer"]["held_ms"]["count"] == 1
        import io

        out = io.StringIO()
        perf_report.render(s, out=out)
        text = out.getvalue()
        assert "concurrency (lock sanitizer)" in text
        assert "STALL: thread 'pt-x'" in text
        assert "THREAD DIED: 'pt-dead'" in text

    def test_quiet_run_has_no_section(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import perf_report
        finally:
            sys.path.pop(0)
        log = tmp_path / "quiet.jsonl"
        log.write_text(json.dumps(
            {"ts": 1.0, "kind": "counter", "name": "executor.compiles",
             "value": 1, "attrs": {"delta": 1}}) + "\n")
        s = perf_report.summarize_log(*perf_report.load_counted(str(log)))
        assert s["concurrency"] is None
