"""Cost-model-guided autotuner (PR 15, core/tuner.py +
tools/autotune.py).

Pins the ISSUE acceptance criteria:

* typed flags.snapshot()/apply()/overrides() API: validated before any
  value changes (UnknownFlagError on a typo — no half-applied
  candidate), exact restore;
* FLAGS_serving_buckets / FLAGS_decode_buckets parse strictly: a
  zero-valued or non-monotonic bucket list raises a typed
  BucketConfigError instead of being silently reordered;
* search-space enumeration + constraint rejection (HBM headroom gates
  batch scaling, bucket sets must cover the batch bound, sharding
  candidates need mesh evidence), counted in
  tuner.constraint_rejections;
* offline replay ranking on a synthetic run log with a known-best
  config: measured per-k medians beat the incumbent, the amortization
  fit extrapolates only when physically valid, knobs without evidence
  cannot claim a win;
* profile round-trip: emit -> load -> apply -> finalize_bench_result
  embeds extra.tuned_profile provenance, and tools/slo_check.py only
  compares rows of matching provenance;
* online A/B trial against the in-process cluster backend: the
  candidate config lands on ONE replica via the swap machinery, the
  router steers/excludes the trial arm, promotion on per-arm p99, and
  an SLO rule trip rolls back within ONE evaluation tick with exactly
  one tuner.rollbacks increment and zero residual flag overrides;
* perf_report renders the "Autotune" section; tools/autotune.py CLI
  smoke (offline emits a profile, exit codes).
"""

import io
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io as pt_io
from paddle_tpu import layers
from paddle_tpu.core import flags as _flags
from paddle_tpu.core import incidents, telemetry, tuner
from paddle_tpu.core.flags import (BucketConfigError, ConfigError,
                                   UnknownFlagError)

pytestmark = pytest.mark.serving

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_tuner():
    snap = _flags.snapshot()
    tuner.clear_active_profile()
    yield
    _flags.apply(snap)
    tuner.clear_active_profile()
    incidents.reset()


def _counter(name):
    return int(telemetry.counters().get(name, 0))


# ---------------------------------------------------------------------------
# satellite: typed snapshot/apply/overrides flag API
# ---------------------------------------------------------------------------


class TestFlagsAPI:
    def test_snapshot_apply_roundtrip(self):
        snap = _flags.snapshot()
        prior = _flags.apply({"FLAGS_exec_steps_per_dispatch": 4,
                              "serving_max_batch_size": 16})
        assert _flags.flag("exec_steps_per_dispatch") == 4
        assert _flags.flag("serving_max_batch_size") == 16
        assert prior == {"exec_steps_per_dispatch":
                         snap["exec_steps_per_dispatch"],
                         "serving_max_batch_size":
                         snap["serving_max_batch_size"]}
        _flags.apply(prior)
        assert _flags.snapshot() == snap

    def test_unknown_flag_is_typed_and_atomic(self):
        before = _flags.flag("exec_steps_per_dispatch")
        with pytest.raises(UnknownFlagError, match="unknown flag"):
            _flags.apply({"exec_steps_per_dispatch": 8,
                          "definitely_not_a_flag": 1})
        # validation happens BEFORE any value changes: no half-applied
        # candidate config
        assert _flags.flag("exec_steps_per_dispatch") == before
        assert issubclass(UnknownFlagError, ValueError)

    def test_uncoercible_value_is_typed(self):
        with pytest.raises(ConfigError):
            _flags.apply({"exec_steps_per_dispatch": "not-an-int"})

    def test_overrides_context_restores_on_exception(self):
        before = _flags.flag("exec_steps_per_dispatch")
        with pytest.raises(RuntimeError, match="boom"):
            with _flags.overrides(exec_steps_per_dispatch=8):
                assert _flags.flag("exec_steps_per_dispatch") == 8
                raise RuntimeError("boom")
        assert _flags.flag("exec_steps_per_dispatch") == before

    def test_set_flags_stays_compatible(self):
        # the public paddle.set_flags surface keeps its ValueError
        # contract (UnknownFlagError subclasses it)
        with pytest.raises(ValueError, match="unknown flag"):
            _flags.set_flags({"FLAGS_nope": 1})


# ---------------------------------------------------------------------------
# satellite: strict bucket-list validation
# ---------------------------------------------------------------------------


class TestBucketValidation:
    def test_parse_good(self):
        assert _flags.parse_buckets("2,4,8", "t") == [2, 4, 8]
        assert _flags.parse_buckets([1, 3], "t") == [1, 3]
        assert _flags.parse_buckets("", "t") is None
        assert _flags.parse_buckets(None, "t") is None

    @pytest.mark.parametrize("bad", ["0,4", "4,2", "4,4", "-1,2", "2,x"])
    def test_parse_bad_is_typed(self, bad):
        with pytest.raises(BucketConfigError):
            _flags.parse_buckets(bad, "t")

    def test_cover(self):
        assert _flags.parse_buckets("2,8", "t", cover=8) == [2, 8]
        with pytest.raises(BucketConfigError, match="does not cover"):
            _flags.parse_buckets("2,4", "t", cover=8)
        with pytest.raises(BucketConfigError, match="end exactly"):
            _flags.parse_buckets("2,16", "t", cover=8, cover_exact=True)

    def test_serving_config_rejects_bad_flag(self):
        from paddle_tpu.serving.engine import ServingConfig

        _flags.apply({"serving_buckets": "8,4"})
        with pytest.raises(BucketConfigError):
            ServingConfig()
        _flags.apply({"serving_buckets": "0,4"})
        with pytest.raises(BucketConfigError):
            ServingConfig()
        _flags.apply({"serving_buckets": "4,8"})
        assert ServingConfig().buckets == [4, 8]
        _flags.apply({"serving_buckets": ""})
        assert ServingConfig(max_batch_size=8).buckets == [1, 2, 4, 8]

    def test_decode_config_rejects_bad_flag(self):
        from paddle_tpu.serving.decode import DecodeConfig

        _flags.apply({"decode_buckets": "4,2", "decode_max_slots": 4})
        with pytest.raises(BucketConfigError):
            DecodeConfig()
        # the set must end exactly at max_slots (fixed-step-shape
        # contract) — a ValueError subclass, like the old behavior
        with pytest.raises(ValueError):
            DecodeConfig(max_slots=4, buckets=[2, 8])
        _flags.apply({"decode_buckets": "2,4"})
        assert DecodeConfig(max_slots=4).buckets == [2, 4]
        _flags.apply({"decode_buckets": ""})
        assert DecodeConfig(max_slots=4).buckets == [4]


# ---------------------------------------------------------------------------
# search space + constraints
# ---------------------------------------------------------------------------


class TestSearchSpace:
    def test_enumerate_default_first_and_counted(self):
        before = _counter("tuner.candidates")
        space = tuner.SearchSpace()
        cands = space.enumerate()
        assert cands[0].label == "default" and cands[0].changes == 0
        assert all(c.changes == 1 for c in cands[1:])
        expected = 1 + sum(len(k.values) - 1 for k in space.knobs)
        assert len(cands) == expected
        assert _counter("tuner.candidates") - before == expected

    def test_bucket_constraints(self):
        space = tuner.SearchSpace()
        before = _counter("tuner.constraint_rejections")
        bad = tuner.Candidate(flags={"serving_buckets": "8,4"})
        assert space.check(bad) == "bucket_set_invalid"
        # a monotonic set that stops short of max_batch_size is rejected
        short = tuner.Candidate(flags={"serving_buckets": "2,4",
                                       "serving_max_batch_size": 16})
        assert space.check(short) == "bucket_set_invalid"
        good = tuner.Candidate(flags={"serving_buckets": "4,8",
                                      "serving_max_batch_size": 8})
        assert space.check(good) is None
        decode_bad = tuner.Candidate(flags={"decode_buckets": "2,4",
                                            "decode_max_slots": 8})
        assert space.check(decode_bad) == "bucket_set_invalid"
        assert _counter("tuner.constraint_rejections") - before == 3

    def test_hbm_headroom_gates_batch(self):
        space = tuner.SearchSpace()
        cand = tuner.Candidate(batch_multiplier=2.0)
        # no capacity configured: a scaled batch is unprovable
        assert space.check(cand, None) == "hbm_capacity_unknown"
        obs = tuner.RunLogObservations()
        obs.gauges["mem.hbm_total_bytes"] = 10e9
        obs.gauges["mem.param_bytes"] = 2e9
        obs.gauges["mem.opt_state_bytes"] = 2e9
        # fixed 4 GB + 6 GB activations * 2 = 16 GB > 12 GB * 0.92
        with _flags.overrides(tuner_hbm_capacity_bytes=int(12e9)):
            assert space.check(cand, obs) == "hbm_headroom"
        # 32 GB device: 16 GB projected fits
        with _flags.overrides(tuner_hbm_capacity_bytes=int(32e9)):
            assert space.check(cand, obs) is None
        # capacity known but the log has no ledger gauges
        with _flags.overrides(tuner_hbm_capacity_bytes=int(32e9)):
            assert space.check(cand, tuner.RunLogObservations()) == \
                "hbm_no_ledger_evidence"

    def test_sharding_needs_mesh_evidence(self):
        space = tuner.SearchSpace()
        cand = tuner.Candidate(zero_stage=2)
        assert space.check(cand, None) == "no_mesh_evidence"
        obs = tuner.RunLogObservations()
        obs.mesh_shape = {"dp": 8}
        assert space.check(cand, obs) is None
        rules = tuner.Candidate(
            axis_rules=tuner.AXIS_RULE_VARIANTS["mp_first"])
        assert space.check(rules, None) == "no_mesh_evidence"


# ---------------------------------------------------------------------------
# offline replay
# ---------------------------------------------------------------------------


def _metric_record(ms_per_step, k, batch=64, metric="mnist",
                   unit="samples/s", value=1.0):
    return {"ts": 1.0, "kind": "metric", "name": metric, "value": value,
            "attrs": {"ms_per_step": ms_per_step,
                      "steps_per_dispatch": k, "batch": batch,
                      "unit": unit}}


def _write_log(tmp_path, records, name="run.jsonl"):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return path


class TestOfflineReplay:
    def test_known_best_amortization(self, tmp_path):
        # ms(k) = 6 + 4/k measured at k=1 and k=4 -> k=8 extrapolates
        # to 6.5, beating every observed point
        path = _write_log(tmp_path, [_metric_record(10.0, 1),
                                     _metric_record(7.0, 4)])
        obs = tuner.RunLogObservations.load(path)
        res = tuner.offline_search(obs)
        assert res.default_score == 10.0
        assert res.improved()
        assert res.best.flags == {"exec_steps_per_dispatch": 8}
        top = res.ranked[0]
        assert top["basis"] == "modeled"
        assert abs(top["score"] - 6.5) < 1e-9

    def test_measured_beats_bad_incumbent(self, tmp_path):
        # the CPU-container reality: the fused scan LOSES — k=4 is 5x
        # slower. The fit is unphysical (host < 0) so NO extrapolation;
        # the measured table still dethrones the hand-picked incumbent.
        path = _write_log(tmp_path, [_metric_record(57.0, 1),
                                     _metric_record(379.0, 4)])
        obs = tuner.RunLogObservations.load(path)
        with _flags.overrides(exec_steps_per_dispatch=4):
            res = tuner.offline_search(obs)
        assert res.default_score == 379.0
        assert res.best.flags == {"exec_steps_per_dispatch": 1}
        assert res.ranked[0]["basis"] == "measured"
        assert res.ranked[0]["score"] == 57.0
        # unobserved k must NOT have been extrapolated from the
        # unphysical fit
        labels = {r["candidate"].label: r for r in res.ranked}
        assert labels["exec_steps_per_dispatch=8"]["basis"] == "default"

    def test_single_k_cannot_invent_a_win(self, tmp_path):
        path = _write_log(tmp_path, [_metric_record(10.0, 1)])
        obs = tuner.RunLogObservations.load(path)
        before = _counter("tuner.insufficient_evidence")
        res = tuner.offline_search(obs)
        assert not res.improved()
        # the incumbent (fewest changes) wins the all-tie ranking
        assert res.ranked[0]["candidate"].changes == 0
        assert _counter("tuner.insufficient_evidence") > before

    def test_raw_jsonl_timer_observations(self, tmp_path):
        recs = [{"ts": 1.0, "kind": "timer", "name": "executor.run_ms",
                 "value": v} for v in (9.0, 10.0, 11.0)]
        recs += [{"ts": 1.0, "kind": "counter",
                  "name": "executor.fused_dispatches", "value": 5,
                  "attrs": {"delta": 5}},
                 {"ts": 1.0, "kind": "counter",
                  "name": "executor.fused_steps", "value": 20,
                  "attrs": {"delta": 20}},
                 {"ts": 1.0, "kind": "timer",
                  "name": "executor.run_steps_ms", "value": 28.0}]
        obs = tuner.RunLogObservations.load(_write_log(tmp_path, recs))
        model = tuner.ReplayModel(obs)
        assert model.measured[1] == 10.0         # run_ms median
        assert model.measured[4] == 7.0          # 28 ms / k=4
        assert model.fit_valid()

    def test_empty_log_is_typed_error(self, tmp_path):
        path = _write_log(tmp_path, [{"ts": 1.0, "kind": "gauge",
                                      "name": "x", "value": 1}])
        with pytest.raises(tuner.TunerError, match="no step-time"):
            tuner.offline_search(tuner.RunLogObservations.load(path))

    def test_roofline_and_bench_wrapper_ingest(self, tmp_path):
        recs = [_metric_record(10.0, 1),
                {"ts": 1.0, "kind": "cost", "name": "costmodel.jit",
                 "value": 1e9, "attrs": {"roofline": "memory_bound",
                                         "intensity": 0.7}},
                {"parsed": _bench_row(8.0, 2)}]
        obs = tuner.RunLogObservations.load(_write_log(tmp_path, recs))
        assert obs.roofline_summary() == {"memory_bound": 1}
        assert {r["k"] for r in obs.step_rows} == {1, 2}


def _bench_row(ms_per_step, k, value=100.0, metric="mnist",
               extra=None):
    ex = {"ms_per_step": ms_per_step, "steps_per_dispatch": k,
          "batch": 64}
    ex.update(extra or {})
    return {"metric": metric, "value": value, "unit": "samples/s",
            "extra": ex}


# ---------------------------------------------------------------------------
# profiles + bench/slo_check provenance
# ---------------------------------------------------------------------------


class TestProfiles:
    def _profile(self):
        cand = tuner.Candidate(flags={"exec_steps_per_dispatch": 2},
                               changes=1, label="k2")
        return tuner.make_profile(cand, objective="step_ms",
                                  replayed=5.0, default_objective=10.0,
                                  origin={"run_id": "r42"},
                                  workload="mnist")

    def test_roundtrip_and_apply(self, tmp_path):
        doc = self._profile()
        path = str(tmp_path / "p.json")
        tuner.save_profile(doc, path)
        loaded = tuner.load_profile(path)
        assert loaded["profile_hash"] == doc["profile_hash"]
        before = _counter("tuner.profiles_loaded")
        prior = tuner.apply_profile(loaded, origin_path=path)
        assert _flags.flag("exec_steps_per_dispatch") == 2
        assert _counter("tuner.profiles_loaded") - before == 1
        prov = tuner.profile_provenance()
        assert prov == {"profile_hash": doc["profile_hash"],
                        "origin": "r42"}
        _flags.apply(prior)

    def test_load_rejects_junk(self, tmp_path):
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            f.write('{"format": "something-else"}')
        with pytest.raises(tuner.ProfileError):
            tuner.load_profile(bad)
        with pytest.raises(tuner.ProfileError):
            tuner.load_profile(str(tmp_path / "missing.json"))

    def test_finalize_bench_result_embeds_provenance(self):
        from tools.bench_models import finalize_bench_result

        out = finalize_bench_result({"metric": "t", "value": 1.0,
                                     "unit": "x", "extra": {}})
        assert out["extra"]["tuned_profile"] == "hand-picked"
        doc = self._profile()
        tuner.apply_profile(doc)
        try:
            out = finalize_bench_result({"metric": "t", "value": 1.0,
                                         "unit": "x", "extra": {}})
            assert out["extra"]["tuned_profile"]["profile_hash"] == \
                doc["profile_hash"]
        finally:
            tuner.clear_active_profile()

    def test_slo_check_matches_provenance(self):
        from tools.slo_check import slo_verdict

        hand = _bench_row(10.0, 1)
        tuned = _bench_row(5.0, 1, value=200.0, extra={
            "tuned_profile": {"profile_hash": "abc", "origin": "r1"}})
        tuned_other = _bench_row(5.0, 1, value=220.0, extra={
            "tuned_profile": {"profile_hash": "def", "origin": "r2"}})
        # a hand-picked row is never judged against tuned history
        v = slo_verdict(_bench_row(9.0, 1, value=95.0),
                        [tuned, tuned_other])
        assert v["verdict"] == "no_baseline"
        # ... and judges fine against hand-picked peers
        v = slo_verdict(_bench_row(9.0, 1, value=95.0), [hand, tuned])
        assert v["verdict"] == "pass" and v["peers"] == 1
        # tuned rows only compare within the SAME profile hash
        v = slo_verdict(dict(tuned, value=100.0), [tuned, tuned_other])
        assert v["peers"] == 1
        assert v["verdict"] == "regress"   # 100 < 200 * 0.95


# ---------------------------------------------------------------------------
# online A/B trial (in-process cluster backend)
# ---------------------------------------------------------------------------

IN_DIM, OUT_DIM = 6, 4


def _publish_mlp(tmp_path):
    from paddle_tpu import checkpoint as _ckpt

    model_dir = str(tmp_path / "mlp")
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [IN_DIM])
        h = layers.fc(x, 8, act="relu")
        y = layers.fc(h, OUT_DIM)
    scope = pt.Scope()
    pt.Executor().run(startup, scope=scope, use_compiled=False)
    pt_io.save_inference_model(model_dir, ["x"], [y],
                               main_program=main, scope=scope)
    root = str(tmp_path / "models")
    _ckpt.publish_model(root, model_dir)
    return root


@pytest.fixture()
def mlp_cluster(tmp_path):
    from paddle_tpu.serving.cluster import ClusterController

    root = _publish_mlp(tmp_path)
    cluster = ClusterController(root, replicas=2, inprocess=True).start()
    try:
        yield cluster
    finally:
        cluster.close()


def _feed_arms(trial, trial_ms, control_ms, n=12):
    """Deterministic per-arm latency evidence, recorded exactly where
    real dispatches record it (ReplicaHandle.dispatch_samples)."""
    for h in trial.router.handles():
        ms = trial_ms if h.name == trial.trial_replica else control_ms
        for _ in range(n):
            h.record_dispatch(ms)


CANDIDATE = {"serving_buckets": "4,8", "serving_batch_timeout_ms": 1.0}


class TestOnlineTrial:
    def test_candidate_lands_on_one_replica_then_promotes(
            self, mlp_cluster):
        snap = _flags.snapshot()
        t0 = _counter("tuner.trials")
        trial = tuner.OnlineTrial(mlp_cluster, CANDIDATE, fraction=0.25,
                                  min_requests=8, max_evals=5,
                                  label="t-promote")
        trial.start()
        assert _counter("tuner.trials") - t0 == 1
        # the candidate config took on the TRIAL replica only — the
        # swap machinery flipped config + predictor on one engine
        for r in mlp_cluster.replicas:
            if r.name == trial.trial_replica:
                assert r.engine.config.buckets == [4, 8]
            else:
                assert r.engine.config.buckets == [1, 2, 4, 8]
        # the router steers the bounded slice / excludes the trial arm
        assert mlp_cluster.router.trial() == (trial.trial_replica, 0.25)
        p0 = _counter("tuner.promotions")
        _feed_arms(trial, trial_ms=5.0, control_ms=10.0)
        res = trial.evaluate_once()
        assert res is not None and res.status == "promoted"
        assert _counter("tuner.promotions") - p0 == 1
        assert mlp_cluster.router.trial() is None
        # promoted flags are the new incumbent; fleet version untouched
        assert _flags.flag("serving_buckets") == "4,8"
        assert mlp_cluster.current_version == 1
        for r in mlp_cluster.replicas:
            assert r.engine.config.buckets == [4, 8]
        _flags.apply(snap)

    def test_latency_regression_rolls_back_clean(self, mlp_cluster):
        snap = _flags.snapshot()
        rb0 = _counter("tuner.rollbacks")
        trial = tuner.OnlineTrial(mlp_cluster, CANDIDATE, fraction=0.25,
                                  min_requests=8, max_evals=5,
                                  label="t-regress")
        trial.start()
        _feed_arms(trial, trial_ms=50.0, control_ms=10.0)
        res = trial.evaluate_once()
        assert res is not None and res.status == "rolled_back"
        assert res.reason == "latency_regression"
        assert _counter("tuner.rollbacks") - rb0 == 1
        # zero residual overrides + every replica back on the incumbent
        assert _flags.snapshot() == snap
        assert mlp_cluster.current_version == 1
        for r in mlp_cluster.replicas:
            assert r.engine.config.buckets == [1, 2, 4, 8]
        # a second evaluate cannot double-book the rollback
        assert trial.evaluate_once() is res
        assert _counter("tuner.rollbacks") - rb0 == 1

    def test_slo_trip_aborts_within_one_tick(self, mlp_cluster):
        snap = _flags.snapshot()
        incidents.reset()
        wd = incidents.arm([incidents.Rule(
            "t_gauge", "tuner_test.g", kind="gauge", threshold=5,
            direction="above", cooldown_s=0.0)])
        rb0 = _counter("tuner.rollbacks")
        sa0 = _counter("tuner.slo_aborts")
        trial = tuner.OnlineTrial(mlp_cluster, CANDIDATE, fraction=0.25,
                                  min_requests=10_000, max_evals=50,
                                  label="t-slo")
        trial.start()
        telemetry.gauge_set("tuner_test.g", 99)
        wd.evaluate()                      # the rule trips mid-trial
        res = trial.evaluate_once()        # ... and ONE tick aborts
        assert res is not None and res.status == "rolled_back"
        assert res.reason == "slo_trip" and res.evals == 1
        assert _counter("tuner.rollbacks") - rb0 == 1
        assert _counter("tuner.slo_aborts") - sa0 == 1
        assert _flags.snapshot() == snap
        assert mlp_cluster.current_version == 1

    def test_undecided_trial_keeps_incumbent(self, mlp_cluster):
        snap = _flags.snapshot()
        trial = tuner.OnlineTrial(mlp_cluster, CANDIDATE, fraction=0.25,
                                  min_requests=10_000, max_evals=2,
                                  label="t-undecided")
        trial.start()
        assert trial.evaluate_once() is None
        res = trial.evaluate_once()
        assert res is not None and res.status == "rolled_back"
        assert res.reason == "undecided"
        assert _flags.snapshot() == snap


class TestRouterTrialSteering:
    def test_split_and_exclusion(self):
        from paddle_tpu.serving.router import ReplicaHandle, Router

        router = Router()
        a = ReplicaHandle("a", "http://127.0.0.1:1")
        b = ReplicaHandle("b", "http://127.0.0.1:2")
        for h in (a, b):
            h.ready = True
            with router._lock:
                router._handles.append(h)
        router.set_trial("b", 0.25)
        picks = [router.pick().name for _ in range(40)]
        # every 4th pick steers to the trial arm, the rest exclude it
        assert picks.count("b") == 10
        assert all(n == "a" for i, n in enumerate(picks)
                   if (i + 1) % 4 != 0)
        # availability beats arm purity: control down -> trial serves
        a.ready = False
        assert router.pick().name == "b"
        router.clear_trial()
        assert router.trial() is None

    def test_dispatch_latency_ring(self):
        from paddle_tpu.serving.router import ReplicaHandle

        h = ReplicaHandle("a", "http://127.0.0.1:1")
        t0 = time.time()
        h.record_dispatch(5.0)
        h.record_dispatch(7.0)
        assert h.dispatch_latencies(0.0) == [5.0, 7.0]
        assert h.dispatch_latencies(t0 + 3600) == []


# ---------------------------------------------------------------------------
# perf_report section + CLI smoke
# ---------------------------------------------------------------------------


class TestReporting:
    def test_perf_report_autotune_section(self):
        sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
        from perf_report import render, summarize_log

        recs = [
            {"ts": 1.0, "kind": "counter", "name": "tuner.trials",
             "value": 2, "attrs": {"delta": 2}},
            {"ts": 1.0, "kind": "counter", "name": "tuner.rollbacks",
             "value": 1, "attrs": {"delta": 1}},
            {"ts": 1.0, "kind": "counter",
             "name": "tuner.constraint_rejections", "value": 3,
             "attrs": {"delta": 3}},
            {"ts": 1.5, "kind": "tuner", "name": "trial_rolled_back",
             "value": 12.5, "attrs": {"reason": "slo_trip",
                                      "candidate": "k8"}},
            {"ts": 1.6, "kind": "tuner", "name": "profile_applied",
             "value": None, "attrs": {"profile_hash": "abc123"}},
        ]
        s = summarize_log(recs)
        assert s["autotune"]["trials"] == 2
        assert s["autotune"]["rollbacks"] == 1
        assert s["autotune"]["constraint_rejections"] == 3
        assert len(s["autotune"]["events"]) == 2
        buf = io.StringIO()
        render(s, out=buf)
        text = buf.getvalue()
        assert "-- autotune" in text
        assert "rollbacks: 1" in text
        assert "profile_applied: abc123" in text

    def test_quiet_log_renders_no_section(self):
        sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
        from perf_report import summarize_log

        assert summarize_log([])["autotune"] is None

    def test_autotune_cli_offline_smoke(self, tmp_path):
        log = _write_log(tmp_path, [_metric_record(10.0, 1),
                                    _metric_record(7.0, 4)])
        out = str(tmp_path / "profile.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "autotune.py"),
             "offline", "--log", log, "--out", out,
             "--require-improvement", "--json"],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["improved"] is True
        assert doc["profile"]["flags"] == {"exec_steps_per_dispatch": 8}
        saved = tuner.load_profile(out)
        assert saved["profile_hash"] == doc["profile"]["profile_hash"]

    def test_autotune_cli_rejects_junk_log(self, tmp_path):
        log = str(tmp_path / "empty.jsonl")
        open(log, "w").close()
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "autotune.py"),
             "offline", "--log", log],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert proc.returncode == 2
        assert "no step-time" in proc.stderr
