"""Flight recorder + SLO watchdog plane (PR 14, core/incidents.py).

Pins the ISSUE acceptance criteria:

* the flight recorder is ALWAYS on (records land with no JSONL sink),
  bounded by FLAGS_blackbox_max_records, pruned to
  FLAGS_blackbox_seconds, and 0 disables it;
* rule trip/cooldown semantics: a sustained breach trips EXACTLY once
  (firing latch), a cleared episode + elapsed cooldown re-trips,
  ratio rules learn their baseline from the warmup window;
* a clean executor run under the default rule set trips ZERO rules
  (the false-positive gate);
* the unified kind:"incident" record bundles ring + ledger + traces +
  rule context, is globally rate-limited, and the legacy
  oom/stall/thread_error records keep their exact old shape (mem_report
  and the PR 10/11 readers stay green);
* /v1/stats grows a "health" section and /metrics grows pt_slo_*
  firing gauges;
* CLI smoke: tools/incident_report.py renders timeline + counter
  deltas + correlated spans; tools/slo_check.py exits 0/1/2;
  tools/trace_view.py marks incidents as instant events;
  tools/chaos_check.py --slo legs pass.
"""

import io
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import costmodel, incidents, telemetry, trace
from paddle_tpu.core.flags import flag as _flag
from paddle_tpu.core.flags import set_flags

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_plane():
    telemetry.configure(None)
    telemetry.reset()
    costmodel.reset()
    incidents.reset()
    set_flags({"blackbox_max_records": 2048, "blackbox_seconds": 120.0,
               "slo_watchdog": "auto", "slo_rules": "",
               "incident_rate_limit_s": 30.0, "slo_eval_s": 5.0,
               "trace_sample_rate": 0.0})
    yield
    telemetry.configure(None)
    telemetry.reset()
    costmodel.reset()
    incidents.reset()
    set_flags({"blackbox_max_records": 2048, "blackbox_seconds": 120.0,
               "slo_watchdog": "auto", "slo_rules": "",
               "incident_rate_limit_s": 30.0, "slo_eval_s": 5.0,
               "trace_sample_rate": 0.0})


def _read(path):
    telemetry.flush_sink()
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _failover_rule(**kw):
    kw.setdefault("window_s", 30.0)
    kw.setdefault("threshold", 3)
    kw.setdefault("cooldown_s", 60.0)
    return incidents.Rule("router_failover_burst", "router.failovers",
                          kind="counter", stat="delta", **kw)


# -- flight recorder ----------------------------------------------------------


class TestFlightRecorder:
    def test_always_on_without_sink(self):
        """The ring sees counters/gauges/hists/events with NO JSONL sink
        configured — the black-box property."""
        assert not telemetry.enabled()
        telemetry.counter_add("router.failovers", 2)
        telemetry.gauge_set("serving.queue_depth", 7)
        telemetry.observe("executor.run_ms", 3.25, kind="timer")
        telemetry.event("compile", "executor", 12.5, {"cause": "program"})
        snap = incidents.flight_recorder().snapshot()
        kinds = [(r["kind"], r["name"]) for r in snap]
        assert ("counter", "router.failovers") in kinds
        assert ("gauge", "serving.queue_depth") in kinds
        assert ("timer", "executor.run_ms") in kinds
        assert ("compile", "executor") in kinds

    def test_ring_bounded_keeps_newest(self):
        set_flags({"blackbox_max_records": 8})
        for i in range(50):
            telemetry.counter_add("router.failovers", 1, i=i)
        rec = incidents.flight_recorder()
        assert len(rec) == 8
        snap = rec.snapshot()
        assert len(snap) == 8
        assert [r["attrs"]["i"] for r in snap] == list(range(42, 50))
        assert rec.dropped > 0

    def test_zero_disables(self):
        set_flags({"blackbox_max_records": 0})
        incidents.flight_recorder().clear()
        telemetry.counter_add("router.failovers", 1)
        assert len(incidents.flight_recorder()) == 0

    def test_snapshot_prunes_by_time_and_caps(self):
        telemetry.counter_add("router.failovers", 1)
        rec = incidents.flight_recorder()
        now = time.time()
        # a record older than the horizon is pruned at snapshot time
        assert rec.snapshot(window_s=60.0, now=now)
        assert rec.snapshot(window_s=60.0, now=now + 120.0) == []
        for _ in range(10):
            telemetry.counter_add("router.failovers", 1)
        assert len(rec.snapshot(limit=4)) == 4


# -- rule semantics -----------------------------------------------------------


class TestRuleSemantics:
    def test_counter_rule_trips_once_latched(self):
        """A sustained breach trips exactly once: the firing latch
        absorbs every later evaluation of the same episode."""
        wd = incidents.Watchdog([_failover_rule()])
        telemetry.counter_add("router.failovers", 5)
        now = time.time()
        assert wd.evaluate(now=now) == ["router_failover_burst"]
        for i in range(5):
            assert wd.evaluate(now=now + i * 0.1) == []
        (rule,) = wd.rules
        assert rule.trips == 1 and rule.firing
        assert telemetry.counter_get("slo.trips") == 1

    def test_cooldown_gates_retrigger(self):
        """After the episode clears, a new breach re-trips only once the
        cooldown elapsed."""
        wd = incidents.Watchdog([_failover_rule(cooldown_s=60.0)])
        telemetry.counter_add("router.failovers", 5)
        now = time.time()
        assert wd.evaluate(now=now) == ["router_failover_burst"]
        (rule,) = wd.rules
        # signal leaves the window -> episode clears
        assert wd.evaluate(now=now + 100.0) == []
        assert not rule.firing
        # new breach inside the cooldown: suppressed (but latched)
        telemetry.counter_add("router.failovers", 5)
        assert wd.evaluate(now=now + 0.1) == []
        assert rule.firing
        # same breach once the cooldown HAS elapsed: trips again
        rule.firing = False
        rule.last_trip_ts = now - 100.0
        assert wd.evaluate(now=now + 0.2) == ["router_failover_burst"]
        assert rule.trips == 2

    def test_hist_baseline_learning_and_regression(self):
        """Ratio rules: the first window satisfying min_samples freezes
        the baseline; a later p99 above baseline*ratio trips."""
        rule = incidents.Rule("step_time_p99", "executor.run_ms",
                              kind="hist", stat="p99", window_s=60.0,
                              ratio=2.0, min_samples=20, cooldown_s=300.0)
        wd = incidents.Watchdog([rule])
        for _ in range(25):
            telemetry.observe("executor.run_ms", 5.0, kind="timer")
        now = time.time()
        assert wd.evaluate(now=now) == []          # learns, no trip
        assert rule.baseline == pytest.approx(5.0)
        assert rule.state() == "ok"
        assert wd.evaluate(now=now + 0.1) == []    # clean stays clean
        for _ in range(25):
            telemetry.observe("executor.run_ms", 50.0, kind="timer")
        assert wd.evaluate(now=now + 0.2) == ["step_time_p99"]
        assert rule.last_value > 2.0 * rule.baseline

    def test_gauge_below_rule_mfu_drop(self):
        rule = incidents.Rule("live_mfu_drop", "cost.live_mfu",
                              kind="gauge", ratio=0.5, direction="below",
                              min_samples=3, cooldown_s=300.0)
        wd = incidents.Watchdog([rule])
        telemetry.gauge_set("cost.live_mfu", 0.4)
        now = time.time()
        assert wd.evaluate(now=now) == []
        assert wd.evaluate(now=now) == []
        assert wd.evaluate(now=now) == []          # 3rd: baseline frozen
        assert rule.baseline == pytest.approx(0.4)
        telemetry.gauge_set("cost.live_mfu", 0.05)
        assert wd.evaluate(now=now + 1) == ["live_mfu_drop"]

    def test_threshold_gauge_queue_saturation(self):
        wd = incidents.Watchdog([incidents.Rule(
            "serving_queue_saturation", "serving.queue_depth",
            kind="gauge", threshold=0.9 * _flag("serving_max_queue_depth"),
            cooldown_s=60.0)])
        telemetry.gauge_set("serving.queue_depth", 4)
        assert wd.evaluate() == []
        telemetry.gauge_set(
            "serving.queue_depth",
            int(0.95 * _flag("serving_max_queue_depth")))
        assert wd.evaluate() == ["serving_queue_saturation"]

    def test_declarative_spec_overrides(self):
        spec = json.dumps([{"name": "my_rule", "metric": "foo.bar",
                            "kind": "counter", "threshold": 7,
                            "window_s": 10, "cooldown_s": 1}])
        rules = incidents.rules_from_spec(spec)
        assert len(rules) == 1
        assert rules[0].name == "my_rule"
        assert rules[0].threshold == 7
        assert rules[0].window_s == 10.0
        with pytest.raises((ValueError, json.JSONDecodeError)):
            incidents.rules_from_spec("{not json")
        with pytest.raises(ValueError):
            incidents.rules_from_spec(json.dumps(
                [{"name": "x", "metric": "m", "kind": "nope",
                  "threshold": 1}]))
        # empty spec -> the built-in set, which covers the ISSUE list
        names = {r.name for r in incidents.rules_from_spec("")}
        assert {"step_time_p99", "live_mfu_drop",
                "serving_queue_saturation", "decode_queue_saturation",
                "pallas_gemm_fallback_spike", "router_failover_burst",
                "ckpt_verify_failures"} <= names

    def test_clean_executor_run_trips_zero_rules(self, scope, tmp_path):
        """ACCEPTANCE (false-positive gate): a real, fault-free
        instrumented executor run under the DEFAULT rule set trips
        nothing."""
        telemetry.configure(str(tmp_path / "run.jsonl"))
        wd = incidents.arm()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=True)
            loss = layers.mean(layers.fc(x, 8, act="relu"))
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        xv = np.ones((4, 4), np.float32)
        trips = []
        for _ in range(5):
            exe.run(main, feed={"x": xv}, fetch_list=[loss], scope=scope)
            trips += wd.evaluate()
        assert trips == []
        assert telemetry.counter_get("incidents.reported") == 0
        assert not [r for r in _read(tmp_path / "run.jsonl")
                    if r["kind"] == "incident"]
        # ...and the run's signals DID reach the window the rules read
        assert telemetry.windowed(60.0)["hists"].get("executor.run_ms")

    def test_executor_tick_drives_evaluation(self, scope, tmp_path):
        """incidents.tick() on the executor hot path evaluates while
        armed (throttled by FLAGS_slo_eval_s) and is inert disarmed."""
        set_flags({"slo_eval_s": 0.0})
        telemetry.configure(str(tmp_path / "run.jsonl"))
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=True)
            loss = layers.mean(layers.fc(x, 8))
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        xv = np.ones((2, 4), np.float32)
        exe.run(main, feed={"x": xv}, fetch_list=[loss], scope=scope)
        assert telemetry.counter_get("slo.evaluations") == 0  # disarmed
        incidents.arm()
        exe.run(main, feed={"x": xv}, fetch_list=[loss], scope=scope)
        assert telemetry.counter_get("slo.evaluations") >= 1


# -- incident pipeline --------------------------------------------------------


class TestIncidentPipeline:
    def test_incident_record_schema(self, tmp_path):
        """ACCEPTANCE: one trip -> one kind:'incident' record bundling
        ring snapshot, ledger, active traces, counters and the rule
        context."""
        set_flags({"trace_sample_rate": 1.0})
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        with trace.span("serving.request"):
            telemetry.counter_add("router.failovers", 5)
        wd = incidents.Watchdog([_failover_rule()])
        assert wd.evaluate() == ["router_failover_burst"]
        (inc,) = [r for r in _read(log) if r["kind"] == "incident"]
        assert inc["name"] == "slo.router_failover_burst"
        a = inc["attrs"]
        assert a["source"] == "slo"
        assert a["id"].startswith("inc-")
        assert a["rule"]["name"] == "router_failover_burst"
        assert a["rule"]["threshold"] == 3
        assert a["rule"]["value"] == 5.0
        assert isinstance(a["ledger"], dict)
        assert a["counters"]["router.failovers"] == 5
        # the ring snapshot carries the events leading to the trip,
        # including the sampled span whose trace id is in `traces`
        ring_kinds = {(r["kind"], r["name"]) for r in a["ring"]}
        assert ("counter", "router.failovers") in ring_kinds
        assert ("span", "serving.request") in ring_kinds
        span_rec = next(r for r in a["ring"] if r["kind"] == "span")
        assert span_rec["attrs"]["trace"] in a["traces"]
        assert telemetry.counter_get("incidents.reported") == 1
        assert telemetry.counter_get("slo.trips") == 1

    def test_global_rate_limit(self, tmp_path):
        """Two rules tripping back-to-back: the second dump is
        rate-limited (counted, not written); legacy records are not."""
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        telemetry.counter_add("router.failovers", 5)
        telemetry.counter_add("ckpt.verify_failures", 1)
        wd = incidents.Watchdog([
            _failover_rule(),
            incidents.Rule("ckpt_verify_failures",
                           "ckpt.verify_failures", kind="counter",
                           stat="delta", window_s=120.0, threshold=0,
                           cooldown_s=60.0)])
        trips = wd.evaluate()
        assert sorted(trips) == ["ckpt_verify_failures",
                                 "router_failover_burst"]
        assert telemetry.counter_get("slo.trips") == 2
        incs = [r for r in _read(log) if r["kind"] == "incident"]
        assert len(incs) == 1
        assert telemetry.counter_get("incidents.reported") == 1
        assert telemetry.counter_get("incidents.rate_limited") == 1

    def test_oom_flows_through_pipeline_legacy_intact(self, tmp_path):
        """The PR 10 OOM dump rides the unified pipeline: the legacy
        kind:'oom' record keeps its exact fields (mem_report reads it),
        plus one incident record with source 'oom'."""
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        err = costmodel.oom_forensics(
            "prog9v1", RuntimeError("RESOURCE_EXHAUSTED: oom"),
            where="executor.dispatch")
        assert isinstance(err, costmodel.OutOfMemoryError)
        recs = _read(log)
        (oom,) = [r for r in recs if r["kind"] == "oom"]
        assert oom["name"] == "costmodel.oom"
        assert oom["attrs"]["where"] == "executor.dispatch"
        assert oom["attrs"]["program"] == "prog9v1"
        assert "ledger" in oom["attrs"] and "top_programs" in oom["attrs"]
        (inc,) = [r for r in recs if r["kind"] == "incident"]
        assert inc["attrs"]["source"] == "oom"
        assert inc["attrs"]["context"]["where"] == "executor.dispatch"
        # mem_report still renders the legacy record
        from tools.mem_report import summarize_mem

        s = summarize_mem(recs)
        assert len(s["ooms"]) == 1
        assert s["ooms"][0]["program"] == "prog9v1"

    def test_thread_death_flows_through_pipeline(self, tmp_path):
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))

        def boom():
            raise RuntimeError("worker died")

        t = threading.Thread(target=boom, name="pt-test-dying",
                             daemon=True)
        t.start()
        t.join(timeout=10)
        recs = _read(log)
        (te,) = [r for r in recs if r["kind"] == "thread_error"]
        assert te["name"] == "pt-test-dying"
        assert te["attrs"]["exc"] == "RuntimeError"
        assert "traceback" in te["attrs"]
        (inc,) = [r for r in recs if r["kind"] == "incident"]
        assert inc["attrs"]["source"] == "thread_error"
        assert inc["attrs"]["context"]["exc"] == "RuntimeError"

    def test_stall_flows_through_pipeline(self, tmp_path):
        """The PR 11 stall dump keeps its legacy shape and gains the
        incident twin (driven directly — wedging a real lock for
        FLAGS_lock_stall_s is a slow-test concern)."""
        from paddle_tpu.core.analysis import lockdep

        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        ident = threading.get_ident()
        lockdep._dump_stall(ident, {"lock": "router.dedup",
                                    "t0": time.monotonic() - 31.0,
                                    "thread": "pt-test"}, 31.0)
        recs = _read(log)
        (st,) = [r for r in recs if r["kind"] == "stall"]
        assert st["name"] == "lockdep.stall"
        assert st["attrs"]["lock"] == "router.dedup"
        assert st["attrs"]["threads"]          # all-thread stacks
        (inc,) = [r for r in recs if r["kind"] == "incident"]
        assert inc["attrs"]["source"] == "stall"
        assert inc["attrs"]["context"]["lock"] == "router.dedup"

    def test_health_and_prometheus_surfaces(self, tmp_path):
        telemetry.configure(str(tmp_path / "run.jsonl"))
        telemetry.counter_add("router.failovers", 5)
        incidents.arm([_failover_rule()])
        incidents.watchdog().evaluate()
        h = incidents.health()
        assert h["watchdog_armed"]
        assert h["incidents_reported"] == 1
        assert h["slo_trips"] == 1
        assert h["rules"]["router_failover_burst"]["state"] == "firing"
        assert h["firing"] == ["router_failover_burst"]
        assert h["last_incident"]["rule"] == "router_failover_burst"
        text = telemetry.prometheus_text()
        assert "pt_slo_router_failover_burst_firing 1" in text

    def test_v1_stats_health_section(self, tmp_path):
        """/v1/stats carries the health section (ACCEPTANCE: the stats
        surface exposes watchdog state)."""
        import urllib.request

        from paddle_tpu.serving.server import ServingHTTPServer
        from tests.test_serving import _engine, _save_mlp

        engine = _engine(_save_mlp(tmp_path)).start(warmup=False)
        srv = ServingHTTPServer(engine).start()
        try:
            assert incidents.armed()     # 'auto' armed by the server
            doc = json.loads(urllib.request.urlopen(
                srv.url + "/v1/stats", timeout=10).read())
            assert "health" in doc
            assert doc["health"]["watchdog_armed"] is True
            assert "incidents_reported" in doc["health"]
        finally:
            srv.shutdown()
            engine.close()
        assert not incidents.armed()     # disarmed on shutdown


# -- CLI surfaces -------------------------------------------------------------


def _make_incident_log(tmp_path):
    set_flags({"trace_sample_rate": 1.0})
    log = tmp_path / "run.jsonl"
    telemetry.configure(str(log))
    with trace.span("serving.request"):
        telemetry.counter_add("router.failovers", 5)
    incidents.Watchdog([_failover_rule()]).evaluate()
    telemetry.flush_sink()
    telemetry.configure(None)
    return log


class TestCLIs:
    def test_incident_report_renders_postmortem(self, tmp_path):
        """ACCEPTANCE: the postmortem carries timeline, counter deltas
        and correlated spans."""
        log = _make_incident_log(tmp_path)
        from tools.incident_report import (load_incidents,
                                           render_incident,
                                           summarize_incident)
        from tools.perf_report import load_counted

        recs, _ = load_counted(str(log))
        (inc,) = load_incidents(recs)
        s = summarize_incident(inc)
        assert s["source"] == "slo"
        assert s["counter_deltas"]
        assert s["spans"] and s["spans"][0]["name"] == "serving.request"
        buf = io.StringIO()
        render_incident(s, out=buf)
        text = buf.getvalue()
        for section in ("-- tripped rule --", "-- counter deltas",
                        "-- correlated spans", "-- timeline around"):
            assert section in text, f"missing {section}"

    def test_incident_report_cli_smoke(self, tmp_path):
        log = _make_incident_log(tmp_path)
        r = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "incident_report.py"),
             str(log)], capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        assert "incident" in r.stdout
        # a log without incidents exits 2
        clean = tmp_path / "clean.jsonl"
        clean.write_text(json.dumps(
            {"ts": 1.0, "kind": "counter", "name": "x", "value": 1,
             "attrs": {}}) + "\n")
        r2 = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "incident_report.py"),
             str(clean)], capture_output=True, text=True, timeout=60)
        assert r2.returncode == 2

    def test_trace_view_incident_markers(self, tmp_path):
        """Incidents render as chrome instant events on the swimlane of
        a span sharing their trace id."""
        log = _make_incident_log(tmp_path)
        from tools import trace_view

        out = tmp_path / "trace.json"
        rc = trace_view.main([str(log), "--out", str(out)])
        assert rc == 0
        doc = json.loads(out.read_text())
        inst = [e for e in doc["traceEvents"] if e.get("ph") == "i"]
        assert len(inst) == 1
        assert inst[0]["name"].startswith("INCIDENT slo.")
        assert inst[0]["args"]["rule"] == "router_failover_burst"
        span_ev = next(e for e in doc["traceEvents"]
                       if e.get("cat") == "span")
        assert inst[0]["pid"] == span_ev["pid"]
        assert inst[0]["tid"] == span_ev["tid"]

    def test_perf_report_incidents_section(self, tmp_path):
        log = _make_incident_log(tmp_path)
        from tools.perf_report import load_counted, render, summarize_log

        recs, malformed = load_counted(str(log))
        s = summarize_log(recs, malformed=malformed)
        ic = s["incidents"]
        assert ic["reported"] == 1
        assert ic["slo_trips"] == 1
        assert ic["rules_firing"]["router_failover_burst"] == 1
        assert ic["incidents"][0]["rule"] == "router_failover_burst"
        buf = io.StringIO()
        render(s, out=buf)
        assert "-- incidents & SLO" in buf.getvalue()
        assert "STILL FIRING" in buf.getvalue()

    def test_slo_check_exit_codes(self, tmp_path):
        from tools import slo_check

        prior = tmp_path / "BENCH_r01.json"
        prior.write_text(json.dumps({"parsed": {
            "metric": "m1", "value": 100.0, "unit": "tokens/s",
            "extra": {"mfu": 0.5, "ms_per_step": 10.0}}}))
        good = tmp_path / "good.json"
        good.write_text(json.dumps({
            "metric": "m1", "value": 101.0, "unit": "tokens/s",
            "extra": {"mfu": 0.51, "ms_per_step": 9.5}}))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({
            "metric": "m1", "value": 60.0, "unit": "tokens/s",
            "extra": {"mfu": 0.3, "ms_per_step": 17.0}}))
        glob_arg = str(tmp_path / "BENCH_r*.json")
        assert slo_check.main([str(good), "--prior", glob_arg]) == 0
        assert slo_check.main([str(bad), "--prior", glob_arg]) == 1
        garbage = tmp_path / "garbage.json"
        garbage.write_text("{\"nope\": 1}")
        assert slo_check.main([str(garbage)]) == 2
        # no comparable prior rows -> pass (no_baseline), not a failure
        other = tmp_path / "other.json"
        other.write_text(json.dumps({"metric": "m2", "value": 1.0,
                                     "unit": "x/s"}))
        assert slo_check.main([str(other), "--prior", glob_arg]) == 0
        # the embedded verdict bench rows carry
        v = slo_check.slo_verdict(json.loads(bad.read_text()),
                                  [json.loads(prior.read_text())["parsed"]])
        assert v["verdict"] == "regress"
        assert any(not c["ok"] for c in v["checks"])

    def test_slo_check_cli_smoke_against_repo_history(self):
        """The committed BENCH history judges its own best row: PASS."""
        r = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "slo_check.py"),
             os.path.join(REPO_ROOT, "BENCH_r05.json")],
            capture_output=True, text=True, timeout=60, cwd=REPO_ROOT)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "PASS" in r.stdout

    @pytest.mark.chaos
    def test_chaos_slo_fault_and_clean_legs(self):
        """ACCEPTANCE: the chaos --slo gate — one fault class leg (trips
        exactly once) + the clean false-positive leg (zero trips)."""
        r = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "chaos_check.py"),
             "--slo", "--slo-class",
             "router_failover,ckpt_verify,clean", "--steps", "4"],
            capture_output=True, text=True, timeout=300, cwd=REPO_ROOT,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        assert "CHAOS OK" in r.stdout
        assert "tripped exactly once" in r.stdout
        assert "0 trips, 0 incidents" in r.stdout
