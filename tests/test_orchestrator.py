"""Process-level crash survival (distributed/launch.py orchestrator,
distributed/demo_trainer.py child, serving decode-session failover).

Contracts under test:
* a SIGKILLed trainer subprocess is detected by the supervising
  orchestrator and respawned within the windowed restart budget — the
  respawned life restores the newest verified checkpoint and the
  ``LOSS <step> <value>`` row stream completes with no step missing;
* every child death lands EXACTLY one ``kind:"incident"`` record
  (exit code, signal, heartbeat age) through
  ``incidents.report_incident``, exempt from the rate-limit window —
  back-to-back deaths all reach the ledger;
* a deterministically crash-looping child (``--crash-at``) exhausts the
  budget into a typed ``RestartBudgetExhaustedError`` — never a silent
  respawn loop;
* ``execute_scale`` is a REAL process resize: checkpoint → drain
  (SIGTERM, the child's ElasticRunner force-saves and bound-joins its
  async writer) → terminate → relaunch at the new world size; a
  2→3→2 resize produces a loss trajectory bitwise-identical to an
  uninterrupted single-process run;
* the orchestrator shutdown path survives a kill DURING the drain
  checkpoint (``PT_CKPT_CRASH_AT=ckpt.save.commit``): the torn save is
  never visible to restore — atomic-commit discipline holds under
  SIGTERM-then-die;
* decode-session failover: a decode replica SIGKILLed mid-generation
  loses nothing — the router re-admits the journaled session on a
  survivor and the merged output is BITWISE-identical to the
  uninterrupted run, greedy and sampled, fp32 and int8, PT_PALLAS off
  and interpret;
* /v1/generate exactly-once: a client retry of an answered request id
  replays the cached response (``router.dedup_hits``) without
  re-generating;
* tier auto-provisioning: a prefill tier is provisioned like decode
  replicas, shipment pull flows THROUGH the router
  (``router.prefill_forwards``), and a killed tier member respawns
  with its role sticky + affinity remapped.

tools/chaos_check.py --orchestrator is the CLI twin.
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from paddle_tpu.core import flags, incidents, telemetry

pytestmark = pytest.mark.chaos

PY = sys.executable
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trainer_argv(steps, ckpt_dir, out, delay_ms=0.0, save_interval=1,
                  crash_at=-1):
    return [PY, "-m", "paddle_tpu.distributed.demo_trainer",
            "--steps", str(steps), "--ckpt-dir", str(ckpt_dir),
            "--out", str(out), "--save-interval", str(save_interval),
            "--step-delay-ms", str(delay_ms), "--crash-at", str(crash_at)]


def _rows(path):
    """LOSS rows keyed by step, LAST occurrence wins (a respawned life
    legitimately re-emits the step it died inside)."""
    rows = {}
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) == 3 and parts[0] == "LOSS":
                rows[int(parts[1])] = parts[2]
    return rows


def _counter(name):
    return int(telemetry.counter_get(name))


def _incident_records(name):
    return [r for r in incidents.flight_recorder().snapshot(window_s=1e9)
            if r.get("kind") == "incident" and r.get("name") == name]


def _generate(url, body, timeout=120):
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


# ---------------------------------------------------------------------------
# training orchestrator
# ---------------------------------------------------------------------------

class TestOrchestratorSupervision:
    def test_sigkill_trainer_respawns_within_budget(self, tmp_path):
        """A SIGKILLed trainer is respawned once, the row stream
        completes, and the death lands exactly one incident."""
        from paddle_tpu.distributed.launch import Orchestrator

        out = tmp_path / "rows.txt"
        argv = _trainer_argv(8, tmp_path / "ck", out, delay_ms=60)
        orch = Orchestrator(argv, world=2, ready_timeout_s=90,
                            drain_timeout_s=20)
        deaths0 = _counter("orch.child_deaths")
        incidents0 = len(_incident_records("child_death"))
        orch.start()

        def killer():
            while orch.max_step() < 2:
                time.sleep(0.02)
            orch.trainers[0].signal(signal.SIGKILL)

        threading.Thread(target=killer, daemon=True).start()
        rc = orch.run()
        assert rc == 0
        assert orch.respawns == 1
        assert _counter("orch.child_deaths") - deaths0 == 1
        recs = _incident_records("child_death")
        assert len(recs) - incidents0 == 1
        ctx = recs[-1]["attrs"]["context"]
        assert ctx["role"] == "trainer"
        assert ctx["signal"] == int(signal.SIGKILL)
        # no step lost: every row present despite the mid-stream kill
        assert sorted(_rows(out)) == list(range(8))

    def test_budget_exhaustion_raises_typed_error(self, tmp_path):
        """--crash-at turns the child into a deterministic crash loop:
        the orchestrator respawns within budget, then raises the typed
        error — and BOTH deaths reach the incident ledger (the reports
        are rate-limit-exempt)."""
        from paddle_tpu.distributed.elastic import \
            RestartBudgetExhaustedError
        from paddle_tpu.distributed.launch import Orchestrator

        argv = _trainer_argv(5, tmp_path / "ck", tmp_path / "rows.txt",
                             crash_at=1)
        orch = Orchestrator(argv, world=1, max_restarts=1,
                            restart_window_s=0.0, ready_timeout_s=90,
                            drain_timeout_s=10)
        exhausted0 = _counter("orch.budget_exhausted")
        incidents0 = len(_incident_records("child_death"))
        orch.start()
        with pytest.raises(RestartBudgetExhaustedError) as ei:
            orch.run()
        assert ei.value.max_restarts == 1
        assert ei.value.used == 2
        assert orch.respawns == 1
        assert _counter("orch.budget_exhausted") - exhausted0 == 1
        assert len(_incident_records("child_death")) - incidents0 == 2

    def test_real_process_2_3_2_resize_matches_uninterrupted(
            self, tmp_path):
        """The tentpole gate: a scheduled 2→3→2 resize executed as
        checkpoint → drain → terminate → relaunch continues the loss
        trajectory BITWISE — every row equal to an uninterrupted
        single-process run."""
        from paddle_tpu.distributed.launch import Orchestrator
        from paddle_tpu.distributed.scaler import ResizeSchedule

        base_out = tmp_path / "base.txt"
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        subprocess.run(
            _trainer_argv(11, tmp_path / "ck_base", base_out),
            env=env, check=True, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, timeout=300)
        base = _rows(base_out)
        assert sorted(base) == list(range(11))

        out = tmp_path / "rows.txt"
        argv = _trainer_argv(11, tmp_path / "ck", out, delay_ms=50)
        scales0 = _counter("orch.scale_events")
        orch = Orchestrator(argv, world=2, ready_timeout_s=90,
                            drain_timeout_s=30,
                            schedule=ResizeSchedule("3:3,8:2"))
        orch.start()
        assert orch.run() == 0
        assert orch.scale_events == 2
        assert _counter("orch.scale_events") - scales0 == 2
        got = _rows(out)
        assert sorted(got) == list(range(11))
        diff = [s for s in base if base[s] != got[s]]
        assert not diff, (
            f"trajectory diverged after resize at steps {diff}: "
            f"{[(base[s], got[s]) for s in diff[:3]]}")

    def test_shutdown_drain_kill_leaves_no_torn_checkpoint(
            self, tmp_path):
        """PT_CKPT_CRASH_AT kill test for the orchestrator shutdown
        path: the child dies between durable data and manifest commit of
        its drain checkpoint — restore must see NOTHING (atomic commit),
        and stop() must return promptly rather than hang."""
        from paddle_tpu.checkpoint import CheckpointManager
        from paddle_tpu.distributed.launch import Orchestrator

        ckpt_dir = tmp_path / "ck"
        env = dict(os.environ)
        # the child's only periodic save is the first (step 0) — the
        # interval pushes every later one past the horizon — so the save
        # at step 4 is the DRAIN's force-save, and the hook kills the
        # child between durable data and manifest commit
        env["PT_CKPT_CRASH_AT"] = "ckpt.save.commit@4"
        argv = _trainer_argv(500, ckpt_dir, tmp_path / "rows.txt",
                             delay_ms=200, save_interval=10000)
        orch = Orchestrator(argv, world=1, ready_timeout_s=90,
                            drain_timeout_s=10, env=env)
        orch.start()
        deadline = time.monotonic() + 60
        while orch.max_step() < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert orch.max_step() >= 2, "trainer never made progress"
        # land the SIGTERM inside step 3's delay so the drain check
        # fires at the top of step 4 — the crash spec's step
        time.sleep(0.05)
        t0 = time.monotonic()
        orch.stop()
        assert time.monotonic() - t0 < 30, "shutdown drain hung"
        child = orch.trainers[0]
        assert not child.alive()
        assert child.returncode() == -int(signal.SIGKILL)
        # the torn drain save (step 4) is invisible: restore falls back
        # to the committed step-0 checkpoint without raising
        step, arrays, _ = CheckpointManager(
            str(ckpt_dir)).restore_latest_arrays()
        assert step == 0 and arrays, (
            f"expected the committed step-0 checkpoint, got step {step} "
            f"with {len(arrays)} arrays")


# ---------------------------------------------------------------------------
# decode-session failover + tier provisioning
# ---------------------------------------------------------------------------

CFG_KW = dict(vocab_size=97, d_model=32, n_head=2, n_layers=2,
              d_inner=64, max_seq_len=64)


@pytest.fixture(scope="module")
def lm_dir(tmp_path_factory):
    from paddle_tpu.models.decoder_lm import (DecoderLMConfig,
                                              decoder_lm_params,
                                              save_decoder_lm)

    d = tmp_path_factory.mktemp("orch_lm")
    cfg = DecoderLMConfig(**CFG_KW)
    save_decoder_lm(str(d), cfg, decoder_lm_params(cfg, seed=0))
    return str(d)


@contextlib.contextmanager
def _decode_flags(monkeypatch, **over):
    """Apply flag overrides BOTH in-process (the registry, for inproc
    engines/routers) and as FLAGS_ env (inherited by replica
    subprocesses)."""
    for k, v in over.items():
        monkeypatch.setenv(f"FLAGS_{k}", str(v))
    prior = flags.apply(over)
    try:
        yield
    finally:
        flags.apply(prior)


PROMPT = [int(t) for t in np.random.RandomState(3).randint(3, 96, 6)]

# greedy/sampled x fp32/int8, with PT_PALLAS off/interpret spread
# across the matrix — the four acceptance identity legs
LEGS = [
    ("greedy-fp32", 0.0, "none", "off"),
    ("sampled-fp32", 0.8, "none", "interpret"),
    ("greedy-int8", 0.0, "int8", "interpret"),
    ("sampled-int8", 0.8, "int8", "off"),
]


class TestDecodeSessionFailover:
    @pytest.mark.parametrize("leg,temperature,quant,pallas", LEGS,
                             ids=[l[0] for l in LEGS])
    def test_decode_sigkill_bitwise_identity(self, lm_dir, monkeypatch,
                                             leg, temperature, quant,
                                             pallas):
        """SIGKILL the serving decode replica mid-generation: the
        journaled session resumes on the survivor and the merged token
        stream is bitwise-identical to the uninterrupted run."""
        from paddle_tpu.serving.cluster import ClusterController

        monkeypatch.setenv("PT_PALLAS", pallas)
        body = {"prompt_ids": PROMPT, "max_new_tokens": 14,
                "temperature": temperature, "seed": 11}
        with _decode_flags(monkeypatch, decode_step_delay_ms=60.0,
                           decode_weight_quant=quant):
            # uninterrupted reference: in-process single decode replica
            ref_cluster = ClusterController(
                "", decode_model_dir=lm_dir, role_counts={"decode": 1},
                inprocess=True).start(ready_timeout_s=120)
            try:
                ref = _generate(ref_cluster.url, body)
            finally:
                ref_cluster.close()
            assert len(ref["tokens"]) >= 6

            failovers0 = _counter("session.failovers")
            cluster = ClusterController(
                "", decode_model_dir=lm_dir,
                role_counts={"decode": 2}).start(ready_timeout_s=180)
            try:
                result = {}

                def client():
                    result.update(_generate(
                        cluster.url, dict(body, request_id=f"s-{leg}")))

                t = threading.Thread(target=client)
                t.start()
                victim = None
                deadline = time.monotonic() + 90
                while time.monotonic() < deadline:
                    rec = cluster.router.sessions.get(f"s-{leg}")
                    if rec and len(rec["accepted"]) >= 3:
                        handle = cluster.router.pick_generate(PROMPT)
                        victim = next(r for r in cluster.replicas
                                      if r.name == handle.name)
                        victim.kill(signal.SIGKILL)
                        break
                    time.sleep(0.01)
                assert victim is not None, \
                    "session journal never showed progress"
                t.join(timeout=180)
                assert result, "client never completed"
            finally:
                cluster.close()
        assert result["tokens"] == ref["tokens"], (
            f"[{leg}] resumed output diverged: {result['tokens']} vs "
            f"uninterrupted {ref['tokens']}")
        assert result.get("failed_over") is True
        assert _counter("session.failovers") - failovers0 >= 1

    def test_generate_dedup_replays_exactly_once(self, lm_dir,
                                                 monkeypatch):
        """A client retry of an answered /v1/generate id replays the
        cached response — the engine never generates twice."""
        from paddle_tpu.serving.cluster import ClusterController

        cluster = ClusterController(
            "", decode_model_dir=lm_dir, role_counts={"decode": 1},
            inprocess=True).start(ready_timeout_s=120)
        try:
            body = {"prompt_ids": PROMPT, "max_new_tokens": 6,
                    "temperature": 0.0, "request_id": "retry-1"}
            first = _generate(cluster.url, body)
            hits0 = _counter("router.dedup_hits")
            prefills0 = _counter("decode.prefills")
            second = _generate(cluster.url, body)
        finally:
            cluster.close()
        assert second["tokens"] == first["tokens"]
        assert _counter("router.dedup_hits") - hits0 == 1
        # the replay came from the dedup cache, not a fresh generation
        assert _counter("decode.prefills") == prefills0

    def test_prefill_tier_provisioned_and_role_sticky_respawn(
            self, lm_dir, monkeypatch):
        """Tier auto-provisioning: the prefill tier serves shipment
        pulls THROUGH the router, and a SIGKILLed decode member
        respawns with its role sticky, affinity remapped, exactly one
        replica-death incident."""
        from paddle_tpu.serving.cluster import ClusterController

        forwards0 = _counter("router.prefill_forwards")
        remaps0 = _counter("router.affinity_remaps")
        incidents0 = len(_incident_records("replica_death"))
        cluster = ClusterController(
            "", decode_model_dir=lm_dir,
            role_counts={"prefill": 1, "decode": 1},
        ).start(ready_timeout_s=180)
        try:
            body = {"prompt_ids": [int(t) for t in
                                   np.random.RandomState(5).randint(
                                       3, 96, 24)],
                    "max_new_tokens": 6, "temperature": 0.0}
            out = _generate(cluster.url, body)
            assert _counter("router.prefill_forwards") - forwards0 >= 1, \
                "decode replica did not pull its prefill via the router"

            victim = cluster.tier_members("decode")[0]
            victim.kill(signal.SIGKILL)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                members = cluster.tier_members("decode")
                if members and members[0] is not victim \
                        and members[0].alive():
                    break
                time.sleep(0.1)
            members = cluster.tier_members("decode")
            assert members and members[0] is not victim, \
                "decode tier member never respawned"
            assert members[0].role == "decode"
            out2 = _generate(cluster.url,
                             dict(body, request_id="after-respawn"))
        finally:
            cluster.close()
        assert out2["tokens"] == out["tokens"]
        assert _counter("router.affinity_remaps") - remaps0 >= 1
        assert len(_incident_records("replica_death")) - incidents0 == 1
