"""save/load program ops + checkpoint_notify + kill-restart (VERDICT r4 #4).

Reference: save_op.cc / load_op.cc / save_combine_op.cc /
load_combine_op.cc run inside programs via the executor;
distributed_ops/checkpoint_notify_op.cc tells every pserver to snapshot.
The decisive test: a 2-server KV-backed job checkpoints mid-run, DIES
(servers shut down, trainer scope discarded), restarts from the
checkpoint on NEW servers, and matches the uninterrupted run
step-for-step."""

import os

import numpy as np
import pytest


def _fresh():
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()


class TestSaveLoadOps:
    def _build(self):
        import paddle_tpu as pt
        from paddle_tpu import layers

        _fresh()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.static_data("x", [4, 6])
            h = layers.fc(x, 8, param_attr=pt.ParamAttr(name="sl_w"),
                          bias_attr=pt.ParamAttr(name="sl_b"))
            loss = layers.mean(h * h)
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        return main, startup, loss

    @pytest.mark.parametrize("combine", [False, True])
    def test_roundtrip_through_program_ops(self, tmp_path, combine):
        import paddle_tpu as pt
        from paddle_tpu import io

        main, startup, loss = self._build()
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(0).randn(4, 6).astype(
            np.float32)}
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        w_trained = np.asarray(scope.find_var("sl_w")).copy()
        fname = "all_params" if combine else None
        # save THROUGH the executor -> save/save_combine ops
        io.save_persistables(exe, str(tmp_path), main, filename=fname,
                             scope=scope)
        if combine:
            assert os.path.exists(tmp_path / "all_params.npz")
        # clobber, then load THROUGH the executor -> load/load_combine
        scope.set("sl_w", np.zeros_like(w_trained))
        io.load_persistables(exe, str(tmp_path), main, filename=fname,
                             scope=scope)
        np.testing.assert_array_equal(np.asarray(scope.find_var("sl_w")),
                                      w_trained)

    def test_op_path_interoperates_with_host_path(self, tmp_path):
        """Files written by the ops must read back via the host-side
        load_vars (and vice versa) — same encoding, same layout."""
        import paddle_tpu as pt
        from paddle_tpu import io

        main, startup, loss = self._build()
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        w = np.asarray(scope.find_var("sl_w")).copy()
        io.save_persistables(exe, str(tmp_path), main, scope=scope)  # ops
        scope.set("sl_w", np.zeros_like(w))
        io.load_persistables(None, str(tmp_path), main, scope=scope)  # host
        np.testing.assert_array_equal(np.asarray(scope.find_var("sl_w")), w)


class TestKillRestart:
    """The cluster-consistent checkpoint/resume flow."""

    DIM = 8

    def _servers(self, n=2):
        from paddle_tpu.distributed.ps import kv_service

        return [kv_service.KVServer("127.0.0.1:0") for _ in range(n)]

    def _teardown(self, servers):
        from paddle_tpu.distributed.ps import kv_service
        from paddle_tpu.distributed.ps.rpc import RPCClient

        for s in servers:
            s.shutdown()
        kv_service._client_cache.clear()
        RPCClient.reset_pool()

    def _build(self, eps):
        import paddle_tpu as pt
        from paddle_tpu import layers

        _fresh()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", [4], dtype="int64", stop_gradient=True)
            label = layers.data("label", [1], dtype="int64",
                                stop_gradient=True)
            emb = layers.distributed_embedding(ids, "ck_tbl", self.DIM,
                                               eps, seed=7, lr=0.1)
            feat = layers.reduce_mean(emb, dim=1)
            logits = layers.fc(
                feat, 3, param_attr=pt.ParamAttr(
                    name="ck_w", initializer=pt.initializer.Xavier(seed=5)),
                bias_attr=pt.ParamAttr(name="ck_b"))
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.SGDOptimizer(0.2).minimize(loss)
        return main, startup, loss

    @staticmethod
    def _feed(step):
        rng = np.random.RandomState(400 + step)
        return {"ids": rng.randint(0, 10 ** 9, (8, 4)).astype(np.int64),
                "label": rng.randint(0, 3, (8, 1)).astype(np.int64)}

    def _steps(self, exe, main, loss, scope, lo, hi):
        out = []
        for s in range(lo, hi):
            r = exe.run(main, feed=self._feed(s), fetch_list=[loss],
                        scope=scope, use_compiled=False)
            out.append(float(np.asarray(r[0]).reshape(-1)[0]))
        return out

    def _notify(self, exe, eps, dirname, load=False):
        """checkpoint_notify as a PROGRAM OP, reference style."""
        import paddle_tpu as pt

        prog = pt.Program()
        prog.global_block().append_op(
            "checkpoint_notify", {}, {"Token": ["@ckpt_token@"]},
            {"endpoints": eps, "dirname": dirname, "load": load})
        exe.run(prog, feed={}, fetch_list=[], scope=pt.Scope(),
                use_compiled=False)

    def test_kill_and_restart_matches_uninterrupted(self, tmp_path):
        import paddle_tpu as pt
        from paddle_tpu import io

        # ---- run A: uninterrupted 6 steps -----------------------------
        servers_a = self._servers()
        eps_a = ",".join(s.endpoint for s in servers_a)
        try:
            main, startup, loss = self._build(eps_a)
            exe = pt.Executor()
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            losses_a = self._steps(exe, main, loss, scope, 0, 6)
        finally:
            self._teardown(servers_a)

        # ---- run B: 3 steps, checkpoint, DIE --------------------------
        ckpt = str(tmp_path / "ckpt")
        servers_b = self._servers()
        eps_b = ",".join(s.endpoint for s in servers_b)
        try:
            main, startup, loss = self._build(eps_b)
            exe = pt.Executor()
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            losses_b1 = self._steps(exe, main, loss, scope, 0, 3)
            # cluster checkpoint: servers snapshot KV tables; trainer
            # saves its persistables through save ops
            self._notify(exe, eps_b, ckpt)
            io.save_persistables(exe, ckpt, main, filename="trainer",
                                 scope=scope)
        finally:
            self._teardown(servers_b)   # the "kill"
        del scope, exe, main

        # ---- run C: fresh servers + trainer, restore, resume ----------
        servers_c = self._servers()
        eps_c = ",".join(s.endpoint for s in servers_c)
        try:
            main, startup, loss = self._build(eps_c)
            exe = pt.Executor()
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            self._notify(exe, eps_c, ckpt, load=True)
            io.load_persistables(exe, ckpt, main, filename="trainer",
                                 scope=scope)
            losses_c = self._steps(exe, main, loss, scope, 3, 6)
        finally:
            self._teardown(servers_c)

        np.testing.assert_allclose(losses_b1, losses_a[:3], rtol=1e-6)
        np.testing.assert_allclose(losses_c, losses_a[3:], rtol=1e-6,
                                   err_msg="resume diverged from the "
                                           "uninterrupted run")

    def test_pserver_dense_checkpoint_roundtrip(self, tmp_path):
        """PServer (dense path) snapshot/restore: params + accumulators
        + step counters survive."""
        import paddle_tpu as pt
        from paddle_tpu.distributed.ps.pserver import PServer
        from paddle_tpu.distributed.ps.rpc import RPCClient

        _fresh()
        # minimal pserver program: SGD apply for one param
        prog, startup = pt.Program(), pt.Program()
        block = prog.global_block()
        block.create_var(name="p0", shape=[4], dtype="float32",
                         persistable=True)
        sb = startup.global_block()
        v = sb.create_var(name="p0", shape=[4], dtype="float32",
                          persistable=True)
        from paddle_tpu.initializer import Constant

        Constant(1.0)(v, sb)
        apply_op = pt.core.ir.OpDesc(
            "sgd", {"Param": ["p0"], "Grad": ["p0@GRAD"],
                    "LearningRate": ["lr0"]},
            {"ParamOut": ["p0"]}, {})
        lv = sb.create_var(name="lr0", shape=[1], dtype="float32",
                           persistable=True)
        Constant(0.5)(lv, sb)
        block.create_var(name="lr0", shape=[1], dtype="float32",
                         persistable=True)
        srv = PServer("127.0.0.1:0", prog, startup, num_trainers=1,
                      sync_mode=False,
                      grad_to_param={"p0@GRAD": "p0"},
                      grad_to_ops={"p0@GRAD": [apply_op]})
        try:
            cli = RPCClient.get(srv.endpoint)
            cli.call("send_grad", "p0@GRAD",
                     np.ones(4, np.float32), 0)
            p_after, _ = cli.call("recv_param", "p0")
            np.testing.assert_allclose(p_after, 0.5)
            cli.call("checkpoint", str(tmp_path / "d") + "|0")
            # wreck the state, then restore
            srv.scope.set("p0", np.zeros(4, np.float32))
            cli.call("checkpoint_load", str(tmp_path / "d") + "|0")
            p_back, _ = cli.call("recv_param", "p0")
            np.testing.assert_allclose(p_back, 0.5)
        finally:
            srv.shutdown()
            RPCClient.reset_pool()


class TestVerifiedSnapshots:
    """PServer snapshots ride the atomic-commit protocol: a torn
    snapshot must fail verification loudly instead of silently serving
    wrong parameters."""

    def test_corrupt_pserver_snapshot_rejected(self, tmp_path):
        import paddle_tpu as pt
        from paddle_tpu.distributed.ps.pserver import PServer
        from paddle_tpu.distributed.ps.rpc import RPCClient

        _fresh()
        prog, startup = pt.Program(), pt.Program()
        prog.global_block().create_var(name="vp", shape=[4],
                                       dtype="float32", persistable=True)
        v = startup.global_block().create_var(
            name="vp", shape=[4], dtype="float32", persistable=True)
        from paddle_tpu.initializer import Constant

        Constant(2.0)(v, startup.global_block())
        apply_op = pt.core.ir.OpDesc(
            "sgd", {"Param": ["vp"], "Grad": ["vp@GRAD"],
                    "LearningRate": ["vlr"]},
            {"ParamOut": ["vp"]}, {})
        lv = startup.global_block().create_var(
            name="vlr", shape=[1], dtype="float32", persistable=True)
        Constant(0.5)(lv, startup.global_block())
        prog.global_block().create_var(name="vlr", shape=[1],
                                       dtype="float32", persistable=True)
        srv = PServer("127.0.0.1:0", prog, startup, num_trainers=1,
                      sync_mode=False, grad_to_param={"vp@GRAD": "vp"},
                      grad_to_ops={"vp@GRAD": [apply_op]})
        try:
            cli = RPCClient.get(srv.endpoint)
            d = str(tmp_path / "snap")
            cli.call("checkpoint", d + "|0")
            # the snapshot is a committed checkpoint dir with a manifest
            from paddle_tpu.checkpoint import DATA_NAME, MANIFEST_NAME

            sdir = os.path.join(d, "pserver_0")
            assert os.path.exists(os.path.join(sdir, MANIFEST_NAME))
            # corrupt the data file: the verified load must refuse it
            data = os.path.join(sdir, DATA_NAME)
            raw = bytearray(open(data, "rb").read())
            raw[len(raw) // 2] ^= 0xFF
            with open(data, "wb") as f:
                f.write(bytes(raw))
            before = np.asarray(srv.scope.find_var("vp")).copy()
            with pytest.raises(Exception, match="(?i)corrupt|sha256|crc"):
                cli.call("checkpoint_load", d + "|0")
            # the server scope was not poisoned by the torn bytes
            np.testing.assert_array_equal(
                np.asarray(srv.scope.find_var("vp")), before)
        finally:
            srv.shutdown()
            RPCClient.reset_pool()
