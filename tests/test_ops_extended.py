"""Extended op tests via the OpTest harness (numpy reference + numeric
gradients) — the reference's test_*_op.py methodology (op_test.py:184)."""

import numpy as np
import pytest

from op_test import OpTest


def _r(*shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(
        np.float32)


class TestMatmulOp(OpTest):
    op_type = "matmul"

    def setup(self):
        x, y = _r(2, 3, seed=1), _r(3, 4, seed=2)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"alpha": 2.0}
        self.outputs = {"Out": 2.0 * (x @ y)}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestLayerNormOp(OpTest):
    op_type = "layer_norm"

    def setup(self):
        x = _r(3, 8, seed=3)
        s, b = _r(8, seed=4), _r(8, seed=5)
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mean) / np.sqrt(var + 1e-5) * s + b
        self.inputs = {"X": x, "Scale": s, "Bias": b}
        self.attrs = {"begin_norm_axis": 1, "epsilon": 1e-5}
        self.outputs = {"Y": y, "Mean": mean.reshape(3),
                        "Variance": var.reshape(3)}

    def test(self):
        self.check_output(atol=1e-4)
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.02)


class TestMishOp(OpTest):
    op_type = "mish"

    def setup(self):
        x = _r(2, 5, seed=6)
        sp = np.log1p(np.exp(x))
        self.inputs = {"X": x}
        self.outputs = {"Out": x * np.tanh(sp)}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"], "Out")


class TestSeluOp(OpTest):
    op_type = "selu"

    def setup(self):
        x = _r(3, 4, seed=7)
        # keep inputs away from the kink at 0 — central differences
        # average the two one-sided slopes there (reference op tests do
        # the same for relu-family ops)
        x = x + np.sign(x) * 0.1
        scale, alpha = 1.0507009873554805, 1.6732632423543772
        self.inputs = {"X": x}
        self.outputs = {"Out": scale * np.where(
            x > 0, x, alpha * (np.exp(x) - 1.0)).astype(np.float32)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestCosSimOp(OpTest):
    op_type = "cos_sim"

    def setup(self):
        x, y = _r(4, 6, seed=8), _r(4, 6, seed=9)
        xn = np.sqrt((x * x).sum(-1, keepdims=True))
        yn = np.sqrt((y * y).sum(-1, keepdims=True))
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": (x * y).sum(-1, keepdims=True) / (xn * yn),
                        "XNorm": xn, "YNorm": yn}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.02)


class TestIndexSelectOp(OpTest):
    op_type = "index_select"

    def setup(self):
        x = _r(5, 3, seed=10)
        idx = np.array([0, 2, 4, 2], np.int64)
        self.inputs = {"X": x, "Index": idx}
        self.attrs = {"dim": 0}
        self.outputs = {"Out": x[idx]}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestInstanceNormOp(OpTest):
    op_type = "instance_norm"

    def setup(self):
        x = _r(2, 3, 4, 4, seed=11)
        s, b = _r(3, seed=12), _r(3, seed=13)
        mean = x.mean((2, 3), keepdims=True)
        var = x.var((2, 3), keepdims=True)
        y = (x - mean) / np.sqrt(var + 1e-5) * s.reshape(1, 3, 1, 1) + \
            b.reshape(1, 3, 1, 1)
        self.inputs = {"X": x, "Scale": s, "Bias": b}
        self.outputs = {"Y": y.astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-4)


class TestEyeLinspaceMeshgrid:
    def test_eye(self):
        import paddle_tpu as pt
        from paddle_tpu.core.executor import run_op
        from paddle_tpu.core.ir import OpDesc

        env = {}
        run_op(OpDesc("eye", {}, {"Out": ["e"]},
                      {"num_rows": 3, "num_columns": 4, "dtype": "float32"}),
               env)
        np.testing.assert_allclose(env["e"], np.eye(3, 4))

    def test_meshgrid(self):
        from paddle_tpu.core.executor import run_op
        from paddle_tpu.core.ir import OpDesc

        env = {"a": np.arange(3.0), "b": np.arange(2.0)}
        run_op(OpDesc("meshgrid", {"X": ["a", "b"]},
                      {"Out": ["ga", "gb"]}, {}), env)
        wa, wb = np.meshgrid(np.arange(3.0), np.arange(2.0), indexing="ij")
        np.testing.assert_allclose(env["ga"], wa)
        np.testing.assert_allclose(env["gb"], wb)


class TestSequenceOps:
    def _run(self, op_type, inputs, outputs, attrs=None):
        from paddle_tpu.core.executor import run_op
        from paddle_tpu.core.ir import OpDesc

        env = dict(inputs)
        run_op(OpDesc(op_type, {k: [k] for k in inputs},
                      {k: [k] for k in outputs}, attrs or {}), env)
        return env

    def test_sequence_mask(self):
        env = self._run("sequence_mask", {"X": np.array([2, 0, 3])},
                        ["Y"], {"maxlen": 4, "out_dtype": "int32"})
        np.testing.assert_array_equal(
            env["Y"], [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])

    def test_sequence_pad_pool_softmax_reverse(self):
        vals = np.arange(10, dtype=np.float32).reshape(5, 2)
        lod = np.array([0, 2, 5], np.int64)     # seqs of len 2 and 3
        env = self._run("sequence_pad", {"X": vals, "Lod": lod}, ["Out"],
                        {"padded_length": 3})
        want = np.zeros((2, 3, 2), np.float32)
        want[0, :2] = vals[:2]
        want[1, :3] = vals[2:]
        np.testing.assert_allclose(env["Out"], want)

        env = self._run("sequence_pool", {"X": vals, "Lod": lod}, ["Out"],
                        {"pooltype": "MEAN"})
        np.testing.assert_allclose(
            env["Out"], [vals[:2].mean(0), vals[2:].mean(0)], atol=1e-6)

        x1 = np.array([1.0, 2.0, 0.5, 0.2, 0.3], np.float32)
        env = self._run("sequence_softmax", {"X": x1, "Lod": lod}, ["Out"])
        w = np.concatenate([np.exp(x1[:2]) / np.exp(x1[:2]).sum(),
                            np.exp(x1[2:]) / np.exp(x1[2:]).sum()])
        np.testing.assert_allclose(env["Out"], w, atol=1e-6)

        env = self._run("sequence_reverse", {"X": vals, "Lod": lod}, ["Y"])
        want = np.concatenate([vals[:2][::-1], vals[2:][::-1]])
        np.testing.assert_allclose(env["Y"], want)


class TestRnnOps:
    def test_lstm_matches_numpy(self):
        from paddle_tpu.core.executor import run_op
        from paddle_tpu.core.ir import OpDesc

        rng = np.random.RandomState(0)
        B, S, D, H = 2, 4, 3, 5
        x = rng.randn(B, S, D).astype(np.float32)
        wx = rng.randn(D, 4 * H).astype(np.float32) * 0.1
        wh = rng.randn(H, 4 * H).astype(np.float32) * 0.1
        bias = rng.randn(4 * H).astype(np.float32) * 0.1
        env = {"Input": x, "WeightX": wx, "WeightH": wh, "Bias": bias}
        run_op(OpDesc("lstm",
                      {"Input": ["Input"], "WeightX": ["WeightX"],
                       "WeightH": ["WeightH"], "Bias": ["Bias"]},
                      {"Out": ["Out"], "LastH": ["LastH"],
                       "LastC": ["LastC"]}, {}), env)

        def sigmoid(v):
            return 1.0 / (1.0 + np.exp(-v))

        h = np.zeros((B, H), np.float32)
        c = np.zeros((B, H), np.float32)
        outs = []
        for t in range(S):
            gates = x[:, t] @ wx + bias + h @ wh
            i, f, g, o = np.split(gates, 4, axis=-1)
            c = sigmoid(f) * c + sigmoid(i) * np.tanh(g)
            h = sigmoid(o) * np.tanh(c)
            outs.append(h.copy())
        want = np.stack(outs, axis=1)
        np.testing.assert_allclose(env["Out"], want, atol=1e-5)
        np.testing.assert_allclose(env["LastH"], h, atol=1e-5)
        np.testing.assert_allclose(env["LastC"], c, atol=1e-5)

    def test_lstm_respects_lengths(self):
        from paddle_tpu.core.executor import run_op
        from paddle_tpu.core.ir import OpDesc

        rng = np.random.RandomState(1)
        B, S, D, H = 2, 5, 3, 4
        x = rng.randn(B, S, D).astype(np.float32)
        wx = rng.randn(D, 4 * H).astype(np.float32) * 0.1
        wh = rng.randn(H, 4 * H).astype(np.float32) * 0.1
        lens = np.array([3, 5], np.int32)
        env = {"Input": x, "WeightX": wx, "WeightH": wh,
               "SequenceLength": lens}
        run_op(OpDesc("lstm",
                      {"Input": ["Input"], "WeightX": ["WeightX"],
                       "WeightH": ["WeightH"],
                       "SequenceLength": ["SequenceLength"]},
                      {"Out": ["Out"], "LastH": ["LastH"],
                       "LastC": ["LastC"]}, {}), env)
        # row 0's state freezes after step 3
        np.testing.assert_allclose(env["Out"][0, 2], env["Out"][0, 4],
                                   atol=1e-6)
        np.testing.assert_allclose(env["LastH"][0], env["Out"][0, 2],
                                   atol=1e-6)

    def test_gru_runs_and_shapes(self):
        from paddle_tpu.core.executor import run_op
        from paddle_tpu.core.ir import OpDesc

        rng = np.random.RandomState(2)
        B, S, D, H = 2, 4, 3, 5
        env = {"Input": rng.randn(B, S, D).astype(np.float32),
               "WeightX": rng.randn(D, 3 * H).astype(np.float32) * 0.1,
               "WeightH": rng.randn(H, 3 * H).astype(np.float32) * 0.1}
        run_op(OpDesc("gru",
                      {"Input": ["Input"], "WeightX": ["WeightX"],
                       "WeightH": ["WeightH"]},
                      {"Out": ["Out"], "LastH": ["LastH"]}, {}), env)
        assert env["Out"].shape == (B, S, H)
        np.testing.assert_allclose(env["Out"][:, -1], env["LastH"],
                                   atol=1e-6)


class TestAucOp:
    def test_streaming_auc(self):
        from paddle_tpu.core.executor import run_op
        from paddle_tpu.core.ir import OpDesc

        rng = np.random.RandomState(0)
        n_t = 200
        stat_pos = np.zeros(n_t + 1, np.float32)
        stat_neg = np.zeros(n_t + 1, np.float32)
        # perfectly separable → AUC ~ 1
        preds = np.concatenate([rng.uniform(0.8, 1.0, (50,)),
                                rng.uniform(0.0, 0.2, (50,))])
        labels = np.concatenate([np.ones(50), np.zeros(50)]).astype(np.int64)
        pred2 = np.stack([1 - preds, preds], axis=1).astype(np.float32)
        env = {"Predict": pred2, "Label": labels,
               "StatPos": stat_pos, "StatNeg": stat_neg}
        run_op(OpDesc("auc",
                      {"Predict": ["Predict"], "Label": ["Label"],
                       "StatPos": ["StatPos"], "StatNeg": ["StatNeg"]},
                      {"AUC": ["AUC"], "StatPosOut": ["StatPos"],
                       "StatNegOut": ["StatNeg"]},
                      {"num_thresholds": n_t}), env)
        assert float(env["AUC"]) > 0.99
