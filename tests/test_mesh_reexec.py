"""Re-exec the mesh suite's smoke legs in a SUBPROCESS with
XLA_FLAGS=--xla_force_host_platform_device_count=8 set explicitly.

The in-process suite gets its 8 virtual devices from conftest.py; this
fixture proves the dp×mp rule-table and ZeRO paths also come up on a
CPU-only CI build that never imports the conftest (fresh interpreter,
env forced by hand) — and skips clean when the platform cannot
materialise the devices at all."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# the two re-exec smoke legs: rule-table fc training on dp×mp, and a
# ZeRO stage-2 step on dp8 (selected via -k reexec)
MESH_SUITE = ["tests/test_axis_rules.py", "tests/test_zero_sharding.py"]


def _forced_env():
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"]).strip()
    env["JAX_PLATFORMS"] = "cpu"
    # the child is a PARTIAL pytest session: it must not inherit (and
    # tear down) the parent suite's op-coverage dir
    env.pop("PT_OP_COVERAGE_DIR", None)
    return env


def _device_count(env):
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            env=env, cwd=ROOT, capture_output=True, text=True, timeout=120)
    except subprocess.TimeoutExpired:
        return 0
    try:
        return int(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return 0


def test_reexec_mesh_suite_under_forced_device_count():
    env = _forced_env()
    n = _device_count(env)
    if n < 8:
        pytest.skip(f"platform cannot materialise 8 host devices (got {n})")
    cmd = [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
           "-p", "no:randomly", "-k", "reexec", *MESH_SUITE]
    r = subprocess.run(cmd, cwd=ROOT, env=env, capture_output=True,
                       text=True, timeout=480)
    tail = (r.stdout[-2000:] + r.stderr[-1000:])
    assert r.returncode == 0, f"re-exec'd mesh suite failed:\n{tail}"
    assert "2 passed" in r.stdout, f"expected both smoke legs to run:\n{tail}"
