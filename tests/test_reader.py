"""Data pipeline: decorators, Dataset/BatchSampler/DataLoader, and the
from_generator queue loader feeding a real training program.

Mirrors reference test_multiprocess_dataloader_*.py / reader decorator tests.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, reader
from paddle_tpu.reader import (BatchSampler, DataLoader, Dataset,
                               IterableDataset, TensorDataset)


def test_decorators_batch_shuffle_chain():
    r = lambda: iter(range(10))  # noqa: E731
    b = reader.batch(r, 3)
    batches = list(b())
    assert [len(x) for x in batches] == [3, 3, 3, 1]
    b2 = reader.batch(r, 3, drop_last=True)
    assert [len(x) for x in b2()] == [3, 3, 3]

    s = reader.shuffle(r, buf_size=10, seed=0)
    out = list(s())
    assert sorted(out) == list(range(10)) and out != list(range(10))

    c = reader.chain(r, r)
    assert len(list(c())) == 20

    f = reader.firstn(r, 4)
    assert list(f()) == [0, 1, 2, 3]

    m = reader.map_readers(lambda a, b: a + b, r, r)
    assert list(m()) == [2 * i for i in range(10)]


def test_buffered_and_xmap_preserve_data():
    r = lambda: iter(range(50))  # noqa: E731
    assert list(reader.buffered(r, 8)()) == list(range(50))
    x = reader.xmap_readers(lambda v: v * v, r, process_num=4, buffer_size=8,
                            order=True)
    assert list(x()) == [i * i for i in range(50)]
    x2 = reader.xmap_readers(lambda v: v * v, r, process_num=4, buffer_size=8)
    assert sorted(x2()) == sorted(i * i for i in range(50))


def test_tensor_dataset_loader_batches():
    xs = np.arange(20, dtype=np.float32).reshape(10, 2)
    ys = np.arange(10, dtype=np.int64)
    ds = TensorDataset([xs, ys])
    dl = DataLoader(ds, batch_size=4, drop_last=False)
    batches = list(dl)
    assert len(batches) == 3
    bx, by = batches[0]
    assert bx.shape == (4, 2) and by.shape == (4,)
    np.testing.assert_array_equal(bx, xs[:4])


def test_loader_shuffle_covers_all():
    ds = TensorDataset([np.arange(16, dtype=np.float32)])
    dl = DataLoader(ds, batch_size=4, shuffle=True)
    seen = np.concatenate([b[0] for b in dl])
    assert sorted(seen.tolist()) == list(range(16))


def test_loader_num_workers_in_order():
    class SlowDS(Dataset):
        def __getitem__(self, i):
            return np.float32(i)

        def __len__(self):
            return 32

    dl = DataLoader(SlowDS(), batch_size=4, num_workers=4)
    got = np.concatenate([b[0] for b in dl])
    np.testing.assert_array_equal(got, np.arange(32, dtype=np.float32))


def test_iterable_dataset():
    class Stream(IterableDataset):
        def __iter__(self):
            yield from (np.float32(i) for i in range(10))

    dl = DataLoader(Stream(), batch_size=3)
    sizes = [len(b[0]) for b in dl]
    assert sizes == [3, 3, 3, 1]


def test_batch_sampler_len():
    ds = TensorDataset([np.zeros(10)])
    assert len(BatchSampler(ds, batch_size=3)) == 4
    assert len(BatchSampler(ds, batch_size=3, drop_last=True)) == 3


def test_from_generator_feeds_training(scope):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        label = layers.data("label", [1], dtype="int64")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(x, 4), label))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)

    rng = np.random.RandomState(0)

    def sample_gen():
        for _ in range(32):
            yield rng.randn(4).astype(np.float32), \
                rng.randint(0, 4, (1,)).astype(np.int64)

    loader = DataLoader.from_generator(feed_list=[x, label], capacity=4)
    loader.set_sample_generator(sample_gen, batch_size=8)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    losses = []
    for epoch in range(6):
        for feed in loader:
            assert set(feed) == {"x", "label"}
            lv, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            losses.append(float(lv))
    assert losses[-1] < losses[0]


class TestProcessWorkers:
    def test_process_workers_shared_memory(self):
        """num_workers>0 + use_shared_memory: fork workers, shm batch
        transport, in-order delivery, parity with the serial loader."""
        import numpy as np
        from paddle_tpu.reader import DataLoader, Dataset

        class Squares(Dataset):
            def __len__(self):
                return 23

            def __getitem__(self, i):
                return (np.full((4,), i, np.float32),
                        np.array([i * i], np.int64))

        ds = Squares()
        serial = list(DataLoader(ds, batch_size=4, num_workers=0,
                                 drop_last=False))
        proc = list(DataLoader(ds, batch_size=4, num_workers=3,
                               use_shared_memory=True, drop_last=False))
        assert len(proc) == len(serial) == 6
        for a, b in zip(serial, proc):
            np.testing.assert_array_equal(a[0], b[0])
            np.testing.assert_array_equal(a[1], b[1])

    def test_process_workers_scale_past_gil(self):
        """CPU-heavy __getitem__ must speed up with process workers
        (the reference's reason for multiprocess loading)."""
        import time

        import numpy as np
        import pytest

        from paddle_tpu.reader import DataLoader, Dataset

        class Heavy(Dataset):
            def __len__(self):
                return 12

            def __getitem__(self, i):
                # pure-python loop: holds the GIL, immune to numpy
                # threading — only process workers can parallelise it
                acc = 0
                for k in range(300000):
                    acc = (acc + k * i) % 1000003
                return np.array([acc], np.int64)

        ds = Heavy()
        serial = list(DataLoader(ds, batch_size=2, num_workers=0))
        par = list(DataLoader(ds, batch_size=2, num_workers=4,
                              use_shared_memory=True))
        for a, b in zip(serial, par):
            np.testing.assert_array_equal(a[0], b[0])
        # timing expectation: real but load-sensitive — the suite often
        # shares the machine with benchmarks/other suites, and a starved
        # worker pool shows no speedup through no fault of the loader.
        # Correctness is asserted above; absence of speedup SKIPs (it
        # still fails loudly when someone breaks parallelism AND the
        # machine is idle enough to measure it).
        for attempt in range(3):
            t0 = time.perf_counter()
            list(DataLoader(ds, batch_size=2, num_workers=0))
            t_serial = time.perf_counter() - t0
            t0 = time.perf_counter()
            list(DataLoader(ds, batch_size=2, num_workers=4,
                            use_shared_memory=True))
            t_par = time.perf_counter() - t0
            if t_par < t_serial * 0.9:
                return
        pytest.skip(f"no speedup measurable under load "
                    f"(serial {t_serial:.2f}s, parallel {t_par:.2f}s)")

    def test_worker_exception_propagates(self):
        import numpy as np
        import pytest

        from paddle_tpu.reader import DataLoader, Dataset

        class Bad(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                if i == 5:
                    raise ValueError("boom")
                return np.zeros((2,), np.float32)

        with pytest.raises(RuntimeError, match="worker failed"):
            list(DataLoader(Bad(), batch_size=2, num_workers=2,
                            use_shared_memory=True))


def test_generator_loader_cursor_state_roundtrip():
    """Resumable double-buffer reader: state_dict tracks the stream
    cursor; set_state fast-forwards the next iteration to it (exact
    resume over a deterministic generator)."""
    from paddle_tpu.reader import DataLoader

    def stream():
        for i in range(6):
            yield np.full((2, 3), i, np.float32)

    loader = DataLoader.from_generator(capacity=2, return_list=True,
                                       use_double_buffer=False)
    loader.set_batch_generator(stream)
    it = iter(loader)
    seen = [int(np.asarray(next(it)[0])[0, 0]) for _ in range(3)]
    assert seen == [0, 1, 2]
    assert loader.state_dict() == {"batches": 3}

    # a fresh iteration armed with the saved cursor resumes at batch 3
    resumed = DataLoader.from_generator(capacity=2, return_list=True,
                                        use_double_buffer=False)
    resumed.set_batch_generator(stream)
    resumed.set_state({"batches": 3})
    vals = [int(np.asarray(b[0])[0, 0]) for b in resumed]
    assert vals == [3, 4, 5]
    assert resumed.state_dict() == {"batches": 6}
    # the cursor re-arms only once: a second pass replays from the start
    assert len(list(resumed)) == 6
