"""Data pipeline: decorators, Dataset/BatchSampler/DataLoader, and the
from_generator queue loader feeding a real training program.

Mirrors reference test_multiprocess_dataloader_*.py / reader decorator tests.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, reader
from paddle_tpu.reader import (BatchSampler, DataLoader, Dataset,
                               IterableDataset, TensorDataset)


def test_decorators_batch_shuffle_chain():
    r = lambda: iter(range(10))  # noqa: E731
    b = reader.batch(r, 3)
    batches = list(b())
    assert [len(x) for x in batches] == [3, 3, 3, 1]
    b2 = reader.batch(r, 3, drop_last=True)
    assert [len(x) for x in b2()] == [3, 3, 3]

    s = reader.shuffle(r, buf_size=10, seed=0)
    out = list(s())
    assert sorted(out) == list(range(10)) and out != list(range(10))

    c = reader.chain(r, r)
    assert len(list(c())) == 20

    f = reader.firstn(r, 4)
    assert list(f()) == [0, 1, 2, 3]

    m = reader.map_readers(lambda a, b: a + b, r, r)
    assert list(m()) == [2 * i for i in range(10)]


def test_buffered_and_xmap_preserve_data():
    r = lambda: iter(range(50))  # noqa: E731
    assert list(reader.buffered(r, 8)()) == list(range(50))
    x = reader.xmap_readers(lambda v: v * v, r, process_num=4, buffer_size=8,
                            order=True)
    assert list(x()) == [i * i for i in range(50)]
    x2 = reader.xmap_readers(lambda v: v * v, r, process_num=4, buffer_size=8)
    assert sorted(x2()) == sorted(i * i for i in range(50))


def test_tensor_dataset_loader_batches():
    xs = np.arange(20, dtype=np.float32).reshape(10, 2)
    ys = np.arange(10, dtype=np.int64)
    ds = TensorDataset([xs, ys])
    dl = DataLoader(ds, batch_size=4, drop_last=False)
    batches = list(dl)
    assert len(batches) == 3
    bx, by = batches[0]
    assert bx.shape == (4, 2) and by.shape == (4,)
    np.testing.assert_array_equal(bx, xs[:4])


def test_loader_shuffle_covers_all():
    ds = TensorDataset([np.arange(16, dtype=np.float32)])
    dl = DataLoader(ds, batch_size=4, shuffle=True)
    seen = np.concatenate([b[0] for b in dl])
    assert sorted(seen.tolist()) == list(range(16))


def test_loader_num_workers_in_order():
    class SlowDS(Dataset):
        def __getitem__(self, i):
            return np.float32(i)

        def __len__(self):
            return 32

    dl = DataLoader(SlowDS(), batch_size=4, num_workers=4)
    got = np.concatenate([b[0] for b in dl])
    np.testing.assert_array_equal(got, np.arange(32, dtype=np.float32))


def test_iterable_dataset():
    class Stream(IterableDataset):
        def __iter__(self):
            yield from (np.float32(i) for i in range(10))

    dl = DataLoader(Stream(), batch_size=3)
    sizes = [len(b[0]) for b in dl]
    assert sizes == [3, 3, 3, 1]


def test_batch_sampler_len():
    ds = TensorDataset([np.zeros(10)])
    assert len(BatchSampler(ds, batch_size=3)) == 4
    assert len(BatchSampler(ds, batch_size=3, drop_last=True)) == 3


def test_from_generator_feeds_training(scope):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        label = layers.data("label", [1], dtype="int64")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(x, 4), label))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)

    rng = np.random.RandomState(0)

    def sample_gen():
        for _ in range(32):
            yield rng.randn(4).astype(np.float32), \
                rng.randint(0, 4, (1,)).astype(np.int64)

    loader = DataLoader.from_generator(feed_list=[x, label], capacity=4)
    loader.set_sample_generator(sample_gen, batch_size=8)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    losses = []
    for epoch in range(6):
        for feed in loader:
            assert set(feed) == {"x", "label"}
            lv, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            losses.append(float(lv))
    assert losses[-1] < losses[0]
