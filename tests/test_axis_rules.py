"""Logical-axis-rule partitioning (parallel/axis_rules.py + api.py):
rule resolution, typed spec validation, rule-driven executor shardings,
and the compile-cache keying on the table fingerprint."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import api, axis_rules, create_mesh
from paddle_tpu.parallel import mesh as meshmod
from paddle_tpu.parallel.api import (ShardingAxisError, clean_spec,
                                     get_logical_axes, set_logical_axes,
                                     shard_tensor, spec_for_var)
from paddle_tpu.parallel.axis_rules import AxisRules


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    meshmod.set_mesh(None)


class TestResolve:
    def test_default_table_maps_batch_and_mlp(self):
        mesh = create_mesh({"dp": 2, "mp": 4})
        rules = axis_rules.get_rules()
        assert rules.resolve(("batch", None), mesh,
                             shape=(16, 8)) == ("dp", None)
        assert rules.resolve(("embed", "mlp"), mesh,
                             shape=(32, 64)) == (None, "mp")

    def test_indivisible_dim_falls_back_to_replicated(self):
        mesh = create_mesh({"dp": 2, "mp": 4})
        rules = axis_rules.get_rules()
        # 10 % 4 != 0 → the mlp→mp rule is skipped, dim replicated
        assert rules.resolve(("embed", "mlp"), mesh,
                             shape=(32, 10)) == (None, None)

    def test_mesh_axis_used_once_per_array(self):
        mesh = create_mesh({"mp": 4})
        rules = AxisRules((("heads", "mp"), ("mlp", "mp")))
        # both dims want mp; only the first gets it
        assert rules.resolve(("heads", "mlp"), mesh,
                             shape=(8, 8)) == ("mp", None)

    def test_fallback_chain_second_rule_wins(self):
        mesh = create_mesh({"sp": 8})
        rules = AxisRules((("batch", "dp"), ("batch", "sp")))
        assert rules.resolve(("batch",), mesh, shape=(16,)) == ("sp",)

    def test_scoped_override_and_fingerprint(self):
        fp0 = axis_rules.fingerprint()
        with axis_rules.axis_rules([("batch", "sp")]):
            assert axis_rules.fingerprint() != fp0
            assert axis_rules.get_rules().first_mesh_axis("batch") == "sp"
        assert axis_rules.fingerprint() == fp0

    def test_batch_mesh_axis_rule_driven(self):
        mesh = create_mesh({"dp": 8})
        assert axis_rules.batch_mesh_axis(mesh) == "dp"
        with axis_rules.axis_rules([("batch", "sp")]):
            # table names sp, mesh has none → dp fallback
            assert axis_rules.batch_mesh_axis(mesh) == "dp"
        mesh2 = create_mesh({"sp": 8})
        with axis_rules.axis_rules([("batch", "sp")]):
            assert axis_rules.batch_mesh_axis(mesh2) == "sp"


class TestValidation:
    def test_shard_tensor_rejects_unknown_axis(self):
        create_mesh({"dp": 8})
        main = pt.Program()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", [8])
        with pytest.raises(ShardingAxisError, match="typo"):
            shard_tensor(x, ("not_an_axis",))

    def test_clean_spec_rejects_unknown_axis(self):
        mesh = create_mesh({"dp": 8})
        with pytest.raises(ShardingAxisError):
            clean_spec(("dq",), mesh)

    def test_clean_spec_drops_known_but_absent_axis(self):
        mesh = create_mesh({"dp": 8})
        assert clean_spec(("mp", "dp"), mesh) == (None, "dp")

    def test_clean_spec_error_mode_raises_on_absent(self):
        mesh = create_mesh({"sp": 8})
        with pytest.raises(ShardingAxisError, match="not in the active"):
            clean_spec(("dp",), mesh, on_missing="error")

    def test_clean_spec_translates_logical_names(self):
        mesh = create_mesh({"dp": 2, "mp": 4})
        assert clean_spec(("batch", "mlp"), mesh) == ("dp", "mp")

    def test_compiled_program_feed_axis_validated(self):
        """A CompiledProgram data axis absent from the mesh fails with a
        typed error at feed-sharding time, not an opaque XLA error."""
        from paddle_tpu.core.compiler import CompiledProgram

        mesh = create_mesh({"sp": 8})
        prog = CompiledProgram(pt.Program()).with_data_parallel(
            mesh=mesh, data_axis="dp")
        with pytest.raises(ShardingAxisError):
            prog._sharding_for_feed({"x": np.zeros((8, 2))})


class TestVarResolution:
    def test_fc_attaches_logical_axes(self):
        main = pt.Program()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", [32])
            layers.fc(x, 64)
        w = next(p for p in main.all_parameters() if p.shape == (32, 64))
        b = next(p for p in main.all_parameters() if p.shape == (64,))
        assert get_logical_axes(w) == ("embed", "mlp")
        assert get_logical_axes(b) == ("mlp",)

    def test_named_sharding_derives_from_rules(self):
        mesh = create_mesh({"dp": 2, "mp": 4})
        main = pt.Program()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", [32])
            layers.fc(x, 64)
        w = next(p for p in main.all_parameters() if p.shape == (32, 64))
        ns = api.named_sharding_for(w, mesh)
        assert tuple(ns.spec) == (None, "mp")

    def test_explicit_spec_overrides_rules(self):
        mesh = create_mesh({"dp": 2, "mp": 4})
        main = pt.Program()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", [32])
            layers.fc(x, 64)
        w = next(p for p in main.all_parameters() if p.shape == (32, 64))
        shard_tensor(w, ("mp", None))
        assert spec_for_var(w, mesh) == ("mp", None)

    def test_use_rules_false_ignores_logical_axes(self):
        mesh = create_mesh({"dp": 2, "mp": 4})
        main = pt.Program()
        with pt.program_guard(main, pt.Program()):
            x = layers.data("x", [32])
            layers.fc(x, 64)
        w = next(p for p in main.all_parameters() if p.shape == (32, 64))
        assert spec_for_var(w, mesh, use_rules=False) is None

    def test_accumulator_inherits_logical_axes(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [32])
            h = layers.fc(x, 64)
            loss = layers.mean(h)
            opt = pt.optimizer.MomentumOptimizer(0.1, 0.9)
            opt.minimize(loss)
        w = next(p for p in main.all_parameters() if p.shape == (32, 64))
        vel = opt._get_accumulator("velocity", w)
        assert get_logical_axes(vel) == ("embed", "mlp")


def test_axis_rules_smoke_reexec():
    """Minimal end-to-end: an fc program trains on a dp×mp mesh with
    rule-derived shardings — the subprocess re-exec fixture
    (test_mesh_reexec.py) runs exactly this under a freshly-forced
    XLA_FLAGS device count."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = create_mesh({"dp": 2, "mp": 4})
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [32])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(x, 64, act="relu")
        logits = layers.fc(h, 8)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.Scope()
    exe.run(startup, scope=sc, use_compiled=False)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(16, 32).astype(np.float32),
            "label": rng.randint(0, 8, (16, 1)).astype(np.int64)}
    lv, = exe.run(main, feed=feed, fetch_list=[loss], scope=sc, mesh=mesh)
    assert np.isfinite(float(np.asarray(lv).reshape(-1)[0]))
    # the fc weight really landed mp-sharded via the rule table
    w = next(p for p in main.all_parameters() if p.shape == (32, 64))
    sharded = sc.find_var(w.name)
    assert "mp" in str(getattr(sharded, "sharding").spec)


def test_rule_table_change_recompiles_with_cause(tmp_path):
    """Swapping the rule table must MISS the compile cache (stale
    shardings otherwise) and the recompile-cause diagnostic names
    axis_rules."""
    from paddle_tpu.core import telemetry

    log = tmp_path / "run.jsonl"
    telemetry.configure(str(log))
    try:
        mesh = create_mesh({"dp": 8})
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8])
            loss = layers.mean(layers.fc(x, 4))
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = pt.Executor(pt.CPUPlace())
        sc = pt.Scope()
        exe.run(startup, scope=sc, use_compiled=False)
        feed = {"x": np.ones((8, 8), np.float32)}
        exe.run(main, feed=feed, fetch_list=[loss], scope=sc, mesh=mesh)
        with axis_rules.axis_rules([("batch", "dp"), ("mlp", "dp")]):
            exe.run(main, feed=feed, fetch_list=[loss], scope=sc, mesh=mesh)
        telemetry.flush_sink()
    finally:
        telemetry.configure(None)
    import json

    causes = [json.loads(ln)["attrs"].get("cause")
              for ln in log.read_text().splitlines()
              if '"compile"' in ln and json.loads(ln).get("kind") == "compile"]
    assert len(causes) == 2
    assert causes[1] == "axis_rules"
