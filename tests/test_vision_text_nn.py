"""vision/text modules + new nn 2.0 layers (LSTM/GRU/MHA/Transformer).

Mirrors the reference's vision/transforms tests, text utils tests, and
nn/layer/rnn + transformer tests."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph, nn
from paddle_tpu.dygraph import to_variable


class TestTransforms:
    def test_compose_pipeline(self):
        from paddle_tpu.vision import transforms

        t = transforms.Compose([transforms.ToTensor(),
                                transforms.Normalize([0.5], [0.5])])
        out = t(np.full((28, 28, 1), 255, np.uint8))
        assert out.shape == (1, 28, 28)
        np.testing.assert_allclose(out, np.ones((1, 28, 28)), atol=1e-6)

    def test_resize_and_crop(self):
        from paddle_tpu.vision import transforms

        img = np.arange(28 * 28, dtype=np.uint8).reshape(28, 28)
        assert transforms.Resize(14)(img).shape == (14, 14)
        rng = np.random.RandomState(0)
        assert transforms.RandomCrop(24, rng=rng)(
            np.zeros((1, 28, 28))).shape == (1, 24, 24)
        flipped = transforms.RandomHorizontalFlip(
            prob=1.0)(np.arange(4).reshape(1, 2, 2))
        np.testing.assert_array_equal(flipped[0], [[1, 0], [3, 2]])
        # HWC: flip the WIDTH axis, never the channel axis
        hwc = np.arange(12).reshape(2, 2, 3)
        fh = transforms.RandomHorizontalFlip(prob=1.0)(hwc)
        np.testing.assert_array_equal(fh, hwc[:, ::-1])
        with pytest.raises(ValueError, match="larger than image"):
            transforms.RandomCrop(32)(np.zeros((28, 28)))

    def test_fake_data_with_loader(self):
        from paddle_tpu.reader import DataLoader
        from paddle_tpu.vision import datasets, transforms

        ds = datasets.FakeData(12, transform=transforms.ToTensor())
        img, label = ds[0]
        assert img.shape == (1, 28, 28) and label.shape == (1,)
        batches = list(DataLoader(ds, batch_size=4, shuffle=False))
        assert len(batches) == 3


class TestTextUtils:
    def test_vocab_roundtrip(self):
        from paddle_tpu.text import Vocab

        v = Vocab.build([["the", "cat"], ["the", "dog"]])
        assert v.to_tokens(v.to_ids(["the", "cat"])) == ["the", "cat"]
        assert v.to_ids(["unseen"]) == [v.unk_id]

    def test_pad_sequences(self):
        from paddle_tpu.text import pad_sequences

        padded, lens = pad_sequences([[1, 2, 3], [4]], maxlen=4, pad_id=9)
        np.testing.assert_array_equal(padded, [[1, 2, 3, 9], [4, 9, 9, 9]])
        assert lens.tolist() == [3, 1]


class TestNewNNLayers:
    def test_lstm_gru_layers_train(self):
        with dygraph.guard():
            rng = np.random.RandomState(0)
            x = to_variable(rng.randn(2, 5, 8).astype(np.float32))
            lstm = nn.LSTM(8, 6)
            out, (h, c) = lstm(x)
            assert out.shape == [2, 5, 6] and h.shape == [2, 6]
            gru = nn.GRU(8, 6)
            gout, gh = gru(x)
            assert gout.shape == [2, 5, 6]
            loss = (out * out).mean() + (gout * gout).mean()
            loss.backward()
            assert any(p.gradient() is not None for p in lstm.parameters())

    def test_transformer_encoder(self):
        with dygraph.guard():
            rng = np.random.RandomState(1)
            x = to_variable(rng.randn(2, 6, 16).astype(np.float32))
            enc = nn.TransformerEncoder(
                lambda: nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0), 2)
            y = enc(x)
            assert y.shape == [2, 6, 16]
            (y * y).mean().backward()
            grads = [p.gradient() for p in enc.parameters()]
            assert sum(g is not None for g in grads) == len(grads)
            # two layers must NOT share parameters
            names = [p.name for p in enc.parameters()]
            assert len(names) == len(set(names))

    def test_lstm_through_to_static(self):
        """nn Layers are dygraph-first; the static path is @to_static
        (static programs use layers.lstm_unit_layer directly)."""
        from paddle_tpu.dygraph import to_static

        class Net(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.rnn = nn.LSTM(8, 6)

            @to_static
            def forward(self, x):
                out, _ = self.rnn(x)
                return out

        with dygraph.guard():
            net = Net()
            x = to_variable(np.ones((2, 5, 8), np.float32))
            y = net(x)
            assert y.shape == [2, 5, 6]

    def test_mha_exports_through_jit_save(self, tmp_path):
        """MultiHeadAttention uses registered ops only, so a to_static
        trace of a transformer block is exportable."""
        from paddle_tpu.dygraph import jit, to_static

        class Net(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.attn = nn.MultiHeadAttention(16, 2, dropout=0.0)

            @to_static
            def forward(self, x):
                return self.attn(x)

        with dygraph.guard():
            net = Net()
            x = np.random.RandomState(0).randn(2, 4, 16).astype(np.float32)
            want = net(dygraph.to_variable(x)).numpy()
            jit.save(net, str(tmp_path / "mha"))
        loaded = jit.load(str(tmp_path / "mha"))
        np.testing.assert_allclose(loaded(x), want, atol=1e-5)


class TestHapiWithVision:
    def test_model_fit_on_fake_mnist(self):
        """hapi Model.fit over a vision dataset + transforms — the
        reference's test_model.py MNIST recipe, end to end."""
        from paddle_tpu.hapi import Model
        from paddle_tpu.metric import Accuracy
        from paddle_tpu.vision import datasets, transforms

        class ConvNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(1, 4, 3, padding=1)
                self.fc = nn.Linear(4 * 14 * 14, 10)
                self.pool = nn.MaxPool2D(2, stride=2)

            def forward(self, x):
                h = self.pool(nn.functional.relu(self.conv(x)))
                b = h.shape[0]
                return self.fc(h.reshape([b, 4 * 14 * 14]))

        ds = datasets.FakeData(
            64, transform=transforms.Compose(
                [transforms.ToTensor(), transforms.Normalize([0.5], [0.5])]))
        with pt.dygraph.guard():
            model = Model(ConvNet())
            model.prepare(pt.optimizer.AdamOptimizer(
                1e-3, parameter_list=model.network.parameters()),
                nn.CrossEntropyLoss(), metrics=Accuracy())
            hist = model.fit(ds, batch_size=16, epochs=2, verbose=0)
            eval_out = model.evaluate(ds, batch_size=16, verbose=0)
        assert np.isfinite(eval_out["eval_loss"])
        assert 0.0 <= eval_out["eval_acc"] <= 1.0
