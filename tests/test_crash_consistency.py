"""Crash consistency end-to-end: kill-during-save subprocess tests and
exact-resume equivalence (RNG + LR + reader cursor) across the elastic,
dataset and hapi training paths.

The decisive property (ISSUE 5 acceptance): a run killed mid-save
restores from the newest VERIFIED checkpoint and reaches final params
bitwise-identical to an uninterrupted run.
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Child for the SIGKILL tests: deterministic 2-layer net, per-step feeds,
# CheckpointManager save every step, final weights dumped at the end.
# PT_CKPT_CRASH_AT (checkpoint.py's kill hook) SIGKILLs it mid-save.
_CHILD = """
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.checkpoint import CheckpointManager

ckpt_dir, out_path, steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
main, startup = pt.Program(), pt.Program()
with pt.program_guard(main, startup):
    x = layers.data("x", [6], stop_gradient=True)
    h = layers.fc(x, 8, act="relu",
                  param_attr=pt.ParamAttr(
                      name="cc_w0", initializer=pt.initializer.Xavier(seed=11)),
                  bias_attr=pt.ParamAttr(name="cc_b0"))
    y = layers.fc(h, 1,
                  param_attr=pt.ParamAttr(
                      name="cc_w1", initializer=pt.initializer.Xavier(seed=12)),
                  bias_attr=False)
    loss = layers.mean(y * y)
    pt.optimizer.AdamOptimizer(0.01).minimize(loss)
exe = pt.Executor(pt.CPUPlace())
scope = pt.Scope()
exe.run(startup, scope=scope, use_compiled=False)
mgr = CheckpointManager(ckpt_dir, max_to_keep=10, async_save=False)
start = mgr.restore_latest(main, scope)
print("RESUMED_AT", start, flush=True)
for step in range(start, steps):
    feed = {"x": np.random.RandomState(100 + step).randn(4, 6)
            .astype(np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    mgr.save(step + 1, main, scope)
np.save(out_path, np.asarray(scope.find_var("cc_w0")))
print("DONE", flush=True)
"""


def _run_child(tmp_path, ckpt_dir, out_path, steps=6, crash_at=None):
    script = tmp_path / "_ckpt_child.py"
    script.write_text(_CHILD)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PT_CKPT_CRASH_AT", None)
    if crash_at:
        env["PT_CKPT_CRASH_AT"] = crash_at
    return subprocess.run(
        [sys.executable, str(script), str(ckpt_dir), str(out_path),
         str(steps)],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True, timeout=180)


class TestKillDuringSave:
    def test_sigkill_mid_save_resumes_bitwise_identical(self, tmp_path):
        """SIGKILL the child in the middle of CheckpointManager.save
        (after the state bytes are staged, before the commit): the
        rerun must skip the torn step, resume from the previous good
        checkpoint, and end with final params bitwise-identical to an
        uninterrupted run."""
        # uninterrupted reference
        ref = _run_child(tmp_path, tmp_path / "clean", tmp_path / "w_ref.npy")
        assert ref.returncode == 0, ref.stdout + ref.stderr
        assert "RESUMED_AT 0" in ref.stdout

        # crashed run: killed mid-save of step 4's checkpoint
        crash = _run_child(tmp_path, tmp_path / "ck", tmp_path / "w_a.npy",
                           crash_at="ckpt.save.commit@4")
        assert crash.returncode == -signal.SIGKILL, \
            crash.stdout + crash.stderr
        assert not (tmp_path / "w_a.npy").exists()
        # the torn step never appeared under a committed name; the
        # staging dir it died in is still lying around
        names = os.listdir(tmp_path / "ck")
        assert "ckpt-%010d" % 4 not in names
        assert any(n.startswith(".tmp-ckpt-") for n in names)

        # rerun the SAME command: restores step 3, finishes, matches
        resume = _run_child(tmp_path, tmp_path / "ck", tmp_path / "w_a.npy")
        assert resume.returncode == 0, resume.stdout + resume.stderr
        assert "RESUMED_AT 3" in resume.stdout
        np.testing.assert_array_equal(np.load(tmp_path / "w_a.npy"),
                                      np.load(tmp_path / "w_ref.npy"))
        # the leftover staging dir was swept into quarantine on restore
        assert not any(n.startswith(".tmp-ckpt-")
                       for n in os.listdir(tmp_path / "ck"))

    @pytest.mark.chaos
    def test_chaos_check_checkpoint_cli(self, tmp_path):
        """Tier-1 smoke of tools/chaos_check.py --checkpoint (satellite:
        CI/tooling): injected commit faults + a kill/restart must still
        converge with an auditable tally."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PT_CKPT_CRASH_AT", None)
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, "tools", "chaos_check.py"),
             "--checkpoint", "--fault-spec",
             "ckpt.save.commit:%3,ckpt.restore.read:@1", "--steps", "8",
             "--telemetry-log", str(tmp_path / "chaos.jsonl")],
            env=env, cwd=REPO_ROOT, capture_output=True, text=True,
            timeout=180)
        assert out.returncode == 0, \
            f"chaos_check --checkpoint failed:\n{out.stdout[-3000:]}\n" \
            f"{out.stderr[-3000:]}"
        assert "CHAOS OK" in out.stdout


def _elastic_net():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8], stop_gradient=True)
        y = layers.fc(x, 1, param_attr=pt.ParamAttr(name="er_w"),
                      bias_attr=False)
        loss = layers.mean(y * y)
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


def _batch_stream(n):
    def gen():
        for i in range(n):
            yield np.random.RandomState(500 + i).randn(4, 8) \
                .astype(np.float32)
    return gen


class TestExactResume:
    def test_elastic_reader_cursor_resumes_exactly(self, tmp_path):
        """A step that fails AFTER consuming its batch must re-read that
        same batch on restart: the runner checkpoints the double-buffer
        reader's cursor and rearms it on restore. Final params must be
        bitwise-identical to an uninterrupted run over the same
        (deterministic, per-step-distinct) stream."""
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.distributed.elastic import ElasticRunner
        from paddle_tpu.distributed.errors import RpcError
        from paddle_tpu.reader import DataLoader

        def fresh():
            ir._main_program, ir._startup_program = (ir.Program(),
                                                     ir.Program())
            unique_name.switch()
            return _elastic_net()

        def train(inject_fail, ckpt):
            main, startup, loss = fresh()
            exe = pt.Executor(pt.CPUPlace())
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            loader = DataLoader.from_generator(capacity=4, return_list=True)
            loader.set_batch_generator(_batch_stream(8))
            runner = ElasticRunner(str(ckpt), main, scope,
                                   save_interval_steps=1, max_restarts=2,
                                   reader=loader, async_save=False)
            it_holder = [iter(loader)]
            failed = [False]

            def step_fn(step):
                batch, = next(it_holder[0])
                if inject_fail and step == 3 and not failed[0]:
                    failed[0] = True
                    # the batch is already consumed: without the cursor
                    # the restarted step would silently train on batch 4
                    raise RpcError("injected transport failure")
                out, = exe.run(main, feed={"x": np.asarray(batch)},
                               fetch_list=[loss], scope=scope)
                return float(np.asarray(out).reshape(-1)[0])

            def on_restart(step, exc):
                it_holder[0] = iter(loader)   # rewound by set_state

            runner.run(step_fn, 6, on_restart=on_restart)
            runner.close()
            return np.asarray(scope.find_var("er_w")).copy(), runner.restarts

        w_fail, restarts = train(True, tmp_path / "a")
        w_ok, _ = train(False, tmp_path / "b")
        assert restarts == 1
        np.testing.assert_array_equal(w_fail, w_ok)

    def test_train_from_dataset_start_step_resumes_exactly(self, tmp_path):
        """The dataset-path reader cursor: checkpoint after N batches,
        reload into a fresh scope, continue with start_step=N — final
        params bitwise-match one uninterrupted pass."""
        import itertools

        from paddle_tpu.core import ir, unique_name

        class StubDataset:
            def __init__(self, n, take=None):
                self.n, self.take = n, take

            def iter_batches(self):
                def gen():
                    for i in range(self.n):
                        yield {"x": np.random.RandomState(700 + i)
                               .randn(4, 8).astype(np.float32)}
                it = gen()
                return itertools.islice(it, self.take) if self.take else it

        def fresh():
            ir._main_program, ir._startup_program = (ir.Program(),
                                                     ir.Program())
            unique_name.switch()
            return _elastic_net()

        # uninterrupted: all 6 batches
        main, startup, loss = fresh()
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        exe.train_from_dataset(main, StubDataset(6), scope=scope)
        w_ref = np.asarray(scope.find_var("er_w")).copy()

        # crashed-at-3: train 3 batches, checkpoint, die
        from paddle_tpu.checkpoint import CheckpointManager

        main, startup, loss = fresh()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        exe.train_from_dataset(main, StubDataset(6, take=3), scope=scope)
        mgr = CheckpointManager(str(tmp_path / "ds"), async_save=False)
        mgr.save(3, main, scope, force=True)
        del scope
        # restart: fresh scope, restore, resume at the stream cursor
        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        resumed = mgr.restore_latest(main, scope2)
        assert resumed == 3
        exe.train_from_dataset(main, StubDataset(6), scope=scope2,
                               start_step=resumed)
        np.testing.assert_array_equal(
            np.asarray(scope2.find_var("er_w")), w_ref)

    def test_lr_schedule_resumes_exactly(self, tmp_path):
        """The persistable @LR_DECAY_COUNTER@ rides the checkpoint: a
        resumed run continues the decay schedule where the crashed run
        left it (a reset counter would re-warm the LR and diverge)."""
        from paddle_tpu.checkpoint import CheckpointManager
        from paddle_tpu.core import ir, unique_name

        def fresh():
            ir._main_program, ir._startup_program = (ir.Program(),
                                                     ir.Program())
            unique_name.switch()
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = layers.data("x", [8], stop_gradient=True)
                y = layers.fc(x, 1, param_attr=pt.ParamAttr(name="lrw"),
                              bias_attr=False)
                loss = layers.mean(y * y)
                lr = layers.exponential_decay(0.2, decay_steps=2,
                                              decay_rate=0.5,
                                              staircase=True)
                pt.optimizer.SGDOptimizer(lr).minimize(loss)
            return main, startup, loss

        def feed(i):
            return {"x": np.random.RandomState(900 + i).randn(4, 8)
                    .astype(np.float32)}

        # uninterrupted 6 steps
        main, startup, loss = fresh()
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        for i in range(6):
            exe.run(main, feed=feed(i), fetch_list=[loss], scope=scope)
        w_ref = np.asarray(scope.find_var("lrw")).copy()

        # 3 steps, checkpoint, die, restore into a fresh scope, resume
        main, startup, loss = fresh()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        for i in range(3):
            exe.run(main, feed=feed(i), fetch_list=[loss], scope=scope)
        ctr = float(np.asarray(
            scope.find_var("@LR_DECAY_COUNTER@")).reshape(-1)[0])
        mgr = CheckpointManager(str(tmp_path / "lr"), async_save=False)
        mgr.save(3, main, scope, force=True)
        del scope
        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        assert mgr.restore_latest(main, scope2) == 3
        np.testing.assert_array_equal(
            np.asarray(scope2.find_var("@LR_DECAY_COUNTER@")).reshape(-1)[0],
            ctr)   # the schedule counter came back
        for i in range(3, 6):
            exe.run(main, feed=feed(i), fetch_list=[loss], scope=scope2)
        np.testing.assert_array_equal(np.asarray(scope2.find_var("lrw")),
                                      w_ref)

    def test_model_fit_resume_from_bitwise(self, tmp_path):
        """Model.fit(resume_from=...): 2 epochs + crash + rerun-to-4
        equals 4 uninterrupted epochs, bitwise — network, optimizer and
        RNG state all ride the verified snapshots."""
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.reader import TensorDataset

        rng = np.random.RandomState(0)
        x = rng.randn(32, 8).astype(np.float32)
        yw = rng.randn(8, 4)
        y = np.argmax(x @ yw, axis=1).astype(np.int64)
        ds = TensorDataset([x, y])

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 16)
                self.fc2 = nn.Linear(16, 4)

            def forward(self, v):
                return self.fc2(nn.functional.relu(self.fc1(v)))

        def make_model():
            with pt.dygraph.guard():
                net = MLP()
                model = Model(net)
                model.prepare(
                    optimizer=pt.optimizer.SGDOptimizer(
                        0.1, parameter_list=net.parameters()),
                    loss=nn.CrossEntropyLoss())
            return model

        def weights(model):
            return {k: np.asarray(v.numpy())
                    for k, v in model.network.state_dict().items()}

        fit_kw = dict(batch_size=8, shuffle=False, verbose=0)

        # uninterrupted 4 epochs (snapshotting along the way)
        m_ref = make_model()
        m_ref.fit(ds, epochs=4, resume_from=str(tmp_path / "ref"), **fit_kw)
        w_ref = weights(m_ref)

        # 2 epochs, "crash" (drop the model), rerun the same fit to 4
        m1 = make_model()
        m1.fit(ds, epochs=2, resume_from=str(tmp_path / "cr"), **fit_kw)
        del m1
        m2 = make_model()
        m2.fit(ds, epochs=4, resume_from=str(tmp_path / "cr"), **fit_kw)
        w2 = weights(m2)
        assert set(w2) == set(w_ref)
        for k in w_ref:
            np.testing.assert_array_equal(w2[k], w_ref[k], err_msg=k)

    def test_model_fit_resume_skips_corrupt_snapshot(self, tmp_path):
        """A torn epoch snapshot must not poison resume: fit falls back
        to the newest snapshot that verifies."""
        import paddle_tpu.nn as nn
        from paddle_tpu.checkpoint import DATA_NAME
        from paddle_tpu.hapi import Model
        from paddle_tpu.reader import TensorDataset

        rng = np.random.RandomState(1)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randint(0, 4, (16,)).astype(np.int64)
        ds = TensorDataset([x, y])

        def make_model():
            with pt.dygraph.guard():
                net = nn.Sequential(nn.Linear(8, 4))
                model = Model(net)
                model.prepare(
                    optimizer=pt.optimizer.SGDOptimizer(
                        0.1, parameter_list=net.parameters()),
                    loss=nn.CrossEntropyLoss())
            return model

        d = str(tmp_path / "fitq")
        m1 = make_model()
        m1.fit(ds, epochs=3, batch_size=8, shuffle=False, verbose=0,
               resume_from=d)
        # corrupt the newest snapshot (epoch 3)
        newest = os.path.join(d, "ckpt-%010d" % 3, DATA_NAME)
        raw = bytearray(open(newest, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(newest, "wb") as f:
            f.write(bytes(raw))
        m2 = make_model()
        from paddle_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(d, async_save=False)
        start = m2._restore_training_state(mgr)
        assert start == 2   # fell back past the torn epoch-3 snapshot
