"""Distributed tracing + live metrics plane tests (ISSUE 6 tier-1 gate).

Contracts under test:
* core/trace.py spans: off-by-default zero-record, root sampling via
  FLAGS_trace_sample_rate, parent/child linkage, inject/extract
  propagation (remote contexts honoured at local rate 0);
* executor.run / run_steps emit feed → dispatch → fetch child spans
  under one trace, and emit NOTHING when tracing is off;
* PS RPC propagation: client call span and server handler span share a
  trace, and a retried+deduped frame (core/faults.py ps.rpc.recv fault)
  keeps its trace id and yields exactly ONE handler span;
* serving end-to-end: one HTTP request traces client → server → queue →
  batch → predictor under a single trace_id, returned in the response
  and pinnable via X-Request-Id;
* tools/trace_view.py merges a two-process log pair into a valid
  chrome://tracing file asserting that linkage (+ CLI smoke incl.
  perf_report on the same logs);
* telemetry rolling-window metrics: windowed() rates/percentiles,
  Prometheus text exposition, start_metrics_server scrape, /metrics on
  the serving server, /v1/stats percentiles + window rates;
* the buffered JSONL sink: line-batching, flush_sink, and
  telemetry.dropped_records on write failure (never raising).
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import faults, telemetry, trace

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    pt.set_flags({"FLAGS_trace_sample_rate": 0.0})
    telemetry.configure(None)
    telemetry.reset()
    faults.configure(None)
    yield
    pt.set_flags({"FLAGS_trace_sample_rate": 0.0,
                  "FLAGS_telemetry_buffer_lines": 64,
                  "FLAGS_telemetry_flush_s": 0.25})
    telemetry.configure(None)
    telemetry.reset()
    faults.configure(None)


def _read(path):
    telemetry.flush_sink()
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _spans(path):
    return [r for r in _read(path) if r["kind"] == "span"]


class TestSpanBasics:
    def test_off_by_default_zero_records(self, tmp_path):
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        with trace.span("root") as c:
            assert c is None
            assert trace.current() is None
            assert trace.inject() is None
        assert _spans(log) == []
        assert telemetry.counter_get("trace.spans") == 0

    def test_sampled_tree_linkage(self, tmp_path):
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        pt.set_flags({"FLAGS_trace_sample_rate": 1.0})
        with trace.span("root") as root:
            assert trace.current() is root
            with trace.span("child") as child:
                assert child.trace_id == root.trace_id
                assert child.span_id != root.span_id
        assert trace.current() is None
        sp = {s["name"]: s for s in _spans(log)}
        assert set(sp) == {"root", "child"}
        assert sp["child"]["attrs"]["parent"] == root.span_id
        assert sp["root"]["attrs"]["parent"] is None
        for s in sp.values():
            assert s["attrs"]["trace"] == root.trace_id
            assert s["value"] >= 0 and s["attrs"]["start"] > 0
            assert s["attrs"]["pid"] == os.getpid()
        assert telemetry.counter_get("trace.spans") == 2

    def test_inject_extract_roundtrip_and_remote_at_rate_zero(
            self, tmp_path):
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        pt.set_flags({"FLAGS_trace_sample_rate": 1.0})
        with trace.span("origin") as origin:
            header = trace.inject()
        ctx = trace.extract(header)
        assert ctx.trace_id == origin.trace_id
        assert ctx.span_id == origin.span_id
        assert trace.extract(None) is None
        assert trace.extract("not a header !") is None
        # the origin sampled; the remote side honours it even at rate 0
        pt.set_flags({"FLAGS_trace_sample_rate": 0.0})
        with trace.span_from(header, "remote.handler") as remote:
            assert remote.trace_id == origin.trace_id
        sp = [s for s in _spans(log) if s["name"] == "remote.handler"]
        assert len(sp) == 1
        assert sp[0]["attrs"]["parent"] == origin.span_id

    def test_root_span_pins_and_sanitizes_external_ids(self):
        pt.set_flags({"FLAGS_trace_sample_rate": 0.0})
        with trace.root_span("req", trace_id="req-42", force=True) as c:
            assert c.trace_id == "req-42"
        with trace.root_span("req", trace_id="weird id\n!", force=True) as c:
            assert len(c.trace_id) == 16 and c.trace_id.isalnum()
        # not forced + rate 0: unsampled
        with trace.root_span("req", trace_id="req-43") as c:
            assert c is None


class TestExecutorSpans:
    def _program(self):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=True)
            loss = layers.mean(layers.fc(x, 8))
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        return main, startup, loss

    def test_run_emits_feed_dispatch_fetch_children(self, scope, tmp_path):
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        main, startup, loss = self._program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        pt.set_flags({"FLAGS_trace_sample_rate": 1.0})
        x = np.ones((4, 4), np.float32)
        exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        sp = _spans(log)
        by_name = {}
        for s in sp:
            by_name.setdefault(s["name"], []).append(s)
        run = by_name["executor.run"][-1]
        for child in ("executor.feed", "executor.dispatch",
                      "executor.fetch"):
            ours = [s for s in by_name[child]
                    if s["attrs"]["trace"] == run["attrs"]["trace"]]
            assert ours, f"missing {child} span"
            assert ours[-1]["attrs"]["parent"] == run["attrs"]["span"]

    def test_run_steps_emits_k_attr(self, scope, tmp_path):
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        main, startup, loss = self._program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feeds = np.stack([np.ones((4, 4), np.float32)] * 3)
        pt.set_flags({"FLAGS_trace_sample_rate": 1.0})
        exe.run_steps(main, feed={"x": feeds}, fetch_list=[loss],
                      scope=scope)
        sp = [s for s in _spans(log) if s["name"] == "executor.run_steps"]
        assert sp and sp[0]["attrs"]["k"] == 3

    def test_disabled_emits_no_span_records(self, scope, tmp_path):
        """Acceptance: default sample rate 0 → zero span records from the
        executor hot path."""
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        main, startup, loss = self._program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.ones((4, 4), np.float32)
        for _ in range(3):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        assert _spans(log) == []
        assert telemetry.counter_get("trace.spans") == 0


@pytest.mark.chaos
class TestRpcTracePropagation:
    def test_client_and_handler_share_one_trace(self, tmp_path):
        from paddle_tpu.distributed.ps.rpc import RPCClient, RPCServer

        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        pt.set_flags({"FLAGS_trace_sample_rate": 1.0})
        srv = RPCServer("127.0.0.1:0", lambda m, n, a, aux: (a, aux))
        try:
            cli = RPCClient(srv.endpoint)
            with trace.span("trainer.step") as root:
                cli.call("echo", "x", np.ones(3, np.float32), 7)
            cli.stop_server()
        finally:
            srv.shutdown()
        sp = _spans(log)
        handler = [s for s in sp if s["name"] == "ps.rpc.handler"]
        call = [s for s in sp if s["name"] == "ps.rpc.call"
                and s["attrs"]["trace"] == root.trace_id]
        assert len(handler) == 1 and len(call) == 1
        assert handler[0]["attrs"]["trace"] == root.trace_id
        assert handler[0]["attrs"]["parent"] == call[0]["attrs"]["span"]
        assert handler[0]["attrs"]["method"] == "echo"

    def test_retried_deduped_frame_one_handler_span(self, tmp_path):
        """ISSUE 6 satellite: under a ps.rpc.recv fault (reply lost AFTER
        the server applied + published) the client retries the same frame
        — the dedup cache replays the reply, the trace id survives, and
        exactly ONE server-side handler span exists for the call."""
        from paddle_tpu.distributed.ps.rpc import RPCClient, RPCServer

        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        pt.set_flags({"FLAGS_trace_sample_rate": 1.0,
                      "FLAGS_ps_rpc_backoff": 0.01})
        applied = []
        srv = RPCServer(
            "127.0.0.1:0",
            lambda m, n, a, aux: (applied.append(m), (a, aux))[1])
        try:
            cli = RPCClient(srv.endpoint)
            faults.configure("ps.rpc.recv:@1", seed=3)
            with trace.span("trainer.step") as root:
                cli.call("send_grad", "g", np.ones(2, np.float32), 1)
            faults.configure(None)
            cli.stop_server()
        finally:
            srv.shutdown()
        assert telemetry.counter_get("ps.rpc_retries") >= 1
        assert telemetry.counter_get("ps.rpc_dedup_hits") == 1
        assert applied.count("send_grad") == 1, \
            "dedup must not re-apply the retried frame"
        sp = _spans(log)
        handler = [s for s in sp if s["name"] == "ps.rpc.handler"
                   and s["attrs"]["trace"] == root.trace_id]
        call = [s for s in sp if s["name"] == "ps.rpc.call"
                and s["attrs"]["trace"] == root.trace_id]
        assert len(call) == 1, "retries stay inside ONE client span"
        assert len(handler) == 1, \
            "a retried+deduped frame must yield exactly one handler span"
        assert handler[0]["attrs"]["parent"] == call[0]["attrs"]["span"]


IN_DIM, OUT_DIM = 6, 4


def _save_mlp(tmp_path, name="m"):
    from paddle_tpu import io

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [IN_DIM])
        y = layers.fc(layers.fc(x, 8, act="relu"), OUT_DIM)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    model_dir = str(tmp_path / name)
    io.save_inference_model(model_dir, ["x"], [y],
                            main_program=main, scope=scope)
    return model_dir


def _engine(model_dir, **cfg):
    from paddle_tpu.inference import AnalysisConfig, create_predictor
    from paddle_tpu.serving import ServingConfig, ServingEngine

    cfg.setdefault("max_batch_size", 8)
    cfg.setdefault("batch_timeout_ms", 5.0)
    return ServingEngine(create_predictor(AnalysisConfig(model_dir)),
                         config=ServingConfig(**cfg))


@pytest.mark.serving
class TestServingTrace:
    def test_http_request_traced_end_to_end(self, tmp_path):
        """Acceptance: one serving HTTP request is traceable end-to-end —
        request → queue-wait → batch-assembly → predictor-run share a
        single trace_id, pinned by X-Request-Id and echoed back."""
        from paddle_tpu.serving.server import ServingHTTPServer

        log = tmp_path / "serving.jsonl"
        telemetry.configure(str(log))
        engine = _engine(_save_mlp(tmp_path)).start(warmup=True)
        srv = ServingHTTPServer(engine).start()
        try:
            body = json.dumps(
                {"inputs": {"x": np.zeros((2, IN_DIM)).tolist()}}).encode()
            req = urllib.request.Request(
                srv.url + "/v1/infer", data=body,
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "it-req-7"})
            resp = urllib.request.urlopen(req, timeout=30)
            doc = json.loads(resp.read())
            assert doc["trace_id"] == "it-req-7"
            assert resp.headers["X-Trace-Id"] == "it-req-7"
            assert "outputs" in doc
        finally:
            srv.shutdown()
            engine.close(drain=True, timeout=10)
        names = {s["name"] for s in _spans(log)
                 if s["attrs"]["trace"] == "it-req-7"}
        for want in ("serving.http_request", "serving.queue_wait",
                     "serving.batch_assemble", "serving.predictor_run"):
            assert want in names, f"missing {want} in {names}"

    def test_untraced_request_emits_nothing(self, tmp_path):
        from paddle_tpu.serving.server import ServingHTTPServer

        log = tmp_path / "serving.jsonl"
        telemetry.configure(str(log))
        engine = _engine(_save_mlp(tmp_path)).start(warmup=True)
        srv = ServingHTTPServer(engine).start()
        try:
            body = json.dumps(
                {"inputs": {"x": np.zeros((1, IN_DIM)).tolist()}}).encode()
            req = urllib.request.Request(
                srv.url + "/v1/infer", data=body,
                headers={"Content-Type": "application/json"})
            doc = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert doc["trace_id"] is None
        finally:
            srv.shutdown()
            engine.close(drain=True, timeout=10)
        assert _spans(log) == []

    def test_stats_percentiles_and_window(self, tmp_path):
        """ISSUE 6 satellite: /v1/stats carries request_ms/batch_ms
        percentiles and rolling-window rates, not just counters."""
        from paddle_tpu.serving import LocalClient
        from paddle_tpu.serving.server import ServingHTTPServer

        engine = _engine(_save_mlp(tmp_path)).start(warmup=True)
        srv = ServingHTTPServer(engine).start()
        try:
            client = LocalClient(engine)
            for _ in range(4):
                client.infer({"x": np.zeros((1, IN_DIM), np.float32)},
                             timeout=30)
            stats = json.loads(urllib.request.urlopen(
                srv.url + "/v1/stats", timeout=10).read())
            assert stats["requests"] >= 4
            for key in ("request_ms", "batch_ms"):
                assert {"p50", "p95", "p99"} <= set(stats[key]), stats
            assert stats["window"]["request_rate"] > 0
            assert stats["window"]["request_ms"]["p99"] >= \
                stats["window"]["request_ms"]["p50"]
        finally:
            srv.shutdown()
            engine.close(drain=True, timeout=10)

    def test_metrics_endpoint_rolling_window(self, tmp_path):
        """Acceptance: GET /metrics returns rolling-window p99 request
        latency and request rate in Prometheus text format."""
        from paddle_tpu.serving import LocalClient
        from paddle_tpu.serving.server import ServingHTTPServer

        engine = _engine(_save_mlp(tmp_path)).start(warmup=True)
        srv = ServingHTTPServer(engine).start()
        try:
            client = LocalClient(engine)
            for _ in range(4):
                client.infer({"x": np.zeros((1, IN_DIM), np.float32)},
                             timeout=30)
            body = urllib.request.urlopen(
                srv.url + "/metrics", timeout=10).read().decode()
        finally:
            srv.shutdown()
            engine.close(drain=True, timeout=10)
        assert "pt_serving_requests_total" in body
        assert 'pt_serving_request_ms{quantile="0.99"}' in body
        import re

        assert re.search(r'^pt_serving_requests_rate\{window="\d+s"\} ',
                         body, re.M)


class TestTraceView:
    def _two_process_pair(self, tmp_path):
        """A trainer log (root + client span) and a pserver log (handler
        span continuing the propagated context) — the merge fixture."""
        a = str(tmp_path / "trainer.jsonl")
        b = str(tmp_path / "pserver.jsonl")
        pt.set_flags({"FLAGS_trace_sample_rate": 1.0})
        telemetry.configure(a)
        with trace.span("trainer.step"):
            with trace.span("ps.rpc.call", method="send_grad") as c:
                header = trace.inject()
                time.sleep(0.002)
        telemetry.flush_sink()
        telemetry.configure(b)
        with trace.span_from(header, "ps.rpc.handler", method="send_grad"):
            time.sleep(0.001)
        telemetry.flush_sink()
        telemetry.configure(None)
        return a, b, c

    def test_merge_two_process_pair_asserts_linkage(self, tmp_path):
        """Acceptance: trace_view merges a two-process JSONL log pair
        into a valid chrome://tracing file with the cross-process
        parent/child linkage intact."""
        sys.path.insert(0, REPO_ROOT)
        try:
            from tools.trace_view import build_trees, chrome_trace, \
                load_spans
        finally:
            sys.path.remove(REPO_ROOT)
        a, b, call_ctx = self._two_process_pair(tmp_path)
        spans, malformed, total = load_spans([a, b])
        assert malformed == 0 and len(spans) == 3
        doc = chrome_trace(spans, [a, b])
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(events) == 3
        assert {e["pid"] for e in events} == {0, 1}   # one row per log
        assert len({e["args"]["trace"] for e in events}) == 1
        handler = [e for e in events if e["name"] == "ps.rpc.handler"][0]
        assert handler["args"]["parent"] == call_ctx.span_id
        json.dumps(doc)   # chrome-loadable: valid JSON
        trees = build_trees(spans)
        (roots, children, _), = trees.values()
        assert [r["name"] for r in roots] == ["trainer.step"]

    def test_cli_end_to_end_smoke(self, tmp_path):
        """ISSUE 6 satellite: trace_view.py + perf_report.py run
        end-to-end (incl. --help) on a generated two-process log pair —
        stdlib-only subprocesses, no jax import."""
        a, b, _ = self._two_process_pair(tmp_path)
        # torn final line (SIGKILLed writer): both tools must tolerate it
        with open(b, "a") as f:
            f.write('{"ts": 1, "kind": "coun')
        out = str(tmp_path / "merged.json")
        r = subprocess.run(
            [sys.executable, os.path.join("tools", "trace_view.py"),
             a, b, "--out", out],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        assert "2 log(s)" in r.stdout and "critical path" in r.stdout
        assert "skipped 1 malformed" in r.stderr
        doc = json.load(open(out))
        assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 3
        r2 = subprocess.run(
            [sys.executable, os.path.join("tools", "perf_report.py"),
             b, "--json"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
        assert r2.returncode == 0, r2.stderr
        s = json.loads(r2.stdout)
        assert s["malformed_lines"] == 1
        assert s["tracing"]["spans"] == 1
        for tool in ("trace_view.py", "perf_report.py"):
            h = subprocess.run(
                [sys.executable, os.path.join("tools", tool), "--help"],
                cwd=REPO_ROOT, capture_output=True, timeout=60)
            assert h.returncode == 0

    def test_missing_trace_exits_2(self, tmp_path):
        a, b, _ = self._two_process_pair(tmp_path)
        r = subprocess.run(
            [sys.executable, os.path.join("tools", "trace_view.py"),
             a, "--trace", "no-such-trace"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
        assert r.returncode == 2


class TestWindowedMetrics:
    def test_rates_and_percentiles(self):
        for _ in range(6):
            telemetry.counter_add("w.hits", 2)
        for v in (1.0, 2.0, 3.0, 4.0, 100.0):
            telemetry.observe("w.ms", v, kind="timer")
        win = telemetry.windowed(30)
        assert win["window_s"] == 30.0
        c = win["counters"]["w.hits"]
        assert c["delta"] == 12 and c["rate"] == pytest.approx(0.4)
        h = win["hists"]["w.ms"]
        assert h["count"] == 5 and h["p99"] == 100.0
        assert h["p50"] == 3.0
        assert h["rate"] == pytest.approx(5 / 30, rel=1e-4)

    def test_old_samples_age_out(self):
        telemetry.counter_add("w.old", 5)
        telemetry.observe("w.oldms", 9.0)
        reg = telemetry.TelemetryRegistry.instance()
        with reg._lock:   # age the entries past any window
            for dq in reg._win_counts.values():
                for entry in dq:
                    entry[0] -= 10_000
            reg._win_samples["w.oldms"] = type(
                reg._win_samples["w.oldms"])(
                [(ts - 10_000, v)
                 for ts, v in reg._win_samples["w.oldms"]],
                maxlen=reg._win_samples["w.oldms"].maxlen)
        win = telemetry.windowed(60)
        assert "w.old" not in win["counters"]
        assert "w.oldms" not in win["hists"]
        # cumulative registry still remembers
        assert telemetry.counter_get("w.old") == 5

    def test_prometheus_text_format(self):
        telemetry.counter_add("p.reqs", 3)
        telemetry.gauge_set("p.depth", 7)
        telemetry.observe("p.ms", 12.5, kind="timer")
        txt = telemetry.prometheus_text()
        assert "# TYPE pt_p_reqs_total counter" in txt
        assert "pt_p_reqs_total 3" in txt
        assert "pt_p_depth 7" in txt
        assert 'pt_p_ms{quantile="0.5"} 12.5' in txt
        assert "pt_p_ms_count 1" in txt
        assert "pt_p_reqs_rate" in txt

    def test_standalone_metrics_server(self):
        telemetry.counter_add("m.probe", 11)
        srv = telemetry.start_metrics_server()
        try:
            body = urllib.request.urlopen(
                srv.url + "/metrics", timeout=10).read().decode()
            assert "pt_m_probe_total 11" in body
            hz = json.loads(urllib.request.urlopen(
                srv.url + "/healthz", timeout=10).read())
            assert hz["status"] == "ok"
            varz = json.loads(urllib.request.urlopen(
                srv.url + "/varz", timeout=10).read())
            assert varz["snapshot"]["counters"]["m.probe"] == 11
        finally:
            srv.shutdown()


class TestBufferedSink:
    def test_line_batching_and_flush_sink(self, tmp_path):
        log = tmp_path / "run.jsonl"
        pt.set_flags({"FLAGS_telemetry_buffer_lines": 1000,
                      "FLAGS_telemetry_flush_s": 3600.0})
        telemetry.configure(str(log))
        for i in range(10):
            telemetry.counter_add("b.x", 1)
        on_disk = [l for l in open(log)] if log.exists() else []
        assert len(on_disk) < 10, "writes must be buffered"
        telemetry.flush_sink()
        assert len([l for l in open(log) if l.strip()]) == 10

    def test_path_change_flushes(self, tmp_path):
        log = tmp_path / "run.jsonl"
        pt.set_flags({"FLAGS_telemetry_buffer_lines": 1000,
                      "FLAGS_telemetry_flush_s": 3600.0})
        telemetry.configure(str(log))
        telemetry.counter_add("b.y", 1)
        telemetry.configure(None)   # close → flush
        recs = [json.loads(l) for l in open(log) if l.strip()]
        assert [r["name"] for r in recs] == ["b.y"]

    def test_write_failure_counts_dropped_never_raises(self, tmp_path):
        log = tmp_path / "run.jsonl"
        pt.set_flags({"FLAGS_telemetry_buffer_lines": 1})
        telemetry.configure(str(log))
        telemetry.counter_add("d.ok", 1)

        class _Broken:
            def write(self, *_):
                raise OSError("disk full")

            def flush(self):
                raise OSError("disk full")

            def close(self):
                pass

        reg = telemetry.TelemetryRegistry.instance()
        with reg._lock:
            reg._file.close()
            reg._file = _Broken()
        # must NOT raise into the instrumented thread
        telemetry.counter_add("d.lost", 1)
        telemetry.counter_add("d.lost", 1)
        assert telemetry.counter_get("telemetry.dropped_records") >= 2
        assert telemetry.counter_get("d.lost") == 2   # in-memory intact
        telemetry.configure(None)
