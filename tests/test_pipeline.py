"""Pipeline parallelism tests — device_guard stages + PipelineOptimizer.

Parity contract mirrors the reference's pipeline tests
(test_pipeline.py / section_worker): the pipelined run must match the
plain single-device run (mean-based loss + equal microbatches make GPipe
gradient accumulation exact)."""

import numpy as np
import pytest


def _build(pipeline: bool, steps=3, B=8, M=4, schedule="gpipe"):
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import ir, unique_name
    from paddle_tpu.parallel import create_mesh

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.device_guard("stage:0" if pipeline else None):
            img = layers.data("img", [32], stop_gradient=True)
            label = layers.data("label", [1], dtype="int64",
                                stop_gradient=True)
            h = layers.fc(img, 64, act="relu",
                          param_attr=pt.ParamAttr(name="w0"),
                          bias_attr=pt.ParamAttr(name="b0"))
        with pt.device_guard("stage:1" if pipeline else None):
            logits = layers.fc(h, 10, param_attr=pt.ParamAttr(name="w1"),
                               bias_attr=pt.ParamAttr(name="b1"))
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
        opt = pt.optimizer.SGDOptimizer(0.5)
        if pipeline:
            opt = pt.optimizer.PipelineOptimizer(opt, num_microbatches=M,
                                                 schedule=schedule)
        opt.minimize(loss)

    mesh = create_mesh({"pp": 2}) if pipeline else None
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    rng = np.random.RandomState(0)
    x = rng.randn(B, 32).astype(np.float32)
    y = rng.randint(0, 10, (B, 1)).astype(np.int64)
    losses = []
    for _ in range(steps):
        out = exe.run(main, feed={"img": x, "label": y},
                      fetch_list=[loss], scope=scope, mesh=mesh)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return losses


class TestPipeline:
    def test_two_stage_matches_dense(self):
        dense = _build(pipeline=False)
        piped = _build(pipeline=True)
        np.testing.assert_allclose(piped, dense, rtol=2e-4)
        assert piped[-1] < piped[0]

    def test_1f1b_matches_dense(self):
        """The hand-scheduled 1F1B (per-stage vjp + recompute, O(stages)
        activation memory) must train identically to the dense run —
        reference parity bar: section_worker 1F1B vs plain executor."""
        dense = _build(pipeline=False)
        piped = _build(pipeline=True, schedule="1f1b")
        np.testing.assert_allclose(piped, dense, rtol=2e-4)
        assert piped[-1] < piped[0]

    def test_1f1b_matches_gpipe(self):
        """Both schedules compute the same math — losses must agree to
        numerical noise across steps."""
        gpipe = _build(pipeline=True, schedule="gpipe")
        f1b = _build(pipeline=True, schedule="1f1b")
        np.testing.assert_allclose(f1b, gpipe, rtol=2e-4)

    def test_1f1b_single_rank_mode(self):
        """The sequential (no 'pp' mesh) fallback of the 1f1b op lowering:
        its loss and grads must match jax.grad of the dense computation."""
        import jax
        import jax.numpy as jnp
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, registry, unique_name

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            with pt.device_guard("stage:0"):
                x = layers.data("x", [16], stop_gradient=True)
                h = layers.fc(x, 32, act="relu",
                              param_attr=pt.ParamAttr(name="w0"),
                              bias_attr=False)
            with pt.device_guard("stage:1"):
                y = layers.fc(h, 1, param_attr=pt.ParamAttr(name="w1"),
                              bias_attr=False)
                loss = layers.mean(y * y)
            opt = pt.optimizer.PipelineOptimizer(
                pt.optimizer.SGDOptimizer(0.1), num_microbatches=2,
                schedule="1f1b")
            opt.minimize(loss)

        op = main.global_block().ops[0]
        assert op.type == "pipeline_1f1b"
        rng = np.random.RandomState(1)
        vals = {"w0": jnp.asarray(rng.randn(16, 32).astype(np.float32)),
                "w1": jnp.asarray(rng.randn(32, 1).astype(np.float32)),
                "x": jnp.asarray(rng.randn(8, 16).astype(np.float32))}
        ins = {"X": [vals[nm] for nm in op.attrs["input_names"]["X"]]}
        out = registry.lookup("pipeline_1f1b").forward(ins, dict(op.attrs))
        m = op.attrs["num_microbatches"]

        def dense(w0, w1):
            hh = jax.nn.relu(vals["x"] @ w0)
            yy = hh @ w1
            return jnp.mean(yy * yy)

        ref_loss = dense(vals["w0"], vals["w1"])
        ref_grads = jax.grad(dense, argnums=(0, 1))(vals["w0"], vals["w1"])
        np.testing.assert_allclose(
            float(out["LossPartial"]) / m, float(ref_loss), rtol=1e-5)
        got = dict(zip(op.attrs["param_names"], out["ParamGrads"]))
        np.testing.assert_allclose(got["w0"], ref_grads[0], rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_allclose(got["w1"], ref_grads[1], rtol=1e-4,
                                   atol=1e-6)

    def test_skip_connection_rejected(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            with pt.device_guard("stage:0"):
                img = layers.data("img", [8], stop_gradient=True)
                h0 = layers.fc(img, 8)
            with pt.device_guard("stage:1"):
                h1 = layers.fc(h0, 8)
            with pt.device_guard("stage:2"):
                # reads h0 (stage 0) at stage 2 — a skip connection
                out = layers.elementwise_add(h1, h0)
                loss = layers.mean(out)
            with pytest.raises(ValueError, match="skip"):
                pt.optimizer.PipelineOptimizer(
                    pt.optimizer.SGDOptimizer(0.1),
                    num_microbatches=2).minimize(loss)


class TestPipelineBert:
    def test_bert_pipeline_matches_dense(self):
        """2-stage pipelined BERT (pp mesh) vs dense single-device, 3 steps.
        NSP mean + globally-mean'd losses make GPipe accumulation... NSP's
        per-microbatch mean over B/M examples averages exactly; the MLM
        num/denom ratio does NOT decompose across microbatches, so compare
        with mask_weight all-ones (denominator constant per microbatch)."""
        import paddle_tpu as pt
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.models import bert
        from paddle_tpu.parallel import create_mesh

        B, S, steps, M = 8, 32, 3, 4
        cfg_kw = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=64,
                      max_position_embeddings=32, hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)
        results = {}
        for mode in ("dense", "pp"):
            ir._main_program, ir._startup_program = ir.Program(), ir.Program()
            unique_name.switch()
            cfg = bert.BertConfig(**cfg_kw)
            pp = 2 if mode == "pp" else 0
            main, startup, feeds, fetches = bert.build_pretraining_program(
                cfg, seq_len=S, optimizer_name="adamw", with_nsp=False,
                pipeline_stages=pp, num_microbatches=M if pp else 1)
            mesh = create_mesh({"pp": 2}) if pp else None
            exe = pt.Executor()
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            batch = bert.synthetic_pretraining_batch(cfg, B, S)
            batch["mask_weight"] = np.ones_like(batch["mask_weight"])
            losses = []
            for _ in range(steps):
                out = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                              scope=scope, mesh=mesh)
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            results[mode] = losses
        np.testing.assert_allclose(results["pp"], results["dense"], rtol=2e-4)

    def test_bert_dp_sp_pp_composed_matches_dense(self):
        """VERDICT r1 item 5: ONE training step composing dp x sp x pp.
        Ring attention shards the sequence inside every pipeline stage
        (collective-uniform branches), the MLM num/denom psums run as
        post ops outside the schedule, and grads sum over all three axes.
        The composed loss is the exact global masked-token mean, so it
        must match the dense single-device run step for step."""
        import paddle_tpu as pt
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.models import bert
        from paddle_tpu.parallel import create_mesh

        B, S, steps, M, K = 8, 32, 3, 2, 4
        cfg_kw = dict(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=64,
                      max_position_embeddings=32, hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)
        results = {}
        for mode in ("dense", "composed"):
            ir._main_program, ir._startup_program = ir.Program(), ir.Program()
            unique_name.switch()
            cfg = bert.BertConfig(**cfg_kw)
            kw = dict(seq_len=S, optimizer_name="adamw", with_nsp=False,
                      max_predictions_per_seq=K)
            if mode == "composed":
                kw.update(sequence_parallel=2, data_parallel=2,
                          pipeline_stages=2, num_microbatches=M)
            main, startup, feeds, fetches = bert.build_pretraining_program(
                cfg, **kw)
            mesh = (create_mesh({"dp": 2, "sp": 2, "pp": 2})
                    if mode == "composed" else None)
            exe = pt.Executor()
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            batch = bert.synthetic_pretraining_batch(
                cfg, B, S, max_predictions_per_seq=K)
            losses = []
            for _ in range(steps):
                out = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                              scope=scope, mesh=mesh)
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            results[mode] = losses
        np.testing.assert_allclose(results["composed"], results["dense"],
                                   rtol=3e-4)
        assert results["composed"][-1] < results["composed"][0]
