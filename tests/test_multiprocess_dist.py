"""Multi-process distributed test — real subprocesses, no mocks (the
reference's test_dist_base.py methodology: launch workers, compare).

Two processes × 4 CPU devices each form one 8-device global mesh via
jax.distributed (the reference's NCCL2 trainer rendezvous, here the
coordination service); each psums its shard and checks the global sum.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
# reference-style env (distributed/parallel.py init_parallel_env)
os.environ["PADDLE_COORDINATOR_ADDR"] = "127.0.0.1:%PORT%"
from paddle_tpu.distributed.parallel import (get_rank, get_world_size,
                                             init_parallel_env)
assert init_parallel_env()
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
assert get_world_size() == 2
devs = np.array(jax.devices()).reshape(8)
mesh = Mesh(devs, ("dp",))
x = jax.numpy.arange(8.0)
xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
total = jax.jit(lambda v: v.sum(), out_shardings=NamedSharding(mesh, P()))(xs)
assert float(total) == 28.0, float(total)
print("RANK", get_rank(), "OK")
"""


@pytest.mark.skipif(os.environ.get("PT_SKIP_MULTIPROC") == "1",
                    reason="multiproc disabled")
def test_two_process_mesh(tmp_path):
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PYTEST_CURRENT_TEST", None)
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = "2"
        script = _WORKER.replace("%PORT%", str(port))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"RANK {rank} OK" in out
