"""Multi-process distributed test — real subprocesses, no mocks (the
reference's test_dist_base.py methodology: launch workers, compare).

Two processes × 4 CPU devices each form one 8-device global mesh via
jax.distributed (the reference's NCCL2 trainer rendezvous, here the
coordination service); each psums its shard and checks the global sum.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
# reference-style env (distributed/parallel.py init_parallel_env)
os.environ["PADDLE_COORDINATOR_ADDR"] = "127.0.0.1:%PORT%"
from paddle_tpu.distributed.parallel import (get_rank, get_world_size,
                                             init_parallel_env)
assert init_parallel_env()
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
assert get_world_size() == 2
devs = np.array(jax.devices()).reshape(8)
mesh = Mesh(devs, ("dp",))
x = jax.numpy.arange(8.0)
xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
total = jax.jit(lambda v: v.sum(), out_shardings=NamedSharding(mesh, P()))(xs)
assert float(total) == 28.0, float(total)
print("RANK", get_rank(), "OK")
"""


@pytest.mark.skipif(os.environ.get("PT_SKIP_MULTIPROC") == "1",
                    reason="multiproc disabled")
def test_two_process_mesh(tmp_path):
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PYTEST_CURRENT_TEST", None)
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = "2"
        script = _WORKER.replace("%PORT%", str(port))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"RANK {rank} OK" in out


_DP_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
os.environ["PADDLE_COORDINATOR_ADDR"] = "127.0.0.1:%PORT%"
from paddle_tpu.distributed.parallel import (get_rank, get_world_size,
                                             init_parallel_env)
init_parallel_env()   # returns False in the 1-process control run
import numpy as np
from paddle_tpu import dygraph, nn
from paddle_tpu.dygraph import DataParallel, to_variable
from paddle_tpu.optimizer import SGDOptimizer
from paddle_tpu.initializer import Xavier, Constant
from paddle_tpu import ParamAttr
import paddle_tpu.nn.functional as F

rank, world = get_rank(), get_world_size()
rng = np.random.RandomState(0)
X = rng.rand(16, 8).astype(np.float32)
Y = rng.randint(0, 4, (16, 1)).astype(np.int64)
half = 16 // world
xs = X[rank * half:(rank + 1) * half]
ys = Y[rank * half:(rank + 1) * half]

with dygraph.guard():
    m = nn.Linear(8, 4,
                  weight_attr=ParamAttr(initializer=Xavier(seed=11)),
                  bias_attr=ParamAttr(initializer=Constant(0.0)))
    dp = DataParallel(m)
    opt = SGDOptimizer(0.5, parameter_list=m.parameters())
    for step in range(3):
        loss = F.cross_entropy(dp(to_variable(xs)), to_variable(ys))
        loss = dp.scale_loss(loss)
        loss.backward()
        dp.apply_collective_grads()
        opt.minimize(loss)
        m.clear_gradients()
    w = m.parameters()[0].numpy()
    print("WSUM", rank, float(np.abs(w).sum()))
print("RANK", rank, "OK")
"""


@pytest.mark.skipif(os.environ.get("PT_SKIP_MULTIPROC") == "1",
                    reason="multiproc disabled")
def test_dygraph_data_parallel_matches_single_process(tmp_path):
    """reference: parallel_dygraph_mnist.py via TestParallelDyGraphRunnerBase
    — 2-process DataParallel must land on the same weights as the
    single-process full-batch run (scale_loss + summed grads == full mean)."""
    import re
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PYTEST_CURRENT_TEST", None)
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = "2"
        script = _DP_WORKER.replace("%PORT%", str(port))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"RANK {rank} OK" in out
    wsums = [float(re.search(r"WSUM \d ([\d.eE+-]+)", o).group(1))
             for o in outs]
    # both ranks hold identical weights after collective training
    assert abs(wsums[0] - wsums[1]) < 1e-5

    # single-process full-batch run for parity
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    env["PADDLE_TRAINER_ID"] = "0"
    env["PADDLE_TRAINERS_NUM"] = "1"
    sock = socket.socket(); sock.bind(("127.0.0.1", 0))
    port1 = sock.getsockname()[1]; sock.close()
    script = _DP_WORKER.replace("%PORT%", str(port1))
    single = subprocess.run([sys.executable, "-c", script], env=env,
                            capture_output=True, text=True, timeout=240,
                            cwd=os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
    assert single.returncode == 0, single.stdout[-2000:]
    wsum1 = float(re.search(r"WSUM \d ([\d.eE+-]+)",
                            single.stdout).group(1))
    assert abs(wsums[0] - wsum1) < 1e-4, (wsums, wsum1)
