"""Profiler / flags / monitor tests (reference: test_profiler.py,
test_global_var_getter_setter.py, monitor.h stats)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, profiler


def _small_program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], stop_gradient=True)
        y = layers.fc(x, 8, act="relu")
        loss = layers.mean(y)
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


class TestProfiler:
    def test_records_ops_and_steps(self, scope, tmp_path):
        main, startup, loss = _small_program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.ones((2, 4), np.float32)
        trace_path = str(tmp_path / "trace.json")
        with profiler.profiler(profile_path=trace_path):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope,
                    use_compiled=False)         # per-op spans
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        summary = profiler.summarize()
        assert any(n in summary for n in ("mul", "matmul_v2", "fc"))
        assert "executor::run" in summary
        with open(trace_path) as f:
            trace = json.load(f)
        assert len(trace["traceEvents"]) == len(profiler.events())
        assert all("dur" in e for e in trace["traceEvents"])

    def test_disabled_records_nothing(self, scope):
        profiler.reset_profiler()
        main, startup, loss = _small_program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss], scope=scope)
        assert profiler.events() == []


class TestFlags:
    def test_get_set_roundtrip(self):
        assert pt.get_flags("FLAGS_check_nan_inf") == \
            {"FLAGS_check_nan_inf": False}
        pt.set_flags({"FLAGS_check_nan_inf": True})
        try:
            assert pt.get_flags("check_nan_inf")["check_nan_inf"] is True
        finally:
            pt.set_flags({"FLAGS_check_nan_inf": False})

    def test_unknown_flag_raises(self):
        with pytest.raises(ValueError, match="unknown flag"):
            pt.get_flags("FLAGS_no_such_flag")

    def test_check_nan_inf_catches(self, scope):
        from paddle_tpu.core.executor import ExecutionError

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [2], stop_gradient=True)
            y = layers.log(x)       # log(-1) -> NaN
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        pt.set_flags({"FLAGS_check_nan_inf": True})
        try:
            with pytest.raises(ExecutionError, match="NaN/Inf"):
                exe.run(main, feed={"x": -np.ones((1, 2), np.float32)},
                        fetch_list=[y], scope=scope)
        finally:
            pt.set_flags({"FLAGS_check_nan_inf": False})


class TestExclusiveTimes:
    """profiler._exclusive_times nesting math (was only exercised
    implicitly through device_profile)."""

    @staticmethod
    def _ev(ts, dur, pid=1, tid=1, name="e"):
        return {"ts": ts, "dur": dur, "pid": pid, "tid": tid, "name": name}

    def test_proper_containment_chain(self):
        from paddle_tpu.profiler import _exclusive_times

        parent = self._ev(0, 100, name="parent")
        child = self._ev(10, 20, name="child")
        grand = self._ev(12, 5, name="grand")
        excl = _exclusive_times([parent, child, grand])
        assert excl[id(parent)] == 80      # 100 - child's 20
        assert excl[id(child)] == 15       # 20 - grand's 5
        assert id(grand) not in excl       # leaf: inclusive == exclusive

    def test_sibling_children(self):
        from paddle_tpu.profiler import _exclusive_times

        parent = self._ev(0, 100, name="parent")
        c1 = self._ev(10, 20, name="c1")
        c2 = self._ev(50, 30, name="c2")
        excl = _exclusive_times([parent, c1, c2])
        assert excl[id(parent)] == 50      # 100 - 20 - 30

    def test_partial_overlap_not_subtracted(self):
        from paddle_tpu.profiler import _exclusive_times

        # b starts inside a but ends after it — NOT properly contained, so
        # nothing is subtracted (malformed traces degrade to inclusive)
        a = self._ev(0, 50, name="a")
        b = self._ev(40, 30, name="b")
        excl = _exclusive_times([a, b])
        assert id(a) not in excl
        assert id(b) not in excl

    def test_multi_pid_tid_timelines_independent(self):
        from paddle_tpu.profiler import _exclusive_times

        # identical time windows on two devices: each (pid, tid) timeline
        # nests independently — no cross-device subtraction
        p1_parent = self._ev(0, 100, pid=1, name="p1")
        p1_child = self._ev(10, 20, pid=1, name="c1")
        p2_span = self._ev(10, 20, pid=2, name="p2")
        t2_span = self._ev(5, 90, pid=1, tid=2, name="t2")
        excl = _exclusive_times([p1_parent, p1_child, p2_span, t2_span])
        assert excl[id(p1_parent)] == 80
        assert id(p2_span) not in excl
        assert id(t2_span) not in excl

    def test_events_without_dur_ignored(self):
        from paddle_tpu.profiler import _exclusive_times

        meta = {"ts": 0, "pid": 1, "tid": 1, "name": "meta"}
        span = self._ev(0, 10)
        assert _exclusive_times([meta, span]) == {}


def test_chrome_tracing_roundtrip(tmp_path, capsys):
    """export_chrome_tracing must round-trip every recorded span with its
    name/ts/dur into chrome://tracing's event format."""
    profiler.start_profiler()
    with profiler.RecordEvent("alpha"):
        with profiler.RecordEvent("beta"):
            pass
    live = profiler.events()
    path = str(tmp_path / "trace.json")
    profiler.stop_profiler(profile_path=path)
    capsys.readouterr()
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert [e["name"] for e in evs] == [e["name"] for e in live]
    for got, src in zip(evs, live):
        assert got["ph"] == "X"
        assert got["ts"] == src["ts"] and got["dur"] == src["dur"]
        assert got["tid"] == src["tid"]


class TestMonitor:
    def test_stat_add(self):
        from paddle_tpu.core.monitor import StatRegistry, stat_add, stat_get

        stat_add("test_stat", 5)
        stat_add("test_stat", 7)
        assert stat_get("test_stat") == 12
        assert StatRegistry.instance().stats()["test_stat"] == 12


def test_device_profile_attributes_to_source():
    """profiler.device_profile (reference: per-op device tables +
    tools/timeline.py; device side via the jax profiler instead of
    CUPTI) must attribute exclusive device time to op-lowering source
    lines. Runs in a subprocess with JAX_PLATFORMS set BEFORE the
    interpreter starts: with the axon PJRT plugin registered and the
    platform switched post-import (this suite's conftest), the XLA
    device tracer never hooks the CPU backend and the trace carries
    only python host events."""
    import os
    import subprocess
    import sys

    child = r'''
import numpy as np
import paddle_tpu as pt
from paddle_tpu import layers, profiler

main, startup = pt.Program(), pt.Program()
with pt.program_guard(main, startup):
    x = layers.data("x", [512])
    h = layers.fc(x, 512, act="relu")
    out = layers.reduce_mean(layers.fc(h, 512))
exe = pt.Executor(pt.CPUPlace())
scope = pt.Scope()
exe.run(startup, scope=scope, use_compiled=False)
feed = {"x": np.random.RandomState(0).randn(256, 512).astype(np.float32)}
exe.run(main, feed=feed, fetch_list=[out], scope=scope)
prof = profiler.device_profile(
    lambda: exe.run(main, feed=feed, fetch_list=[out], scope=scope),
    steps=2)
assert prof["ms_per_step"] > 0, prof
assert any("math_ops" in src for src, _ in prof["rows"]), prof["rows"]
print("DEVICE_PROFILE_OK")
'''
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=240)
    assert "DEVICE_PROFILE_OK" in r.stdout, (r.stdout[-500:],
                                             r.stderr[-1500:])
