"""run_program op (VERDICT r5 #6): a @to_static sub-module runs as ONE
op on the dygraph tape, and training through it matches pure dygraph
step-for-step (reference: operators/run_program_op.cc via
partial_program.py)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.dygraph import to_variable
from paddle_tpu.dygraph.jit import ProgramTranslator, to_static


class Sub(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 8)

    @to_static
    def forward(self, x):
        h = self.fc(x)
        return nn.functional.relu(h) * 2.0


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.sub = Sub()
        self.head = nn.Linear(8, 1)

    def forward(self, x):
        return self.head(self.sub(x))


def _train(n_steps=5, enable_to_static=True, seed=7):
    ProgramTranslator.get_instance().enable(enable_to_static)
    try:
        with pt.dygraph.guard():
            np.random.seed(seed)
            net = Net()
            # deterministic init across both runs
            for p in net.parameters():
                p.set_value(np.random.RandomState(len(p.shape))
                            .randn(*p.shape).astype(np.float32) * 0.3)
            opt = pt.optimizer.AdamOptimizer(
                0.01, parameter_list=net.parameters())
            rng = np.random.RandomState(0)
            x = rng.randn(6, 4).astype(np.float32)
            y = rng.randn(6, 1).astype(np.float32)
            losses = []
            for _ in range(n_steps):
                from paddle_tpu.dygraph.tracer import trace_op

                pred = net(to_variable(x))
                diff = pred - to_variable(y)
                loss = trace_op("reduce_mean", {"X": [diff * diff]},
                                {"reduce_all": True})["Out"][0]
                loss.backward()
                opt.minimize(loss)
                net.clear_gradients()
                losses.append(float(np.asarray(loss.numpy())))
            return losses
    finally:
        ProgramTranslator.get_instance().enable(True)


def test_to_static_submodule_trains_like_dygraph():
    static_losses = _train(enable_to_static=True)
    dyg_losses = _train(enable_to_static=False)
    assert static_losses[-1] < static_losses[0]
    np.testing.assert_allclose(static_losses, dyg_losses, rtol=1e-5,
                               atol=1e-6)


def test_run_program_op_on_tape():
    """The tape must carry run_program (not an opaque function op)."""
    from paddle_tpu.core.executor import EXECUTED_OP_TYPES

    EXECUTED_OP_TYPES.discard("run_program")
    _train(n_steps=1, enable_to_static=True)
    assert "run_program" in EXECUTED_OP_TYPES
