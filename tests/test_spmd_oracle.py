"""SPMD interpreting oracle (VERDICT r2 #7): collective programs run
rank-by-rank op-by-op must match the compiled shard_map path exactly —
every collective lowering gets a differential check, not just the parity
tests someone remembered to write. Reference analog: the single-device
Executor as ParallelExecutor's oracle (framework/executor.cc:180)."""

import numpy as np
import pytest


def _fresh():
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()


def _train(use_compiled, mesh_axes, build_fn, steps=3):
    import paddle_tpu as pt
    from paddle_tpu.parallel import create_mesh

    _fresh()
    mesh = create_mesh(mesh_axes)
    main, startup, feed_fn, loss = build_fn(mesh)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    losses = []
    for s in range(steps):
        out = exe.run(main, feed=feed_fn(s), fetch_list=[loss],
                      scope=scope, use_compiled=use_compiled, mesh=mesh)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    params = {n: np.asarray(scope.find_var(n))
              for n in ("w0", "b0") if scope.find_var(n) is not None}
    return losses, params


def _build_dp(mesh, dropout=0.0):
    """Plain data-parallel MLP: per-shard loss + c_allreduce'd grads."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.distributed.fleet.meta_optimizers import \
        insert_grad_allreduce

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8], stop_gradient=True)
        label = layers.data("label", [1], dtype="int64", stop_gradient=True)
        h = layers.fc(x, 16, act="relu",
                      param_attr=pt.ParamAttr(
                          name="w0", initializer=pt.initializer.Xavier(
                              seed=3)),
                      bias_attr=pt.ParamAttr(name="b0"))
        if dropout:
            h = layers.dropout(h, dropout_prob=dropout)
        logits = layers.fc(h, 4, param_attr=pt.ParamAttr(
            name="w1", initializer=pt.initializer.Xavier(seed=4)),
            bias_attr=pt.ParamAttr(name="b1"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = pt.optimizer.SGDOptimizer(0.2)
        params_grads = opt.backward(loss)
        insert_grad_allreduce(main, params_grads, nranks=4,
                              axis_name="dp", average=True)
        opt.apply_gradients(params_grads)

    def feed_fn(s):
        rng = np.random.RandomState(100 + s)
        return {"x": rng.randn(8, 8).astype(np.float32),
                "label": rng.randint(0, 4, (8, 1)).astype(np.int64)}

    return main, startup, feed_fn, loss


def _build_dp_sp_bert(mesh):
    """dp2 x sp2 BERT MLM: ring attention + global loss psums — the
    composed collective program from the SP test suite."""
    import paddle_tpu as pt
    from paddle_tpu.models import bert

    cfg = bert.BertConfig(vocab_size=64, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64, max_position_embeddings=32,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0,
                          use_ring_attention=True)
    main, startup, feeds, fetches = bert.build_pretraining_program(
        cfg, seq_len=32, batch_size=4, lr=5e-3, with_nsp=False,
        sequence_parallel=2, data_parallel=2)

    def feed_fn(s):
        return bert.synthetic_pretraining_batch(cfg, 4, 32, seed=200 + s)

    return main, startup, feed_fn, fetches["loss"]


def _build_dp_sp_pp_bert(mesh):
    """The dryrun's hardest composition: dp2 x sp2 x pp2 — ring
    attention inside pipeline stages, 3-axis grad allreduce."""
    import paddle_tpu as pt
    from paddle_tpu.models import bert

    cfg = bert.BertConfig(vocab_size=64, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64, max_position_embeddings=32,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0,
                          use_ring_attention=True)
    main, startup, feeds, fetches = bert.build_pretraining_program(
        cfg, seq_len=32, batch_size=4, lr=5e-3, with_nsp=False,
        sequence_parallel=2, data_parallel=2, pipeline_stages=2,
        num_microbatches=2)

    def feed_fn(s):
        return bert.synthetic_pretraining_batch(cfg, 4, 32, seed=300 + s)

    return main, startup, feed_fn, fetches["loss"]


def _build_ep_moe(mesh):
    """dp x ep MoE: GShard all_to_all dispatch (the dryrun's 4th
    program)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.distributed.fleet.meta_optimizers import \
        insert_grad_allreduce
    from paddle_tpu.parallel.api import get_sharding_spec, shard_tensor

    ep = 4
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.static_data("x", [8, 32], "float32")
        y = layers.static_data("y", [8, 1], "int64")
        h = layers.fc(x, 32, act="relu",
                      param_attr=pt.ParamAttr(
                          name="w0",
                          initializer=pt.initializer.Xavier(seed=3)))
        moe_out, aux = layers.switch_moe(h, num_experts=ep, d_ff=64,
                                         ep_size=ep, tokens_sharded=True)
        logits = layers.fc(moe_out, 4, param_attr=pt.ParamAttr(
            name="w1", initializer=pt.initializer.Xavier(seed=4)))
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, y)) + 0.01 * aux
        opt = pt.optimizer.AdamOptimizer(1e-3)
        params_grads = opt.backward(loss)
        repl = [(p, g) for p, g in params_grads
                if not (get_sharding_spec(p) or [None])[0]]
        shard = [(p, g) for p, g in params_grads if (p, g) not in repl]
        insert_grad_allreduce(main, repl, nranks=ep, axis_name="ep",
                              average=True)
        blk = main.global_block()
        for _, g in shard:
            blk.append_op("scale", {"X": [g]}, {"Out": [g]},
                          {"scale": 1.0 / ep})
        opt.apply_gradients(params_grads)
    shard_tensor(x, ("ep", None))
    shard_tensor(y, ("ep", None))

    def feed_fn(s):
        rng = np.random.RandomState(500 + s)
        return {"x": rng.randn(8, 32).astype(np.float32),
                "y": rng.randint(0, 4, (8, 1)).astype(np.int64)}

    return main, startup, feed_fn, loss


class TestSPMDOracle:
    def test_dp_program_interpreted_matches_compiled(self):
        lc, pc = _train(True, {"dp": 4}, _build_dp)
        li, pi = _train(False, {"dp": 4}, _build_dp)
        np.testing.assert_allclose(li, lc, rtol=2e-5)
        for n in pc:
            np.testing.assert_allclose(pi[n], pc[n], rtol=2e-5,
                                       err_msg=n)
        assert lc[-1] < lc[0]

    def test_dp_dropout_masks_decorrelate_and_match_compiled(self):
        """ADVICE r3: per-rank dropout masks must decorrelate on the
        oracle path exactly like the compiled path (axis coordinate
        folded into the key when axis_index is unavailable)."""
        import functools

        build = functools.partial(_build_dp, dropout=0.4)
        lc, pc = _train(True, {"dp": 4}, build)
        li, pi = _train(False, {"dp": 4}, build)
        np.testing.assert_allclose(li, lc, rtol=2e-5)
        for n in pc:
            np.testing.assert_allclose(pi[n], pc[n], rtol=2e-5, err_msg=n)

    def test_dp_sp_ring_attention_interpreted_matches_compiled(self):
        lc, _ = _train(True, {"dp": 2, "sp": 2}, _build_dp_sp_bert)
        li, _ = _train(False, {"dp": 2, "sp": 2}, _build_dp_sp_bert)
        np.testing.assert_allclose(li, lc, rtol=5e-5)

    def test_dp_sp_pp_pipeline_interpreted_matches_compiled(self):
        """VERDICT r4 #8: the composed pipeline program under the
        oracle — the schedule op interprets as its per-stage lowering
        under a per-op shard_map, lockstep with every other op."""
        lc, _ = _train(True, {"dp": 2, "sp": 2, "pp": 2},
                       _build_dp_sp_pp_bert, steps=2)
        li, _ = _train(False, {"dp": 2, "sp": 2, "pp": 2},
                       _build_dp_sp_pp_bert, steps=2)
        np.testing.assert_allclose(li, lc, rtol=5e-5)

    def test_ep_moe_interpreted_matches_compiled(self):
        """VERDICT r4 #8: dp x ep MoE all_to_all under the oracle."""
        lc, _ = _train(True, {"ep": 4}, _build_ep_moe, steps=3)
        li, _ = _train(False, {"ep": 4}, _build_ep_moe, steps=3)
        np.testing.assert_allclose(li, lc, rtol=5e-5)
