"""Parameter-server runtime tests.

Mirrors the reference's dist tests (test_dist_base.py:578 TestDistBase —
real localhost subprocesses, no mocks): 2 pservers x 2 trainers in sync
mode must track the single-process run exactly (the average of the two
trainers' half-batch losses equals the local full-batch loss, since
grads are averaged server-side and inits are seed-deterministic).
"""

import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "ps_fixture.py")


def _losses(txt):
    return {int(m[0]): float(m[1])
            for m in re.findall(r"LOSS (\d+) ([\d.]+)", txt)}


class TestTranspiler:
    def _build(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [16], stop_gradient=True)
            h = layers.fc(x, 32, param_attr=pt.ParamAttr(name="w0"),
                          bias_attr=pt.ParamAttr(name="b0"))
            y = layers.fc(h, 4, param_attr=pt.ParamAttr(name="w1"),
                          bias_attr=pt.ParamAttr(name="b1"))
            loss = layers.mean(y * y)
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        return main, startup, loss

    def test_program_split(self):
        from paddle_tpu.distributed.ps import DistributeTranspiler

        main, startup, loss = self._build()
        eps = "127.0.0.1:7000,127.0.0.1:7001"
        t = DistributeTranspiler()
        t.transpile(0, program=main, startup_program=startup, pservers=eps,
                    trainers=2, sync_mode=True)

        trainer = t.get_trainer_program()
        ttypes = [op.type for op in trainer.global_block().ops]
        assert "sgd" not in ttypes          # optimizer moved off trainer
        assert ttypes.count("send") == 4 and ttypes.count("recv") == 4
        assert "send_barrier" in ttypes and "fetch_barrier" in ttypes

        # params balanced across both endpoints; every pserver program
        # holds only optimizer ops for its own params
        all_params = set()
        for ep in eps.split(","):
            prog, ps_startup = t.get_pserver_programs(ep)
            ops = prog.global_block().ops
            assert ops and all(op.type == "sgd" for op in ops)
            params = set(prog._ps_grad_to_param.values())
            assert params, f"pserver {ep} owns no params"
            all_params |= params
            # startup initialises exactly the vars this pserver needs
            sblk = ps_startup.global_block()
            for p in params:
                assert any(p in op.output_names() for op in sblk.ops)
        assert all_params == {"w0", "b0", "w1", "b1"}

    def test_lr_decay_runs_once_per_global_step(self):
        """A pserver hosting N params must advance the LR-decay counter
        once per GLOBAL step, not N times (advisor r2 medium): the
        schedule's increment/lr ops are common_ops run by the first grad
        of each step."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.distributed.ps import DistributeTranspiler, PServer
        from paddle_tpu.distributed.ps.rpc import RPCClient

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=True)
            h = layers.fc(x, 4, param_attr=pt.ParamAttr(name="w0"),
                          bias_attr=pt.ParamAttr(name="b0"))
            y = layers.fc(h, 2, param_attr=pt.ParamAttr(name="w1"),
                          bias_attr=pt.ParamAttr(name="b1"))
            loss = layers.mean(y * y)
            lr = layers.exponential_decay(0.1, decay_steps=1,
                                          decay_rate=0.5, staircase=True)
            pt.optimizer.SGDOptimizer(learning_rate=lr).minimize(loss)

        t = DistributeTranspiler()
        t.transpile(0, program=main, startup_program=startup,
                    pservers="127.0.0.1:17471", trainers=1, sync_mode=True)
        # LR-schedule ops are moved OFF the trainer and are NOT
        # replicated into any per-grad group
        ttypes = [op.type for op in
                  t.get_trainer_program().global_block().ops]
        assert "lr_schedule" not in ttypes and "increment" not in ttypes
        prog, ps_startup = t.get_pserver_programs("127.0.0.1:17471")
        assert [op.type for op in prog._ps_common_ops] \
            == ["increment", "lr_schedule"]
        assert all(op.type == "sgd"
                   for ops in prog._ps_grad_to_ops.values() for op in ops)

        server = PServer("127.0.0.1:17471", prog, ps_startup,
                         num_trainers=1, sync_mode=True,
                         grad_to_param=prog._ps_grad_to_param,
                         grad_to_ops=prog._ps_grad_to_ops,
                         common_ops=prog._ps_common_ops)
        try:
            cli = RPCClient(server.endpoint)
            steps = 3
            for s in range(steps):
                for g, p in prog._ps_grad_to_param.items():
                    shape = main.global_block().var(p).shape
                    cli.call("send_grad", g,
                             np.ones(shape, np.float32) * 0.01, aux=0)
            # counter initialised -1, +1 per STEP (4 params must not
            # advance it 4x): after 3 steps it reads steps-1
            counter = server.scope.find_var("@LR_DECAY_COUNTER@")
            assert counter is not None
            assert int(np.asarray(counter)[0]) == steps - 1, \
                f"LR counter advanced {np.asarray(counter)[0]} in {steps} steps"
            lr_val = float(np.asarray(
                server.scope.find_var(lr.name))[0])
            assert lr_val == pytest.approx(0.1 * 0.5 ** (steps - 1))
        finally:
            server.shutdown()

    def test_slice_var_up_matches_whole_param(self):
        """slice_var_up (reference distribute_transpiler.py:545): big
        params split into one block per pserver; each server holds and
        updates ONLY its block, the trainer splits grads / concats
        params — and training matches the single-process run EXACTLY
        (momentum, so per-block accumulators are exercised too)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.distributed.ps import DistributeTranspiler, PServer
        from paddle_tpu.distributed.ps.rpc import RPCClient
        from paddle_tpu.ops.ps_ops import reset_recv_versions

        def build():
            ir._main_program, ir._startup_program = ir.Program(), ir.Program()
            unique_name.switch()
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = layers.data("x", [16], stop_gradient=True)
                label = layers.data("label", [1], dtype="int64",
                                    stop_gradient=True)
                h = layers.fc(x, 64, act="relu",
                              param_attr=pt.ParamAttr(
                                  name="w_big",
                                  initializer=pt.initializer.Xavier(
                                      seed=5)),
                              bias_attr=pt.ParamAttr(name="b0"))
                logits = layers.fc(h, 4, param_attr=pt.ParamAttr(
                    name="w_out", initializer=pt.initializer.Xavier(
                        seed=6)), bias_attr=pt.ParamAttr(name="b1"))
                loss = layers.mean(
                    layers.softmax_with_cross_entropy(logits, label))
                pt.optimizer.MomentumOptimizer(0.1, 0.9).minimize(loss)
            return main, startup, loss

        rng = np.random.RandomState(0)
        xv = rng.randn(8, 16).astype(np.float32)
        yv = rng.randint(0, 4, (8, 1)).astype(np.int64)

        # local baseline
        main, startup, loss = build()
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        local_losses = []
        for _ in range(4):
            out = exe.run(main, feed={"x": xv, "label": yv},
                          fetch_list=[loss], scope=scope,
                          use_compiled=False)
            local_losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        w_local = np.asarray(scope.find_var("w_big"))

        # sliced 2-pserver cluster (in-process servers, 1 trainer)
        main, startup, loss = build()
        eps = "127.0.0.1:17491,127.0.0.1:17492"
        t = DistributeTranspiler()
        t.transpile(0, program=main, startup_program=startup, pservers=eps,
                    trainers=1, sync_mode=True, slice_var_up=True,
                    min_block_size=1)
        assert "w_big" in t._sliced
        assert t._sliced["w_big"]["sections"] == [8, 8]
        servers = []
        try:
            for ep in eps.split(","):
                prog, ps_startup = t.get_pserver_programs(ep)
                servers.append(PServer(
                    ep, prog, ps_startup, num_trainers=1, sync_mode=True,
                    grad_to_param=prog._ps_grad_to_param,
                    grad_to_ops=prog._ps_grad_to_ops,
                    common_ops=prog._ps_common_ops))
            # each server owns exactly one block of the sliced param
            owned = [{p for p in s.grad_to_param.values()
                      if p.startswith("w_big.block")} for s in servers]
            assert all(len(o) == 1 for o in owned) and owned[0] != owned[1]

            reset_recv_versions()
            trainer_prog = t.get_trainer_program()
            exe2 = pt.Executor(pt.CPUPlace())
            scope2 = pt.Scope()
            exe2.run(t.get_startup_program(), scope=scope2,
                     use_compiled=False)
            ps_losses = []
            for _ in range(4):
                out = exe2.run(trainer_prog, feed={"x": xv, "label": yv},
                               fetch_list=[loss], scope=scope2,
                               use_compiled=False)
                ps_losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            np.testing.assert_allclose(ps_losses, local_losses, rtol=1e-5)
            w_blocks = np.concatenate(
                [np.asarray(servers[k].scope.find_var(f"w_big.block{k}"))
                 for k in range(2)], axis=0)
            np.testing.assert_allclose(w_blocks, w_local, rtol=1e-5)
        finally:
            for s in servers:
                s.shutdown()
            RPCClient.reset_pool()
            reset_recv_versions()

    def test_half_async_merges_before_apply(self):
        """HalfAsync (reference communicator.h:343): no barriers, but
        grads buffer and apply as the mean of merge_size contributions —
        two sends of g and -g/3 must apply ONE update with their mean."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.distributed.ps import DistributeTranspiler, PServer
        from paddle_tpu.distributed.ps.rpc import RPCClient

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=True)
            y = layers.fc(x, 2, param_attr=pt.ParamAttr(name="w"))
            loss = layers.mean(y * y)
            pt.optimizer.SGDOptimizer(1.0).minimize(loss)
        t = DistributeTranspiler()
        t.transpile(0, program=main, startup_program=startup,
                    pservers="127.0.0.1:17481", trainers=2, sync_mode=False)
        prog, ps_startup = t.get_pserver_programs("127.0.0.1:17481")
        server = PServer("127.0.0.1:17481", prog, ps_startup,
                         num_trainers=2, mode="half_async", merge_size=2,
                         grad_to_param=prog._ps_grad_to_param,
                         grad_to_ops=prog._ps_grad_to_ops,
                         common_ops=prog._ps_common_ops)
        try:
            cli = RPCClient(server.endpoint)
            (g,) = [g for g in prog._ps_grad_to_param
                    if prog._ps_grad_to_param[g] == "w"]
            w0 = np.asarray(server.scope.find_var("w")).copy()
            gv = np.ones_like(w0)
            cli.call("send_grad", g, gv, aux=0)
            # buffered, not yet applied
            np.testing.assert_allclose(
                np.asarray(server.scope.find_var("w")), w0)
            cli.call("send_grad", g, -gv / 3.0, aux=0)
            # applied once with mean (1 - 1/3)/2 = 1/3, lr 1.0
            np.testing.assert_allclose(
                np.asarray(server.scope.find_var("w")), w0 - gv / 3.0,
                rtol=1e-6)
        finally:
            server.shutdown()

    def test_no_optimizer_raises(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.distributed.ps import DistributeTranspiler

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4])
            layers.fc(x, 2)
        with pytest.raises(ValueError, match="no optimizer"):
            DistributeTranspiler().transpile(
                0, program=main, startup_program=startup)


class TestPSCluster:
    """reference: test_dist_base.py TestDistBase.check_with_place:1007 —
    launch pservers + trainers as subprocesses, compare losses."""

    def _run_cluster(self, sync, steps=4, ports=(17411, 17412)):
        eps = ",".join(f"127.0.0.1:{p}" for p in ports)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        servers = [subprocess.Popen(
            [sys.executable, FIXTURE, "pserver", ep, eps, "2",
             "1" if sync else "0"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for ep in eps.split(",")]
        time.sleep(5)
        try:
            trainers = [subprocess.Popen(
                [sys.executable, FIXTURE, "trainer", str(tid), eps, "2",
                 "1" if sync else "0", str(steps)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env) for tid in range(2)]
            outs = [p.communicate(timeout=180)[0] for p in trainers]
            assert all("DONE" in o for o in outs), \
                f"trainer failed:\n{outs[0][-2000:]}\n{outs[1][-2000:]}"
        finally:
            from paddle_tpu.distributed.ps.rpc import RPCClient

            for ep in eps.split(","):
                try:
                    RPCClient(ep).stop_server()
                except Exception:
                    pass
            for s in servers:
                try:
                    s.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    s.kill()
        local = subprocess.run(
            [sys.executable, FIXTURE, "local", str(steps)],
            capture_output=True, text=True, env=env, timeout=180).stdout
        return [_losses(o) for o in outs], _losses(local)

    def test_sync_2x2_matches_local(self):
        (l0, l1), ll = self._run_cluster(sync=True)
        assert len(l0) == len(l1) == len(ll) == 4
        for s in ll:
            dist = (l0[s] + l1[s]) / 2   # grads averaged server-side
            assert abs(dist - ll[s]) < 1e-4, \
                f"step {s}: dist {dist} vs local {ll[s]}"

    def test_async_2x2_trains(self, ):
        (l0, l1), ll = self._run_cluster(sync=False, steps=6,
                                         ports=(17421, 17422))
        # async has no step-equivalence guarantee; it must run all steps
        # and stay in a sane loss range (reference asserts convergence
        # over many steps; 6 steps here just proves the machinery)
        assert len(l0) == len(l1) == 6
        assert all(np.isfinite(v) for v in l0.values())
        assert all(np.isfinite(v) for v in l1.values())


class TestHeartBeat:
    def test_monitor_flags_silent_trainer(self):
        """reference: operators/distributed/heart_beat_monitor.h:51 —
        a trainer that stops pinging is marked dead; pinging revives."""
        import time

        from paddle_tpu.distributed.ps.pserver import HeartBeatMonitor

        dead = []
        m = HeartBeatMonitor(2, timeout=0.3, interval=0.05,
                             on_dead=dead.append).start()
        m.ping(0)
        m.ping(1)
        for _ in range(20):         # keep trainer 0 alive, let 1 go silent
            m.ping(0)
            time.sleep(0.05)
        m.stop()
        assert dead == [1]
        assert 1 in m.dead and 0 not in m.dead

    def test_pserver_heartbeat_rpc(self):
        """A PServer with heartbeat_timeout accepts heartbeat RPCs and
        tracks last-seen per trainer."""
        import numpy as np

        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.distributed.ps import (DistributeTranspiler,
                                               PServer)
        from paddle_tpu.distributed.ps.rpc import RPCClient

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=True)
            y = layers.fc(x, 2, param_attr=pt.ParamAttr(name="w"))
            loss = layers.mean(y * y)
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        ep = "127.0.0.1:0"
        t = DistributeTranspiler()
        t.transpile(0, program=main, startup_program=startup,
                    pservers="127.0.0.1:17461", trainers=1, sync_mode=False)
        prog, ps_startup = t.get_pserver_programs("127.0.0.1:17461")
        server = PServer("127.0.0.1:17461", prog, ps_startup,
                         num_trainers=1, sync_mode=False,
                         grad_to_param=prog._ps_grad_to_param,
                         grad_to_ops=prog._ps_grad_to_ops,
                         common_ops=prog._ps_common_ops,
                         heartbeat_timeout=30.0)
        try:
            cli = RPCClient(server.endpoint)
            cli.call("heartbeat", aux=0)
            cli.call("heartbeat", aux=0)
            assert 0 in server.monitor.last_seen
            assert server.monitor.dead == set()
        finally:
            server.shutdown()
