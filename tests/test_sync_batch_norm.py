"""sync_batch_norm (VERDICT r3 #2): cross-rank batch statistics.

Reference: operators/sync_batch_norm_op.cu:21 (SyncBatchNormKernel does an
explicit NCCL allreduce of sum/sumsq before normalising) and
framework/ir/sync_batch_norm_pass.cc (BuildStrategy flips batch_norm ->
sync_batch_norm). The decisive check: under the shard_map collective mode a
dp4 SyncBatchNorm run must match single-rank full-batch BN exactly, while
plain BatchNorm (rank-local stats) must NOT."""

import numpy as np
import pytest


def _fresh():
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()


PARAM_NAMES = ("bn_s", "bn_b", "bn_m", "bn_v")


def _feed(s):
    rng = np.random.RandomState(50 + s)
    x = rng.randn(8, 4, 2, 2).astype(np.float32)
    # make per-rank shards statistically distinct so local-vs-global
    # stats visibly diverge: shift each dp shard (2 samples) differently
    for r in range(4):
        x[2 * r:2 * r + 2] += 2.0 * r
    return {"x": x}


def _train(sync, nranks, steps=3):
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        insert_grad_allreduce, rewrite_sync_batch_norm)
    from paddle_tpu.parallel import create_mesh

    _fresh()
    mesh = create_mesh({"dp": nranks}) if nranks > 1 else None
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.static_data("x", [8, 4, 2, 2])
        y = layers.batch_norm(
            x, param_attr=pt.ParamAttr(name="bn_s"),
            bias_attr=pt.ParamAttr(name="bn_b"),
            moving_mean_name="bn_m", moving_variance_name="bn_v")
        loss = layers.mean(y * y * y + y)  # nonlinear: grads see the stats
        if sync:
            assert rewrite_sync_batch_norm(main) == 1
        opt = pt.optimizer.SGDOptimizer(0.1)
        params_grads = opt.backward(loss)
        if nranks > 1:
            insert_grad_allreduce(main, params_grads, nranks=nranks,
                                  axis_name="dp", average=True)
        opt.apply_gradients(params_grads)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    for s in range(steps):
        exe.run(main, feed=_feed(s), fetch_list=[loss], scope=scope,
                mesh=mesh)
    return {n: np.asarray(scope.find_var(n)) for n in PARAM_NAMES}


class TestSyncBatchNorm:
    def test_dp4_sync_matches_single_rank_full_batch(self):
        oracle = _train(sync=False, nranks=1)
        dp4 = _train(sync=True, nranks=4)
        for n in PARAM_NAMES:
            np.testing.assert_allclose(dp4[n], oracle[n], rtol=2e-5,
                                       atol=1e-6, err_msg=n)

    def test_dp4_plain_bn_diverges(self):
        """The hole sync_batch_norm closes: rank-local stats drift."""
        oracle = _train(sync=False, nranks=1)
        dp4 = _train(sync=False, nranks=4)
        diff = max(np.abs(dp4[n] - oracle[n]).max() for n in PARAM_NAMES)
        assert diff > 1e-3, "plain BN unexpectedly matched global stats"

    def test_single_rank_sync_degenerates_to_bn(self):
        a = _train(sync=False, nranks=1)
        b = _train(sync=True, nranks=1)
        for n in PARAM_NAMES:
            np.testing.assert_allclose(b[n], a[n], rtol=1e-6, err_msg=n)

    def test_registry_has_op(self):
        from paddle_tpu.core.registry import registered_ops

        assert "sync_batch_norm" in registered_ops()


class TestSyncBatchNormLayer:
    def test_dygraph_forward_matches_bn_single_rank(self):
        import paddle_tpu as pt
        import paddle_tpu.nn as nn

        _fresh()
        with pt.dygraph.guard():
            x = pt.to_tensor(
                np.random.RandomState(0).randn(4, 3, 2, 2).astype(
                    np.float32))
            bn = nn.BatchNorm2D(3)
            sbn = nn.SyncBatchNorm(3)
            bn.train(), sbn.train()
            np.testing.assert_allclose(np.asarray(sbn(x)), np.asarray(bn(x)),
                                       rtol=1e-6)

    def test_convert_sync_batchnorm(self):
        import paddle_tpu as pt
        import paddle_tpu.nn as nn

        _fresh()
        with pt.dygraph.guard():
            net = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4),
                                nn.ReLU())
            w_before = np.asarray(net[1].weight)
            net = nn.SyncBatchNorm.convert_sync_batchnorm(net)
            assert isinstance(net[1], nn.SyncBatchNorm)
            np.testing.assert_array_equal(np.asarray(net[1].weight), w_before)
            x = pt.to_tensor(np.ones((2, 3, 4, 4), np.float32))
            y = net(x)
            assert tuple(y.shape) == (2, 4, 2, 2)
