"""io.py: persistable save/load, pruning, inference-model export.

Mirrors the reference's test_inference_model_io.py / save-load suites
(python/paddle/fluid/tests/unittests/test_io_save_load.py style).
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def _model(optimizer=True):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(x, 16, act="relu")
        logits = layers.fc(h, 4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        if optimizer:
            pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return main, startup, logits, loss


def _feed(n=8, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(n, 8).astype(np.float32),
            "label": rng.randint(0, 4, (n, 1)).astype(np.int64)}


def test_save_load_persistables_roundtrip(tmp_path, scope):
    main, startup, logits, loss = _model()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = _feed()
    for _ in range(3):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    ref, = exe.run(main.clone(for_test=True), feed=feed, fetch_list=[logits],
                   scope=scope)
    saved = pt.io.save_persistables(exe, str(tmp_path / "ckpt"), main, scope=scope)
    assert saved  # includes adam moments, not just params
    assert any("moment" in n.lower() or "beta" in n.lower() for n in saved)

    s2 = pt.Scope()
    pt.io.load_persistables(exe, str(tmp_path / "ckpt"), main, scope=s2)
    out, = exe.run(main.clone(for_test=True), feed=feed, fetch_list=[logits],
                   scope=s2)
    np.testing.assert_allclose(ref, out, rtol=1e-5, atol=1e-6)


def test_save_load_combined_file(tmp_path, scope):
    main, startup, logits, _ = _model(optimizer=False)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    pt.io.save_params(exe, str(tmp_path), main, filename="params.npz", scope=scope)
    s2 = pt.Scope()
    pt.io.load_params(exe, str(tmp_path), main, filename="params.npz", scope=s2)
    feed = _feed()
    a, = exe.run(main, feed=feed, fetch_list=[logits], scope=scope)
    b, = exe.run(main, feed=feed, fetch_list=[logits], scope=s2)
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_prune_program_drops_backward(scope):
    main, startup, logits, loss = _model()
    pruned = pt.io.prune_program(main, ["x"], [logits.name])
    kept_types = {op.type for op in pruned.global_block().ops}
    assert "sgd" not in kept_types and "adam" not in kept_types
    assert not any(op.is_backward_op() for op in pruned.global_block().ops)
    # label path must be gone: logits don't depend on it
    for op in pruned.global_block().ops:
        assert "label" not in op.input_names()


def test_save_load_inference_model(tmp_path, scope):
    main, startup, logits, loss = _model()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = _feed()
    exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    ref, = exe.run(main.clone(for_test=True), feed=feed, fetch_list=[logits],
                   scope=scope)
    pt.io.save_inference_model(str(tmp_path / "model"), ["x"], [logits], exe,
                               main, scope=scope)

    s2 = pt.Scope()
    prog, feeds, fetches = pt.io.load_inference_model(str(tmp_path / "model"),
                                                      exe, scope=s2)
    assert feeds == ["x"]
    out, = exe.run(prog, feed={"x": feed["x"]}, fetch_list=fetches, scope=s2)
    np.testing.assert_allclose(ref, out, rtol=1e-5, atol=1e-6)


def test_atomic_write_torn_write_regression(tmp_path):
    """A writer that dies mid-payload must leave the previous file
    byte-identical and no temp litter — the torn-export regression the
    atomic temp-file + os.replace protocol exists for."""
    import pytest

    target = tmp_path / "weights.npy"
    pt.io.atomic_save_npy(str(target), np.arange(8, dtype=np.float32))
    before = target.read_bytes()

    def torn_writer(f):
        f.write(b"half a paylo")          # partial bytes hit the temp file
        raise ConnectionError("killed mid-write")

    with pytest.raises(ConnectionError):
        pt.io.atomic_write(str(target), torn_writer)
    assert target.read_bytes() == before          # final name untouched
    assert [p.name for p in tmp_path.iterdir()] == ["weights.npy"]  # no tmp


def test_save_inference_model_overwrite_is_atomic(tmp_path, scope,
                                                  monkeypatch):
    """Re-exporting over an existing model dir must not tear
    __model__.json even if the export dies: the old model keeps
    loading."""
    import pytest

    main, startup, logits, loss = _model(optimizer=False)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    mdir = str(tmp_path / "model")
    pt.io.save_inference_model(mdir, ["x"], [logits], exe, main, scope=scope)
    good = open(tmp_path / "model" / "__model__.json").read()

    real_dump = pt.io.json.dump

    def exploding_dump(doc, f, *a, **k):
        f.write('{"torn": ')
        raise OSError("disk died mid-export")

    monkeypatch.setattr(pt.io.json, "dump", exploding_dump)
    with pytest.raises(OSError):
        pt.io.save_inference_model(mdir, ["x"], [logits], exe, main,
                                   scope=scope)
    monkeypatch.setattr(pt.io.json, "dump", real_dump)
    assert open(tmp_path / "model" / "__model__.json").read() == good
    prog, feeds, fetches = pt.io.load_inference_model(mdir, exe,
                                                      scope=pt.Scope())
    assert feeds == ["x"]


def test_static_save_load_state(tmp_path, scope):
    main, startup, logits, loss = _model()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
    state = pt.io.get_program_state(main, scope=scope)
    pt.io.save(main, str(tmp_path / "m" / "model"), scope=scope)
    s2 = pt.Scope()
    pt.io.load(main, str(tmp_path / "m" / "model"), scope=s2)
    for k, v in state.items():
        np.testing.assert_array_equal(v, np.asarray(s2.find_var(k)))
