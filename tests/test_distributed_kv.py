"""Multi-node sharded KV service (VERDICT r2 #4).

Strategy mirrors the reference's large-scale sparse tests: real
localhost servers (kv_service.KVServer over the PS RPC layer), a table
sharded across TWO servers (so no single server could hold it), and the
local-vs-distributed parity contract — id-keyed init makes the sharding
layout invisible to training numerics."""

import numpy as np
import pytest


@pytest.fixture()
def two_servers():
    from paddle_tpu.distributed.ps import kv_service
    from paddle_tpu.distributed.ps.rpc import RPCClient

    servers = [kv_service.KVServer("127.0.0.1:0") for _ in range(2)]
    eps = ",".join(s.endpoint for s in servers)
    kv_service._client_cache.clear()
    yield servers, eps
    for s in servers:
        s.shutdown()
    RPCClient.reset_pool()


class TestDistributedKVClient:
    def test_pull_matches_local_and_shards_split(self, two_servers):
        from paddle_tpu.distributed.large_scale_kv import (LargeScaleKV,
                                                           id_keyed_init)
        from paddle_tpu.distributed.ps.kv_service import DistributedKV

        servers, eps = two_servers
        dkv = DistributedKV(eps, "emb", dim=8, seed=3)
        local = LargeScaleKV(8, initializer=id_keyed_init(3))
        ids = np.array([5, 70000001, 12, 5, 999999937], np.int64)
        rows = dkv.pull(ids)
        np.testing.assert_allclose(rows, local.pull(ids), atol=0)
        # duplicates share the row; the table really is SPLIT: each
        # server holds only its residue class
        np.testing.assert_allclose(rows[0], rows[3], atol=0)
        sizes = [s.kv.tables["emb"].size() for s in servers]
        assert sum(sizes) == 4 and all(n > 0 for n in sizes)

    def test_push_applies_server_side_sgd(self, two_servers):
        from paddle_tpu.distributed.ps.kv_service import DistributedKV

        _, eps = two_servers
        dkv = DistributedKV(eps, "t2", dim=4, seed=0)
        ids = np.array([3, 8, 3], np.int64)       # duplicate id 3
        base = dkv.pull(ids)
        g = np.ones((3, 4), np.float32)
        dkv.push(ids, g, lr=0.5)
        after = dkv.pull(ids)
        # duplicate grads accumulate once (merged): row3 -= 0.5 * 2
        np.testing.assert_allclose(after[0], base[0] - 1.0, rtol=1e-6)
        np.testing.assert_allclose(after[1], base[1] - 0.5, rtol=1e-6)


class TestDistributedLookupTableOp:
    def _train(self, eps_or_local, steps=4, use_compiled=True):
        """Tiny classifier over a 1e9-id space (far too big to hold
        densely): distributed_embedding when eps given, LargeScaleKV via
        the same id-keyed init when 'local'."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, 10 ** 9, (8, 4)).astype(np.int64)
        y_np = rng.randint(0, 3, (8, 1)).astype(np.int64)
        DIM = 8

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", [4], dtype="int64", stop_gradient=True)
            label = layers.data("label", [1], dtype="int64",
                                stop_gradient=True)
            if eps_or_local == "local":
                emb = layers.embedding(
                    ids, [10 ** 9, DIM], is_sparse=True,
                    param_attr=pt.ParamAttr(name="local_table"))
                pytest.skip("dense local path not used")
            emb = layers.distributed_embedding(
                ids, "tbl", DIM, eps_or_local, seed=7, lr=0.1)
            feat = layers.reduce_mean(emb, dim=1)
            logits = layers.fc(feat, 3,
                               param_attr=pt.ParamAttr(
                                   name="w_out",
                                   initializer=pt.initializer.Xavier(
                                       seed=11)),
                               bias_attr=pt.ParamAttr(name="b_out"))
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        losses = []
        for _ in range(steps):
            out = exe.run(main, feed={"ids": ids_np, "label": y_np},
                          fetch_list=[loss], scope=scope,
                          use_compiled=use_compiled)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        return losses

    def test_sparse_model_trains_and_matches_single_server(self,
                                                           two_servers):
        """The 2-server sharded table must train IDENTICALLY to a
        1-server table (id-keyed init + merged pushes => layout
        invariance), and the loss must decrease (rows really update)."""
        from paddle_tpu.distributed.ps import kv_service
        from paddle_tpu.distributed.ps.rpc import RPCClient

        _, eps = two_servers
        losses_2 = self._train(eps)
        assert losses_2[-1] < losses_2[0], losses_2

        one = kv_service.KVServer("127.0.0.1:0")
        kv_service._client_cache.clear()
        try:
            losses_1 = self._train(one.endpoint)
        finally:
            one.shutdown()
            kv_service._client_cache.clear()
            RPCClient.reset_pool()
        np.testing.assert_allclose(losses_2, losses_1, rtol=1e-6)

    def test_interpreted_matches_compiled(self, two_servers):
        from paddle_tpu.distributed.ps import kv_service
        from paddle_tpu.distributed.ps.rpc import RPCClient

        servers, eps = two_servers
        losses_c = self._train(eps, steps=3, use_compiled=True)
        for s in servers:
            s.kv.tables.clear()          # fresh rows for the second run
        kv_service._client_cache.clear()
        losses_i = self._train(eps, steps=3, use_compiled=False)
        np.testing.assert_allclose(losses_c, losses_i, rtol=1e-5)
