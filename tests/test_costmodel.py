"""Cost & memory observability plane tests (PR 10 tier-1 gate).

Contracts under test (paddle_tpu/core/costmodel.py + the wiring):
* every fresh executor compile captures XLA cost/memory analyses keyed
  by the compile-cache entry (flops/bytes at level 'cost', plus peak/
  argument/output/temp bytes at level 'full'), and the HBM ledger
  gauges (mem.param_bytes / mem.opt_state_bytes / mem.peak_temp_bytes /
  mem.hbm_total_bytes) + live MFU gauge land on the metrics plane;
* 'auto' capture costs nothing in uninstrumented runs and turns on when
  a telemetry sink or metrics server is active;
* a backend without the analysis APIs degrades by COUNTING
  (costmodel.unavailable) — executor, predictor and serving engine all
  stay green (ISSUE satellite);
* an allocation failure dumps an OOM-forensics record (ledger snapshot
  + top cached programs + the offending program) and raises a typed
  OutOfMemoryError;
* serving warmup captures per-bucket footprints into /v1/stats and
  mem.serving.bucket<B>_peak_bytes gauges;
* BENCH rows embed extra.model_flops + extra.live_mfu;
* tools/mem_report.py renders the ledger + per-program table from a
  run log, and --smoke self-checks (ISSUE satellite);
* no emitted cost.*/mem.*/costmodel.*/sharding.*state_bytes* metric is
  silently orphaned — every one is rendered by perf_report or
  mem_report (ISSUE satellite: metric-name drift guard).
"""

import json
import os
import re
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import costmodel, telemetry
from paddle_tpu.core.flags import set_flags

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_plane():
    telemetry.configure(None)
    telemetry.reset()
    costmodel.reset()
    set_flags({"cost_capture": "auto"})
    yield
    set_flags({"cost_capture": "auto", "device_peak_flops": 0.0,
               "device_peak_bw": 0.0})
    telemetry.configure(None)
    telemetry.reset()
    costmodel.reset()


def _mlp_program(hidden=8):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], stop_gradient=True)
        y = layers.fc(x, hidden, act="relu")
        loss = layers.mean(y)
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


def _run_steps(scope, n=3, log=None, level="full"):
    if log is not None:
        telemetry.configure(str(log))
    set_flags({"cost_capture": level})
    main, startup, loss = _mlp_program()
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    x = np.ones((4, 4), np.float32)
    out = None
    for _ in range(n):
        out = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
    return exe, float(np.asarray(out[0]).reshape(-1)[0])


def _read(path):
    telemetry.flush_sink()
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class TestCaptureExecutor:
    def test_full_capture_program_record_and_ledger(self, scope, tmp_path):
        """Acceptance core: a full-capture run records flops + memory
        stats per compile-cache entry and composes the HBM ledger."""
        log = tmp_path / "run.jsonl"
        _run_steps(scope, n=3, log=log)
        recs = costmodel.programs()
        assert len(recs) == 1
        rec = recs[0]
        assert rec.kind == "executor"
        assert rec.flops > 0 and rec.bytes_accessed > 0
        assert rec.source == "compiled"
        assert rec.temp_bytes > 0 and rec.arg_bytes > 0
        assert rec.peak_bytes >= rec.temp_bytes
        assert rec.roofline() in ("compute_bound", "memory_bound")
        g = telemetry.gauges()
        assert g["mem.param_bytes"] > 0          # fc weights
        assert g["mem.opt_state_bytes"] > 0      # lr counter etc.
        assert g["mem.peak_temp_bytes"] == rec.temp_bytes
        led = costmodel.ledger()
        assert led["total_bytes"] == (led["param_bytes"] +
                                      led["opt_state_bytes"] +
                                      led["peak_temp_bytes"] +
                                      led.get("serving_kv_pool_bytes", 0))
        assert g["mem.hbm_total_bytes"] == led["total_bytes"]
        # dispatch accounting + live MFU gauge (set on first dispatch)
        assert telemetry.counter_get("cost.dispatch_flops") >= 3 * rec.flops
        assert costmodel.live_mfu() > 0
        assert g["cost.live_mfu"] > 0
        # the run log carries the per-compile cost record
        cost_recs = [r for r in _read(log) if r["kind"] == "cost"]
        assert len(cost_recs) == 1
        attrs = cost_recs[0]["attrs"]
        assert attrs["flops"] == rec.flops
        assert attrs["temp_bytes"] == rec.temp_bytes
        assert attrs["roofline"] == rec.roofline()
        assert attrs["key"] == rec.key_id

    def test_cost_level_skips_memory_stats(self, scope, tmp_path):
        """'cost' level: flops/bytes from the lowered module only — no
        second XLA compile, no temp bytes."""
        _run_steps(scope, n=1, log=tmp_path / "r.jsonl", level="cost")
        (rec,) = costmodel.programs()
        assert rec.source == "lowered"
        assert rec.flops > 0
        assert rec.temp_bytes == 0 and rec.peak_bytes == 0

    def test_auto_is_off_when_uninstrumented(self, scope):
        """No sink, no metrics server → 'auto' captures nothing (bare CI
        runs pay zero)."""
        assert costmodel.capture_mode() == "off"
        _run_steps(scope, n=1, log=None, level="auto")
        assert costmodel.programs() == []
        assert telemetry.counter_get("cost.captures") == 0

    def test_auto_is_on_with_sink(self, scope, tmp_path):
        telemetry.configure(str(tmp_path / "r.jsonl"))
        assert costmodel.capture_mode() == "cost"
        _run_steps(scope, n=1, log=None, level="auto")
        assert telemetry.counter_get("cost.captures") == 1

    def test_run_steps_capture_covers_the_fused_scan(self, scope, tmp_path):
        """K-step fusion: the captured program IS the scan — flops scale
        ~k× the single-step program and the record names k."""
        telemetry.configure(str(tmp_path / "r.jsonl"))
        set_flags({"cost_capture": "cost"})
        main, startup, loss = _mlp_program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.ones((4, 4), np.float32)
        exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        stacked = {"x": np.stack([x] * 4)}
        exe.run_steps(main, feed=stacked, fetch_list=[loss], k=4,
                      scope=scope)
        recs = {r.steps_per_dispatch: r for r in costmodel.programs()}
        assert set(recs) == {1, 4}
        # XLA cost analysis counts the scan body ONCE — the per-dispatch
        # figure scales it by k
        assert recs[4].flops_per_dispatch() >= 3 * recs[1].flops_per_dispatch()
        assert recs[4].flops == pytest.approx(recs[1].flops, rel=0.25)

    def test_peak_flops_override(self):
        set_flags({"device_peak_flops": 123.0})
        assert costmodel.peak_device_flops() == 123.0
        set_flags({"device_peak_flops": 0.0})
        assert costmodel.peak_device_flops() > 1e12   # table fallback

    def test_normalize_cost_analysis_shapes(self):
        """One place knows XLA's key spelling — list-vs-dict and the
        'bytes accessed' name (satellite: audit_hlo rebases on this)."""
        flat = costmodel.normalize_cost_analysis(
            {"flops": 2.0, "bytes accessed": 3.0, "transcendentals": 1.0,
             "bytes accessed0{}": 99.0})
        assert flat == {"flops": 2.0, "bytes_accessed": 3.0,
                        "transcendentals": 1.0}
        assert costmodel.normalize_cost_analysis(
            [{"flops": 5.0}])["flops"] == 5.0
        assert costmodel.normalize_cost_analysis(None) == {}
        assert costmodel.normalize_cost_analysis("nope") == {}


class TestDegradation:
    """ISSUE satellite: a backend without cost_analysis/memory_analysis
    degrades by counting — executor/predictor/serving all stay green."""

    def test_executor_green_without_analysis_apis(self, scope, tmp_path,
                                                  monkeypatch):
        import jax

        def boom(self, *a, **kw):
            raise NotImplementedError("no analysis on this backend")

        monkeypatch.setattr(jax.stages.Lowered, "cost_analysis", boom)
        monkeypatch.setattr(jax.stages.Lowered, "compile", boom)
        _exe, loss = _run_steps(scope, n=2, log=tmp_path / "r.jsonl")
        assert np.isfinite(loss)                 # run unaffected
        assert costmodel.programs() == []        # nothing captured
        assert telemetry.counter_get("costmodel.unavailable") >= 1
        assert telemetry.counter_get("cost.captures") == 0

    def test_memory_analysis_only_missing(self, scope, tmp_path,
                                          monkeypatch):
        """cost_analysis works, memory_analysis raises → partial record
        (flops yes, temp bytes no), unavailable counted once."""
        import jax

        def boom(self, *a, **kw):
            raise NotImplementedError("CompiledMemoryStats unavailable")

        monkeypatch.setattr(jax.stages.Compiled, "memory_analysis", boom)
        _run_steps(scope, n=1, log=tmp_path / "r.jsonl")
        (rec,) = costmodel.programs()
        assert rec.flops > 0 and rec.temp_bytes == 0
        assert telemetry.counter_get("costmodel.unavailable") == 1

    def test_serving_green_without_analysis_apis(self, tmp_path,
                                                 monkeypatch):
        import jax

        def boom(self, *a, **kw):
            raise NotImplementedError("no analysis")

        monkeypatch.setattr(jax.stages.Lowered, "cost_analysis", boom)
        monkeypatch.setattr(jax.stages.Lowered, "compile", boom)
        telemetry.configure(str(tmp_path / "r.jsonl"))
        set_flags({"cost_capture": "full"})
        from tests.test_serving import _engine, _save_mlp

        engine = _engine(_save_mlp(tmp_path)).start(warmup=True)
        try:
            out, = engine.infer(
                {"x": np.ones((2, 6), np.float32)}, timeout=30)
            assert out.shape == (2, 4)
            assert engine.stats().get("memory") is None
            assert telemetry.counter_get("costmodel.unavailable") >= 1
        finally:
            engine.close()


class TestOOMForensics:
    def test_is_oom_error_markers(self):
        assert costmodel.is_oom_error(
            RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
        assert costmodel.is_oom_error(MemoryError("Out of memory"))
        assert not costmodel.is_oom_error(ValueError("bad shape"))

    def test_oom_forensics_record_contents(self, scope, tmp_path):
        """The forensics record carries the ledger + top programs by
        peak bytes + the offending program id, and mem.oom_events is
        counted."""
        log = tmp_path / "run.jsonl"
        _run_steps(scope, n=1, log=log)
        err = costmodel.oom_forensics(
            "prog7v1", RuntimeError("RESOURCE_EXHAUSTED: oom"),
            where="executor.dispatch")
        assert isinstance(err, costmodel.OutOfMemoryError)
        assert "prog7v1" in str(err)
        assert telemetry.counter_get("mem.oom_events") == 1
        ooms = [r for r in _read(log) if r["kind"] == "oom"]
        assert len(ooms) == 1
        attrs = ooms[0]["attrs"]
        assert attrs["program"] == "prog7v1"
        assert attrs["where"] == "executor.dispatch"
        assert attrs["ledger"]["total_bytes"] > 0
        assert attrs["top_programs"] and \
            attrs["top_programs"][0]["peak_bytes"] > 0

    def test_executor_dispatch_wraps_oom(self, scope, tmp_path):
        """An allocation failure out of the jitted dispatch surfaces as
        the typed OutOfMemoryError with the forensics landed."""
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        set_flags({"cost_capture": "full"})
        main, startup, loss = _mlp_program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.ones((4, 4), np.float32)
        exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        (entry,) = exe._cache.values()

        def exhausted(*a, **kw):
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating 1234 bytes")

        entry.jitted = exhausted
        with pytest.raises(costmodel.OutOfMemoryError):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        ooms = [r for r in _read(log) if r["kind"] == "oom"]
        assert len(ooms) == 1
        assert ooms[0]["attrs"]["where"] == "executor.dispatch"
        assert str(main.uid) in str(ooms[0]["attrs"]["program"])


class TestLiveMetricsPlane:
    def test_metrics_server_exposes_cost_and_mem_gauges(self, scope):
        """Acceptance: /metrics exposes pt_cost_*/pt_mem_* mid-run. A
        running metrics server alone (no sink) turns 'auto' capture on."""
        srv = telemetry.start_metrics_server(port=0)
        try:
            assert telemetry.metrics_server_active()
            assert costmodel.capture_mode() == "cost"
            set_flags({"cost_capture": "full"})
            _run_steps(scope, n=2, log=None, level="full")
            with urllib.request.urlopen(srv.url + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            assert "pt_cost_captures_total" in text
            assert "pt_cost_live_mfu" in text
            assert "pt_mem_param_bytes" in text
            assert "pt_mem_hbm_total_bytes" in text
            assert "pt_cost_dispatch_flops_total" in text
        finally:
            srv.shutdown()
        assert not telemetry.metrics_server_active()


class TestServingBuckets:
    def test_warmup_captures_bucket_footprints(self, tmp_path):
        """Per-bucket cost/memory footprints land in /v1/stats and on
        mem.serving.bucket<B>_peak_bytes gauges at engine warmup."""
        telemetry.configure(str(tmp_path / "r.jsonl"))
        set_flags({"cost_capture": "full"})
        from tests.test_serving import _engine, _save_mlp

        engine = _engine(_save_mlp(tmp_path)).start(warmup=True)
        try:
            stats = engine.stats()
            mem = stats["memory"]
            # pow2 buckets up to max_batch_size=8 → 1, 2, 4, 8
            assert set(mem["buckets"]) == {"1", "2", "4", "8"}
            for rec in mem["buckets"].values():
                assert rec["peak_bytes"] > 0
                assert rec["flops"] > 0
            assert mem["ledger"]["param_bytes"] > 0
            g = telemetry.gauges()
            assert g["mem.serving.bucket8_peak_bytes"] > 0
            assert g["mem.serving.bucket8_peak_bytes"] >= \
                g["mem.serving.bucket1_peak_bytes"]
        finally:
            engine.close()


class TestBenchEmbedding:
    def test_bench_row_embeds_model_flops_and_live_mfu(self, tmp_path):
        """Acceptance: a BENCH row carries extra.model_flops (analytic)
        + extra.live_mfu (runtime gauge) — self-attributing rows."""
        telemetry.configure(str(tmp_path / "bench.jsonl"))
        set_flags({"cost_capture": "full"})
        sys.path.insert(0, REPO_ROOT)
        from tools.bench_models import bench_mnist, finalize_bench_result

        row = finalize_bench_result(bench_mnist(steps=4, batch=16))
        ex = row["extra"]
        assert ex["model_flops"] > 0
        assert "live_mfu" in ex and ex["live_mfu"] >= 0
        assert ex["cost_captures"] >= 1
        assert ex["cost_dispatch_flops"] > 0
        assert ex["mem_hbm_total_bytes"] > 0


class TestMemReportCLI:
    def _produce_log(self, scope, tmp_path):
        log = tmp_path / "run.jsonl"
        _run_steps(scope, n=3, log=log)
        costmodel.oom_forensics("progX", RuntimeError(
            "RESOURCE_EXHAUSTED: oom"), where="executor.dispatch")
        telemetry.flush()
        return log

    def test_cli_renders_ledger_and_cost_table(self, scope, tmp_path):
        """Acceptance: mem_report renders the HBM ledger (param/opt/peak
        temp bytes) + per-program cost table + OOM forensics from a real
        LeNet/MLP-harness run log."""
        log = self._produce_log(scope, tmp_path)
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "mem_report.py"),
             str(log)],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
        assert proc.returncode == 0, proc.stderr
        out = proc.stdout
        assert "-- HBM ledger --" in out
        assert "params" in out and "optimizer state" in out
        assert "peak program scratch" in out
        assert "-- per-program cost table" in out
        assert "executor" in out
        assert "-- OOM forensics" in out
        assert "-- capture health --" in out

    def test_cli_json_summary(self, scope, tmp_path):
        log = self._produce_log(scope, tmp_path)
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "mem_report.py"),
             str(log), "--json"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
        assert proc.returncode == 0, proc.stderr
        s = json.loads(proc.stdout)
        assert s["ledger"]["param_bytes"] > 0
        assert s["ledger"]["peak_temp_bytes"] > 0
        assert len(s["programs"]) == 1
        assert s["programs"][0]["flops"] > 0
        assert len(s["ooms"]) == 1

    def test_smoke_self_check(self):
        """ISSUE satellite: `mem_report --smoke` (synthetic log →
        nonzero exit on missing sections) in the tools smoke path."""
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "mem_report.py"),
             "--smoke"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "ok" in proc.stdout

    def test_smoke_fails_when_renderer_loses_a_section(self, monkeypatch):
        """The smoke must actually bite: drop a section from the
        renderer and --smoke exits nonzero."""
        sys.path.insert(0, REPO_ROOT)
        from tools import mem_report

        real_render = mem_report.render

        def lossy(s, out=sys.stdout):
            import io

            buf = io.StringIO()
            real_render(s, out=buf)
            out.write(buf.getvalue().replace("-- HBM ledger --", ""))

        monkeypatch.setattr(mem_report, "render", lossy)
        assert mem_report.smoke() == 2

    def test_perf_report_memcost_section(self, scope, tmp_path):
        """perf_report gains a 'Memory & cost' section for instrumented
        runs."""
        log = self._produce_log(scope, tmp_path)
        from tools.perf_report import load_counted, render, summarize_log
        import io

        recs, malformed = load_counted(str(log))
        s = summarize_log(recs, malformed=malformed)
        mc = s["memcost"]
        assert mc["captures"] == 1
        assert mc["programs"] == 1
        assert mc["param_bytes"] > 0
        assert mc["oom_events"] == 1
        assert mc["roofline"]
        buf = io.StringIO()
        render(s, out=buf)
        assert "-- memory & cost" in buf.getvalue()


# -- metric-name drift guard (ISSUE satellite) -------------------------------

_EMIT_RE = re.compile(
    r"(?:counter_add|counter_quiet|counter_set|gauge_set|observe)\(\s*"
    r"f?\"([a-zA-Z0-9_.{}]+)\"")


def _emitted_metric_names():
    """Every cost.*/mem.*/costmodel.*/pallas.*/sharding.*state_bytes*
    metric name the framework emits, scraped from the source (f-string
    placeholders truncate the name at '{' — the renderer must reference
    the static prefix)."""
    names = set()
    roots = [os.path.join(REPO_ROOT, "paddle_tpu"),
             os.path.join(REPO_ROOT, "tools")]
    for root in roots:
        for dirpath, _dirs, files in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                with open(os.path.join(dirpath, fn)) as f:
                    src = f.read()
                for m in _EMIT_RE.finditer(src):
                    name = m.group(1).split("{", 1)[0]
                    if name.startswith(("cost.", "mem.", "costmodel.",
                                        "pallas.", "incidents.",
                                        "slo.", "tuner.",
                                        "goodput.", "fleet.",
                                        "scaler.", "elastic.",
                                        "kv.", "disagg.",
                                        "orch.", "session.")) or \
                            (name.startswith("sharding.")
                             and ("state_bytes" in name
                                  or "zero_regroup" in name)):
                        names.add(name)
    return names


class TestMetricDriftGuard:
    def test_every_cost_mem_metric_is_rendered(self):
        """No silently-orphaned telemetry: every cost.*/mem.*/
        costmodel.*/pallas.*/incidents.*/slo.*/sharding.*state_bytes*
        metric the code emits must be referenced by perf_report.py or
        mem_report.py."""
        names = _emitted_metric_names()
        # the plane exists: the guard must be looking at real names
        assert "cost.captures" in names
        assert "mem.param_bytes" in names
        assert "costmodel.unavailable" in names
        assert any(n.startswith("mem.serving.bucket") for n in names)
        assert "sharding.optimizer_state_bytes" in names
        # the Pallas serving kernels count every dispatch and fallback
        assert "pallas.int8_gemm_dispatches" in names
        assert "pallas.paged_attn_dispatches" in names
        assert "pallas.int8_gemm_fallbacks" in names
        assert "pallas.paged_attn_fallbacks" in names
        # the incident pipeline + SLO watchdog (core/incidents.py)
        assert "incidents.reported" in names
        assert "incidents.rate_limited" in names
        assert "slo.trips" in names
        assert "slo.evaluations" in names
        # the cost-model-guided autotuner (core/tuner.py)
        assert "tuner.trials" in names
        assert "tuner.promotions" in names
        assert "tuner.rollbacks" in names
        assert "tuner.constraint_rejections" in names
        # the goodput ledger (core/goodput.py) — badput_<phase> emits
        # via an f-string, so the scraped name is the static prefix
        assert "goodput.productive_ms" in names
        assert "goodput.wall_ms" in names
        assert "goodput.ratio" in names
        assert "goodput.badput_" in names
        # the elastic resize / autoscaling plane (distributed/scaler.py
        # policy engine + distributed/elastic.py runner)
        assert "scaler.evaluations" in names
        assert "scaler.decisions" in names
        assert "scaler.scale_up" in names
        assert "scaler.scale_down" in names
        assert "scaler.suppressed_cooldown" in names
        assert "scaler.clamped" in names
        assert "elastic.restarts" in names
        assert "elastic.scale_events" in names
        assert "elastic.restart_budget_refunds" in names
        assert "incidents.scale_events" in names
        assert "sharding.zero_regroup_events" in names
        # the content-addressed prefix store + disaggregated prefill
        # plane (serving/prefix_store.py + disagg.py)
        assert "kv.prefix_hits" in names
        assert "kv.prefix_misses" in names
        assert "kv.bytes_saved" in names
        assert "kv.cow_forks" in names
        assert "kv.reclaims" in names
        assert "kv.audit_failures" in names
        assert "kv.prefix_blocks" in names
        assert "mem.serving.kv_prefix_saved_bytes" in names
        assert "disagg.ships" in names
        assert "disagg.ship_bytes" in names
        assert "disagg.installs" in names
        assert "disagg.crc_rejects" in names
        assert "disagg.fallback_prefills" in names
        # the process-level crash-survival plane: the launch.py
        # orchestrator and the decode-session failover journal
        # (serving/session.py)
        assert "orch.spawns" in names
        assert "orch.child_deaths" in names
        assert "orch.respawns" in names
        assert "orch.budget_exhausted" in names
        assert "orch.drains" in names
        assert "orch.drain_kills" in names
        assert "orch.scale_events" in names
        assert "orch.restart_budget_refunds" in names
        assert "session.journaled" in names
        assert "session.evicted" in names
        assert "session.resumed" in names
        assert "session.resumed_tokens" in names
        assert "session.journal_errors" in names
        assert "session.failovers" in names
        assert "elastic.drains" in names
        assert "elastic.drain_timeouts" in names
        # the fleet observatory (core/fleetobs.py)
        assert "fleet.scrapes" in names
        assert "fleet.scrape_failures" in names
        assert "fleet.members_went_stale" in names
        assert "fleet.stragglers" in names
        assert "fleet.qps" in names
        renderers = ""
        for tool in ("perf_report.py", "mem_report.py"):
            with open(os.path.join(REPO_ROOT, "tools", tool)) as f:
                renderers += f.read()
        orphaned = sorted(n for n in names if n not in renderers)
        assert not orphaned, \
            f"metrics emitted but rendered nowhere: {orphaned}"
