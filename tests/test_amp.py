"""AMP: dygraph auto_cast + GradScaler; static bf16 rewrite + loss scaling.

Mirrors reference test_imperative_auto_mixed_precision.py /
test_mixed_precision (contrib) coverage points at smoke scale.
"""

import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import layers
from paddle_tpu.amp import GradScaler, auto_cast
from paddle_tpu.dygraph import guard, to_variable


def test_auto_cast_runs_matmul_in_bf16():
    import jax.numpy as jnp

    with guard():
        x = to_variable(np.random.randn(4, 8).astype(np.float32))
        w = to_variable(np.random.randn(8, 8).astype(np.float32))
        with auto_cast():
            from paddle_tpu.dygraph.tracer import trace_op

            out = trace_op("matmul_v2", {"X": x, "Y": w}, {})["Out"][0]
            assert out._array.dtype == jnp.bfloat16
        # outside the context: fp32 again
        out2 = trace_op("matmul_v2", {"X": x, "Y": w}, {})["Out"][0]
        assert out2._array.dtype == jnp.float32


def test_auto_cast_training_converges_and_grads_fp32():
    rng = np.random.RandomState(0)
    xs = rng.randn(32, 8).astype(np.float32)
    ys = np.argmax(xs[:, :4], axis=1).astype(np.int64)
    with guard():
        net = nn.Linear(8, 4)
        opt = pt.optimizer.SGDOptimizer(0.5, parameter_list=net.parameters())
        loss_fn = nn.CrossEntropyLoss()
        losses = []
        for _ in range(20):
            with auto_cast():
                logits = net(to_variable(xs))
                loss = loss_fn(logits, to_variable(ys))
            loss.backward()
            # params + their grads must stay fp32 (master weights)
            for p in net.parameters():
                assert np.dtype(p.dtype) == np.float32
                assert np.dtype(p.grad.dtype) == np.float32
            opt.minimize(loss)
            net.clear_gradients()
            losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_grad_scaler_scales_and_recovers():
    with guard():
        net = nn.Linear(4, 2)
        opt = pt.optimizer.SGDOptimizer(0.1, parameter_list=net.parameters())
        scaler = GradScaler(init_loss_scaling=64.0,
                            decr_every_n_nan_or_inf=1)
        x = to_variable(np.ones((2, 4), np.float32))
        w_before = net.weight.numpy().copy()
        loss = net(x).mean()
        scaled = scaler.scale(loss)
        assert abs(float(scaled.numpy()) - 64.0 * float(loss.numpy())) < 1e-3
        scaled.backward()
        scaler.minimize(opt, scaled)
        net.clear_gradients()
        # grads were unscaled before the update: the step must equal a
        # plain lr*grad step, not 64x it
        delta = np.abs(net.weight.numpy() - w_before).max()
        assert delta < 0.1, delta


def test_grad_scaler_skips_on_overflow():
    with guard():
        net = nn.Linear(4, 2)
        opt = pt.optimizer.SGDOptimizer(0.1, parameter_list=net.parameters())
        scaler = GradScaler(init_loss_scaling=64.0, decr_every_n_nan_or_inf=1)
        w_before = net.weight.numpy().copy()
        x = to_variable(np.ones((2, 4), np.float32))
        loss = net(x).mean()
        scaled = scaler.scale(loss)
        scaled.backward()
        # poison a grad with inf
        net.weight.grad._array = net.weight.grad._array * np.inf
        scaler.minimize(opt, scaled)
        np.testing.assert_array_equal(net.weight.numpy(), w_before)
        assert scaler.get_loss_scaling() == 32.0  # halved after 1 bad step


def test_static_bf16_rewrite_inserts_casts(scope):
    from paddle_tpu.contrib.mixed_precision import decorate

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(x, 16, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, 4), label))
        opt = decorate(pt.optimizer.SGDOptimizer(0.1),
                       use_dynamic_loss_scaling=True)
        opt.minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert "cast" in types
    assert "check_finite_and_unscale" in types
    assert "update_loss_scaling" in types
    # trains without NaN
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"x": np.random.randn(8, 8).astype(np.float32),
            "label": np.random.randint(0, 4, (8, 1)).astype(np.int64)}
    losses = []
    for _ in range(10):
        lv, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        losses.append(float(lv))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
