"""DynamicRNN / lod_rank_table / tensor-array ops (VERDICT r2 #5).

Reference surface: layers/control_flow.py DynamicRNN + lod_rank_table,
controlflow/tensor_array_read_write.cc. Padded-dense contract: memories
freeze at each row's length; outputs zero past it; grads flow through
exactly the live steps."""

import numpy as np
import pytest


def _fresh():
    import paddle_tpu as pt
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()
    return pt.Program(), pt.Program()


class TestArrayOps:
    def test_read_write_roundtrip(self):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        buf = jnp.zeros((4, 2, 3))
        v = jnp.ones((2, 3))
        w = registry.lookup("array_write").forward(
            {"X": [buf], "I": [jnp.int32(2)], "V": [v]}, {})["Out"]
        r = registry.lookup("array_read").forward(
            {"X": [w], "I": [jnp.int32(2)]}, {})["Out"]
        np.testing.assert_allclose(r, v)
        assert float(jnp.sum(w)) == 6.0

    def test_lod_rank_table(self):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        out = registry.lookup("lod_rank_table").forward(
            {"X": [jnp.asarray([2, 5, 0, 5], jnp.int64)]}, {})
        np.testing.assert_array_equal(out["Items"], [5, 5, 2, 0])
        np.testing.assert_array_equal(out["Index"], [1, 3, 0, 2])
        assert out["Index"].dtype == np.int32


class TestDynamicRNN:
    def _build(self):
        import paddle_tpu as pt
        from paddle_tpu import layers

        main, startup = _fresh()
        B, S, D, H = 4, 6, 3, 5
        with pt.program_guard(main, startup):
            x = layers.data("x", [S, D], stop_gradient=True)
            ln = layers.data("len", [], dtype="int64", stop_gradient=True)
            label = layers.data("label", [H], stop_gradient=True)
            drnn = layers.DynamicRNN()
            with drnn.block():
                w = drnn.step_input(x, length=ln)
                prev = drnn.memory(shape=[H])
                inp = layers.concat([w, prev], axis=1)
                h = layers.fc(inp, H, act="tanh",
                              param_attr=pt.ParamAttr(
                                  name="rnn_w",
                                  initializer=pt.initializer.Xavier(
                                      seed=3)),
                              bias_attr=pt.ParamAttr(name="rnn_b"))
                drnn.update_memory(prev, h)
                drnn.output(h)
            seq_out = drnn()
            final = drnn.final_memories()[0]
            diff = final - label
            loss = layers.mean(diff * diff)
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        return main, startup, seq_out, final, loss

    def _oracle(self, xv, lv, w, b):
        B, S, D = xv.shape
        H = b.shape[0]
        out = np.zeros((B, S, H), np.float32)
        mem = np.zeros((B, H), np.float32)
        for bi in range(B):
            h = np.zeros(H, np.float32)
            for t in range(int(lv[bi])):
                h = np.tanh(np.concatenate([xv[bi, t], h]) @ w + b)
                out[bi, t] = h
            mem[bi] = h
        return out, mem

    def test_matches_oracle_and_trains(self):
        import paddle_tpu as pt

        main, startup, seq_out, final, loss = self._build()
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(0)
        B, S, D, H = 4, 6, 3, 5
        xv = rng.randn(B, S, D).astype(np.float32)
        lv = np.array([6, 3, 1, 0], np.int64)
        lab = rng.randn(B, H).astype(np.float32)
        w = np.asarray(scope.find_var("rnn_w")).copy()
        b = np.asarray(scope.find_var("rnn_b")).copy()

        losses = []
        for step in range(6):
            o, f, l = exe.run(main,
                              feed={"x": xv, "len": lv, "label": lab},
                              fetch_list=[seq_out, final, loss],
                              scope=scope)
            if step == 0:
                want_o, want_f = self._oracle(xv, lv, w, b)
                np.testing.assert_allclose(np.asarray(o), want_o,
                                           rtol=1e-4, atol=1e-5)
                np.testing.assert_allclose(np.asarray(f), want_f,
                                           rtol=1e-4, atol=1e-5)
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        # grads flow through the live steps: training reduces the loss
        # monotonically (the zero-length row's target is unreachable, so
        # part of the loss is irreducible)
        assert all(b < a for a, b in zip(losses, losses[1:])), losses
        assert losses[-1] < losses[0] * 0.9, losses

    def test_zero_length_rows_contribute_nothing(self):
        import paddle_tpu as pt

        main, startup, seq_out, final, loss = self._build()
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(1)
        B, S, D, H = 4, 6, 3, 5
        xv = rng.randn(B, S, D).astype(np.float32)
        lab = rng.randn(B, H).astype(np.float32)
        o, f = exe.run(main, feed={"x": xv,
                                   "len": np.zeros(B, np.int64),
                                   "label": lab},
                       fetch_list=[seq_out, final], scope=scope)
        np.testing.assert_allclose(np.asarray(o), 0.0)
        np.testing.assert_allclose(np.asarray(f), 0.0)
