"""Pipelined multi-step execution (K-step fused dispatch) tests.

The executor's run_steps fuses K training steps into one jitted
lax.scan dispatch; these tests pin the contract: bitwise parity with K
sequential run() calls (params AND losses, under buffer donation,
single-device and on a 2-device dp mesh), async-fetch semantics
(sync_fetch=False), stacked-feed shape validation, the fused on-device
NaN/Inf check, the reader's sharding-aware prefetch, and the
train_from_dataset / bench auto-stacking loops.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import telemetry
from paddle_tpu.core.executor import ExecutionError


def _mlp_program(optimizer="adam", hidden=32):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = layers.data("img", [784])
        h = layers.fc(img, hidden, act="relu")
        label = layers.data("label", [1], dtype="int64")
        logits = layers.fc(h, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        if optimizer == "adam":
            pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
        else:
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


def _feeds(k, n=16, seed=0):
    rng = np.random.RandomState(seed)
    return [{"img": rng.randn(n, 784).astype(np.float32),
             "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}
            for _ in range(k)]


def _stack(feeds):
    return {n: np.stack([f[n] for f in feeds]) for n in feeds[0]}


def _clone_scope(src):
    """Independent host copies — donated buffers must not be shared
    between the sequential and fused scopes."""
    dst = pt.Scope()
    for n, v in list(src.items()):
        dst.set(n, np.array(np.asarray(v)))
    return dst


def _init(main, startup):
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    return exe, scope


def _assert_scopes_bitwise(s1, s2):
    names = sorted(set(s1.local_var_names()) & set(s2.local_var_names()))
    assert names
    for n in names:
        a, b = np.asarray(s1.find_var(n)), np.asarray(s2.find_var(n))
        assert a.dtype == b.dtype and a.shape == b.shape, n
        assert np.array_equal(a, b), (
            f"{n} diverged: max abs diff "
            f"{np.max(np.abs(a.astype(np.float64) - b.astype(np.float64)))}")


class TestFusedParity:
    @pytest.mark.parametrize("k", [1, 4])
    def test_bitwise_parity_vs_sequential(self, k):
        """run_steps(k) == k sequential run() calls, bit for bit: every
        persistable (params + adam moments + step counter) and every
        per-step loss — state donated on both paths."""
        main, startup, loss = _mlp_program("adam")
        exe, s_seq = _init(main, startup)
        s_fused = _clone_scope(s_seq)
        feeds = _feeds(k, seed=3)

        seq_losses = [exe.run(main, feed=f, fetch_list=[loss], scope=s_seq)[0]
                      for f in feeds]
        fused = exe.run_steps(main, feed=_stack(feeds), fetch_list=[loss],
                              k=k, scope=s_fused)
        assert fused[0].shape[0] == k
        for i, sl in enumerate(seq_losses):
            assert np.array_equal(np.asarray(sl).reshape(()), fused[0][i])
        _assert_scopes_bitwise(s_seq, s_fused)
        assert int(np.asarray(s_fused.find_var("@STEP_COUNTER@"))) == \
            int(np.asarray(s_seq.find_var("@STEP_COUNTER@")))

    def test_bitwise_parity_on_dp_mesh(self):
        """Same parity under a 2-device data-parallel mesh: the fused
        scan shards the per-step batch dim (dim 1 of the stacked feed)
        over dp exactly like single steps shard dim 0."""
        import jax
        from paddle_tpu.parallel import mesh as mesh_mod

        mesh_mod.create_mesh({"dp": 2}, devices=jax.devices()[:2])
        main, startup, loss = _mlp_program("sgd")
        exe, s_seq = _init(main, startup)
        s_fused = _clone_scope(s_seq)
        feeds = _feeds(4, seed=7)

        seq_losses = [exe.run(main, feed=f, fetch_list=[loss], scope=s_seq)[0]
                      for f in feeds]
        fused = exe.run_steps(main, feed=_stack(feeds), fetch_list=[loss],
                              k=4, scope=s_fused)
        for i, sl in enumerate(seq_losses):
            assert np.array_equal(np.asarray(sl).reshape(()), fused[0][i])
        _assert_scopes_bitwise(s_seq, s_fused)

    def test_fused_telemetry_and_cache(self):
        """Each k gets its own compile-cache entry; repeat dispatches are
        cache hits; fused_steps counts device steps not dispatches."""
        main, startup, loss = _mlp_program("sgd")
        exe, scope = _init(main, startup)
        feeds = _feeds(4, seed=1)
        d0 = telemetry.counter_get("executor.fused_dispatches")
        s0 = telemetry.counter_get("executor.fused_steps")
        misses0 = telemetry.counter_get("executor.cache_misses")
        exe.run_steps(main, feed=_stack(feeds), fetch_list=[loss], scope=scope)
        exe.run_steps(main, feed=_stack(feeds), fetch_list=[loss], scope=scope)
        exe.run_steps(main, feed=_stack(feeds[:2]), fetch_list=[loss], k=2,
                      scope=scope)
        assert telemetry.counter_get("executor.fused_dispatches") - d0 == 3
        assert telemetry.counter_get("executor.fused_steps") - s0 == 10
        # k=4 compile + k=2 compile, second k=4 dispatch is a hit
        assert telemetry.counter_get("executor.cache_misses") - misses0 == 2


class TestStackedFeedValidation:
    def test_unstacked_feed_raises(self):
        main, startup, loss = _mlp_program("sgd")
        exe, scope = _init(main, startup)
        f = _feeds(1)[0]
        with pytest.raises(ExecutionError, match=r"stacked \[k, \.\.\.\]"):
            exe.run_steps(main, feed=f, fetch_list=[loss], k=4, scope=scope)

    def test_mismatched_k_raises(self):
        main, startup, loss = _mlp_program("sgd")
        exe, scope = _init(main, startup)
        stacked = _stack(_feeds(3))
        with pytest.raises(ExecutionError, match="k=4"):
            exe.run_steps(main, feed=stacked, fetch_list=[loss], k=4,
                          scope=scope)

    def test_k_inferred_from_feed(self):
        main, startup, loss = _mlp_program("sgd")
        exe, scope = _init(main, startup)
        out = exe.run_steps(main, feed=_stack(_feeds(2)), fetch_list=[loss],
                            scope=scope)
        assert out[0].shape == (2,)

    def test_no_feed_needs_explicit_k(self):
        main, startup, loss = _mlp_program("sgd")
        exe, scope = _init(main, startup)
        with pytest.raises(ExecutionError, match="needs k="):
            exe.run_steps(main, feed={}, fetch_list=[loss], scope=scope)

    def test_bad_k_raises(self):
        main, startup, loss = _mlp_program("sgd")
        exe, scope = _init(main, startup)
        with pytest.raises(ExecutionError, match="k must be >= 1"):
            exe.run_steps(main, feed={}, fetch_list=[loss], k=0, scope=scope)


class TestAsyncFetch:
    def test_sync_fetch_false_returns_device_arrays(self):
        import jax

        main, startup, loss = _mlp_program("sgd")
        exe, scope = _init(main, startup)
        f = _feeds(1)[0]
        a0 = telemetry.counter_get("executor.async_fetches")
        out = exe.run(main, feed=f, fetch_list=[loss], scope=scope,
                      sync_fetch=False)
        assert isinstance(out[0], jax.Array)
        assert not isinstance(out[0], np.ndarray)
        assert telemetry.counter_get("executor.async_fetches") == a0 + 1
        # the device value materializes to the same loss a synced run of
        # the same state would have produced
        assert np.isfinite(float(np.asarray(out[0])))

    def test_run_steps_async_fetch(self):
        import jax

        main, startup, loss = _mlp_program("sgd")
        exe, scope = _init(main, startup)
        out = exe.run_steps(main, feed=_stack(_feeds(3)), fetch_list=[loss],
                            scope=scope, sync_fetch=False)
        assert isinstance(out[0], jax.Array)
        assert out[0].shape == (3,)

    def test_async_fetch_values_match_sync(self):
        main, startup, loss = _mlp_program("sgd")
        exe, s1 = _init(main, startup)
        s2 = _clone_scope(s1)
        f = _feeds(1, seed=5)[0]
        sync = exe.run(main, feed=f, fetch_list=[loss], scope=s1)
        async_ = exe.run(main, feed=f, fetch_list=[loss], scope=s2,
                         sync_fetch=False)
        assert np.array_equal(np.asarray(sync[0]), np.asarray(async_[0]))


class TestFusedNanInfCheck:
    def test_fused_check_names_bad_var(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [2], stop_gradient=True)
            y = layers.log(x)   # log(-1) -> NaN
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        pt.set_flags({"FLAGS_check_nan_inf": True})
        try:
            with pytest.raises(ExecutionError, match="NaN/Inf"):
                exe.run(main, feed={"x": -np.ones((1, 2), np.float32)},
                        fetch_list=[y], scope=scope)
        finally:
            pt.set_flags({"FLAGS_check_nan_inf": False})

    def test_fused_check_covers_run_steps(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [2], stop_gradient=True)
            y = layers.log(x)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        pt.set_flags({"FLAGS_check_nan_inf": True})
        try:
            with pytest.raises(ExecutionError, match="NaN/Inf"):
                exe.run_steps(
                    main, feed={"x": -np.ones((2, 1, 2), np.float32)},
                    fetch_list=[y], k=2, scope=scope)
            # clean feeds pass the same check
            out = exe.run_steps(
                main, feed={"x": np.ones((2, 1, 2), np.float32)},
                fetch_list=[y], k=2, scope=scope)
            assert np.all(np.isfinite(out[0]))
        finally:
            pt.set_flags({"FLAGS_check_nan_inf": False})


class TestReaderShardingPrefetch:
    def test_prefetch_uses_mesh_sharding(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.parallel import mesh as mesh_mod
        from paddle_tpu.reader import _prefetch_device_put

        mesh = mesh_mod.create_mesh({"dp": 2}, devices=jax.devices()[:2])
        batch = {"img": np.zeros((8, 4), np.float32),
                 "odd": np.zeros((7, 4), np.float32),   # not dp-divisible
                 "scalar": np.float32(1.0)}
        out = _prefetch_device_put(batch)
        assert out["img"].sharding.is_equivalent_to(
            NamedSharding(mesh, P("dp")), 2)
        # ragged / scalar entries replicate (executor fallback parity)
        assert out["odd"].sharding.is_equivalent_to(
            NamedSharding(mesh, P()), 2)
        assert out["scalar"].sharding.is_equivalent_to(
            NamedSharding(mesh, P()), 0)

    def test_prefetch_no_mesh_plain_device_put(self):
        import jax
        from paddle_tpu.reader import _prefetch_device_put

        out = _prefetch_device_put({"x": np.ones((4, 2), np.float32)})
        assert isinstance(out["x"], jax.Array)

    def test_generator_loader_yields_sharded(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from paddle_tpu.parallel import mesh as mesh_mod
        from paddle_tpu.reader import DataLoader

        mesh = mesh_mod.create_mesh({"dp": 2}, devices=jax.devices()[:2])
        loader = DataLoader.from_generator(capacity=2, return_list=True)
        loader.set_batch_generator(
            lambda: iter([np.ones((8, 3), np.float32)]))
        batches = list(loader)
        assert len(batches) == 1
        arr = batches[0][0]
        assert arr.sharding.is_equivalent_to(NamedSharding(mesh, P("dp")), 2)


class TestTrainFromDatasetStacking:
    def _dataset_and_prog(self, tmp_path, rows=24, batch=4, feat=8):
        """MultiSlot files + a 2-slot classifier program (the
        test_native_dataset fixture geometry)."""
        files = []
        rng = np.random.RandomState(7)
        path = str(tmp_path / "part-0")
        with open(path, "w") as f:
            for _ in range(rows):
                vals = rng.randn(feat).astype(np.float32)
                label = int(rng.randint(0, 4))
                f.write(f"{feat} " + " ".join(f"{v:.6f}" for v in vals)
                        + f" 1 {label}\n")
        files.append(path)

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            feat_v = layers.data("feat", [feat], stop_gradient=True)
            label = layers.data("label", [1], dtype="int64",
                                stop_gradient=True)
            h = layers.fc(feat_v, 16, act="relu")
            logits = layers.fc(h, 4)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.SGDOptimizer(0.2).minimize(loss)

        dataset = pt.DatasetFactory().create_dataset("InMemoryDataset")
        dataset.set_batch_size(batch)
        dataset.set_use_var([feat_v, label])
        dataset.set_filelist(files)
        dataset.load_into_memory()
        return main, startup, loss, dataset

    def test_fused_loop_matches_sequential(self, tmp_path):
        main, startup, loss, ds = self._dataset_and_prog(tmp_path)
        exe, s_seq = _init(main, startup)
        s_fused = _clone_scope(s_seq)

        seq = exe.train_from_dataset(main, ds, scope=s_seq,
                                     fetch_list=[loss])
        pt.set_flags({"FLAGS_exec_steps_per_dispatch": 3})
        try:
            fused = exe.train_from_dataset(main, ds, scope=s_fused,
                                           fetch_list=[loss])
        finally:
            pt.set_flags({"FLAGS_exec_steps_per_dispatch": 1})
        assert np.array_equal(np.asarray(seq[0]), np.asarray(fused[0]))
        _assert_scopes_bitwise(s_seq, s_fused)

    def test_ragged_tail_runs_unfused(self, tmp_path):
        """28 rows / batch 4 = 7 batches at k=3 → two fused dispatches,
        one tail batch run singly."""
        main, startup, loss, ds = self._dataset_and_prog(tmp_path, rows=28)
        exe, scope = _init(main, startup)
        d0 = telemetry.counter_get("executor.fused_dispatches")
        pt.set_flags({"FLAGS_exec_steps_per_dispatch": 3})
        try:
            exe.train_from_dataset(main, ds, scope=scope, fetch_list=[loss])
        finally:
            pt.set_flags({"FLAGS_exec_steps_per_dispatch": 1})
        assert telemetry.counter_get("executor.fused_dispatches") - d0 == 2

    def test_exec_strategy_drop_scope_maps_to_fusion(self, tmp_path):
        """A CompiledProgram's ExecutionStrategy.num_iteration_per_drop_
        scope drives K-step fusion when the flag is unset (reference
        knob parity)."""
        from paddle_tpu.core.compiler import CompiledProgram, \
            ExecutionStrategy

        main, startup, loss, ds = self._dataset_and_prog(tmp_path)
        exe, scope = _init(main, startup)
        es = ExecutionStrategy()
        es.num_iteration_per_drop_scope = 2
        cp = CompiledProgram(main)
        cp._exec_strategy = es
        d0 = telemetry.counter_get("executor.fused_dispatches")
        exe.train_from_dataset(cp, ds, scope=scope, fetch_list=[loss])
        assert telemetry.counter_get("executor.fused_dispatches") - d0 == 3


class TestHapiAsyncLoss:
    def test_train_batch_sync_false_returns_device_loss(self):
        import jax
        from paddle_tpu import nn
        from paddle_tpu.hapi import Model

        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 3, (8,)).astype(np.int64)
        with pt.dygraph.guard():
            net = nn.Linear(4, 3)
            model = Model(net)
            model.prepare(
                optimizer=pt.optimizer.SGDOptimizer(
                    0.1, parameter_list=net.parameters()),
                loss=nn.CrossEntropyLoss())
            out = model.train_batch([x], [y], sync=False)
            assert isinstance(out[0], jax.Array)
            out_sync = model.train_batch([x], [y])
            assert isinstance(out_sync[0], float)
            assert np.isfinite(float(np.asarray(out[0])))

    def test_fit_defers_loss_sync_to_log_steps(self):
        """fit with log_freq>1 runs async between log points and still
        trains (finite weights, finite logged loss)."""
        from paddle_tpu import nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.reader import TensorDataset

        rng = np.random.RandomState(0)
        xs = rng.randn(32, 4).astype(np.float32)
        ys = rng.randint(0, 3, (32,)).astype(np.int64)
        with pt.dygraph.guard():
            net = nn.Linear(4, 3)
            model = Model(net)
            model.prepare(
                optimizer=pt.optimizer.SGDOptimizer(
                    0.05, parameter_list=net.parameters()),
                loss=nn.CrossEntropyLoss())
        model.fit(TensorDataset([xs, ys]), batch_size=8, epochs=1,
                  log_freq=4, verbose=0)
        with pt.dygraph.guard():
            w = np.asarray(net.parameters()[0].numpy())
        assert np.all(np.isfinite(w))


class TestBenchHarnessFused:
    def test_time_steps_fused_window(self, scope):
        """tools/bench_models._time_steps under
        FLAGS_exec_steps_per_dispatch=2 drives run_steps dispatches and
        returns a sane ms/step + finite loss."""
        import sys, os
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        from tools.bench_models import _time_steps

        main, startup, loss = _mlp_program("sgd")
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        f = _feeds(1, seed=2)[0]
        d0 = telemetry.counter_get("executor.fused_dispatches")
        pt.set_flags({"FLAGS_exec_steps_per_dispatch": 2})
        try:
            ms, lv = _time_steps(exe, main, f, loss, scope, steps=5,
                                 windows=1, warmup=1)
        finally:
            pt.set_flags({"FLAGS_exec_steps_per_dispatch": 1})
        assert ms > 0 and np.isfinite(lv)
        assert telemetry.counter_get("executor.fused_dispatches") > d0

    def test_bench_extra_records_steps_per_dispatch(self):
        from tools.bench_models import finalize_bench_result

        pt.set_flags({"FLAGS_exec_steps_per_dispatch": 4})
        try:
            out = finalize_bench_result(
                {"metric": "m", "value": 1.0, "unit": "x",
                 "extra": {"ms_per_step": 1.0}})
        finally:
            pt.set_flags({"FLAGS_exec_steps_per_dispatch": 1})
        assert out["extra"]["steps_per_dispatch"] == 4


class TestPerfReportFusedSection:
    def test_fused_section_renders(self, tmp_path):
        import io
        import sys, os
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        from tools.perf_report import load, render, summarize_log

        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        try:
            main, startup, loss = _mlp_program("sgd")
            exe, scope = _init(main, startup)
            feeds = _feeds(4, seed=9)
            exe.run(main, feed=feeds[0], fetch_list=[loss], scope=scope)
            exe.run(main, feed=feeds[0], fetch_list=[loss], scope=scope)
            exe.run_steps(main, feed=_stack(feeds), fetch_list=[loss],
                          scope=scope)
            exe.run_steps(main, feed=_stack(feeds), fetch_list=[loss],
                          scope=scope)
        finally:
            telemetry.configure(None)
        s = summarize_log(load(str(log)))
        assert s["fused"] is not None
        assert s["fused"]["dispatches"] == 2
        assert s["fused"]["fused_steps"] == 8
        assert s["fused"]["steps_per_dispatch"] == 4.0
        # one non-compile single-step run observed → saved-ms estimate
        assert "host_dispatch_ms_saved" in s["fused"]
        buf = io.StringIO()
        render(s, out=buf)
        assert "fused dispatch" in buf.getvalue()

    def test_no_fused_section_without_fusion(self, tmp_path):
        from tools.perf_report import summarize_log

        s = summarize_log([{"ts": 1.0, "kind": "counter",
                            "name": "executor.cache_hits", "value": 1,
                            "attrs": {"delta": 1}}])
        assert s["fused"] is None
