"""CRF / CTC-align / edit-distance op family (VERDICT r2 #8).

Strategy mirrors the reference's unit tests
(test_linear_chain_crf_op.py — numpy brute-force oracle over all paths;
test_crf_decoding_op.py; test_ctc_align_op.py; test_edit_distance_op.py)
plus a tiny NER end-to-end fixture."""

import itertools

import numpy as np
import pytest

from op_test import OpTest


def _crf_brute(e, w, label, length):
    """Enumerate all tag paths: returns (nll per row, best path per row).
    e [B,S,T] f64, w [T+2,T], label [B,S], length [B]."""
    b, s, t = e.shape
    start_w, stop_w, trans = w[0], w[1], w[2:]
    nll = np.zeros(b)
    best = np.zeros((b, s), np.int64)
    for i in range(b):
        ln = int(length[i])
        scores = {}
        for path in itertools.product(range(t), repeat=ln):
            sc = start_w[path[0]] + e[i, 0, path[0]]
            for k in range(1, ln):
                sc += trans[path[k - 1], path[k]] + e[i, k, path[k]]
            sc += stop_w[path[-1]]
            scores[path] = sc
        arr = np.array(list(scores.values()))
        m = arr.max()
        log_z = m + np.log(np.exp(arr - m).sum())
        gold = tuple(int(x) for x in label[i, :ln])
        nll[i] = log_z - scores[gold]
        bp = max(scores, key=scores.get)
        best[i, :ln] = bp
    return nll, best


class TestLinearChainCRF(OpTest):
    op_type = "linear_chain_crf"

    def setup(self):
        rng = np.random.RandomState(7)
        b, s, t = 3, 4, 3
        e = rng.randn(b, s, t).astype(np.float32)
        w = (rng.randn(t + 2, t) * 0.5).astype(np.float32)
        label = rng.randint(0, t, (b, s)).astype(np.int64)
        length = np.array([4, 3, 2], np.int64)
        nll, _ = _crf_brute(e.astype(np.float64), w.astype(np.float64),
                            label, length)
        self.inputs = {"Emission": e, "Transition": w, "Label": label,
                       "Length": length}
        self.outputs = {"LogLikelihood": nll[:, None].astype(np.float32)}

    def test_output_vs_bruteforce(self):
        self.check_output(atol=1e-4, no_check_set=("Alpha", "Exps"))

    def test_numeric_grad(self):
        # the reference's analytic-grad check (test_linear_chain_crf_op
        # check_grad) — here vs central differences through the scan
        self.check_grad(["Emission", "Transition"], "LogLikelihood",
                        max_relative_error=0.01)


class TestCRFDecoding:
    def _decode(self, e, w, length, label=None):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        ins = {"Emission": [jnp.asarray(e)], "Transition": [jnp.asarray(w)],
               "Length": [jnp.asarray(length)]}
        if label is not None:
            ins["Label"] = [jnp.asarray(label)]
        return np.asarray(registry.lookup("crf_decoding").forward(
            ins, {})["ViterbiPath"])

    def test_viterbi_vs_bruteforce(self):
        rng = np.random.RandomState(3)
        b, s, t = 4, 5, 3
        e = rng.randn(b, s, t).astype(np.float32)
        w = (rng.randn(t + 2, t) * 0.7).astype(np.float32)
        length = np.array([5, 4, 2, 1], np.int64)
        _, best = _crf_brute(e.astype(np.float64), w.astype(np.float64),
                             np.zeros((b, s), np.int64), length)
        got = self._decode(e, w, length)
        for i in range(b):
            ln = int(length[i])
            np.testing.assert_array_equal(got[i, :ln], best[i, :ln],
                                          err_msg=f"row {i}")
            assert (got[i, ln:] == 0).all()

    def test_label_mode_correctness_mask(self):
        rng = np.random.RandomState(4)
        e = rng.randn(2, 4, 3).astype(np.float32)
        w = rng.randn(5, 3).astype(np.float32)
        length = np.array([4, 3], np.int64)
        path = self._decode(e, w, length)
        mask = self._decode(e, w, length, label=path)
        valid = np.arange(4)[None, :] < length[:, None]
        np.testing.assert_array_equal(mask, valid.astype(np.int64))


class TestCTCAlign(OpTest):
    op_type = "ctc_align"

    def setup(self):
        # reference test_ctc_align_op fixture style: merge repeats, drop
        # blanks (0), respect per-row lengths, pad with padding_value
        x = np.array([[0, 1, 1, 0, 2, 2, 0, 3],
                      [1, 1, 2, 0, 0, 3, 3, 0]], np.int32)
        length = np.array([8, 6], np.int64)
        out = np.array([[1, 2, 3, -1, -1, -1, -1, -1],
                        [1, 2, 3, -1, -1, -1, -1, -1]], np.int64)
        self.inputs = {"Input": x, "InputLength": length}
        self.attrs = {"blank": 0, "padding_value": -1}
        self.outputs = {"Output": out,
                        "OutputLength": np.array([[3], [3]], np.int32)}

    def test_output(self):
        self.check_output()


class TestEditDistance(OpTest):
    op_type = "edit_distance"

    @staticmethod
    def _lev(a, b):
        dp = np.arange(len(b) + 1, dtype=np.float64)
        for i, ca in enumerate(a):
            prev = dp.copy()
            dp[0] = i + 1
            for j, cb in enumerate(b):
                dp[j + 1] = min(prev[j + 1] + 1, dp[j] + 1,
                                prev[j] + (ca != cb))
        return dp[len(b)]

    def setup(self):
        rng = np.random.RandomState(11)
        b, s1, s2 = 4, 6, 5
        hyp = rng.randint(1, 5, (b, s1)).astype(np.int64)
        ref = rng.randint(1, 5, (b, s2)).astype(np.int64)
        hl = np.array([6, 4, 3, 1], np.int64)
        rl = np.array([5, 5, 2, 4], np.int64)
        want = np.array([[self._lev(hyp[i, :hl[i]], ref[i, :rl[i]])]
                         for i in range(b)], np.float32)
        self.inputs = {"Hyps": hyp, "Refs": ref, "HypsLength": hl,
                       "RefsLength": rl}
        self.attrs = {"normalized": False}
        self.outputs = {"Out": want,
                        "SequenceNum": np.array([b], np.int32)}

    def test_output(self):
        self.check_output()

    def test_normalized(self):
        self.setup()
        self.attrs = {"normalized": True}
        rl = self.inputs["RefsLength"]
        self.outputs = {"Out": (self.outputs["Out"]
                                / np.maximum(rl[:, None], 1)).astype(
                                    np.float32),
                        "SequenceNum": self.outputs["SequenceNum"]}
        inputs, attrs, outputs = self.inputs, self.attrs, self.outputs
        self.setup = lambda: (setattr(self, "inputs", inputs),
                              setattr(self, "attrs", attrs),
                              setattr(self, "outputs", outputs))
        self.check_output()


class TestSequenceExpandAs(OpTest):
    op_type = "sequence_expand_as"

    def setup(self):
        x = np.arange(6, dtype=np.float32).reshape(3, 2)
        ln = np.array([3, 0, 2], np.int64)
        out = np.zeros((3, 4, 2), np.float32)
        for i, n in enumerate(ln):
            out[i, :n] = x[i]
        self.inputs = {"X": x, "YLength": ln}
        self.attrs = {"max_len": 4}
        self.outputs = {"Out": out,
                        "OutLength": ln.astype(np.int32)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestSequenceTopkAvgPooling:
    def test_matches_reference_semantics(self):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        rng = np.random.RandomState(5)
        b, c, r, w = 2, 2, 3, 5
        x = rng.randn(b, c, r, w).astype(np.float32)
        row_ln = np.array([3, 2], np.int32)
        col_ln = np.array([5, 3], np.int32)
        topks = [1, 3]
        got = registry.lookup("sequence_topk_avg_pooling").forward(
            {"X": [jnp.asarray(x)], "ROW": [jnp.asarray(row_ln)],
             "COLUMN": [jnp.asarray(col_ln)]},
            {"topks": topks, "channel_num": c})
        out = np.asarray(got["Out"])
        assert out.shape == (b, r, c * len(topks))
        for i in range(b):
            for rr in range(r):
                for j in range(c):
                    vals = np.sort(x[i, j, rr, :col_ln[i]])[::-1]
                    for ki, k in enumerate(topks):
                        want = vals[:min(k, len(vals))].sum() / k
                        if rr >= row_ln[i]:
                            want = 0.0
                        np.testing.assert_allclose(
                            out[i, rr, j * len(topks) + ki], want,
                            rtol=1e-5, atol=1e-6)


class TestNERFixture:
    def test_tiny_ner_trains(self):
        """Tiny BiLSTM-free NER: embedding → fc emissions → CRF loss must
        decrease, and crf_decoding accuracy on the training batch must
        beat chance (the reference's sequence-labeling demo contract,
        e.g. test_linear_chain_crf layers usage)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        B, S, V, T = 8, 6, 30, 4
        with pt.program_guard(main, startup):
            words = layers.data("words", [S], dtype="int64",
                                stop_gradient=True)
            label = layers.data("label", [S], dtype="int64",
                                stop_gradient=True)
            length = layers.data("length", [], dtype="int64",
                                 stop_gradient=True)
            emb = layers.embedding(words, [V, 16])
            emission = layers.fc(emb, T, num_flatten_dims=2)
            nll = layers.linear_chain_crf(
                emission, label, length=length,
                param_attr=pt.ParamAttr(name="crf_trans"))
            loss = layers.mean(nll)
            decoded = layers.crf_decoding(
                emission, pt.ParamAttr(name="crf_trans"), length=length)
            pt.optimizer.AdamOptimizer(0.05).minimize(loss)

        rng = np.random.RandomState(0)
        w = rng.randint(0, V, (B, S)).astype(np.int64)
        y = (w % T).astype(np.int64)          # learnable mapping
        ln = rng.randint(3, S + 1, (B,)).astype(np.int64)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        losses = []
        for _ in range(30):
            out = exe.run(main, feed={"words": w, "label": y, "length": ln},
                          fetch_list=[loss, decoded], scope=scope)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        assert losses[-1] < losses[0] * 0.5, losses
        path = np.asarray(out[1])
        valid = np.arange(S)[None, :] < ln[:, None]
        acc = (path == y)[valid].mean()
        assert acc > 0.8, f"decode accuracy {acc}"


class TestRow6Ops:
    """pool3d / spectral_norm / affine_grid / hierarchical_sigmoid
    (coverage row 6 leftovers)."""

    def _fwd(self, op, ins, attrs):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        return registry.lookup(op).forward(
            {k: [jnp.asarray(v)] for k, v in ins.items()}, attrs)

    def test_pool3d(self):
        x = np.arange(2 * 1 * 4 * 4 * 4, dtype=np.float32).reshape(
            2, 1, 4, 4, 4)
        out = np.asarray(self._fwd("pool3d", {"X": x},
                                   {"ksize": [2, 2, 2],
                                    "pooling_type": "max"})["Out"])
        assert out.shape == (2, 1, 2, 2, 2)
        np.testing.assert_allclose(out[0, 0, 0, 0, 0], x[0, 0, :2, :2, :2].max())
        avg = np.asarray(self._fwd("pool3d", {"X": x},
                                   {"pooling_type": "avg",
                                    "global_pooling": True})["Out"])
        np.testing.assert_allclose(avg[1, 0, 0, 0, 0], x[1].mean(), rtol=1e-6)

    def test_spectral_norm(self):
        rng = np.random.RandomState(0)
        w = rng.randn(8, 6).astype(np.float32) * 3
        u = rng.randn(8).astype(np.float32)
        v = rng.randn(6).astype(np.float32)
        out = np.asarray(self._fwd(
            "spectral_norm", {"Weight": w, "U": u, "V": v},
            {"dim": 0, "power_iters": 50})["Out"])
        sv = np.linalg.svd(out, compute_uv=False)
        np.testing.assert_allclose(sv[0], 1.0, rtol=1e-4)

    def test_affine_grid_identity(self):
        theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], np.float32),
                        (2, 1, 1))
        grid = np.asarray(self._fwd(
            "affine_grid", {"Theta": theta},
            {"output_shape": [2, 1, 3, 5], "align_corners": True})["Output"])
        assert grid.shape == (2, 3, 5, 2)
        np.testing.assert_allclose(grid[0, 0, :, 0],
                                   np.linspace(-1, 1, 5), atol=1e-6)
        np.testing.assert_allclose(grid[0, :, 0, 1],
                                   np.linspace(-1, 1, 3), atol=1e-6)

    @pytest.mark.parametrize("c", [8, 6])
    def test_hierarchical_sigmoid_is_a_distribution(self, c):
        """sum_c exp(-cost(c)) == 1 for any x — the tree codes partition
        probability mass exactly (reference SimpleCode contract)."""
        rng = np.random.RandomState(1)
        b, d = 4, 5
        x = rng.randn(b, d).astype(np.float32)
        w = rng.randn(c - 1, d).astype(np.float32)
        bias = rng.randn(c - 1).astype(np.float32)
        total = np.zeros(b)
        for cls in range(c):
            label = np.full((b, 1), cls, np.int64)
            cost = np.asarray(self._fwd(
                "hierarchical_sigmoid",
                {"X": x, "W": w, "Label": label, "Bias": bias},
                {"num_classes": c})["Out"]).reshape(-1)
            total += np.exp(-cost)
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)
