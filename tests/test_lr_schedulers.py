"""LR schedules: fluid op-driven (layers.*_decay over the step counter) and
2.0 host-driven (optimizer.lr.LRScheduler.step()).

Mirrors reference test_learning_rate_scheduler.py: compares the in-program
schedule against a python reference at several steps.
"""

import math

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _run_schedule(make_lr, steps=6):
    """Build loss + schedule + SGD, run `steps`, return lr value per step."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        loss = layers.mean(layers.fc(x, 1))
        lr = make_lr()
        pt.optimizer.SGDOptimizer(lr).minimize(loss)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"x": np.zeros((2, 4), np.float32)}
    out = []
    for _ in range(steps):
        lv, = exe.run(main, feed=feed, fetch_list=[lr.name], scope=scope)
        out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out


def test_exponential_decay_matches_formula():
    got = _run_schedule(lambda: layers.exponential_decay(0.1, 2, 0.5))
    expect = [0.1 * 0.5 ** (s / 2.0) for s in range(6)]
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_exponential_decay_staircase():
    got = _run_schedule(
        lambda: layers.exponential_decay(0.1, 2, 0.5, staircase=True))
    expect = [0.1 * 0.5 ** (s // 2) for s in range(6)]
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_noam_decay_matches_formula():
    got = _run_schedule(lambda: layers.noam_decay(64, 4, learning_rate=2.0))
    expect = [2.0 * 64 ** -0.5 * min((s + 1) ** -0.5, (s + 1) * 4 ** -1.5)
              for s in range(6)]
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_piecewise_decay_boundaries():
    got = _run_schedule(
        lambda: layers.piecewise_decay([2, 4], [0.1, 0.01, 0.001]))
    np.testing.assert_allclose(got, [0.1, 0.1, 0.01, 0.01, 0.001, 0.001],
                               rtol=1e-6)


def test_polynomial_decay():
    got = _run_schedule(
        lambda: layers.polynomial_decay(0.1, 4, end_learning_rate=0.01,
                                        power=2.0))
    expect = [(0.1 - 0.01) * (1 - min(s, 4) / 4.0) ** 2 + 0.01
              for s in range(6)]
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_cosine_decay():
    got = _run_schedule(lambda: layers.cosine_decay(0.1, 2, 3))
    expect = [0.5 * 0.1 * (math.cos((s // 2) * math.pi / 3) + 1)
              for s in range(6)]
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_linear_warmup_wraps_schedule():
    got = _run_schedule(
        lambda: layers.linear_lr_warmup(
            layers.exponential_decay(0.1, 2, 0.5), 3, 0.0, 0.1))
    for s, v in enumerate(got):
        if s < 3:
            assert abs(v - 0.1 * s / 3.0) < 1e-7
        else:
            assert abs(v - 0.1 * 0.5 ** (s / 2.0)) < 1e-7


def test_scheduler_classes_host_driven():
    lr = pt.optimizer.lr.StepDecay(0.5, step_size=2, gamma=0.1)
    vals = []
    for _ in range(5):
        vals.append(lr())
        lr.step()
    np.testing.assert_allclose(vals, [0.5, 0.5, 0.05, 0.05, 0.005], rtol=1e-6)

    cos = pt.optimizer.lr.CosineAnnealingDecay(1.0, T_max=4)
    cos.step(2)
    assert abs(cos() - 0.5) < 1e-7

    warm = pt.optimizer.lr.LinearWarmup(
        pt.optimizer.lr.ExponentialDecay(0.1, 0.5), 2, 0.0, 0.1)
    warm.step(1)
    assert abs(warm() - 0.05) < 1e-9
    warm.step(4)  # 2 past warmup → wrapped at epoch 2
    assert abs(warm() - 0.1 * 0.25) < 1e-9


def test_reduce_on_plateau():
    lr = pt.optimizer.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
    for m in [1.0, 1.0, 1.0]:   # no improvement
        lr.step(m)
    assert abs(lr() - 0.05) < 1e-9


def test_scheduler_drives_static_optimizer():
    sched = pt.optimizer.lr.PiecewiseDecay([2], [0.1, 0.001])
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        loss = layers.mean(layers.fc(x, 1))
        opt = pt.optimizer.SGDOptimizer(sched)
        opt.minimize(loss)
    pt.core.scope.reset_global_scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, use_compiled=False)
    assert abs(opt.current_step_lr() - 0.1) < 1e-8
    sched.step()
    sched.step()
    assert abs(opt.current_step_lr() - 0.001) < 1e-8
