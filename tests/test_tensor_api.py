"""paddle.tensor-style 2.0 functional API tests — dual-mode dispatch
(reference: python/paddle/tensor/ function lib tests)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph, layers
from paddle_tpu import tensor as T


class TestDygraphTensorApi:
    def test_math_and_grad(self):
        with dygraph.guard():
            x = pt.to_tensor(np.arange(6.0).reshape(2, 3).astype(np.float32),
                             stop_gradient=False)
            y = T.matmul(x, T.transpose(x, [1, 0]))
            assert y.shape == [2, 2]
            # clip bounds strictly between samples (grads at exact
            # boundaries are subgradient 0.5 in jax)
            loss = T.sum(T.exp(T.clip(x, -0.5, 4.5)))
            loss.backward()
            g = x.gradient()
            base = np.arange(6.0).reshape(2, 3)
            want = np.exp(np.clip(base, -0.5, 4.5))
            want[base > 4.5] = 0
            np.testing.assert_allclose(g, want, rtol=1e-5)

    def test_creation_and_manipulation(self):
        with dygraph.guard():
            o = T.ones([2, 2])
            z = T.zeros_like(o)
            a = T.concat([o, z], axis=0)
            assert a.shape == [4, 2]
            st = T.stack([o, o], axis=0)
            assert st.shape == [2, 2, 2]
            parts = T.split(T.ones([4, 2]), 2, axis=0)
            assert len(parts) == 2 and parts[0].shape == [2, 2]
            v, i = T.topk(pt.to_tensor(np.array([3.0, 1.0, 2.0], np.float32)), 2)
            assert v.numpy().tolist() == [3.0, 2.0]
            assert i.numpy().tolist() == [0, 2]
            np.testing.assert_allclose(
                T.tril(T.ones([3, 3])).numpy(),
                np.tril(np.ones((3, 3))))
            r = T.arange(5, dtype="int32")
            assert r.numpy().tolist() == [0, 1, 2, 3, 4]

    def test_reductions_and_compare(self):
        with dygraph.guard():
            x = pt.to_tensor(np.array([[1.0, 5.0], [3.0, 2.0]], np.float32))
            assert float(T.max(x).numpy().reshape(-1)[0]) == 5.0
            m = T.mean(x, axis=0)
            np.testing.assert_allclose(m.numpy(), [2.0, 3.5])
            eq = T.greater_than(x, T.full([2, 2], 2.5))
            assert eq.numpy().astype(int).sum() == 2


class TestStaticTensorApi:
    def test_static_mode_builds_and_runs(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [3], stop_gradient=True)
            y = T.add(T.scale(x, scale=2.0), T.ones([1, 3]))
            s = T.sum(y)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        out, = exe.run(main, feed={"x": np.ones((1, 3), np.float32)},
                       fetch_list=[s], scope=scope)
        assert float(np.asarray(out).reshape(-1)[0]) == pytest.approx(9.0)

    def test_static_split(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=True)
            a, b = T.split(x, 2, axis=1)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        av, bv = exe.run(main, feed={"x": np.arange(4.0, dtype=np.float32)
                                     .reshape(1, 4)},
                         fetch_list=[a, b], scope=scope)
        np.testing.assert_allclose(av, [[0.0, 1.0]])
        np.testing.assert_allclose(bv, [[2.0, 3.0]])


class TestTensorApiEdgeCases:
    def test_topk_axis0(self):
        with dygraph.guard():
            x = pt.to_tensor(np.array([[3, 1], [0, 5], [2, 4]], np.float32))
            v, i = T.topk(x, 2, axis=0)
            np.testing.assert_allclose(v.numpy(), [[3, 5], [2, 4]])
            np.testing.assert_array_equal(i.numpy(), [[0, 1], [2, 2]])

    def test_arange_float_inference(self):
        with dygraph.guard():
            r = T.arange(0, 1, 0.25)
            np.testing.assert_allclose(r.numpy(), [0.0, 0.25, 0.5, 0.75])

    def test_clip_preserves_int_dtype(self):
        with dygraph.guard():
            x = pt.to_tensor(np.array([1, 5, 9], np.int32))
            y = T.clip(x, max=4)
            assert "int" in str(y.numpy().dtype)
            np.testing.assert_array_equal(y.numpy(), [1, 4, 4])

    def test_eye_zero_columns(self):
        with dygraph.guard():
            assert T.eye(3, 0).shape == [3, 0]

    def test_argmax_flatten_default(self):
        with dygraph.guard():
            x = pt.to_tensor(np.array([[1, 9], [3, 2]], np.float32))
            assert int(T.argmax(x).numpy().reshape(-1)[0]) == 1
            per_row = T.argmax(x, axis=1)
            np.testing.assert_array_equal(per_row.numpy(), [1, 0])
