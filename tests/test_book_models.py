"""Book-fixture model zoo (reference: tests/book/) — VGG16 and the two
understand_sentiment nets train end-to-end and learn."""

import numpy as np
import pytest


def _train(build, make_feed, steps, fetches_key="loss"):
    import paddle_tpu as pt

    main, startup, feeds, fetches = build
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    losses = []
    for s in range(steps):
        out = exe.run(main, feed=make_feed(s), fetch_list=[
            fetches[fetches_key]], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return losses


class TestVGG:
    def test_vgg16_trains(self):
        from paddle_tpu.models import vision_extra

        build = vision_extra.build_vgg_program(batch_size=4, lr=3e-4)

        def feed(s):
            return vision_extra.synthetic_batch(4, seed=0)  # memorise one

        losses = _train(build, feed, steps=12)
        assert all(np.isfinite(losses)), losses
        # dropout keeps single steps noisy; the trend must still drop
        assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses

    def test_vgg16_eval_mode_builds(self):
        from paddle_tpu.models import vision_extra

        main, startup, feeds, fetches = vision_extra.build_vgg_program(
            batch_size=2, is_test=True, with_optimizer=False)
        import paddle_tpu as pt

        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        out = exe.run(main, feed=vision_extra.synthetic_batch(2),
                      fetch_list=[fetches["loss"]], scope=scope)
        assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[0]))


class TestSentiment:
    @pytest.mark.parametrize("net", ["stacked_lstm", "conv"])
    def test_learns_vocab_halves(self, net):
        from paddle_tpu.models import sentiment

        build = sentiment.build_sentiment_program(net=net, batch_size=16)

        def feed(s):
            return sentiment.synthetic_batch(16, seed=s % 4)

        losses = _train(build, feed, steps=16)
        assert all(np.isfinite(losses)), losses
        # the half-vocab task is linearly separable — loss must drop
        assert np.mean(losses[-4:]) < 0.75 * np.mean(losses[:4]), losses
