"""ZeRO stage-1/2 sharded optimizer (fleet ShardingOptimizer) on the
8-virtual-device dp mesh: bitwise parity with grad-allreduce DP, the
1/dp optimizer-state memory claim (asserted via telemetry), run_steps
K-step fusion composition, and exact-resume checkpointing incl. a
reshard-on-load restore under a different rule table."""

import json

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import telemetry
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel import axis_rules, create_mesh
from paddle_tpu.parallel import mesh as meshmod

DP = 8


@pytest.fixture(autouse=True)
def _mesh():
    import jax

    if len(jax.devices()) < DP:
        pytest.skip(f"needs {DP} virtual devices")
    mesh = create_mesh({"dp": DP})
    yield mesh
    meshmod.set_mesh(None)


def _build(strategy=None, lr=0.1, opt_factory=None):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(x, 32, act="relu")
        logits = layers.fc(h, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = (opt_factory or pt.optimizer.SGDOptimizer)(lr)
        if strategy is not None:
            dopt = fleet.distributed_optimizer(opt, strategy)
            dopt.minimize(loss)
            return main, startup, loss, dopt
        opt.minimize(loss)
    return main, startup, loss, opt


def _zero_strategy(stage):
    s = fleet.DistributedStrategy()
    s.sharding = True
    s.sharding_configs = {"stage": stage}
    return s


def _feed(seed, n=16):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(n, 16).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


def _train(main, startup, loss, mesh, steps=3, scope=None, start_seed=0):
    exe = pt.Executor(pt.CPUPlace())
    sc = scope or pt.Scope()
    if scope is None:
        exe.run(startup, scope=sc, use_compiled=False)
    out = None
    for s in range(steps):
        out, = exe.run(main, feed=_feed(start_seed + s), fetch_list=[loss],
                       scope=sc, mesh=mesh)
    return sc, float(np.asarray(out).reshape(-1)[0])


def _params(main, sc):
    return {p.name: np.asarray(sc.find_var(p.name))
            for p in main.all_parameters()}


def _fresh():
    from paddle_tpu.core import unique_name

    unique_name.switch()


class TestZeroParity:
    def test_stage1_stage2_bitwise_vs_allreduce_dp(self, _mesh):
        """Final params after k steps are BITWISE identical to the classic
        scale+allreduce DP baseline, for both ZeRO stages (SGD)."""
        fleet.init(is_collective=True)
        main0, start0, loss0, _ = _build(fleet.DistributedStrategy())
        ops0 = [op.type for op in main0.global_block().ops]
        assert "c_allreduce_sum" in ops0
        sc0, l0 = _train(main0, start0, loss0, _mesh)
        base = _params(main0, sc0)
        for stage in (1, 2):
            _fresh()
            main, start, loss, _ = _build(_zero_strategy(stage))
            ops = [op.type for op in main.global_block().ops]
            assert "c_allgather" in ops and "c_scatter" in ops
            if stage == 2:
                assert "c_reducescatter" in ops
                assert "c_allreduce_sum" not in ops
            else:
                assert "c_allreduce_sum" in ops
            sc, l = _train(main, start, loss, _mesh)
            assert l == l0
            got = _params(main, sc)
            for name, want in base.items():
                np.testing.assert_array_equal(
                    want, got[name],
                    err_msg=f"stage {stage} param {name} diverged")

    def test_adam_stage2_bitwise_and_state_shrinks(self, _mesh, tmp_path):
        """Adam under ZeRO stage 2: bitwise param parity AND per-device
        optimizer-state bytes ~1/dp (telemetry gauges), with the dp
        collective payloads booked per dispatch."""
        log = tmp_path / "run.jsonl"
        fleet.init(is_collective=True)
        adam = lambda lr: pt.optimizer.AdamOptimizer(lr)  # noqa: E731
        main0, start0, loss0, _ = _build(fleet.DistributedStrategy(),
                                         opt_factory=adam)
        sc0, _ = _train(main0, start0, loss0, _mesh)
        base = _params(main0, sc0)

        _fresh()
        telemetry.configure(str(log))
        try:
            c_before = telemetry.counters()
            main, start, loss, dopt = _build(_zero_strategy(2),
                                             opt_factory=adam)
            sc, _ = _train(main, start, loss, _mesh)
            rep = dopt.inner.report_state_sharding(sc)
            counters = telemetry.counters()
            telemetry.flush_sink()
        finally:
            telemetry.configure(None)
        got = _params(main, sc)
        for name, want in base.items():
            np.testing.assert_array_equal(want, got[name])

        # moments shard 1/dp; only the [1] beta-pow scalars replicate
        assert rep["total_bytes"] > 0
        assert rep["per_device_bytes"] < rep["total_bytes"] / DP * 1.5
        # byte counters: 3 steps of reduce-scatter + allgather payloads
        rs = counters.get("sharding.reduce_scatter_bytes", 0) - \
            c_before.get("sharding.reduce_scatter_bytes", 0)
        ag = counters.get("sharding.allgather_bytes", 0) - \
            c_before.get("sharding.allgather_bytes", 0)
        n_payload = sum(-(-int(np.prod(p.shape)) // DP) * DP * 4
                        for p in main.all_parameters())
        assert rs == 3 * n_payload
        assert ag == 3 * n_payload

        # the run log renders a Sharding section in perf_report
        import importlib.util as _ilu
        import os
        import sys

        tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        spec = _ilu.spec_from_file_location(
            "perf_report", os.path.join(tools, "perf_report.py"))
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        recs, malformed = mod.load_counted(str(log))
        summary = mod.summarize_log(recs, malformed=malformed)
        assert summary["sharding"] is not None
        assert summary["sharding"]["zero_stage"] == 2
        assert summary["sharding"]["reduce_scatter_bytes"] > 0
        import io

        buf = io.StringIO()
        mod.render(summary, out=buf)
        assert "sharding (rule-table partitioning + ZeRO)" in buf.getvalue()

    def test_zero_smoke_reexec(self, _mesh):
        """Tiny stage-2 step (the subprocess re-exec fixture's ZeRO leg —
        test_mesh_reexec.py runs this under freshly-forced XLA_FLAGS)."""
        fleet.init(is_collective=True)
        _fresh()
        main, start, loss, dopt = _build(_zero_strategy(2))
        sc, l = _train(main, start, loss, _mesh, steps=2)
        assert np.isfinite(l)
        assert main._zero_stage == 2

    def test_grad_clip_rejected(self, _mesh):
        from paddle_tpu.distributed.fleet.meta_optimizers import \
            ShardingOptimizer

        opt = pt.optimizer.SGDOptimizer(0.1)
        opt._grad_clip = lambda pgs: pgs
        zo = ShardingOptimizer(opt, {"stage": 2}, nranks=DP)
        with pytest.raises(ValueError, match="grad_clip"):
            zo.apply_gradients([])

    def test_sharding_excludes_gradient_merge(self, _mesh):
        fleet.init(is_collective=True)
        s = _zero_strategy(1)
        s.gradient_merge = True
        with pytest.raises(ValueError, match="gradient_merge"):
            fleet.distributed_optimizer(pt.optimizer.SGDOptimizer(0.1), s)


class TestZeroRunSteps:
    def test_run_steps_fusion_bitwise(self, _mesh):
        """The ZeRO schedule lives inside the scanned step body: k=2
        fused dispatch == 2 sequential runs, bitwise."""
        fleet.init(is_collective=True)
        _fresh()
        main, start, loss, _ = _build(_zero_strategy(2))
        sc_seq, _ = _train(main, start, loss, _mesh, steps=4)
        exe = pt.Executor(pt.CPUPlace())
        sc_fused = pt.Scope()
        exe.run(start, scope=sc_fused, use_compiled=False)
        feeds = [_feed(s) for s in range(4)]
        for i in (0, 2):
            stacked = {n: np.stack([f[n] for f in feeds[i:i + 2]])
                       for n in feeds[0]}
            exe.run_steps(main, feed=stacked, fetch_list=[loss], k=2,
                          scope=sc_fused, mesh=_mesh)
        a, b = _params(main, sc_seq), _params(main, sc_fused)
        for name in a:
            np.testing.assert_array_equal(a[name], b[name])


class TestZeroCheckpoint:
    def test_exact_resume_with_sharded_state(self, _mesh, tmp_path):
        """PR 5 exact-resume protocol holds with ZeRO-sharded optimizer
        state: save mid-run, restore into a fresh scope, continue — final
        params bitwise-identical to the uninterrupted run. Momentum state
        makes a silently-lost accumulator visible."""
        from paddle_tpu import checkpoint as ckpt

        mom = lambda lr: pt.optimizer.MomentumOptimizer(lr, 0.9)  # noqa: E731
        fleet.init(is_collective=True)
        _fresh()
        main, start, loss, dopt = _build(_zero_strategy(2), opt_factory=mom)

        # uninterrupted: 4 steps
        sc_full, _ = _train(main, start, loss, _mesh, steps=4)
        want = _params(main, sc_full)

        # interrupted: 2 steps → checkpoint → fresh scope → 2 more
        sc_a, _ = _train(main, start, loss, _mesh, steps=2)
        path = str(tmp_path / "zero-ckpt")
        ckpt.save_checkpoint(path, program=main, scope=sc_a)
        manifest = json.load(open(f"{path}/MANIFEST.json"))
        sh = manifest["extras"]["sharding"]
        assert sh["zero_stage"] == 2
        assert sh["axis_rules"] == axis_rules.fingerprint()

        sc_b = pt.Scope()
        step = ckpt.load_checkpoint(path, program=main, scope=sc_b)
        # (the interpreted startup run advanced the counter once too)
        assert step == int(np.asarray(
            sc_a.find_var("@STEP_COUNTER@")).reshape(-1)[0])
        sc_b, _ = _train(main, start, loss, _mesh, steps=2, scope=sc_b,
                         start_seed=2)
        got = _params(main, sc_b)
        for name in want:
            np.testing.assert_array_equal(want[name], got[name])

    def test_restore_under_different_rule_table_resharding(self, _mesh,
                                                           tmp_path):
        """Restoring a ZeRO checkpoint under a DIFFERENT rule table counts
        a reshard-on-load event and continues bitwise-correct: arrays are
        saved at global shape, so the new table just changes the next
        compile's shardings."""
        from paddle_tpu import checkpoint as ckpt

        fleet.init(is_collective=True)
        _fresh()
        main, start, loss, _ = _build(_zero_strategy(1))
        sc_full, _ = _train(main, start, loss, _mesh, steps=3)
        want = _params(main, sc_full)

        sc_a, _ = _train(main, start, loss, _mesh, steps=2)
        path = str(tmp_path / "zero-ckpt-rt")
        ckpt.save_checkpoint(path, program=main, scope=sc_a)

        before = telemetry.counters().get("sharding.resharding_events", 0)
        with axis_rules.axis_rules([("batch", "dp")]):
            sc_b = pt.Scope()
            ckpt.load_checkpoint(path, program=main, scope=sc_b)
            after = telemetry.counters().get("sharding.resharding_events", 0)
            assert after == before + 1
            sc_b, _ = _train(main, start, loss, _mesh, steps=1, scope=sc_b,
                             start_seed=2)
        got = _params(main, sc_b)
        for name in want:
            np.testing.assert_array_equal(want[name], got[name])
