"""Child entry for test_distributed spawn tests (module-level so the
'spawn' start method can pickle it)."""

import json
import os


def write_env_info(out_dir):
    # sitecustomize pins JAX_PLATFORMS=axon; the env var alone is not
    # enough in a fresh interpreter — force the CPU platform via config
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

    import paddle_tpu.distributed as dist

    env = dist.ParallelEnv()
    initialized = dist.init_parallel_env()
    import jax

    info = {"rank": env.rank, "world_size": env.world_size,
            "initialized": initialized,
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "endpoints": env.trainer_endpoints,
            "current_endpoint": env.current_endpoint}
    with open(os.path.join(out_dir, f"rank{env.rank}.json"), "w") as f:
        json.dump(info, f)
    # barrier before exit: rank 0 hosts the coordination service — if it
    # returns first the service dies under the still-joining peers
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("spawn_fixture_done")


def crash_on_rank1(out_dir):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.distributed as dist

    if dist.ParallelEnv().rank == 1:
        raise RuntimeError("boom")  # peers are left blocked in rendezvous
    dist.init_parallel_env()  # blocks waiting for the crashed peer
