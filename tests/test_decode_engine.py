"""Generative decode engine tests (paddle_tpu/serving/decode.py +
kv_cache.py + models/decoder_lm.py).

Contracts under test:
* continuous-batched generation is BITWISE-identical to sequential
  one-request-at-a-time decode — greedy and temperature-sampled with
  pinned per-request RNG — because the step program runs at fixed
  slot-array shapes and sampling is host-side per row;
* the KV page pool's alloc/free accounting is exact under admit/retire
  churn (no double allocation, no leak, high-water tracked) and returns
  to baseline after every request resolves;
* a request whose worst-case page need can never fit is refused at
  submit with typed KVCacheExhaustedError (admission, not an OOM), and
  the pool's bytes are visible in the HBM ledger and /v1/stats;
* per-request deadlines are enforced at STEP granularity — an expired
  generation retires mid-flight with DeadlineExceededError and frees
  its pages without draining the batch;
* int8 weight-only serving is a config flip with the same bitwise
  continuous-vs-sequential guarantee;
* injected decode.step faults surface as per-request errors and the
  engine keeps serving (never a wedged queue);
* the HTTP front end exposes /v1/generate, decode stats and the
  pt_decode_* / pt_mem_serving_kv_* live metrics.
"""

import json
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.serving

CFG_KW = dict(vocab_size=97, d_model=32, n_head=2, n_layers=2,
              d_inner=64, max_seq_len=32)
POOL_KW = dict(max_slots=4, page_size=4, kv_pages=28, prefill_buckets=[8])


def _model_cfg(**over):
    from paddle_tpu.models.decoder_lm import DecoderLMConfig

    return DecoderLMConfig(**{**CFG_KW, **over})


@pytest.fixture(scope="module")
def lm_params():
    from paddle_tpu.models.decoder_lm import decoder_lm_params

    return decoder_lm_params(_model_cfg(), seed=0)


@pytest.fixture(scope="module")
def workload():
    rng = np.random.RandomState(7)
    prompts = [rng.randint(3, 96, rng.randint(2, 8)).astype(np.int32)
               for _ in range(6)]
    max_news = [5, 9, 4, 12, 7, 6]
    return prompts, max_news


@pytest.fixture(scope="module")
def engines(lm_params):
    """(continuous, sequential-use) engine pair sharing one param set —
    module-scoped so every test reuses the same jit entries."""
    from paddle_tpu.serving import DecodeConfig, DecodeEngine

    cont = DecodeEngine(_model_cfg(), lm_params,
                        DecodeConfig(**POOL_KW)).start()
    seq = DecodeEngine(_model_cfg(), lm_params,
                       DecodeConfig(**POOL_KW)).start()
    yield cont, seq
    cont.close(drain=True, timeout=30)
    seq.close(drain=True, timeout=30)


class TestBitwiseIdentity:
    def test_greedy_continuous_equals_sequential(self, engines, workload):
        """All requests submitted at once (continuous batching across
        admit/retire churn) vs the same requests run one at a time —
        generated token ids must be bitwise identical."""
        from paddle_tpu.core import telemetry

        cont, seq = engines
        prompts, max_news = workload
        steps_before = telemetry.counter_get("decode.steps")
        reqs = [cont.submit(p, max_new_tokens=m)
                for p, m in zip(prompts, max_news)]
        got = [r.result(timeout=120) for r in reqs]
        want = [seq.generate(p, max_new_tokens=m, timeout=120)
                for p, m in zip(prompts, max_news)]
        for i, (g, w) in enumerate(zip(got, want)):
            assert np.array_equal(g, w), \
                f"request {i}: continuous-batched decode diverged from " \
                f"sequential decode"
        # the continuous run actually batched: fewer steps than the
        # total token count (sequential pays one step per token)
        cont_tokens = sum(len(g) for g in got)
        assert telemetry.counter_get("decode.steps") - steps_before \
            < 2 * cont_tokens
        # slot churn left zero pages behind in BOTH pools
        for eng in engines:
            s = eng.pool.stats()
            assert s["pages_used"] == 0
            assert s["pages_free"] == s["pages_total"]
            assert s["high_water_pages"] > 0

    def test_sampled_pinned_rng_equals_sequential(self, engines, workload):
        """Temperature sampling with per-request seeds: token choice is
        a host-side pure function of (logits bits, own RNG stream), so
        scheduling must not perturb it either."""
        cont, seq = engines
        prompts, max_news = workload
        reqs = [cont.submit(p, max_new_tokens=m, temperature=0.8,
                            seed=100 + i)
                for i, (p, m) in enumerate(zip(prompts, max_news))]
        got = [r.result(timeout=120) for r in reqs]
        want = [seq.generate(p, max_new_tokens=m, temperature=0.8,
                             seed=100 + i, timeout=120)
                for i, (p, m) in enumerate(zip(prompts, max_news))]
        for g, w in zip(got, want):
            assert np.array_equal(g, w)

    def test_sampled_without_seed_rejected(self, engines):
        with pytest.raises(Exception) as ei:
            cont, _ = engines
            cont.generate(np.array([5, 6], np.int32), max_new_tokens=2,
                          temperature=0.7, timeout=30)
        assert "seed" in str(ei.value)


class TestCachedKVOps:
    """Numpy-oracle OpTests for the paged-cache ops
    (ops/attention_ops.py) — the registry-gate coverage for
    cached_kv_attention and kv_cache_write."""

    def test_kv_cache_write_places_tokens_and_masks_padding(self):
        from paddle_tpu.core.registry import lookup

        rng = np.random.RandomState(3)
        B, S, D, N, P, MP = 2, 6, 8, 10, 4, 3
        k = rng.randn(B, S, D).astype(np.float32)
        v = rng.randn(B, S, D).astype(np.float32)
        pool_k = rng.randn(N, P, D).astype(np.float32)
        pool_v = rng.randn(N, P, D).astype(np.float32)
        table = np.array([[3, 4, 0], [7, 2, 0]], np.int32)
        lengths = np.array([6, 3], np.int32)
        out = lookup("kv_cache_write").forward(
            {"K": [k], "V": [v], "PoolK": [pool_k], "PoolV": [pool_v],
             "PageTable": [table], "Lengths": [lengths]}, {})
        got_k = np.asarray(out["PoolKOut"])
        # every valid (b, s) landed at (table[b, s//P], s%P)
        for b in range(B):
            for s in range(int(lengths[b])):
                np.testing.assert_array_equal(
                    got_k[table[b, s // P], s % P], k[b, s])
        # pages NOT owned by either row are untouched (masked prompt
        # tail goes to the reserved scratch page 0)
        for p in set(range(N)) - {0, 2, 3, 4, 7}:
            np.testing.assert_array_equal(got_k[p], pool_k[p])

    def test_cached_kv_attention_matches_numpy_oracle(self):
        from paddle_tpu.core.registry import lookup

        rng = np.random.RandomState(4)
        B, D, N, P, MP, nh = 2, 8, 9, 4, 2, 2
        hd = D // nh
        q = rng.randn(B, D).astype(np.float32)
        k = rng.randn(B, D).astype(np.float32)
        v = rng.randn(B, D).astype(np.float32)
        pool_k = rng.randn(N, P, D).astype(np.float32)
        pool_v = rng.randn(N, P, D).astype(np.float32)
        table = np.array([[1, 2], [5, 6]], np.int32)
        pos = np.array([5, 2], np.int32)     # contexts of 6 and 3 tokens
        out = lookup("cached_kv_attention").forward(
            {"Q": [q], "K": [k], "V": [v], "PoolK": [pool_k],
             "PoolV": [pool_v], "PageTable": [table], "Positions": [pos]},
            {"num_heads": nh, "head_dim": hd})
        got = np.asarray(out["Out"])
        new_pk = np.asarray(out["PoolKOut"])
        new_pv = np.asarray(out["PoolVOut"])
        # the new token's K landed at (table[b, pos//P], pos%P)
        for b in range(B):
            np.testing.assert_array_equal(
                new_pk[table[b, pos[b] // P], pos[b] % P], k[b])
        for b in range(B):
            ctx_k = new_pk[table[b]].reshape(MP * P, nh, hd)
            ctx_v = new_pv[table[b]].reshape(MP * P, nh, hd)
            qh = q[b].reshape(nh, hd)
            scores = np.einsum("nh,snh->ns", qh, ctx_k) / np.sqrt(hd)
            scores[:, pos[b] + 1:] = -1e9    # future + stale masked out
            e = np.exp(scores - scores.max(-1, keepdims=True))
            probs = e / e.sum(-1, keepdims=True)
            want = np.einsum("ns,snh->nh", probs, ctx_v).reshape(-1)
            np.testing.assert_allclose(got[b], want, rtol=2e-5,
                                       atol=2e-6)


class TestPagePool:
    def test_alloc_free_invariants_under_churn(self):
        """Free-list exactness: no double allocation, no loss, high
        water monotone, full return to baseline."""
        from paddle_tpu.serving import KVPagePool

        pool = KVPagePool(n_layers=2, num_pages=17, page_size=4,
                          kv_dim=32)
        assert pool.capacity_pages == 16
        rng = np.random.RandomState(0)
        held = []
        for _ in range(200):
            if held and rng.rand() < 0.5:
                pool.free(held.pop(rng.randint(len(held))))
            else:
                got = pool.try_alloc(int(rng.randint(1, 4)))
                if got:
                    held.append(got)
            flat = [p for h in held for p in h]
            assert len(flat) == len(set(flat)), "page double-allocated"
            assert 0 not in flat, "reserved scratch page handed out"
            assert pool.free_pages() + len(flat) == 16
        for h in held:
            pool.free(h)
        s = pool.stats()
        assert s["pages_free"] == 16 and s["pages_used"] == 0
        assert 0 < s["high_water_pages"] <= 16
        assert s["high_water_bytes"] >= s["used_bytes"]

    def test_double_free_raises(self):
        from paddle_tpu.serving import KVPagePool

        pool = KVPagePool(n_layers=1, num_pages=4, page_size=2, kv_dim=8)
        pages = pool.try_alloc(2)
        pool.free(pages)
        with pytest.raises(AssertionError):
            pool.free(pages)

    def test_pool_gauges_booked(self):
        from paddle_tpu.core import telemetry
        from paddle_tpu.serving import KVPagePool

        pool = KVPagePool(n_layers=2, num_pages=9, page_size=4, kv_dim=16)
        g = telemetry.gauges()
        assert g["mem.serving.kv_pool_bytes"] == pool.pool_bytes
        assert pool.pool_bytes == 2 * 2 * 9 * 4 * 16 * 4


class TestAdmission:
    def test_over_budget_request_refused_typed(self, lm_params):
        """A request that could NEVER fit the pool gets a typed refusal
        at submit — and the engine keeps serving small requests."""
        from paddle_tpu.core import costmodel, telemetry
        from paddle_tpu.serving import (DecodeConfig, DecodeEngine,
                                        KVCacheExhaustedError)

        eng = DecodeEngine(_model_cfg(), lm_params,
                           DecodeConfig(max_slots=2, page_size=4,
                                        kv_pages=4, prefill_buckets=[8]))
        try:
            before = telemetry.counter_get("decode.kv_refusals")
            with pytest.raises(KVCacheExhaustedError) as ei:
                eng.submit(np.arange(3, 11, dtype=np.int32),
                           max_new_tokens=12)   # 20 tokens -> 5 > 3 pages
            assert "KV pages" in str(ei.value)
            assert telemetry.counter_get("decode.kv_refusals") == before + 1
            # the pool's preallocation is on the HBM ledger
            led = costmodel.ledger()
            assert led["serving_kv_pool_bytes"] == eng.pool.pool_bytes
            assert led["total_bytes"] >= eng.pool.pool_bytes
            # a request that fits still serves (engine not wedged)
            eng.start()
            out = eng.generate(np.array([5, 6, 7], np.int32),
                               max_new_tokens=3, timeout=60)
            assert len(out) == 3
        finally:
            eng.close(drain=True, timeout=30)

    def test_queue_backpressure_typed(self, lm_params):
        """Bounded admission: the decode queue rejects past max depth
        with ServerOverloadedError (decode.rejects counts it)."""
        from paddle_tpu.serving import (DecodeConfig, DecodeEngine,
                                        ServerOverloadedError)

        eng = DecodeEngine(_model_cfg(), lm_params,
                           DecodeConfig(max_slots=2, page_size=4,
                                        kv_pages=28, max_queue_depth=2,
                                        prefill_buckets=[8]))
        # never started: submissions sit in the queue
        p = np.array([5, 6], np.int32)
        eng.submit(p, max_new_tokens=2)
        eng.submit(p, max_new_tokens=2)
        with pytest.raises(ServerOverloadedError):
            eng.submit(p, max_new_tokens=2)
        eng.close(drain=False)

    def test_model_length_cap_is_value_error(self, engines):
        cont, _ = engines
        with pytest.raises(ValueError) as ei:
            cont.submit(np.arange(3, 23, dtype=np.int32),
                        max_new_tokens=30)   # 50 > max_seq_len 32
        assert "max_seq_len" in str(ei.value)


class TestDeadline:
    def test_deadline_expires_mid_generation(self, lm_params):
        """A generation whose deadline elapses mid-flight retires at a
        step boundary with DeadlineExceededError and frees its pages —
        without draining the rest of the batch."""
        from paddle_tpu.core import telemetry
        from paddle_tpu.models.decoder_lm import decoder_lm_params
        from paddle_tpu.serving import (DeadlineExceededError,
                                        DecodeConfig, DecodeEngine)

        cfg = _model_cfg(max_seq_len=128)
        eng = DecodeEngine(cfg, decoder_lm_params(cfg, seed=0),
                           DecodeConfig(max_slots=2, page_size=4,
                                        kv_pages=36, prefill_buckets=[8]))
        eng.start()
        try:
            # warm every program OUTSIDE the deadline window
            eng.generate(np.array([5, 6, 7], np.int32), max_new_tokens=2,
                         timeout=60)
            before = telemetry.counter_get("decode.deadline_expired")
            req = eng.submit(np.array([5, 6, 7, 8], np.int32),
                             max_new_tokens=120, deadline_ms=10)
            with pytest.raises(DeadlineExceededError) as ei:
                req.result(timeout=60)
            # step-granularity expiry, not queue-side: the generation
            # was already producing tokens
            assert "generation" in str(ei.value)
            assert len(req.tokens) > 0
            assert telemetry.counter_get("decode.deadline_expired") \
                == before + 1
            s = eng.pool.stats()
            assert s["pages_used"] == 0, "expired request leaked pages"
        finally:
            eng.close(drain=True, timeout=30)


class TestInt8WeightOnly:
    def test_int8_config_bitwise_continuous_vs_sequential(self, lm_params):
        """int8 weight-only serving is a config flip with the same
        continuous-vs-sequential bitwise guarantee; weights really are
        stored int8."""
        from paddle_tpu.serving import DecodeConfig, DecodeEngine

        kw = dict(max_slots=2, page_size=4, kv_pages=20,
                  prefill_buckets=[8], weight_quant="int8")
        cont = DecodeEngine(_model_cfg(), lm_params,
                            DecodeConfig(**kw)).start()
        seq = DecodeEngine(_model_cfg(), lm_params,
                           DecodeConfig(**kw)).start()
        try:
            i8 = [n for n, v in cont._params.items()
                  if n.endswith("_w_i8")]
            assert len(i8) == 2 * 6   # every dense weight, both layers
            assert all(str(cont._params[n].dtype) == "int8" for n in i8)
            prompts = [np.array([5, 6, 7], np.int32),
                       np.array([9, 10, 11, 12], np.int32),
                       np.array([20, 21], np.int32)]
            reqs = [cont.submit(p, max_new_tokens=6) for p in prompts]
            got = [r.result(timeout=120) for r in reqs]
            want = [seq.generate(p, max_new_tokens=6, timeout=120)
                    for p in prompts]
            for g, w in zip(got, want):
                assert np.array_equal(g, w)
        finally:
            cont.close(drain=True, timeout=30)
            seq.close(drain=True, timeout=30)


@pytest.mark.chaos
class TestChaos:
    def test_step_fault_is_per_request_error_not_wedge(self, engines):
        """An injected decode.step fault fails the in-flight generations
        individually, frees their pages, and the engine keeps serving."""
        from paddle_tpu.core import faults, telemetry
        from paddle_tpu.serving import ServingError

        cont, _ = engines
        faults.configure("decode.step:@1")
        try:
            before = telemetry.counter_get("decode.errors")
            reqs = [cont.submit(np.array([5, 6, 7], np.int32),
                                max_new_tokens=6) for _ in range(2)]
            errors = 0
            for r in reqs:
                try:
                    r.result(timeout=60)
                except ServingError:
                    errors += 1
            assert errors >= 1
            assert telemetry.counter_get("decode.errors") > before
        finally:
            faults.configure("")
        # queue not wedged, pool back to baseline
        out = cont.generate(np.array([5, 6, 7], np.int32),
                            max_new_tokens=3, timeout=60)
        assert len(out) == 3
        assert cont.pool.stats()["pages_used"] == 0


class TestHTTP:
    def test_generate_stats_and_live_metrics(self, engines):
        """POST /v1/generate round-trips; /v1/stats carries the decode
        section + KV pool; /metrics exposes pt_decode_* and the
        mem.serving.kv_* gauges; /healthz is ready."""
        from paddle_tpu.serving import ServingHTTPServer

        cont, _ = engines
        srv = ServingHTTPServer(None, decode_engine=cont).start()
        try:
            body = json.dumps({"prompt_ids": [5, 6, 7],
                               "max_new_tokens": 4}).encode()
            doc = json.loads(urllib.request.urlopen(urllib.request.Request(
                srv.url + "/v1/generate", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=30).read())
            assert len(doc["tokens"]) == doc["num_tokens"] == 4
            assert doc["ttft_ms"] is not None
            want = cont.generate(np.array([5, 6, 7], np.int32),
                                 max_new_tokens=4, timeout=60)
            assert np.array_equal(np.asarray(doc["tokens"], np.int32),
                                  want)
            stats = json.loads(urllib.request.urlopen(
                srv.url + "/v1/stats", timeout=10).read())
            dc = stats["decode"]
            assert dc["kv_cache"]["pool_bytes"] == cont.pool.pool_bytes
            assert dc["tokens"] > 0 and dc["retired"] > 0
            mtx = urllib.request.urlopen(srv.url + "/metrics",
                                         timeout=10).read().decode()
            assert "pt_decode_tokens_total" in mtx
            assert "pt_mem_serving_kv_pool_bytes" in mtx
            hz = urllib.request.urlopen(srv.url + "/healthz", timeout=10)
            assert hz.status == 200
        finally:
            srv.shutdown()

    def test_generate_error_mapping(self, lm_params):
        """KV over-budget → HTTP 429 with the typed name; bad body →
        400."""
        from paddle_tpu.serving import (DecodeConfig, DecodeEngine,
                                        ServingHTTPServer)

        eng = DecodeEngine(_model_cfg(), lm_params,
                           DecodeConfig(max_slots=2, page_size=4,
                                        kv_pages=4, prefill_buckets=[8]))
        srv = ServingHTTPServer(None, decode_engine=eng).start()
        try:
            body = json.dumps({"prompt_ids": list(range(3, 11)),
                               "max_new_tokens": 12}).encode()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    srv.url + "/v1/generate", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=30)
            assert ei.value.code == 429
            payload = json.loads(ei.value.read())
            assert payload["error_type"] == "KVCacheExhaustedError"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    srv.url + "/v1/generate", data=b"{}",
                    headers={"Content-Type": "application/json"}),
                    timeout=30)
            assert ei.value.code == 400
        finally:
            srv.shutdown()
            eng.close(drain=False)


class TestReporting:
    def test_perf_report_decode_section(self, tmp_path):
        """A run log from a decode engine renders the Decode section
        with tokens, occupancy and the KV pool lines."""
        import io as _io

        from tools.perf_report import render, summarize_log

        recs = [
            {"ts": 1.0, "kind": "counter", "name": "decode.requests",
             "value": 4, "attrs": {"delta": 4}},
            {"ts": 1.0, "kind": "counter", "name": "decode.prefills",
             "value": 4, "attrs": {"delta": 4}},
            {"ts": 1.1, "kind": "counter", "name": "decode.prefill_tokens",
             "value": 16, "attrs": {"delta": 16}},
            {"ts": 1.2, "kind": "counter", "name": "decode.steps",
             "value": 10, "attrs": {"delta": 10}},
            {"ts": 2.0, "kind": "counter", "name": "decode.tokens",
             "value": 30, "attrs": {"delta": 30}},
            {"ts": 2.0, "kind": "counter", "name": "decode.retired",
             "value": 4, "attrs": {"delta": 4}},
            {"ts": 2.0, "kind": "counter",
             "name": "decode.kv_pages_allocated", "value": 9,
             "attrs": {"delta": 9}},
            {"ts": 2.0, "kind": "counter", "name": "decode.kv_pages_freed",
             "value": 8, "attrs": {"delta": 8}},
            {"ts": 1.5, "kind": "hist", "name": "decode.batch_occupancy",
             "value": 0.75, "attrs": {}},
            {"ts": 1.5, "kind": "timer", "name": "decode.step_ms",
             "value": 1.25, "attrs": {}},
            {"ts": 1.5, "kind": "timer", "name": "decode.prefill_ms",
             "value": 2.5, "attrs": {}},
            {"ts": 1.6, "kind": "gauge",
             "name": "mem.serving.kv_pool_bytes", "value": 4096,
             "attrs": {}},
            {"ts": 1.6, "kind": "gauge",
             "name": "mem.serving.kv_high_water_bytes", "value": 2048,
             "attrs": {}},
        ]
        s = summarize_log(recs)
        dc = s["decode"]
        assert dc["tokens"] == 30 and dc["steps"] == 10
        assert dc["tokens_per_s"] == 30.0   # 30 tokens over 1s of log
        assert dc["kv_pool_bytes"] == 4096
        assert dc["batch_occupancy"]["mean"] == 0.75
        buf = _io.StringIO()
        render(s, out=buf)
        text = buf.getvalue()
        assert "-- decode (continuous-batching generative engine)" in text
        assert "LEAKED 1" in text   # 9 allocated vs 8 freed
        assert "kv page pool" in text

    def test_mem_report_kv_ledger_lines(self):
        import io as _io

        from tools.mem_report import render, summarize_mem

        recs = [
            {"ts": 1.0, "kind": "gauge", "name": "mem.param_bytes",
             "value": 1024, "attrs": {}},
            {"ts": 1.0, "kind": "gauge",
             "name": "mem.serving.kv_pool_bytes", "value": 8192,
             "attrs": {}},
            {"ts": 1.0, "kind": "gauge",
             "name": "mem.serving.kv_used_bytes", "value": 4096,
             "attrs": {}},
            {"ts": 1.0, "kind": "gauge",
             "name": "mem.serving.kv_high_water_bytes", "value": 6144,
             "attrs": {}},
        ]
        s = summarize_mem(recs)
        led = s["ledger"]
        assert led["serving_kv_pool_bytes"] == 8192
        assert led["total_bytes"] == 1024 + 8192
        buf = _io.StringIO()
        render(s, out=buf)
        assert "KV page pool" in buf.getvalue()
