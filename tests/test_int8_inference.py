"""Quantized inference END-TO-END (VERDICT r5 #7): train a small
classifier, PTQ-calibrate, convert to the int8 engine
(contrib/slim.convert_to_int8_program) and RUN it through
AnalysisPredictor — top-1 parity against the fp predictor."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.contrib import slim
from paddle_tpu.inference.predictor import AnalysisConfig, AnalysisPredictor


def _build_and_train(scope, steps=60):
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.static_data("x", [-1, 16], "float32")
        y = layers.static_data("y", [-1, 1], "int64")
        h = layers.fc(x, 32, act="relu")
        logits = layers.fc(h, 4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        pt.optimizer.AdamOptimizer(0.01).minimize(loss)
    # pure-inference program (no loss ops): rebuild x->logits with the
    # SAME parameter names (fresh unique_name context, same call order)
    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()
    infer, _istart = pt.Program(), pt.Program()
    with pt.program_guard(infer, _istart):
        xi = layers.static_data("x", [-1, 16], "float32")
        hi = layers.fc(xi, 32, act="relu")
        ilogits = layers.fc(hi, 4)
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    rng = np.random.RandomState(0)
    centers = rng.randn(4, 16).astype(np.float32) * 2.0
    def batch(n, seed):
        r = np.random.RandomState(seed)
        lab = r.randint(0, 4, (n, 1))
        return {"x": (centers[lab[:, 0]]
                      + r.randn(n, 16).astype(np.float32) * 0.5),
                "y": lab.astype(np.int64)}
    for i in range(steps):
        exe.run(main, feed=batch(64, i), fetch_list=[loss], scope=scope)
    return infer, ilogits, batch


def test_int8_predictor_top1_parity(scope):
    infer, logits, batch = _build_and_train(scope)
    feeds = ["x"]
    fetches = [logits.name]

    # fp32 reference predictor
    fp_pred = AnalysisPredictor(AnalysisConfig(), program=infer,
                                feed_names=feeds, fetch_names=fetches,
                                scope=scope)
    test = batch(256, 999)
    fp_logits, = fp_pred.run({"x": test["x"]})
    fp_top1 = np.argmax(fp_logits, axis=1)
    fp_acc = float(np.mean(fp_top1 == test["y"][:, 0]))
    assert fp_acc > 0.9, f"fp model underfit: {fp_acc}"

    # PTQ calibration for activation scales
    exe = pt.Executor()
    ptq = slim.PostTrainingQuantization(
        exe, infer.clone(for_test=True), feeds, scope,
        [batch(64, 7), batch(64, 8)])
    ptq.quantize()
    assert ptq.calibrated_scales

    # convert a CLEAN copy of the inference program to the int8 engine
    import copy

    int8_scope = pt.Scope()
    int8_scope._vars = {k: np.copy(v) for k, v in scope.items()}
    int8_prog = slim.convert_to_int8_program(
        infer.clone(for_test=True), int8_scope, ptq.calibrated_scales)
    types = [op.type for op in int8_prog.global_block().ops]
    assert "int8_matmul" in types, types
    for name, val in int8_scope.items():
        if name.endswith("@int8_scale"):
            base = name[:-len("@int8_scale")]
            assert np.asarray(int8_scope.find_var(base)).dtype == np.int8

    q_pred = AnalysisPredictor(AnalysisConfig(), program=int8_prog,
                               feed_names=feeds, fetch_names=fetches,
                               scope=int8_scope)
    q_logits, = q_pred.run({"x": test["x"]})
    q_top1 = np.argmax(q_logits, axis=1)
    agree = float(np.mean(q_top1 == fp_top1))
    assert agree >= 0.97, f"int8 top-1 agreement {agree}"
    q_acc = float(np.mean(q_top1 == test["y"][:, 0]))
    assert q_acc > 0.85, q_acc


def test_weight_only_path(scope):
    """Without activation scales every matmul-family op takes the
    weight-only ``int8_matmul`` route (NO act_scale attr — the lowering
    the Pallas int8 MXU GEMM kernel sits behind; before this the
    weight-only convert emitted dequantize_weight + stock matmul and
    the kernel never fired) and still matches closely."""
    infer, logits, batch = _build_and_train(scope, steps=30)
    fp = AnalysisPredictor(AnalysisConfig(), program=infer,
                           feed_names=["x"], fetch_names=[logits.name],
                           scope=scope)
    test = batch(128, 555)
    fp_logits, = fp.run({"x": test["x"]})

    int8_scope = pt.Scope()
    int8_scope._vars = {k: np.copy(v) for k, v in scope.items()}
    prog = slim.convert_to_int8_program(infer.clone(for_test=True),
                                        int8_scope, act_scales=None)
    mm_ops = [op for op in prog.global_block().ops
              if op.type == "int8_matmul"]
    assert len(mm_ops) == 2, \
        [op.type for op in prog.global_block().ops]
    assert all(not op.attrs.get("act_scale") for op in mm_ops)
    q = AnalysisPredictor(AnalysisConfig(), program=prog,
                          feed_names=["x"], fetch_names=[logits.name],
                          scope=int8_scope)
    q_logits, = q.run({"x": test["x"]})
    agree = np.mean(np.argmax(q_logits, 1) == np.argmax(fp_logits, 1))
    assert agree >= 0.98, agree

    # regression: numeric parity with the OLD weight-only lowering
    # (dequantize_weight + stock matmul — dequant-then-dot instead of
    # the kernel's dot-then-scale; same math, different rounding order,
    # pinned within float tolerance)
    def old_lowering(x):
        h = x
        for i, op in enumerate(mm_ops):
            w8 = np.asarray(int8_scope.find_var(op.inputs["Y"][0]))
            sc = np.asarray(int8_scope.find_var(op.inputs["YScale"][0]))
            b = np.asarray(int8_scope.find_var(f"fc_{i}.b_0"))
            h = h @ (w8.astype(np.float32) * sc[None, :]) + b
            if i == 0:
                h = np.maximum(h, 0.0)
        return h

    want = old_lowering(test["x"].astype(np.float32))
    np.testing.assert_allclose(np.asarray(q_logits), want,
                               rtol=2e-4, atol=2e-4)


def test_weight_tied_param_stays_fp(scope):
    """A parameter read by BOTH a quantizable matmul and a non-quantized
    consumer (weight tying, e.g. an embedding doubling as the output
    projection) must NOT be overwritten with int8 in the scope."""
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.static_data("ids", [-1, 3], "int64")
        emb = layers.embedding(ids, [50, 8],
                               param_attr=pt.ParamAttr(name="tied_w"))
        pooled = layers.reduce_mean(emb, dim=[1])          # [B, 8]
        w = main.global_block().var("tied_w")              # [50, 8]
        logits = layers.matmul(pooled, w, transpose_y=True)
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"ids": np.random.RandomState(0).randint(0, 50, (4, 3))
            .astype(np.int64)}
    ref, = exe.run(main, feed=feed, fetch_list=[logits], scope=scope)

    prog = slim.convert_to_int8_program(main, scope, act_scales=None)
    assert np.asarray(scope.find_var("tied_w")).dtype == np.float32
    got, = exe.run(prog, feed=feed, fetch_list=[logits], scope=scope)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5)
