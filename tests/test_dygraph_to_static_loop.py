"""@to_static loop transformer (VERDICT r2 #6).

Mirrors the reference's dygraph_to_static loop suite
(unittests/dygraph_to_static/test_loop.py — tensor-dependent while/for
become program while ops): a loop whose trip count is a tensor trains
with correct grads, and CHANGING the count does not retrace.

NOTE: the decorated functions live at module scope reading VarBase from
module globals — @to_static skips functions with closures (same
constraint as the if-rewriter, jit.py _transform_fn); Layer methods
access state via `self`, so real models are unaffected.
"""

import warnings

import numpy as np
import pytest

from paddle_tpu import dygraph
from paddle_tpu.dygraph import to_static
from paddle_tpu.dygraph.varbase import VarBase

W_GLOBAL = None


@to_static(loop_max_iters=8)
def scaled_while(x, n):
    i = VarBase(np.zeros((), np.int32))
    while i < n:
        x = x * 1.1 + 0.5
        i = i + 1
    return x


@to_static
def count_while(x, n):
    i = VarBase(np.zeros((), np.int32))
    while i < n:
        x = x + 1.0
        i = i + 1
    return x


@to_static(loop_max_iters=8)
def for_range_tensor(x, n):
    for i in range(n):
        x = x + 2.0
    return x


@to_static
def for_range_python(x):
    acc = x * 0.0
    for i in range(3):
        acc = acc + x * (i + 1)
    return acc


@to_static(loop_max_iters=8)
def add_global_weight(x, n):
    i = VarBase(np.zeros((), np.int32))
    while i < n:
        x = x + W_GLOBAL
        i = i + 1
    return x


class TestTensorWhile:
    def test_runtime_trip_count_no_retrace(self):
        with dygraph.guard():
            scaled_while._cache.clear()
            x = np.ones((3,), np.float32)
            for k in (3, 5, 0):
                out = scaled_while(VarBase(x), VarBase(np.int32(k)))
                want = x.copy()
                for _ in range(k):
                    want = want * 1.1 + 0.5
                np.testing.assert_allclose(out.numpy(), want, rtol=1e-5,
                                           err_msg=f"count {k}")
            # ONE trace for all three counts
            assert len(scaled_while._cache) == 1

    def test_grads_flow_through_active_iterations(self):
        with dygraph.guard():
            for k in (2, 4):
                x = VarBase(np.full((3,), 2.0, np.float32),
                            stop_gradient=False)
                y = scaled_while(x, VarBase(np.int32(k)))
                loss = (y * y).sum()
                loss.backward()
                # dy/dx = 1.1^k ; dloss/dx = 2*y*1.1^k
                want = 2.0 * y.numpy() * (1.1 ** k)
                np.testing.assert_allclose(x.grad.numpy(), want, rtol=1e-4,
                                           err_msg=f"count {k}")

    def test_default_bound_warns_and_works(self):
        with dygraph.guard():
            count_while._cache.clear()
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                out = count_while(VarBase(np.zeros((2,), np.float32)),
                                  VarBase(np.int32(3)))
            assert any("bounded at" in str(x.message) for x in w)
            np.testing.assert_allclose(out.numpy(), [3.0, 3.0])
            # within the inferred bound (2x traced count): still correct
            out = count_while(VarBase(np.zeros((2,), np.float32)),
                              VarBase(np.int32(6)))
            np.testing.assert_allclose(out.numpy(), [6.0, 6.0])


class TestTensorFor:
    def test_for_range_tensor_stop(self):
        with dygraph.guard():
            for_range_tensor._cache.clear()
            for k in (1, 4):
                out = for_range_tensor(VarBase(np.zeros((2,), np.float32)),
                                       VarBase(np.int32(k)))
                np.testing.assert_allclose(out.numpy(), [2.0 * k] * 2)
            assert len(for_range_tensor._cache) == 1

    def test_python_range_keeps_python_semantics(self):
        with dygraph.guard():
            out = for_range_python(VarBase(np.ones((2,), np.float32)))
            np.testing.assert_allclose(out.numpy(), [6.0, 6.0])

    def test_loop_reads_global_weight(self):
        """External tensors read inside the loop body ride along as Ext
        inputs of the while op."""
        global W_GLOBAL
        with dygraph.guard():
            W_GLOBAL = VarBase(np.full((2,), 3.0, np.float32),
                               stop_gradient=False)
            add_global_weight._cache.clear()
            out = add_global_weight(VarBase(np.zeros((2,), np.float32)),
                                    VarBase(np.int32(4)))
            np.testing.assert_allclose(out.numpy(), [12.0, 12.0])


@to_static(loop_max_iters=8)
def loop_with_branch(x, n):
    i = VarBase(np.zeros((), np.int32))
    while i < n:
        if (i > 0).sum() > 0:
            x = x + 1.0
        else:
            x = x + 10.0
        i = i + 1
    return x


@to_static(loop_max_iters=8)
def loop_with_temp(x, n):
    i = VarBase(np.zeros((), np.int32))
    while i < n:
        t = x * 2.0
        x = t + 1.0
        i = i + 1
    return x


@to_static(loop_max_iters=8)
def for_zero_trip(x, n):
    for i in range(n):
        x = x + 2.0
    return x


class TestLoopEdgeCases:
    """Regressions from the round-3 review: loop+if, body-local temps,
    zero-trip trace input."""

    def test_loop_containing_tensor_if(self):
        with dygraph.guard():
            loop_with_branch._cache.clear()
            out = loop_with_branch(VarBase(np.zeros((2,), np.float32)),
                                   VarBase(np.int32(3)))
            # i=0 -> +10, i=1,2 -> +1
            np.testing.assert_allclose(out.numpy(), [12.0, 12.0])
            out = loop_with_branch(VarBase(np.zeros((2,), np.float32)),
                                   VarBase(np.int32(1)))
            np.testing.assert_allclose(out.numpy(), [10.0, 10.0])
            assert len(loop_with_branch._cache) == 1

    def test_body_local_temp(self):
        with dygraph.guard():
            loop_with_temp._cache.clear()
            out = loop_with_temp(VarBase(np.ones((2,), np.float32)),
                                 VarBase(np.int32(2)))
            # x -> 2x+1: 1 -> 3 -> 7
            np.testing.assert_allclose(out.numpy(), [7.0, 7.0])

    def test_zero_trip_first_trace(self):
        with dygraph.guard():
            for_zero_trip._cache.clear()
            out = for_zero_trip(VarBase(np.zeros((2,), np.float32)),
                                VarBase(np.int32(0)))
            np.testing.assert_allclose(out.numpy(), [0.0, 0.0])
            # SAME trace must then iterate for a nonzero count
            out = for_zero_trip(VarBase(np.zeros((2,), np.float32)),
                                VarBase(np.int32(3)))
            np.testing.assert_allclose(out.numpy(), [6.0, 6.0])
            assert len(for_zero_trip._cache) == 1
