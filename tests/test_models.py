"""Book-style end-to-end model tests (SURVEY.md §4.3): each model builds,
runs a step, and overfits a tiny batch. ResNet runs at toy image size to
keep CPU CI fast; geometry checks run at full 224 config."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import resnet, word2vec


def test_resnet50_builds_full_geometry():
    cfg = resnet.resnet50()
    main, startup, feeds, fetches = resnet.build_classifier_program(
        cfg, with_optimizer=False, is_test=True)
    # 53 convs in resnet-50 (1 stem + 3*16 bottleneck + 4 shortcut convs)
    n_convs = sum(1 for op in main.global_block().ops if op.type == "conv2d")
    assert n_convs == 53
    logits_like = [v for v in main.global_block().vars.values()
                   if v.shape == (-1, 1000)]
    assert logits_like


@pytest.mark.parametrize("depth", [18, 50])
def test_resnet_small_trains(depth, scope):
    cfg = resnet.ResNetConfig(depth=depth, num_classes=10,
                              image_shape=(3, 32, 32))
    main, startup, feeds, fetches = resnet.build_classifier_program(
        cfg, optimizer_name="momentum", lr=0.01)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    batch = resnet.synthetic_batch(cfg, 8)
    losses = []
    for _ in range(8):
        lv, a1, a5 = exe.run(main, feed=batch,
                             fetch_list=[fetches["loss"], fetches["acc1"],
                                         fetches["acc5"]], scope=scope)
        losses.append(float(lv))
    assert np.isfinite(losses).all()
    # deep nets can transiently spike on random data; require recovery below
    # the early-loss level by the end
    assert losses[-1] < max(losses[:2]), losses
    assert 0.0 <= float(a1) <= float(a5) <= 1.0


def test_resnet_train_vs_eval_bn(scope):
    """BN must use batch stats in train and running stats in eval."""
    cfg = resnet.ResNetConfig(depth=18, num_classes=4, image_shape=(3, 16, 16))
    main, startup, feeds, fetches = resnet.build_classifier_program(cfg)
    test_prog = main.clone(for_test=True)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    batch = resnet.synthetic_batch(cfg, 4)
    mean0 = np.array(scope.find_var("conv1_bn_mean"))
    for _ in range(3):
        exe.run(main, feed=batch, fetch_list=[fetches["loss"]], scope=scope)
    mean1 = np.array(scope.find_var("conv1_bn_mean"))
    assert not np.allclose(mean0, mean1), "running mean did not update"
    lv, = exe.run(test_prog, feed=batch, fetch_list=[fetches["loss"]],
                  scope=scope)
    assert np.isfinite(lv)
    # eval twice → identical (no dropout/bn randomness, stats frozen)
    lv2, = exe.run(test_prog, feed=batch, fetch_list=[fetches["loss"]],
                   scope=scope)
    mean2 = np.array(scope.find_var("conv1_bn_mean"))
    np.testing.assert_array_equal(mean1, mean2)


def test_word2vec_overfits(scope):
    dict_size = 50
    main, startup, feeds, fetches = word2vec.build_word2vec_program(
        dict_size, lr=0.5)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    batch = word2vec.synthetic_batch(dict_size, 16)
    losses = []
    for _ in range(80):
        lv, = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                      scope=scope)
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_transformer_tiny_trains(scope):
    from paddle_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(src_vocab_size=64, tgt_vocab_size=64,
                                d_model=32, n_head=4, d_inner=64,
                                n_encoder_layers=2, n_decoder_layers=2)
    main, startup, feeds, fetches = tfm.build_wmt_program(
        cfg, seq_len=8, warmup_steps=100, lr_scale=2.0)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    batch = tfm.synthetic_batch(cfg, 4, 8)
    losses = []
    for _ in range(25):
        lv, tn = exe.run(main, feed=batch,
                         fetch_list=[fetches["loss"], fetches["token_num"]],
                         scope=scope)
        losses.append(float(lv))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    assert float(tn) == batch["lbl_weight"].sum()


def test_transformer_tp_dryrun():
    """Megatron TP: the same program runs under a dp×mp mesh; GSPMD inserts
    the collectives the reference lacked first-class TP for."""
    import jax

    from paddle_tpu.models import transformer as tfm
    from paddle_tpu.parallel import create_mesh

    cfg = tfm.TransformerConfig(src_vocab_size=64, tgt_vocab_size=64,
                                d_model=32, n_head=4, d_inner=64,
                                n_encoder_layers=1, n_decoder_layers=1)
    main, startup, feeds, fetches = tfm.build_wmt_program(
        cfg, seq_len=8, warmup_steps=2)
    mesh = create_mesh({"dp": 2, "mp": 2}, devices=jax.devices()[:4])
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    batch = tfm.synthetic_batch(cfg, 4, 8)
    lv, = exe.run(main, feed=batch, fetch_list=[fetches["loss"]], scope=scope,
                  mesh=mesh)
    assert np.isfinite(float(lv))


def test_masked_gather_mlm_head_parity():
    """max_predictions_per_seq gathers only masked positions before the
    vocab projection; when the mask count fits, the loss is exact."""
    import paddle_tpu as pt
    from paddle_tpu.core import ir, unique_name
    from paddle_tpu.models import bert

    res = {}
    for k in (0, 40):
        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        cfg = bert.BertConfig(
            vocab_size=128, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=128,
            max_position_embeddings=64, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0)
        main, startup, feeds, fetches = bert.build_pretraining_program(
            cfg, seq_len=64, with_nsp=False, optimizer_name="adamw",
            max_predictions_per_seq=k)
        exe = pt.Executor()
        sc = pt.Scope()
        exe.run(startup, scope=sc, use_compiled=False)
        batch = bert.synthetic_pretraining_batch(cfg, 4, 64)
        out = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                      scope=sc)
        res[k] = float(np.asarray(out[0]).reshape(-1)[0])
    np.testing.assert_allclose(res[40], res[0], rtol=1e-5)


def test_seq2seq_machine_translation_trains():
    """Book config 'machine translation': LSTM encoder/decoder + Luong
    attention trains on synthetic pairs (reference:
    tests/book/test_machine_translation.py)."""
    import paddle_tpu as pt
    from paddle_tpu.models import seq2seq

    cfg = seq2seq.Seq2SeqConfig(src_vocab_size=64, tgt_vocab_size=64,
                                embed_dim=16, hidden_size=32)
    main, startup, feeds, fetches = seq2seq.build_seq2seq_program(
        cfg, src_len=10, tgt_len=8, lr=5e-3)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    batch = seq2seq.synthetic_translation_batch(cfg, 8, 10, 8)
    losses = []
    for _ in range(15):
        lv, = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                      scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0] - 0.2


def test_seq2seq_decoding_greedy_and_beam():
    """Decoding after training a copy task: greedy + beam produce valid,
    deterministic sequences; beam with k=1 equals greedy (reference:
    book machine_translation decode_main/beam_search)."""
    import paddle_tpu as pt
    from paddle_tpu.models import seq2seq

    cfg = seq2seq.Seq2SeqConfig(src_vocab_size=24, tgt_vocab_size=24,
                                embed_dim=16, hidden_size=32)
    S = 6
    main, startup, feeds, fetches = seq2seq.build_seq2seq_program(
        cfg, src_len=S, tgt_len=S, lr=2e-2)
    exe = pt.Executor()
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    rng = np.random.RandomState(0)
    # copy task: target = source (teacher forced)
    for _ in range(200):
        src = rng.randint(3, cfg.src_vocab_size, (16, S)).astype(np.int64)
        feed = dict(src_ids=src, src_mask=np.ones((16, S), np.float32),
                    tgt_in=np.concatenate(
                        [np.ones((16, 1), np.int64), src[:, :-1]], 1),
                    tgt_out=src, tgt_mask=np.ones((16, S), np.float32))
        exe.run(main, feed=feed, fetch_list=[fetches["loss"]], scope=scope)

    src = rng.randint(3, cfg.src_vocab_size, (4, S)).astype(np.int64)
    mask = np.ones((4, S), np.float32)
    g1 = seq2seq.greedy_decode(cfg, scope, src, mask, max_len=S)
    g2 = seq2seq.greedy_decode(cfg, scope, src, mask, max_len=S)
    np.testing.assert_array_equal(g1, g2)        # deterministic
    assert g1.shape == (4, S) and g1.dtype == np.int32
    b1 = seq2seq.beam_search_decode(cfg, scope, src, mask, beam_size=1,
                                    max_len=S, length_penalty=0.0)
    np.testing.assert_array_equal(b1, g1)        # k=1 beam == greedy
    b4 = seq2seq.beam_search_decode(cfg, scope, src, mask, beam_size=4,
                                    max_len=S)
    assert b4.shape == (4, S)
    # decode must reflect the trained model: accuracy well above the
    # 1/24 chance level (empirically it tracks exp(-loss) of the
    # teacher-forced training loss, confirming the decode recurrence
    # matches the training-time lstm op)
    acc = float((g1 == src).mean())
    assert acc > 0.15, acc          # ~4x chance


def test_transformer_flash_matches_unfused(scope):
    """The flash-attention routing in _mha (causal=True decoder self,
    kv-padding cross bias) must produce the same forward loss as the
    unfused matmul+softmax path at dropout=0 with ragged padding."""
    from paddle_tpu.core import ir, unique_name
    from paddle_tpu.models import transformer as tfm

    losses = {}
    for flash in (False, True):
        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        cfg = tfm.TransformerConfig(src_vocab_size=64, tgt_vocab_size=64,
                                    d_model=32, n_head=4, d_inner=64,
                                    n_encoder_layers=1, n_decoder_layers=1,
                                    dropout=0.0,
                                    use_flash_attention=flash)
        main, startup, feeds, fetches = tfm.build_wmt_program(
            cfg, seq_len=8, warmup_steps=100, is_test=True,
            with_optimizer=False)
        exe = pt.Executor(pt.CPUPlace())
        sc = pt.Scope()
        rng = np.random.RandomState(0)
        exe.run(startup, scope=sc, use_compiled=False)
        # identical params: re-seed deterministically by name (crc32 —
        # hash() varies with PYTHONHASHSEED). NEVER touch structural
        # non-trainable tables: the causal mask only exists in the
        # UNFUSED program, so overwriting it would silently de-causal
        # the reference side of the comparison.
        import zlib

        for name in sorted(sc._vars):
            if "causal_mask" in name or "pos_enc" in name:
                continue
            v = sc.find_var(name)
            if hasattr(v, "shape") and getattr(v, "dtype", None) is not None:
                arr = np.asarray(v)
                if np.issubdtype(arr.dtype, np.floating) and arr.ndim >= 1:
                    r = np.random.RandomState(
                        zlib.crc32(name.encode()) % (2**31))
                    sc.set(name, (r.standard_normal(arr.shape) * 0.05
                                  ).astype(arr.dtype))
        batch = tfm.synthetic_batch(cfg, 3, 8, seed=5)
        # ragged source padding exercises the kv-bias path
        batch["src_mask"][:, 5:] = 0.0
        lv, = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                      scope=sc)
        losses[flash] = float(np.asarray(lv).reshape(-1)[0])
    assert np.isfinite(list(losses.values())).all(), losses
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-5, atol=1e-5)
