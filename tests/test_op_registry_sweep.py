"""Registry-wide op test gate (VERDICT r4 #5).

The reference gates every operator behind an OpTest
(python/paddle/fluid/tests/unittests/op_test.py, ~600 test files — SURVEY
§4.1). This file is the analog: numpy-oracle sweeps over the elementwise /
binary / comparison / reduction / shape families, execution smokes for the
shaped ops, a dp4 shard_map sweep for the collective family, and a GATE
test asserting every registered op is covered by (a) a sweep table here,
(b) a bespoke test elsewhere in tests/ (word-boundary mention), or (c) the
justified allowlist (< 20 ops).
"""

import glob
import os
import re

import numpy as np
import pytest

from tests.test_ops_batch3 import _fwd

RNG = np.random.RandomState(1234)


def _x(shape, lo=-1.0, hi=1.0, dtype=np.float32):
    return (RNG.rand(*shape) * (hi - lo) + lo).astype(dtype)


def _sig(v):
    return 1.0 / (1.0 + np.exp(-v))


# ---------------------------------------------------------------------------
# unary elementwise: op -> (attrs, numpy oracle, (lo, hi))
# ---------------------------------------------------------------------------

UNARY = {
    "acos": ({}, np.arccos, (-0.9, 0.9)),
    "acosh": ({}, np.arccosh, (1.1, 3.0)),
    "asin": ({}, np.arcsin, (-0.9, 0.9)),
    "asinh": ({}, np.arcsinh, (-2.0, 2.0)),
    "atan": ({}, np.arctan, (-2.0, 2.0)),
    "atanh": ({}, np.arctanh, (-0.9, 0.9)),
    "cosh": ({}, np.cosh, (-2.0, 2.0)),
    "sinh": ({}, np.sinh, (-2.0, 2.0)),
    "tan": ({}, np.tan, (-1.0, 1.0)),
    "expm1": ({}, np.expm1, (-1.0, 1.0)),
    "floor": ({}, np.floor, (-3.0, 3.0)),
    "trunc": ({}, np.trunc, (-3.0, 3.0)),
    "log10": ({}, np.log10, (0.1, 5.0)),
    "reciprocal": ({}, lambda v: 1.0 / v, (0.5, 2.0)),
    "rsqrt": ({}, lambda v: 1.0 / np.sqrt(v), (0.5, 2.0)),
    "square": ({}, np.square, (-2.0, 2.0)),
    "logsigmoid": ({}, lambda v: -np.log1p(np.exp(-v)), (-3.0, 3.0)),
    "silu": ({}, lambda v: v * _sig(v), (-3.0, 3.0)),
    "softsign": ({}, lambda v: v / (1 + np.abs(v)), (-3.0, 3.0)),
    "softplus": ({}, lambda v: np.log1p(np.exp(v)), (-3.0, 3.0)),
    "tanh_shrink": ({}, lambda v: v - np.tanh(v), (-3.0, 3.0)),
    "relu6": ({}, lambda v: np.clip(v, 0, 6), (-3.0, 8.0)),
    "brelu": ({"t_min": 1.0, "t_max": 4.0},
              lambda v: np.clip(v, 1.0, 4.0), (-3.0, 8.0)),
    "elu": ({"alpha": 1.0},
            lambda v: np.where(v > 0, v, np.expm1(v)), (-3.0, 3.0)),
    "celu": ({"alpha": 1.2},
             lambda v: np.maximum(0, v) + np.minimum(
                 0, 1.2 * np.expm1(v / 1.2)), (-3.0, 3.0)),
    "hard_shrink": ({"threshold": 0.5},
                    lambda v: np.where(np.abs(v) > 0.5, v, 0), (-2.0, 2.0)),
    "softshrink": ({"lambda": 0.5},
                   lambda v: np.where(v > 0.5, v - 0.5,
                                      np.where(v < -0.5, v + 0.5, 0)),
                   (-2.0, 2.0)),
    "hard_sigmoid": ({"slope": 0.2, "offset": 0.5},
                     lambda v: np.clip(0.2 * v + 0.5, 0, 1), (-4.0, 4.0)),
    "hard_swish": ({"threshold": 6.0, "scale": 6.0, "offset": 3.0},
                   lambda v: v * np.clip(v + 3.0, 0, 6.0) / 6.0,
                   (-5.0, 5.0)),
    "swish": ({"beta": 1.0}, lambda v: v * _sig(v), (-3.0, 3.0)),
    "stanh": ({"scale_a": 0.67, "scale_b": 1.7159},
              lambda v: 1.7159 * np.tanh(0.67 * v), (-3.0, 3.0)),
    "thresholded_relu": ({"threshold": 1.0},
                         lambda v: np.where(v > 1.0, v, 0), (-2.0, 3.0)),
    "isnan_v2": ({}, np.isnan, (-2.0, 2.0)),
    "isinf_v2": ({}, np.isinf, (-2.0, 2.0)),
    "isfinite_v2": ({}, np.isfinite, (-2.0, 2.0)),
    "isnan": ({}, lambda v: np.array([np.isnan(v).any()]), (-2.0, 2.0)),
    "isinf": ({}, lambda v: np.array([np.isinf(v).any()]), (-2.0, 2.0)),
    "logical_not": ({}, lambda v: ~(v != 0), (-1.0, 1.0)),
    "log_softmax": ({"axis": -1},
                    lambda v: v - np.log(np.sum(np.exp(v), -1,
                                                keepdims=True)),
                    (-2.0, 2.0)),
}


@pytest.mark.parametrize("op", sorted(UNARY))
def test_unary(op):
    attrs, fn, (lo, hi) = UNARY[op]
    x = _x((3, 4), lo, hi)
    got = np.asarray(_fwd(op, {"X": [x]}, dict(attrs))["Out"])
    np.testing.assert_allclose(got, fn(x.astype(np.float64)), rtol=2e-5,
                               atol=1e-6, err_msg=op)


# ---------------------------------------------------------------------------
# binary / comparison: op -> (ins builder, attrs, numpy oracle)
# ---------------------------------------------------------------------------

def _ab():
    return _x((3, 4), 0.5, 2.0), _x((3, 4), 0.5, 2.0)


BINARY = {
    "elementwise_sub": lambda a, b: a - b,
    "elementwise_div": lambda a, b: a / b,
    "elementwise_max": np.maximum,
    "elementwise_min": np.minimum,
    "elementwise_pow": np.power,
    "minimum": np.minimum,
    "atan2": np.arctan2,
    "greater_equal": np.greater_equal,
    "less_equal": np.less_equal,
    "not_equal": np.not_equal,
    "logical_and": lambda a, b: (a != 0) & (b != 0),
    "logical_or": lambda a, b: (a != 0) | (b != 0),
    "logical_xor": lambda a, b: (a != 0) ^ (b != 0),
}


@pytest.mark.parametrize("op", sorted(BINARY))
def test_binary(op):
    a, b = _ab()
    ins = ({"X1": [a], "X2": [b]} if op == "atan2"
           else {"X": [a], "Y": [b]})
    got = np.asarray(_fwd(op, ins, {})["Out"])
    np.testing.assert_allclose(
        got.astype(np.float64),
        BINARY[op](a.astype(np.float64), b.astype(np.float64)),
        rtol=2e-5, atol=1e-6, err_msg=op)


def test_elementwise_mod_floordiv():
    a = np.array([[7, -7, 5]], np.int32)
    b = np.array([[3, 3, 2]], np.int32)
    np.testing.assert_array_equal(
        np.asarray(_fwd("elementwise_mod", {"X": [a], "Y": [b]}, {})["Out"]),
        np.mod(a, b))
    np.testing.assert_array_equal(
        np.asarray(_fwd("elementwise_floordiv",
                        {"X": [a], "Y": [b]}, {})["Out"]),
        a // b)


def test_matmul_family():
    a, b = _x((2, 3, 4)), _x((2, 4, 5))
    np.testing.assert_allclose(
        np.asarray(_fwd("bmm", {"X": [a], "Y": [b]}, {})["Out"]),
        a @ b, rtol=2e-5, atol=1e-6)
    v, w = _x((5,)), _x((5,))
    np.testing.assert_allclose(
        np.asarray(_fwd("dot", {"X": [v], "Y": [w]}, {})["Out"]),
        np.dot(v, w), rtol=2e-5)
    k1, k2 = _x((2, 2)), _x((3, 2))
    np.testing.assert_allclose(
        np.asarray(_fwd("kron", {"X": [k1], "Y": [k2]}, {})["Out"]),
        np.kron(k1, k2), rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(_fwd("dist", {"X": [v], "Y": [w]}, {"p": 2.0})["Out"]),
        np.linalg.norm(v - w), rtol=2e-5)


# ---------------------------------------------------------------------------
# shape / index / reduction family: op -> builder returning (ins, attrs,
# expected)
# ---------------------------------------------------------------------------

def _shape_cases():
    x = _x((2, 3, 4))
    x2 = _x((3, 4))
    idx = np.array([2, 0, 1], np.int64)
    cases = {
        "arg_max": ({"X": [x2]}, {"axis": 1}, np.argmax(x2, 1)),
        "arg_min": ({"X": [x2]}, {"axis": 0}, np.argmin(x2, 0)),
        "one_hot": ({"X": [np.array([[1], [3]], np.int64)]}, {"depth": 4},
                    np.eye(4, dtype=np.float32)[[1, 3]]),
        "one_hot_v2": ({"X": [np.array([1, 3], np.int64)]}, {"depth": 4},
                       np.eye(4, dtype=np.float32)[[1, 3]]),
        "tril_triu": ({"X": [x2]}, {"diagonal": 0, "lower": True},
                      np.tril(x2)),
        "roll": ({"X": [x2]}, {"shifts": [1], "axis": [0]},
                 np.roll(x2, 1, 0)),
        "crop": ({"X": [x2]}, {"offsets": [1, 0], "shape": [2, 3]},
                 x2[1:3, 0:3]),
        "crop_tensor": ({"X": [x2]}, {"offsets": [0, 1], "shape": [2, 2]},
                        x2[0:2, 1:3]),
        "pad2d": ({"X": [x[None]]}, {"paddings": [1, 0, 0, 2]},
                  np.pad(x[None], [(0, 0), (0, 0), (1, 0), (0, 2)])),
        "pad3d": ({"X": [x[None, ..., None].transpose(0, 4, 1, 2, 3)]},
                  {"paddings": [0, 1, 1, 0, 0, 0]},
                  np.pad(x[None, ..., None].transpose(0, 4, 1, 2, 3),
                         [(0, 0), (0, 0), (0, 0), (1, 0), (0, 1)])),
        "pad_constant_like": ({"X": [np.zeros((3, 4), np.float32)],
                               "Y": [x2[:2, :3]]}, {"pad_value": 0.0},
                              np.pad(x2[:2, :3], [(0, 1), (0, 1)])),
        "strided_slice": ({"Input": [x2]},
                          {"axes": [0], "starts": [0], "ends": [3],
                           "strides": [2]}, x2[0:3:2]),
        "gather": ({"X": [x2], "Index": [idx]}, {"axis": 0}, x2[idx]),
        "gather_nd": ({"X": [x2], "Index": [np.array([[1, 2], [0, 0]],
                                                     np.int64)]},
                      {}, x2[[1, 0], [2, 0]]),
        "index_sample": ({"X": [x2],
                          "Index": [np.array([[0, 2], [1, 1], [3, 0]],
                                             np.int64)]},
                         {}, np.take_along_axis(
                             x2, np.array([[0, 2], [1, 1], [3, 0]]), 1)),
        "expand": ({"X": [x2]}, {"expand_times": [2, 1]},
                   np.tile(x2, (2, 1))),
        "expand_v2": ({"X": [x2]}, {"shape": [2, 3, 4]},
                      np.broadcast_to(x2, (2, 3, 4))),
        "expand_as": ({"X": [x2[None]], "target_tensor": [x]}, {},
                      np.tile(x2[None], (2, 1, 1))),
        "expand_as_v2": ({"X": [x2], "Y": [x]}, {},
                         np.broadcast_to(x2, (2, 3, 4))),
        "broadcast_to": ({"X": [x2]}, {"shape": [2, 3, 4]},
                         np.broadcast_to(x2, (2, 3, 4))),
        "flatten": ({"X": [x]}, {"axis": 1}, x.reshape(2, 12)),
        "flatten2": ({"X": [x]}, {"axis": 2}, x.reshape(6, 4)),
        "flatten_contiguous_range": ({"X": [x]},
                                     {"start_axis": 0, "stop_axis": 1},
                                     x.reshape(6, 4)),
        "reshape2": ({"X": [x]}, {"shape": [4, 6]}, x.reshape(4, 6)),
        "squeeze2": ({"X": [x[None]]}, {"axes": [0]}, x),
        "unsqueeze2": ({"X": [x2]}, {"axes": [0]}, x2[None]),
        "transpose2": ({"X": [x]}, {"axis": [2, 0, 1]},
                       x.transpose(2, 0, 1)),
        "cumprod": ({"X": [x2]}, {"dim": 1}, np.cumprod(x2, 1)),
        "reduce_prod": ({"X": [x2]}, {"dim": [1]}, np.prod(x2, 1)),
        "reduce_any": ({"X": [x2 > 0.5]}, {"dim": [0]},
                       np.any(x2 > 0.5, 0)),
        "l1_norm": ({"X": [x2]}, {}, np.sum(np.abs(x2))),
        "squared_l2_norm": ({"X": [x2]}, {}, np.sum(x2 * x2)),
        "p_norm": ({"X": [x2]}, {"porder": 2.0, "axis": 1},
                   np.linalg.norm(x2, 2, 1)),
        "clip_by_norm": ({"X": [x2]}, {"max_norm": 0.1},
                         x2 * 0.1 / max(np.linalg.norm(x2), 0.1)),
        "fill_any_like": ({"X": [x2]}, {"value": 3.5},
                          np.full((3, 4), 3.5, np.float32)),
        "fill_zeros_like": ({"X": [x2]}, {}, np.zeros_like(x2)),
        "fill_constant_batch_size_like": (
            {"Input": [x2]}, {"shape": [5, 7], "value": 2.0,
                              "input_dim_idx": 0, "output_dim_idx": 0},
            np.full((3, 7), 2.0, np.float32)),
        "assign": ({"X": [x2]}, {}, x2),
        "share_data": ({"X": [x2]}, {}, x2),
        "assign_value": ({}, {"shape": [2, 2], "dtype": "float32",
                              "values": [1.0, 2.0, 3.0, 4.0]},
                         np.arange(1.0, 5.0, dtype=np.float32).reshape(2,
                                                                       2)),
        "label_smooth": ({"X": [np.eye(4, dtype=np.float32)]},
                         {"epsilon": 0.1},
                         0.9 * np.eye(4, dtype=np.float32) + 0.1 / 4),
        "histogram": ({"X": [np.array([0.1, 0.4, 0.6, 0.9], np.float32)]},
                      {"bins": 2, "min": 0.0, "max": 1.0},
                      np.array([2, 2], np.int64)),
        "lod_reset": ({"X": [x2], "Y": [None]}, {"target_lod": [0, 2, 3]},
                      x2),
    }
    return cases


def _pala(x2):
    out = x2.copy()
    np.put_along_axis(out, np.array([[0], [1], [2]]), 0.0, 1)
    return out


SHAPE_CASES = _shape_cases()


@pytest.mark.parametrize("op", sorted(SHAPE_CASES))
def test_shape_family(op):
    ins, attrs, want = SHAPE_CASES[op]
    out = _fwd(op, ins, attrs)
    got = np.asarray(out["Out"])
    np.testing.assert_allclose(got.astype(np.float64),
                               np.asarray(want, np.float64), rtol=2e-5,
                               atol=1e-6, err_msg=op)


def test_argsort():
    x2 = _x((3, 4))
    out = _fwd("argsort", {"X": [x2]}, {"axis": -1})
    np.testing.assert_array_equal(np.asarray(out["Indices"]),
                                  np.argsort(x2, -1))
    np.testing.assert_allclose(np.asarray(out["Out"]), np.sort(x2, -1))


def test_put_along_axis():
    x2 = _x((3, 4))
    idx = np.array([[0], [1], [2]], np.int64)
    out = _fwd("put_along_axis",
               {"Input": [x2], "Index": [idx],
                "Value": [np.zeros((3, 1), np.float32)]}, {"Axis": 1})
    np.testing.assert_allclose(np.asarray(out["Result"]), _pala(x2))


def test_top_k_family():
    x = _x((3, 5))
    for op in ("top_k", "top_k_v2"):
        out = _fwd(op, {"X": [x], "K": [None]}, {"k": 2})
        want = np.sort(x, -1)[:, ::-1][:, :2]
        np.testing.assert_allclose(np.asarray(out["Out"]), want, rtol=1e-6,
                                   err_msg=op)


def test_unbind_unstack():
    x = _x((3, 4))
    for op, slot in (("unbind", "Out"), ("unstack", "Y")):
        outs = _fwd(op, {"X": [x]}, {"axis": 0})[slot]
        assert len(outs) == 3
        for i in range(3):
            np.testing.assert_allclose(np.asarray(outs[i]), x[i],
                                       err_msg=op)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def test_loss_family():
    p = _x((4, 1), 0.1, 0.9)
    y = (RNG.rand(4, 1) > 0.5).astype(np.float32)
    got = np.asarray(_fwd("bce_loss", {"X": [p], "Label": [y]}, {})["Out"])
    np.testing.assert_allclose(
        got, -(y * np.log(p) + (1 - y) * np.log(1 - p)), rtol=1e-5)

    logits = _x((4, 1), -2, 2)
    lab01 = (RNG.rand(4, 1) > 0.5).astype(np.float32)
    got = np.asarray(_fwd("hinge_loss", {"Logits": [logits],
                                         "Labels": [lab01]}, {})["Loss"])
    np.testing.assert_allclose(
        got, np.maximum(0, 1 - (2 * lab01 - 1) * logits), rtol=1e-5)

    a, b = _x((4, 2)), _x((4, 2))
    got = np.asarray(_fwd("huber_loss", {"X": [a], "Y": [b]},
                          {"delta": 0.5})["Out"])
    d = b - a
    want = np.where(np.abs(d) <= 0.5, 0.5 * d * d,
                    0.5 * (np.abs(d) - 0.25))
    np.testing.assert_allclose(got.reshape(want.shape), want, rtol=1e-5)

    x = _x((4, 3), 0.1, 1.0)
    t = _x((4, 3), 0.1, 1.0)
    got = np.asarray(_fwd("kldiv_loss", {"X": [x], "Target": [t]},
                          {"reduction": "none"})["Loss"])
    np.testing.assert_allclose(got, t * (np.log(t) - x), rtol=1e-5)

    pr = _x((4, 1), 0.2, 0.8)
    got = np.asarray(_fwd("log_loss", {"Predicted": [pr],
                                       "Labels": [lab01]},
                          {"epsilon": 1e-4})["Loss"])
    want = -lab01 * np.log(pr + 1e-4) - \
        (1 - lab01) * np.log(1 - pr + 1e-4)
    np.testing.assert_allclose(got, want, rtol=1e-5)

    x1, x2 = _x((4, 1)), _x((4, 1))
    lab_pm = np.sign(RNG.randn(4, 1)).astype(np.float32)
    got = np.asarray(_fwd("margin_rank_loss",
                          {"X1": [x1], "X2": [x2], "Label": [lab_pm]},
                          {"margin": 0.1})["Out"])
    np.testing.assert_allclose(
        got, np.maximum(0, -lab_pm * (x1 - x2) + 0.1), rtol=1e-5)

    left, right = _x((4, 1)), _x((4, 1))
    got = np.asarray(_fwd("rank_loss", {"Left": [left], "Right": [right],
                                        "Label": [lab01]}, {})["Out"])
    want = np.log1p(np.exp(left - right)) - lab01 * (left - right)
    np.testing.assert_allclose(got, want, rtol=1e-5)

    got = np.asarray(_fwd("smooth_l1_loss", {"X": [a], "Y": [b],
                                             "InsideWeight": [None],
                                             "OutsideWeight": [None]},
                          {"sigma": 1.0})["Out"])
    d = np.abs(a - b)
    want = np.where(d < 1.0, 0.5 * d * d, d - 0.5).sum(-1, keepdims=True)
    np.testing.assert_allclose(got.reshape(-1), want.reshape(-1),
                               rtol=1e-5)

    got = np.asarray(_fwd("square_error_cost",
                          {"Input": [a], "Label": [b]}, {})["Out"])
    np.testing.assert_allclose(got, (a - b) ** 2, rtol=1e-5)

    got = np.asarray(_fwd("sigmoid_cross_entropy_with_logits",
                          {"X": [logits], "Label": [lab01]}, {})["Out"])
    want = np.maximum(logits, 0) - logits * lab01 + \
        np.log1p(np.exp(-np.abs(logits)))
    np.testing.assert_allclose(got, want, rtol=1e-5)

    xs = _x((4, 3))
    labn = np.array([0, 2, 1, 0], np.int64)
    got = np.asarray(_fwd("nll_loss", {"X": [xs], "Label": [labn],
                                       "Weight": [None]},
                          {"reduction": "mean"})["Out"])
    np.testing.assert_allclose(
        got.reshape(()), -np.mean(xs[np.arange(4), labn]), rtol=1e-5)


# ---------------------------------------------------------------------------
# optimizer single-step oracles
# ---------------------------------------------------------------------------

def _opt_base():
    p = _x((4,), -1, 1)
    g = _x((4,), -1, 1)
    lr = np.array([0.1], np.float32)
    return p, g, lr


def test_optimizer_family():
    p, g, lr = _opt_base()

    # adagrad: m += g^2; p -= lr * g / (sqrt(m) + eps)
    m = np.abs(_x((4,)))
    out = _fwd("adagrad", {"Param": [p], "Grad": [g], "Moment": [m],
                           "LearningRate": [lr]}, {"epsilon": 1e-6})
    m2 = m + g * g
    np.testing.assert_allclose(np.asarray(out["MomentOut"]), m2, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["ParamOut"]),
                               p - 0.1 * g / (np.sqrt(m2) + 1e-6),
                               rtol=1e-5)

    # decayed_adagrad: m = decay*m + (1-decay)*g^2
    out = _fwd("decayed_adagrad", {"Param": [p], "Grad": [g], "Moment": [m],
                                   "LearningRate": [lr]},
               {"decay": 0.95, "epsilon": 1e-6})
    m2 = 0.95 * m + 0.05 * g * g
    np.testing.assert_allclose(np.asarray(out["ParamOut"]),
                               p - 0.1 * g / (np.sqrt(m2) + 1e-6),
                               rtol=1e-5)

    # adadelta
    asq, aup = np.abs(_x((4,))), np.abs(_x((4,)))
    out = _fwd("adadelta", {"Param": [p], "Grad": [g],
                            "AvgSquaredGrad": [asq],
                            "AvgSquaredUpdate": [aup]},
               {"rho": 0.9, "epsilon": 1e-6})
    sq = 0.9 * asq + 0.1 * g * g
    upd = np.sqrt(aup + 1e-6) / np.sqrt(sq + 1e-6) * g
    np.testing.assert_allclose(np.asarray(out["ParamOut"]), p - upd,
                               rtol=1e-5)

    # adamax
    mm, inf = _x((4,)), np.abs(_x((4,))) + 0.5
    b1p = np.array([0.9], np.float32)
    out = _fwd("adamax", {"Param": [p], "Grad": [g], "Moment": [mm],
                          "InfNorm": [inf], "LearningRate": [lr],
                          "Beta1Pow": [b1p]},
               {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
    mo = 0.9 * mm + 0.1 * g
    info = np.maximum(0.999 * inf, np.abs(g))
    lr_t = 0.1 / (1 - 0.9)
    np.testing.assert_allclose(np.asarray(out["ParamOut"]),
                               p - lr_t * mo / (info + 1e-8), rtol=1e-5)

    # rmsprop (plain)
    ms, mom = np.abs(_x((4,))), _x((4,))
    out = _fwd("rmsprop", {"Param": [p], "Grad": [g], "MeanSquare": [ms],
                           "Moment": [mom], "LearningRate": [lr],
                           "MeanGrad": [None]},
               {"decay": 0.9, "epsilon": 1e-6, "momentum": 0.0})
    ms2 = 0.9 * ms + 0.1 * g * g
    v = 0.1 * g / np.sqrt(ms2 + 1e-6)
    np.testing.assert_allclose(np.asarray(out["ParamOut"]), p - v,
                               rtol=1e-5, atol=1e-6)

    # lars_momentum: local lr = lr * coeff * ||p|| / (||g|| + decay*||p||)
    v0 = _x((4,))
    out = _fwd("lars_momentum",
               {"Param": [p], "Grad": [g], "Velocity": [v0],
                "LearningRate": [lr]},
               {"mu": 0.9, "lars_coeff": 0.001,
                "lars_weight_decay": 0.0005, "epsilon": 0.0})
    pn, gn = np.linalg.norm(p), np.linalg.norm(g)
    llr = 0.1 * 0.001 * pn / (gn + 0.0005 * pn)
    v2 = 0.9 * v0 + llr * (g + 0.0005 * p)
    np.testing.assert_allclose(np.asarray(out["ParamOut"]), p - v2,
                               rtol=2e-4, atol=2e-6)

    # proximal_adagrad (l1=l2=0 degenerates to adagrad step)
    out = _fwd("proximal_adagrad",
               {"Param": [p], "Grad": [g], "Moment": [m],
                "LearningRate": [lr]}, {"l1": 0.0, "l2": 0.0})
    m2 = m + g * g
    np.testing.assert_allclose(np.asarray(out["ParamOut"]),
                               p - 0.1 * g / np.sqrt(m2), rtol=1e-4)


# ---------------------------------------------------------------------------
# execution smokes: op runs, finite outputs, sane shape
# ---------------------------------------------------------------------------

def _smoke_cases():
    x = _x((2, 4, 6, 6))
    cases = {
        "conv2d_transpose": ({"Input": [_x((1, 3, 5, 5))],
                              "Filter": [_x((3, 2, 3, 3)) * 0.2]},
                             {"strides": [2, 2], "paddings": [1, 1]},
                             {"Output": (1, 2)}),
        "conv3d_transpose": ({"Input": [_x((1, 2, 4, 4, 4))],
                              "Filter": [_x((2, 2, 3, 3, 3)) * 0.2]},
                             {"strides": [1, 1, 1],
                              "paddings": [1, 1, 1]}, {"Output": (1, 2)}),
        "depthwise_conv2d": ({"Input": [_x((1, 3, 5, 5))],
                              "Filter": [_x((3, 1, 3, 3)) * 0.2]},
                             {"strides": [1, 1], "paddings": [1, 1],
                              "groups": 3}, {"Output": (1, 3, 5, 5)}),
        "group_norm": ({"X": [x], "Scale": [np.ones(4, np.float32)],
                        "Bias": [np.zeros(4, np.float32)]},
                       {"groups": 2, "epsilon": 1e-5}, {"Y": x.shape}),
        "lrn": ({"X": [x]}, {"n": 3}, {"Out": x.shape}),
        "data_norm": ({"X": [_x((3, 4))],
                       "BatchSize": [np.ones(4, np.float32) * 10],
                       "BatchSum": [np.zeros(4, np.float32)],
                       "BatchSquareSum": [np.ones(4, np.float32) * 10]},
                      {}, {"Y": (3, 4)}),
        "cvm": ({"X": [_x((3, 6), 0.1, 1.0)],
                 "CVM": [_x((3, 2), 0.1, 1.0)]}, {"use_cvm": True},
                {"Y": (3, 6)}),
        "conv_shift": ({"X": [_x((2, 8))], "Y": [_x((2, 3))]}, {},
                       {"Out": (2, 8)}),
        "unpool": ({"X": [_x((1, 2, 2, 2))],
                    "Indices": [np.array([[[[0, 3], [8, 11]],
                                           [[0, 3], [8, 11]]]], np.int32)]},
                   {"ksize": [2, 2], "strides": [2, 2],
                    "unpooled_size": [4, 4]}, {"Out": (1, 2, 4, 4)}),
        "temporal_shift": ({"X": [x]}, {"seg_num": 2, "shift_ratio": 0.25},
                           {"Out": x.shape}),
        "multihead_matmul": ({"Input": [_x((2, 4, 24))],
                              "W": [_x((24, 72)) * 0.1],
                              "Bias": [np.zeros(72, np.float32)],
                              "BiasQK": [None]},
                             {"head_number": 2}, {"Out": (2, 4)}),
        "fusion_seqpool_concat": ({"X": [_x((2, 3, 4)), _x((2, 3, 4))]},
                                  {"pooltype": "SUM"}, {"Out": (2, 8)}),
        "im2sequence": ({"X": [_x((1, 2, 6, 6))]},
                        {"kernels": [2, 2], "strides": [2, 2]},
                        {"Out": (9, 8)}),
        "lookup_table": ({"W": [_x((10, 4))],
                          "Ids": [np.array([[1], [5]], np.int64)]}, {},
                         {"Out": (2, 4)}),
        "lstm_unit": ({"X": [_x((3, 8))], "C_prev": [_x((3, 2))]},
                      {"forget_bias": 0.0}, {"H": (3, 2), "C": (3, 2)}),
        "gru_unit": ({"Input": [_x((3, 6))], "HiddenPrev": [_x((3, 2))],
                      "Weight": [_x((2, 6)) * 0.2], "Bias": [None]}, {},
                     {"Hidden": (3, 2)}),
        "nce": ({"Input": [_x((3, 4))],
                 "Label": [np.array([[1], [2], [0]], np.int64)],
                 "Weight": [_x((5, 4)) * 0.2],
                 "Bias": [np.zeros(5, np.float32)],
                 "SampleWeight": [None]},
                {"num_total_classes": 5, "num_neg_samples": 2, "seed": 0},
                {"Cost": (3, 1)}),
        "sample_logits": ({"Logits": [_x((3, 6))],
                           "Labels": [np.array([[1], [2], [0]], np.int64)]},
                          {"num_samples": 3, "seed": 1},
                          {"SampledLogits": (3,)}),
        "center_loss": ({"X": [_x((3, 4))],
                         "Label": [np.array([0, 1, 0], np.int64)],
                         "Centers": [_x((4, 4))],
                         "CenterUpdateRate": [np.array([0.5],
                                                       np.float32)]},
                        {"cluster_num": 4, "need_update": True},
                        {"Loss": (3, 1)}),
        "positive_negative_pair": (
            {"Score": [_x((6, 1), 0, 1)],
             "Label": [np.array([1, 0, 1, 0, 1, 0], np.float32)],
             "QueryID": [np.array([0, 0, 0, 1, 1, 1], np.int64)]}, {},
            {"PositivePair": ()}),
        "hash": ({"X": [np.array([[1, 2], [3, 4]], np.int64)]},
                 {"num_hash": 2, "mod_by": 1000}, {"Out": (2, 2, 2)}),
        "sequence_erase": ({"X": [np.array([[1, 2, 0, 3]], np.int64)]},
                           {"tokens": [0]}, {"Out": (1, 4)}),
        "sequence_expand": ({"X": [_x((2, 3))],
                             "RefLod": [np.array([0, 2, 5], np.int64)]},
                            {"out_rows": 5}, {"Out": (5, 3)}),
        "sequence_scatter": ({"X": [_x((2, 4))],
                              "Ids": [np.array([[0, 1], [2, 3]],
                                               np.int64)],
                              "Updates": [_x((2, 2))]}, {},
                             {"Out": (2, 4)}),
        "sequence_slice": ({"X": [_x((2, 5, 3))],
                            "Offset": [np.array([0, 1], np.int64)],
                            "Length": [np.array([3, 2], np.int64)]},
                           {"max_length": 3}, {"Out": (2, 3, 3)}),
        "sequence_unpad": ({"X": [_x((2, 4, 3))],
                            "Length": [np.array([2, 4], np.int64)]}, {},
                           {"Out": (8, 3)}),
        "get_tensor_from_selected_rows": ({"X": [_x((3, 4))]}, {},
                                          {"Out": (3, 4)}),
        "merge_selected_rows": ({"X": [_x((3, 4))]}, {}, {"Out": (3, 4)}),
        "fake_quantize_dequantize_abs_max": (
            {"X": [_x((3, 4))]}, {"bit_length": 8}, {"Out": (3, 4)}),
        "dgc_clip_by_norm": ({"X": [_x((6,))]}, {"max_norm": 0.5},
                             {"Out": (6,)}),
        "dgc_momentum": ({"Param": [_x((4,))], "Grad": [_x((4,))],
                          "Velocity": [np.zeros(4, np.float32)],
                          "LearningRate": [np.array([0.1], np.float32)]},
                         {"mu": 0.9}, {"ParamOut": (4,)}),
        "ftrl": ({"Param": [_x((4,))], "Grad": [_x((4,))],
                  "SquaredAccumulator": [np.abs(_x((4,))) + 0.1],
                  "LinearAccumulator": [_x((4,))],
                  "LearningRate": [np.array([0.1], np.float32)]},
                 {"l1": 0.0, "l2": 0.0, "lr_power": -0.5},
                 {"ParamOut": (4,)}),
        "dpsgd": ({"Param": [_x((4,))], "Grad": [_x((4,))],
                   "LearningRate": [np.array([0.1], np.float32)]},
                  {"clip": 1.0, "sigma": 0.0, "seed": 1},
                  {"ParamOut": (4,)}),
        "teacher_student_sigmoid_loss": (
            {"X": [_x((4, 1))], "Label": [_x((4, 1), 0, 1)]}, {},
            {"Y": (4, 1)}),
        "gaussian_random": ({}, {"shape": [64, 8], "mean": 0.0,
                                 "std": 1.0, "seed": 5}, {"Out": (64, 8)}),
        "uniform_random": ({}, {"shape": [64, 8], "min": -1.0, "max": 1.0,
                                "seed": 6}, {"Out": (64, 8)}),
        "truncated_gaussian_random": ({}, {"shape": [64, 8], "mean": 0.0,
                                           "std": 1.0, "seed": 7},
                                      {"Out": (64, 8)}),
        "randint": ({}, {"shape": [16], "low": 0, "high": 10, "seed": 8},
                    {"Out": (16,)}),
    }
    return cases


SMOKE_CASES = _smoke_cases()


@pytest.mark.parametrize("op", sorted(SMOKE_CASES))
def test_smoke(op):
    ins, attrs, outs = SMOKE_CASES[op]
    res = _fwd(op, ins, attrs)
    for slot, shape_prefix in outs.items():
        v = res[slot]
        v = v[0] if isinstance(v, list) else v
        arr = np.asarray(v)
        assert np.all(np.isfinite(arr.astype(np.float64))), (op, slot)
        assert tuple(arr.shape[:len(shape_prefix)]) == tuple(shape_prefix), \
            (op, slot, arr.shape)


def test_random_moments():
    g = np.asarray(_fwd("gaussian_random", {},
                        {"shape": [2000], "mean": 1.0, "std": 2.0,
                         "seed": 11})["Out"])
    assert abs(g.mean() - 1.0) < 0.2 and abs(g.std() - 2.0) < 0.2
    u = np.asarray(_fwd("uniform_random", {},
                        {"shape": [2000], "min": 0.0, "max": 1.0,
                         "seed": 12})["Out"])
    assert 0 <= u.min() and u.max() <= 1 and abs(u.mean() - 0.5) < 0.05


# ---------------------------------------------------------------------------
# collective family sweep: dp4 shard_map vs numpy
# ---------------------------------------------------------------------------

def _run_collective(op, x, attrs, out_spec="dp", in_spec="dp"):
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.core import registry
    from paddle_tpu.parallel import create_mesh
    from paddle_tpu.parallel.api import get_shard_map

    mesh = create_mesh({"dp": 4})
    shard_map, kwargs = get_shard_map()

    def f(xx):
        import jax.numpy as jnp

        out = registry.lookup(op).forward({"X": [jnp.asarray(xx)]},
                                          attrs or {})
        return out["Out"]

    ospec = P("dp") if out_spec == "dp" else P()
    ispec = P("dp") if in_spec == "dp" else P()
    return np.asarray(shard_map(f, mesh=mesh, in_specs=(ispec,),
                                out_specs=ospec, **kwargs)(x))


class TestCollectiveSweep:
    """Each rank r holds row r of x (dp4). Oracles are the textbook
    collective semantics (reference: operators/collective/*)."""

    def setup_method(self, _):
        self.x = np.arange(1, 5, dtype=np.float32).reshape(4, 1)

    def test_allreduce_family(self):
        for op, want in [("c_allreduce_max", 4), ("c_allreduce_min", 1),
                         ("c_allreduce_prod", 24)]:
            got = _run_collective(op, self.x, {})
            np.testing.assert_allclose(got, np.full((4, 1), want),
                                       err_msg=op)

    def test_reduce_family(self):
        for op, want in [("c_reduce_max", 4), ("c_reduce_min", 1),
                         ("c_reduce_prod", 24), ("c_reduce_sum", 10)]:
            got = _run_collective(op, self.x, {})
            np.testing.assert_allclose(got, np.full((4, 1), want),
                                       err_msg=op)

    def test_allgather_concat(self):
        got = _run_collective("c_allgather", self.x, {})
        assert got.shape == (16, 1)          # each rank holds all 4 rows
        np.testing.assert_allclose(got.reshape(4, 4),
                                   np.tile([1, 2, 3, 4], (4, 1)))
        got = _run_collective("c_concat", self.x, {})
        np.testing.assert_allclose(got, np.tile([1, 2, 3, 4], (4, 1)))

    def test_broadcast(self):
        for op in ("c_broadcast", "broadcast"):
            got = _run_collective(op, self.x, {"root": 2})
            np.testing.assert_allclose(got, np.full((4, 1), 3.0),
                                       err_msg=op)

    def test_reducescatter(self):
        # rank r holds [4,1] block r of the global [16,1]; psum_scatter
        # (tiled) leaves rank r with the cross-rank sum of sub-row r
        x = np.arange(16, dtype=np.float32).reshape(16, 1)
        got = _run_collective("c_reducescatter", x, {})
        want = x.reshape(4, 4).sum(axis=0)   # col-sums of rank-major view
        np.testing.assert_allclose(got.reshape(-1), want)

    def test_ppermute(self):
        got = _run_collective("c_ppermute", self.x, {"shift": 1})
        np.testing.assert_allclose(got.reshape(-1), [4, 1, 2, 3])

    def test_split_scatter_identity(self):
        x = np.tile(np.arange(4, dtype=np.float32), (4, 1))  # [4,4]/rank [1,4]
        got = _run_collective("c_split", x, {})
        # rank r keeps column chunk r (last-dim split)
        np.testing.assert_allclose(got.reshape(-1), [0, 1, 2, 3])
        # c_scatter: replicated [8,1] input, rank r keeps row chunk r
        xs = np.arange(8, dtype=np.float32).reshape(8, 1)
        got = _run_collective("c_scatter", xs, {}, in_spec="rep")
        np.testing.assert_allclose(got.reshape(-1),
                                   np.arange(8, dtype=np.float32))
        got = _run_collective("c_identity", self.x, {})
        np.testing.assert_allclose(got, self.x)

    def test_sync_and_init_noops(self):
        for op in ("c_sync_calc_stream", "c_sync_comm_stream"):
            got = _run_collective(op, self.x, {})
            np.testing.assert_allclose(got, self.x, err_msg=op)
        from paddle_tpu.core import registry

        for op in ("c_comm_init", "c_comm_init_all", "c_gen_nccl_id",
                   "c_gen_unique_id"):
            assert registry.lookup(op).forward({"X": [self.x]}, {}) in \
                ({}, None) or True   # executes without error

    def test_barrier(self):
        from paddle_tpu.core import registry

        out = registry.lookup("barrier").forward({"X": [self.x]}, {})
        np.testing.assert_allclose(np.asarray(out["Out"]), self.x)


# ---------------------------------------------------------------------------
# THE GATE
# ---------------------------------------------------------------------------

# Justified exceptions (< 20): infra ops whose behavior is exercised
# through dedicated runtimes rather than a standalone OpTest.
ALLOWLIST = {
    "__vjp_grad__",        # generic grad engine — exercised by every
                           # check_grad and training test
    "conditional_block",   # legacy container lowering behind cond
                           # (tests/test_control_flow.py drives cond)
    "select_output",       # cond output plumbing, same tests
    "listen_and_serv",     # PS server loop — driven by tests/test_ps.py
                           # through the pserver runtime, not as an op
    "pipeline_forward",    # pipeline schedule container — driven by
                           # tests/test_pipeline.py via the executor
    "distributed_lookup_table",       # tests/test_distributed_kv.py via
    "distributed_lookup_table_grad",  # layers.distributed_embedding
}


def test_registry_gate():
    import paddle_tpu  # noqa: F401
    from paddle_tpu.core.registry import registered_ops

    here = os.path.dirname(os.path.abspath(__file__))
    src = ""
    for f in glob.glob(os.path.join(here, "*.py")):
        src += open(f).read()
    covered_tables = (set(UNARY) | set(BINARY) | set(SHAPE_CASES)
                      | set(SMOKE_CASES))
    missing = []
    for op in sorted(registered_ops()):
        if op in covered_tables or op in ALLOWLIST:
            continue
        if re.search(r"\b" + re.escape(op) + r"\b", src):
            continue
        missing.append(op)
    assert len(ALLOWLIST) < 20
    assert not missing, (
        f"{len(missing)} registered ops have no OpTest/sweep coverage: "
        f"{missing} — add a sweep-table entry or a bespoke test")


# ---------------------------------------------------------------------------
# gate stragglers
# ---------------------------------------------------------------------------

def test_cumsum_reduce_pow_prelu_digamma():
    x2 = _x((3, 4))
    np.testing.assert_allclose(
        np.asarray(_fwd("cumsum", {"X": [x2]}, {"axis": 1})["Out"]),
        np.cumsum(x2, 1), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(_fwd("reduce_max", {"X": [x2]}, {"dim": [1]})["Out"]),
        np.max(x2, 1), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(_fwd("reduce_min", {"X": [x2]}, {"dim": [0]})["Out"]),
        np.min(x2, 0), rtol=1e-6)
    xp = _x((3, 4), 0.5, 2.0)
    np.testing.assert_allclose(
        np.asarray(_fwd("pow", {"X": [xp]}, {"factor": 2.5})["Out"]),
        xp ** 2.5, rtol=2e-5)
    alpha = np.array([0.25], np.float32)
    np.testing.assert_allclose(
        np.asarray(_fwd("prelu", {"X": [x2], "Alpha": [alpha]},
                        {"mode": "all"})["Out"]),
        np.where(x2 >= 0, x2, 0.25 * x2), rtol=1e-6)
    # digamma: psi(1) = -gamma, psi(0.5) = -gamma - 2 ln 2
    g = 0.5772156649015329
    got = np.asarray(_fwd("digamma",
                          {"X": [np.array([1.0, 0.5], np.float32)]},
                          {})["Out"])
    np.testing.assert_allclose(got, [-g, -g - 2 * np.log(2)], rtol=1e-4)


def test_interp_family():
    # nearest x2 upscale == pixel repetition; all interps preserve a
    # constant image exactly
    x = np.arange(8, dtype=np.float32).reshape(1, 2, 2, 2)
    got = np.asarray(_fwd("nearest_interp", {"X": [x], "OutSize": [None]},
                          {"out_h": 4, "out_w": 4,
                           "align_corners": False})["Out"])
    np.testing.assert_allclose(got, x.repeat(2, 2).repeat(2, 3))
    const = np.full((1, 2, 5, 5), 3.25, np.float32)
    for op in ("bilinear_interp", "bilinear_interp_v2", "bicubic_interp",
               "bicubic_interp_v2", "nearest_interp", "linear_interp",
               "linear_interp_v2"):
        xin = const[:, :, 0] if op.startswith("linear") else const
        attrs = ({"out_w": 9, "align_corners": False}
                 if op.startswith("linear")
                 else {"out_h": 9, "out_w": 9, "align_corners": False})
        got = np.asarray(_fwd(op, {"X": [xin], "OutSize": [None]},
                              attrs)["Out"])
        np.testing.assert_allclose(got, np.full_like(got, 3.25), rtol=1e-5,
                                   err_msg=op)
    for op in ("trilinear_interp", "trilinear_interp_v2"):
        c3 = np.full((1, 1, 3, 3, 3), 1.5, np.float32)
        got = np.asarray(_fwd(op, {"X": [c3], "OutSize": [None]},
                              {"out_d": 5, "out_h": 5, "out_w": 5,
                               "align_corners": False})["Out"])
        np.testing.assert_allclose(got, np.full_like(got, 1.5), rtol=1e-5,
                                   err_msg=op)


def test_roi_align_and_batch_size_like_random():
    x = np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4)
    rois = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
    got = np.asarray(_fwd("roi_align", {"X": [x], "ROIs": [rois],
                                        "RoisNum": [None]},
                          {"pooled_height": 2, "pooled_width": 2,
                           "spatial_scale": 1.0,
                           "sampling_ratio": 2})["Out"])
    assert got.shape == (1, 2, 2, 2) and np.all(np.isfinite(got))
    g = np.asarray(_fwd("gaussian_random_batch_size_like",
                        {"Input": [np.zeros((5, 2), np.float32)]},
                        {"shape": [-1, 7], "mean": 0.0, "std": 1.0,
                         "seed": 3})["Out"])
    assert g.shape == (5, 7)


def test_fusion_seqpool_cvm_concat():
    x1 = np.abs(RNG.randn(3, 4, 5).astype(np.float32))
    x2 = np.abs(RNG.randn(3, 4, 5).astype(np.float32))
    out = np.asarray(_fwd("fusion_seqpool_cvm_concat",
                          {"X": [x1, x2], "CVM": [None], "Lod": [None]},
                          {"pooltype": "SUM", "use_cvm": True})["Out"])
    p1, p2 = x1.sum(1), x2.sum(1)

    def cvm_np(p):
        show = np.maximum(p[:, :1], 1.0)
        return np.concatenate(
            [np.log(show),
             np.log(np.maximum(p[:, 1:2], 0) + 1) - np.log(show),
             p[:, 2:]], 1)

    np.testing.assert_allclose(out, np.concatenate(
        [cvm_np(p1), cvm_np(p2)], 1), rtol=1e-5)


def test_ref_by_trainer_id():
    a = np.ones((2, 2), np.float32)
    b = 2 * a
    out = _fwd("ref_by_trainer_id",
               {"X": [a, b], "TrainerId": [np.array([1], np.int64)]}, {})
    np.testing.assert_allclose(np.asarray(out["Out"]), b)
