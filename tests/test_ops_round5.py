"""Round-5 op tail: CPU-fused RNN family, split/merge_lod_tensor + IfElse,
pool3d-with-index, depthwise conv transpose, and the contrib/CTR ops —
each differential-tested against an independent numpy oracle
(the reference's OpTest strategy, SURVEY.md §4)."""

import numpy as np
import pytest

from tests.op_test import OpTest


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


class TestFusionLstm:
    def _oracle(self, x, wx, wh, b, lens):
        B, S, M = x.shape
        H = wh.shape[0]
        xx = x @ wx + b
        h = np.zeros((B, H), np.float64)
        c = np.zeros((B, H), np.float64)
        hs = np.zeros((B, S, H), np.float64)
        cs = np.zeros((B, S, H), np.float64)
        for t in range(S):
            gates = xx[:, t] + h @ wh
            cand, i, f, o = np.split(gates, 4, axis=-1)
            i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
            c_new = np.tanh(cand) * i + f * c
            h_new = o * np.tanh(c_new)
            alive = (t < lens)[:, None]
            h = np.where(alive, h_new, h)
            c = np.where(alive, c_new, c)
            hs[:, t] = np.where(alive, h, 0.0)   # zeros past each length
            cs[:, t] = np.where(alive, c, 0.0)
        return xx, hs, cs

    def test_output_and_grad(self):
        rng = np.random.RandomState(0)
        B, S, M, H = 2, 4, 3, 5
        x = rng.randn(B, S, M).astype(np.float32) * 0.5
        wx = rng.randn(M, 4 * H).astype(np.float32) * 0.3
        wh = rng.randn(H, 4 * H).astype(np.float32) * 0.3
        b = rng.randn(4 * H).astype(np.float32) * 0.1
        lens = np.array([4, 3], np.int32)
        oracle = self._oracle(x.astype(np.float64), wx.astype(np.float64),
                              wh.astype(np.float64), b.astype(np.float64),
                              lens)

        class T(OpTest):
            op_type = "fusion_lstm"

            def setup(t):
                t.inputs = {"X": x, "WeightX": wx, "WeightH": wh, "Bias": b,
                            "SequenceLength": lens}
                t.outputs = {"XX": oracle[0].astype(np.float32),
                             "Hidden": oracle[1].astype(np.float32),
                             "Cell": oracle[2].astype(np.float32)}

        t = T()
        t.check_output(atol=1e-4, rtol=1e-4)
        t.check_grad(["X", "WeightH"], "Hidden", delta=1e-2, atol=6e-3)


class TestFusionGru:
    def _oracle(self, x, wx, wh, b, origin):
        B, S, M = x.shape
        H = wh.shape[0]
        xx = x @ wx + b
        h = np.zeros((B, H), np.float64)
        hs = np.zeros((B, S, H), np.float64)
        for t in range(S):
            ur = _sigmoid(xx[:, t, :2 * H] + h @ wh[:, :2 * H])
            u, r = ur[:, :H], ur[:, H:]
            cand = np.tanh(xx[:, t, 2 * H:] + (r * h) @ wh[:, 2 * H:])
            h = u * h + (1 - u) * cand if origin else \
                u * cand + (1 - u) * h
            hs[:, t] = h
        return xx, hs

    @pytest.mark.parametrize("origin", [False, True])
    def test_output(self, origin):
        rng = np.random.RandomState(1)
        B, S, M, H = 2, 3, 4, 3
        x = rng.randn(B, S, M).astype(np.float32) * 0.5
        wx = rng.randn(M, 3 * H).astype(np.float32) * 0.3
        wh = rng.randn(H, 3 * H).astype(np.float32) * 0.3
        b = rng.randn(3 * H).astype(np.float32) * 0.1
        xx, hs = self._oracle(x.astype(np.float64), wx.astype(np.float64),
                              wh.astype(np.float64), b.astype(np.float64),
                              origin)

        class T(OpTest):
            op_type = "fusion_gru"

            def setup(t):
                t.inputs = {"X": x, "WeightX": wx, "WeightH": wh, "Bias": b}
                t.attrs = {"origin_mode": origin}
                t.outputs = {"XX": xx.astype(np.float32),
                             "Hidden": hs.astype(np.float32)}

        t = T()
        t.check_output(atol=1e-4, rtol=1e-4)
        if not origin:
            t.check_grad(["X", "WeightX"], "Hidden", delta=1e-2, atol=6e-3)


class TestAttentionLstm:
    def _oracle(self, x, c0, h0, aw, ab, scal, scal_b, lw, lb, lens):
        B, S, M = x.shape
        D = c0.shape[1]
        atted = x @ aw[:M, 0] + ab          # [B, S]
        h, c = h0.copy(), c0.copy()
        hs = np.zeros((B, S, D))
        cs = np.zeros((B, S, D))
        for t in range(S):
            for bi in range(B):
                L = lens[bi]
                if t >= L:
                    continue
                fc = np.maximum(atted[bi, :L] + c[bi] @ aw[M:, 0], 0.0)
                fc = np.maximum(fc * scal + scal_b, 0.0)
                e = np.exp(fc - fc.max())
                wgt = e / e.sum()
                lstm_x = wgt @ x[bi, :L]
                gates = lstm_x @ lw[D:] + h[bi] @ lw[:D] + lb
                f = _sigmoid(gates[:D])
                i = _sigmoid(gates[D:2 * D])
                o = _sigmoid(gates[2 * D:3 * D])
                cand = np.tanh(gates[3 * D:])
                c[bi] = f * c[bi] + i * cand
                h[bi] = o * np.tanh(c[bi])
                hs[bi, t] = h[bi]
                cs[bi, t] = c[bi]
        return hs, cs

    def test_output_and_grad(self):
        rng = np.random.RandomState(2)
        B, S, M, D = 2, 3, 4, 3
        x = rng.randn(B, S, M).astype(np.float32) * 0.5
        c0 = rng.randn(B, D).astype(np.float32) * 0.3
        h0 = rng.randn(B, D).astype(np.float32) * 0.3
        aw = rng.randn(M + D, 1).astype(np.float32) * 0.4
        ab = np.float32(0.1)
        scal = np.float32(1.3)
        scal_b = np.float32(0.05)
        lw = rng.randn(D + M, 4 * D).astype(np.float32) * 0.3
        lb = rng.randn(4 * D).astype(np.float32) * 0.1
        lens = np.array([3, 2], np.int32)
        hs, cs = self._oracle(x.astype(np.float64), c0.astype(np.float64),
                              h0.astype(np.float64), aw.astype(np.float64),
                              float(ab), float(scal), float(scal_b),
                              lw.astype(np.float64), lb.astype(np.float64),
                              lens)

        class T(OpTest):
            op_type = "attention_lstm"

            def setup(t):
                t.inputs = {"X": x, "C0": c0, "H0": h0,
                            "AttentionWeight": aw,
                            "AttentionBias": np.array([ab], np.float32),
                            "AttentionScalar": np.array([scal], np.float32),
                            "AttentionScalarBias": np.array([scal_b],
                                                            np.float32),
                            "LSTMWeight": lw, "LSTMBias": lb,
                            "SequenceLength": lens}
                t.outputs = {"Hidden": hs.astype(np.float32),
                             "Cell": cs.astype(np.float32)}

        t = T()
        t.check_output(atol=1e-4, rtol=1e-3)
        t.check_grad(["X"], "Hidden", delta=1e-2, atol=8e-3)


class TestFusionSeqconvEltaddRelu:
    def test_output_and_grad(self):
        # seed chosen so no preactivation sits within 0.13 of the relu
        # kink — central-difference grads are exact away from it
        rng = np.random.RandomState(0)
        B, S, D, WIN, MO = 2, 5, 3, 3, 4
        x = rng.randn(B, S, D).astype(np.float32)
        w = rng.randn(WIN * D, MO).astype(np.float32) * 0.3
        b = rng.randn(MO).astype(np.float32) * 0.2
        start = -1
        ctx = np.zeros((B, S, WIN * D))
        for k in range(WIN):
            for t in range(S):
                src = t + start + k
                if 0 <= src < S:
                    ctx[:, t, k * D:(k + 1) * D] = x[:, src]
        want = np.maximum(ctx @ w + b, 0.0)

        class T(OpTest):
            op_type = "fusion_seqconv_eltadd_relu"

            def setup(t):
                t.inputs = {"X": x, "Filter": w, "Bias": b}
                t.attrs = {"contextLength": WIN, "contextStart": start,
                           "contextStride": 1}
                t.outputs = {"Out": want.astype(np.float32)}

        t = T()
        t.check_output(atol=1e-5)
        t.check_grad(["X", "Filter"], "Out", delta=1e-2, atol=5e-3)


class TestFusionSeqexpandConcatFc:
    def test_output_and_grad(self):
        rng = np.random.RandomState(4)
        B, S, D0, D1, H = 2, 3, 3, 2, 4
        x0 = rng.randn(B, S, D0).astype(np.float32)
        x1 = rng.randn(B, D1).astype(np.float32)
        w = rng.randn(D0 + D1, H).astype(np.float32) * 0.4
        b = rng.randn(H).astype(np.float32) * 0.1
        cat = np.concatenate(
            [x0, np.broadcast_to(x1[:, None], (B, S, D1))], axis=-1)
        want = np.maximum(cat @ w + b, 0.0)

        class T(OpTest):
            op_type = "fusion_seqexpand_concat_fc"

            def setup(t):
                t.inputs = {"X": [("x0", x0), ("x1", x1)],
                            "FCWeight": w, "FCBias": b}
                t.attrs = {"fc_activation": "relu"}
                t.outputs = {"Out": want.astype(np.float32)}

        t = T()
        t.check_output(atol=1e-5)
        t.check_grad(["x0", "FCWeight"], "Out", delta=1e-2, atol=5e-3)


class TestSplitMergeLodTensor:
    def test_split_merge_roundtrip_and_grad(self):
        rng = np.random.RandomState(5)
        x = rng.randn(4, 3).astype(np.float32)
        mask = np.array([[1], [0], [1], [0]], np.int32)
        m = mask.reshape(-1).astype(bool)

        class TS(OpTest):
            op_type = "split_lod_tensor"

            def setup(t):
                t.inputs = {"X": x, "Mask": mask}
                t.outputs = {
                    "OutTrue": np.where(m[:, None], x, 0).astype(np.float32),
                    "OutFalse": np.where(m[:, None], 0, x).astype(np.float32)}

        t = TS()
        t.check_output()
        t.check_grad(["X"], "OutTrue", delta=1e-2, atol=5e-3)

        it = rng.randn(4, 3).astype(np.float32)
        if_ = rng.randn(4, 3).astype(np.float32)

        class TM(OpTest):
            op_type = "merge_lod_tensor"

            def setup(t):
                t.inputs = {"InTrue": it, "InFalse": if_, "Mask": mask}
                t.outputs = {"Out": np.where(m[:, None], it, if_)}

        t2 = TM()
        t2.check_output()
        t2.check_grad(["InTrue", "InFalse"], "Out", delta=1e-2, atol=5e-3)

    def test_ifelse_layer(self, scope):
        """IfElse over split/merge matches the rowwise select semantics
        (reference: fluid/layers/control_flow.py IfElse)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.ir import Program, program_guard

        main, startup = Program(), Program()
        with program_guard(main, startup):
            xv = layers.static_data("x", [4, 3], "float32")
            mk = layers.static_data("mk", [4, 1], "float32")
            ie = layers.IfElse(mk)
            with ie.true_block():
                ie.output(ie.input(xv) * 2.0)
            with ie.false_block():
                ie.output(ie.input(xv) - 1.0)
            out, = ie()
        rng = np.random.RandomState(6)
        x = rng.randn(4, 3).astype(np.float32)
        mask = np.array([[1], [0], [0], [1]], np.float32)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        got, = exe.run(main, feed={"x": x, "mk": mask}, fetch_list=[out],
                       scope=scope)
        want = np.where(mask.astype(bool), x * 2.0, x - 1.0)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)


class TestMaxPool3dWithIndex:
    def test_output_and_grad(self):
        rng = np.random.RandomState(7)
        N, C, D, H, W = 1, 2, 4, 4, 4
        x = rng.randn(N, C, D, H, W).astype(np.float32)
        ks, st = 2, 2
        od, oh, ow = D // st, H // st, W // st
        out = np.zeros((N, C, od, oh, ow), np.float32)
        idx = np.zeros((N, C, od, oh, ow), np.int32)
        for n in range(N):
            for c in range(C):
                for i in range(od):
                    for j in range(oh):
                        for k in range(ow):
                            blk = x[n, c, i * st:i * st + ks,
                                    j * st:j * st + ks, k * st:k * st + ks]
                            out[n, c, i, j, k] = blk.max()
                            a = np.unravel_index(blk.argmax(), blk.shape)
                            idx[n, c, i, j, k] = \
                                (i * st + a[0]) * H * W + \
                                (j * st + a[1]) * W + (k * st + a[2])

        class T(OpTest):
            op_type = "max_pool3d_with_index"

            def setup(t):
                t.inputs = {"X": x}
                t.attrs = {"ksize": [ks] * 3, "strides": [st] * 3,
                           "paddings": [0, 0, 0]}
                t.outputs = {"Out": out, "Mask": idx}

        t = T()
        t.check_output()
        t.check_grad(["X"], "Out", delta=1e-2, atol=5e-3)


class TestDepthwiseConv2dTranspose:
    def test_output_and_grad(self):
        rng = np.random.RandomState(8)
        N, C, H, W, K, S = 1, 3, 4, 4, 3, 2
        x = rng.randn(N, C, H, W).astype(np.float32)
        w = rng.randn(C, 1, K, K).astype(np.float32) * 0.4
        pad = 1
        oh = (H - 1) * S - 2 * pad + K
        out = np.zeros((N, C, oh, oh), np.float32)
        for n in range(N):
            for c in range(C):
                for i in range(H):
                    for j in range(W):
                        for ki in range(K):
                            for kj in range(K):
                                oi = i * S - pad + ki
                                oj = j * S - pad + kj
                                if 0 <= oi < oh and 0 <= oj < oh:
                                    out[n, c, oi, oj] += \
                                        x[n, c, i, j] * w[c, 0, ki, kj]

        class T(OpTest):
            op_type = "depthwise_conv2d_transpose"

            def setup(t):
                t.inputs = {"Input": x, "Filter": w}
                t.attrs = {"strides": [S, S], "paddings": [pad, pad],
                           "dilations": [1, 1]}
                t.outputs = {"Output": out}

        t = T()
        t.check_output(atol=1e-4)
        t.check_grad(["Input"], "Output", delta=1e-2, atol=5e-3)


def _np_tree_patch(edges, max_depth):
    """Independent numpy port of the reference patch construction
    (math/tree2col.cc construct_patch — DFS stack, depth-limited)."""
    tr = {}
    for u, v in edges:
        if u == 0 and v == 0:
            break
        tr.setdefault(u, []).append(v)
    nodes = sorted({u for u, v in edges if u or v}
                   | {v for u, v in edges if u or v})
    patches = {}
    for root in nodes:
        # (node, index, pclen, depth)
        stack = [(root, 1, 1, 0)]
        patch = [(root, 1, 1, 0)]
        visited = {root}
        while stack:
            node, idx, pclen, depth = stack[-1]
            end = True
            for i, v in enumerate(tr.get(node, [])):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, len(tr[node]), depth + 1))
                    patch.append((v, i + 1, len(tr[node]), depth + 1))
                    end = False
            if end:
                stack.pop()
        patches[root] = patch
    return patches


class TestTreeConv:
    def test_output_and_grad(self):
        rng = np.random.RandomState(9)
        B, N, F, OUT, CH, MD = 1, 6, 3, 2, 2, 3
        #     1
        #    / \
        #   2   3
        #  / \
        # 4   5
        edges = [(1, 2), (1, 3), (2, 4), (2, 5), (0, 0)]
        E = len(edges)
        edge_arr = np.zeros((B, E, 2), np.int32)
        edge_arr[0] = np.array(edges, np.int32)
        nodes = rng.randn(B, N, F).astype(np.float32)
        filt = rng.randn(F, 3, OUT, CH).astype(np.float32) * 0.4

        patches = _np_tree_patch(edges, MD)
        want = np.zeros((B, N, OUT, CH), np.float64)
        w2 = filt.reshape(F * 3, OUT * CH).astype(np.float64)
        for row, root in enumerate(sorted(patches)):
            p = np.zeros(3 * F)
            for (node, idx, pclen, depth) in patches[root]:
                eta_t = (MD - depth) / MD
                eta_l = (1 - eta_t) * (0.5 if pclen == 1
                                       else (idx - 1.0) / (pclen - 1.0))
                eta_r = (1 - eta_t) * (1 - eta_l)
                fv = nodes[0, node - 1].astype(np.float64)
                p[0::3] += eta_l * fv
                p[1::3] += eta_r * fv
                p[2::3] += eta_t * fv
            # patch rows are root-ordered == node-id-ordered here
            want[0, root - 1] = (p @ w2).reshape(OUT, CH)

        class T(OpTest):
            op_type = "tree_conv"

            def setup(t):
                t.inputs = {"NodesVector": nodes, "EdgeSet": edge_arr,
                            "Filter": filt}
                t.attrs = {"max_depth": MD}
                t.outputs = {"Out": want.astype(np.float32)}

        t = T()
        t.check_output(atol=1e-4)
        t.check_grad(["NodesVector", "Filter"], "Out", delta=1e-2,
                     atol=5e-3)


class TestVarConv2d:
    def test_output_and_grad(self):
        rng = np.random.RandomState(10)
        B, CIN, H, W, COUT, K = 2, 2, 5, 5, 3, 3
        x = rng.randn(B, CIN, H, W).astype(np.float32)
        w = rng.randn(COUT, CIN * K * K).astype(np.float32) * 0.3
        rl = np.array([5, 3], np.int32)
        cl = np.array([4, 5], np.int32)
        filt = w.reshape(COUT, CIN, K, K)
        pad = (K - 1) // 2
        # reference semantics: each image is convolved bare — values
        # beyond (rl, cl) must not leak into in-extent boundary windows
        xz = x.copy()
        for n in range(B):
            xz[n, :, rl[n]:, :] = 0
            xz[n, :, :, cl[n]:] = 0
        xp = np.pad(xz, [(0, 0), (0, 0), (pad, pad), (pad, pad)])
        out = np.zeros((B, COUT, H, W), np.float64)
        for n in range(B):
            for co in range(COUT):
                for i in range(H):
                    for j in range(W):
                        out[n, co, i, j] = np.sum(
                            xp[n, :, i:i + K, j:j + K] * filt[co])
        for n in range(B):
            out[n, :, rl[n]:, :] = 0
            out[n, :, :, cl[n]:] = 0

        class T(OpTest):
            op_type = "var_conv_2d"

            def setup(t):
                t.inputs = {"X": x, "W": w, "RowLength": rl,
                            "ColLength": cl}
                t.attrs = {"kernel_h": K, "kernel_w": K, "stride_h": 1,
                           "stride_w": 1, "output_channel": COUT}
                t.outputs = {"Out": out.astype(np.float32)}

        t = T()
        t.check_output(atol=1e-4)
        t.check_grad(["X", "W"], "Out", delta=1e-2, atol=5e-3)


def _np_xxh32(words, seed):
    """Independent scalar numpy XXH32 over uint32 word streams."""
    P1, P2, P3, P4, P5 = 2654435761, 2246822519, 3266489917, 668265263, \
        374761393
    M = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & M

    n = len(words)
    i = 0
    if n >= 4:
        v = [(seed + P1 + P2) & M, (seed + P2) & M, seed & M,
             (seed - P1) & M]
        while i + 4 <= n:
            for lane in range(4):
                v[lane] = (rotl((v[lane] + words[i + lane] * P2) & M, 13)
                           * P1) & M
            i += 4
        h = (rotl(v[0], 1) + rotl(v[1], 7) + rotl(v[2], 12)
             + rotl(v[3], 18)) & M
    else:
        h = (seed + P5) & M
    h = (h + n * 4) & M
    while i < n:
        h = (rotl((h + words[i] * P3) & M, 17) * P4) & M
        i += 1
    h ^= h >> 15
    h = (h * P2) & M
    h ^= h >> 13
    h = (h * P3) & M
    return h ^ (h >> 16)


class TestPyramidHash:
    def test_output_and_grad(self):
        rng = np.random.RandomState(11)
        B, S = 2, 4
        NUM_EMB, SPACE, RAND, LAYERS = 4, 13, 2, 3
        x = rng.randint(1, 50, (B, S)).astype(np.float32)
        w = rng.randn(SPACE + RAND, 1).astype(np.float32)
        lens = np.array([4, 3], np.int32)

        slots = []
        for l in range(2, LAYERS + 1):
            for p0 in range(S - l + 1):
                slots.append((l, p0))
        want = np.zeros((B, len(slots), NUM_EMB), np.float64)
        mask = np.zeros((B, len(slots)), np.int32)
        for bi in range(B):
            for si, (l, p0) in enumerate(slots):
                if p0 + l > lens[bi]:
                    continue
                mask[bi, si] = 1
                gram = list(x[bi, p0:p0 + l].view(np.uint32))
                for ji, j in enumerate(range(0, NUM_EMB, RAND)):
                    seed = 0 if ji == 0 else ji * RAND
                    pos = _np_xxh32([int(g) for g in gram], seed) % SPACE
                    want[bi, si, j:j + RAND] = w[pos:pos + RAND, 0]

        class T(OpTest):
            op_type = "pyramid_hash"

            def setup(t):
                t.inputs = {"X": x, "W": w, "Length": lens}
                t.attrs = {"num_emb": NUM_EMB, "space_len": SPACE,
                           "rand_len": RAND, "pyramid_layer": LAYERS,
                           "white_list_len": 0, "black_list_len": 0}
                t.outputs = {"Out": want.astype(np.float32),
                             "DropPos": mask}

        t = T()
        t.check_output(atol=1e-5)
        t.check_grad(["W"], "Out", delta=1e-2, atol=5e-3)


class TestRankAttention:
    def test_output_and_grad(self):
        rng = np.random.RandomState(12)
        N, D, K, P = 3, 2, 2, 3
        x = rng.randn(N, D).astype(np.float32)
        param = rng.randn(K * K * D, P).astype(np.float32) * 0.4
        # rows: [rank, tag0, idx0, tag1, idx1]
        ro = np.array([[1, 1, 0, 2, 1],
                       [2, 1, 0, 2, 1],
                       [0, 0, 0, 0, 0]], np.int32)     # row 2 invalid
        want = np.zeros((N, P), np.float64)
        ih = np.zeros((N, K * D), np.float64)
        pb = param.reshape(K * K, D, P).astype(np.float64)
        for i in range(N):
            rank = ro[i, 0]
            if rank < 1:
                continue
            for k in range(K):
                tag, idx = ro[i, 1 + 2 * k], ro[i, 2 + 2 * k]
                if tag < 1:
                    continue
                ih[i, k * D:(k + 1) * D] = x[idx]
                blk = (rank - 1) * K + (tag - 1)
                want[i] += x[idx].astype(np.float64) @ pb[blk]

        class T(OpTest):
            op_type = "rank_attention"

            def setup(t):
                t.inputs = {"X": x, "RankOffset": ro, "RankParam": param}
                t.attrs = {"MaxRank": K}
                t.outputs = {"Out": want.astype(np.float32),
                             "InputHelp": ih.astype(np.float32)}

        t = T()
        t.check_output(atol=1e-5, no_check_set=("InsRank",))
        t.check_grad(["X", "RankParam"], "Out", delta=1e-2, atol=5e-3)
