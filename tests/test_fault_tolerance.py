"""Fault-tolerance tests: seeded fault injection, the retrying PS
transport (reconnect/backoff/deadline, exactly-once retries via
sequence-number dedup), sync-barrier degradation to survivors, and
pserver kill→restart→ElasticRunner resume.

Reference analogs: heart_beat_monitor.h, the gRPC retry env knobs
consumed by grpc_client.cc, checkpoint_notify recovery. All localhost
sockets + sub-second injected timeouts — tier-1-safe chaos (`chaos`
marker, tools/chaos_check.py is the CLI twin).
"""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.chaos

_FLAG_DEFAULTS = {
    "FLAGS_fault_spec": "",
    "FLAGS_fault_seed": 0,
    "FLAGS_ps_rpc_timeout": 150.0,
    "FLAGS_ps_rpc_max_retries": 8,
    "FLAGS_ps_rpc_backoff": 0.05,
    "FLAGS_ps_sync_barrier_timeout": 120.0,
    "FLAGS_ps_degrade_to_survivors": False,
}


@pytest.fixture(autouse=True)
def _clean_fault_state():
    import paddle_tpu as pt
    from paddle_tpu.core import faults, telemetry
    from paddle_tpu.distributed.ps.rpc import RPCClient
    from paddle_tpu.ops.ps_ops import reset_recv_versions

    def scrub():
        for var in ("PT_FAULT_SPEC", "PT_FAULT_SEED"):
            os.environ.pop(var, None)
        pt.set_flags(_FLAG_DEFAULTS)
        faults.reset()
        telemetry.configure(None)
        telemetry.reset()
        RPCClient.reset_pool()
        reset_recv_versions()

    scrub()
    yield
    scrub()


def _fresh():
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()


def _build_net(in_dim=8, hidden=8, out_dim=2, lr=0.1):
    """Deterministic 2-layer net; returns (main, startup, loss)."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    _fresh()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [in_dim], stop_gradient=True)
        h = layers.fc(x, hidden, act="relu",
                      param_attr=pt.ParamAttr(
                          name="ft_w0",
                          initializer=pt.initializer.Xavier(seed=11)),
                      bias_attr=pt.ParamAttr(name="ft_b0"))
        y = layers.fc(h, out_dim,
                      param_attr=pt.ParamAttr(
                          name="ft_w1",
                          initializer=pt.initializer.Xavier(seed=12)),
                      bias_attr=pt.ParamAttr(name="ft_b1"))
        loss = layers.mean(y * y)
        pt.optimizer.SGDOptimizer(lr).minimize(loss)
    return main, startup, loss


def _make_pserver(endpoint, trainers, main, startup, sync=True, **kw):
    from paddle_tpu.distributed.ps import DistributeTranspiler, PServer

    t = DistributeTranspiler()
    t.transpile(0, program=main, startup_program=startup,
                pservers=endpoint, trainers=trainers, sync_mode=sync)
    prog, ps_startup = t.get_pserver_programs(endpoint)
    server = PServer(endpoint, prog, ps_startup, num_trainers=trainers,
                     sync_mode=sync, grad_to_param=prog._ps_grad_to_param,
                     grad_to_ops=prog._ps_grad_to_ops,
                     common_ops=prog._ps_common_ops, **kw)
    return server, t


def _free_endpoint():
    """A concrete localhost endpoint the transpiler can pin params to
    (trainer-program ops carry the endpoint STRING, so port-0 rebinding
    would leave them pointing nowhere)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    ep = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    return ep


def _echo_server():
    from paddle_tpu.distributed.ps.rpc import RPCServer

    return RPCServer("127.0.0.1:0", lambda m, n, a, aux: (a, aux))


class TestFaultSpec:
    def test_seeded_probabilistic_pattern_reproduces(self):
        """The fire pattern is a pure function of (seed, call index)."""
        from paddle_tpu.core import faults

        def pattern(seed):
            faults.configure("t.site:0.3", seed=seed)
            fired = []
            for _ in range(200):
                try:
                    faults.maybe_fail("t.site")
                    fired.append(False)
                except ConnectionError:
                    fired.append(True)
            return fired

        p_a, p_b, p_other = pattern(7), pattern(7), pattern(11)
        assert p_a == p_b, "same seed must reproduce the fire pattern"
        assert p_a != p_other, "different seed must change the pattern"
        assert 20 < sum(p_a) < 120   # ~60 expected at p=0.3

    def test_nth_and_every_triggers(self):
        from paddle_tpu.core import faults

        faults.configure("a:@3:RuntimeError,b:%4:OSError")
        a_fired = []
        for i in range(8):
            try:
                faults.maybe_fail("a")
                a_fired.append(False)
            except RuntimeError:
                a_fired.append(True)
        assert a_fired == [False, False, True] + [False] * 5, \
            "@3 fires exactly once, on the 3rd call"
        b_fired = []
        for i in range(9):
            try:
                faults.maybe_fail("b")
                b_fired.append(False)
            except OSError:
                b_fired.append(True)
        assert [i + 1 for i, f in enumerate(b_fired) if f] == [4, 8]

    def test_injection_emits_telemetry(self, tmp_path):
        import json

        from paddle_tpu.core import faults, telemetry

        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        faults.configure("t.x:@1:ConnectionError")
        with pytest.raises(ConnectionError, match="injected fault at t.x"):
            faults.maybe_fail("t.x", method="send_grad")
        assert telemetry.counter_get("faults.injected") == 1
        telemetry.flush_sink()   # the sink line-batches writes
        recs = [json.loads(line) for line in open(log) if line.strip()]
        inj = [r for r in recs if r["name"] == "faults.injected"]
        assert inj and inj[0]["attrs"]["site"] == "t.x"
        assert inj[0]["attrs"]["exc"] == "ConnectionError"
        assert any(r["kind"] == "fault" for r in recs)

    def test_malformed_specs_raise(self):
        from paddle_tpu.core import faults
        from paddle_tpu.core.faults import FaultSpecError

        for bad in ("justasite", "s:2.0", "s:0", "s:@0", "s:%0",
                    "s:0.1:NoSuchError", "s:0.1:extra:bits"):
            with pytest.raises(FaultSpecError):
                faults.configure(bad)
            faults.configure(None)

    def test_env_var_alias(self):
        """PT_FAULT_SPEC / PT_FAULT_SEED drive the registry when the
        flags are unset — the no-code-changes chaos knob."""
        from paddle_tpu.core import faults

        os.environ["PT_FAULT_SPEC"] = "env.site:@1:OSError"
        faults.reset()
        assert faults.active()
        with pytest.raises(OSError):
            faults.maybe_fail("env.site")
        faults.maybe_fail("env.site")   # @1 is spent


class TestRetryTransport:
    def test_retry_until_success_under_send_faults(self):
        import paddle_tpu as pt
        from paddle_tpu.core import faults, telemetry
        from paddle_tpu.distributed.ps.rpc import RPCClient

        srv = _echo_server()
        try:
            pt.set_flags({"FLAGS_ps_rpc_backoff": 0.01})
            faults.configure("ps.rpc.send:%2")   # every 2nd attempt dies
            cli = RPCClient(srv.endpoint)
            for i in range(6):
                out, aux = cli.call("echo", "x",
                                    np.full(3, i, np.float32), i)
                assert aux == i and np.all(out == i)
            assert telemetry.counter_get("ps.rpc_retries") >= 3
        finally:
            srv.shutdown()

    def test_deadline_exceeded_raises_within_budget(self):
        """A silent peer (accepts, never replies) must cost one deadline,
        not hang: RpcDeadlineError (a TimeoutError) inside ~budget."""
        import paddle_tpu as pt
        from paddle_tpu.core import telemetry
        from paddle_tpu.distributed.errors import RpcDeadlineError
        from paddle_tpu.distributed.ps.rpc import RPCClient

        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.bind(("127.0.0.1", 0))
        lst.listen(4)
        try:
            pt.set_flags({"FLAGS_ps_rpc_timeout": 0.4,
                          "FLAGS_ps_rpc_backoff": 0.01})
            cli = RPCClient(f"127.0.0.1:{lst.getsockname()[1]}")
            t0 = time.monotonic()
            with pytest.raises(RpcDeadlineError):
                cli.call("echo", "x")
            assert time.monotonic() - t0 < 3.0
            assert telemetry.counter_get("ps.rpc_deadline_exceeded") == 1
            assert issubclass(RpcDeadlineError, TimeoutError)
        finally:
            lst.close()

    def test_retries_exhausted_raises_rpc_error(self):
        import paddle_tpu as pt
        from paddle_tpu.core import telemetry
        from paddle_tpu.distributed.errors import RpcError
        from paddle_tpu.distributed.ps.rpc import RPCClient

        probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        probe.bind(("127.0.0.1", 0))
        dead_ep = f"127.0.0.1:{probe.getsockname()[1]}"
        probe.close()   # nothing listens here now
        pt.set_flags({"FLAGS_ps_rpc_max_retries": 2,
                      "FLAGS_ps_rpc_backoff": 0.01})
        with pytest.raises(RpcError, match="after 3 attempts"):
            RPCClient(dead_ep).call("echo")
        assert telemetry.counter_get("ps.rpc_retries") == 2

    def test_pool_evicts_dead_client_and_reconnects(self):
        """A pooled client whose server died must not stay a corpse: the
        failed call evicts it, and once a server is back on the endpoint
        the next get() talks to it."""
        import paddle_tpu as pt
        from paddle_tpu.core import telemetry
        from paddle_tpu.distributed.errors import RpcError
        from paddle_tpu.distributed.ps.rpc import RPCClient, RPCServer

        srv = _echo_server()
        ep = srv.endpoint
        cli = RPCClient.get(ep)
        _, aux = cli.call("echo", "x", None, 1)
        assert aux == 1
        srv.shutdown()
        pt.set_flags({"FLAGS_ps_rpc_max_retries": 1,
                      "FLAGS_ps_rpc_backoff": 0.01,
                      "FLAGS_ps_rpc_timeout": 5.0})
        with pytest.raises(RpcError):
            cli.call("echo", "x", None, 2)
        assert ep not in RPCClient._pool, "dead client must be evicted"

        srv2 = RPCServer(ep, lambda m, n, a, aux: (a, aux))   # same port
        try:
            _, aux = RPCClient.get(ep).call("echo", "x", None, 3)
            assert aux == 3
            assert telemetry.counter_get("ps.rpc_calls") >= 2
        finally:
            srv2.shutdown()

    def test_server_reaps_finished_connection_threads(self):
        from paddle_tpu.distributed.ps.rpc import RPCClient

        srv = _echo_server()
        try:
            for _ in range(40):
                cli = RPCClient(srv.endpoint)
                cli.call("echo")
                cli._close()
            time.sleep(0.2)   # let closed-conn threads notice and exit
            # one extra live call keeps at most a few threads alive; the
            # 40 finished ones must have been swept from the list
            assert len(srv._threads) <= 32
        finally:
            srv.shutdown()
            assert not any(t.is_alive() for t in srv._threads)


class TestExactlyOnce:
    def test_duplicate_send_grad_applies_once(self):
        """Reply lost after the server applied the grad: the retry must
        be answered from the dedup cache — version bumps once, the
        param moves once."""
        import paddle_tpu as pt
        from paddle_tpu.core import faults, telemetry
        from paddle_tpu.distributed.ps.rpc import RPCClient

        main, startup, loss = _build_net()
        server, _ = _make_pserver("127.0.0.1:0", 1, main, startup)
        try:
            (g,) = [g for g, p in server.grad_to_param.items()
                    if p == "ft_w0"]
            w0 = np.asarray(server.scope.find_var("ft_w0")).copy()
            grad = np.ones_like(w0)
            pt.set_flags({"FLAGS_ps_rpc_backoff": 0.01})
            # the FIRST reply read dies AFTER the request reached the
            # server — the classic duplicate-apply hazard
            faults.configure("ps.rpc.recv:@1:ConnectionError")
            cli = RPCClient(server.endpoint)
            _, ver = cli.call("send_grad", g, grad, aux=0)
            assert ver == 1, "version must bump exactly once"
            assert server._apply_count[g] == 1
            assert telemetry.counter_get("ps.rpc_dedup_hits") >= 1
            np.testing.assert_allclose(
                np.asarray(server.scope.find_var("ft_w0")),
                w0 - 0.1 * grad, rtol=1e-6,
                err_msg="grad applied more than once under retry")
        finally:
            server.shutdown()

    def test_2trainer_chaos_run_matches_fault_free(self, tmp_path):
        """Acceptance criterion: 10% connection drops on ps.rpc.send via
        PT_FAULT_SPEC, 2-trainer sync run → final params IDENTICAL to
        the fault-free run (exactly-once), ps.rpc_retries in the log."""
        import json

        import paddle_tpu as pt
        from paddle_tpu.core import faults, telemetry
        from paddle_tpu.distributed.ps.rpc import RPCClient

        steps = 5

        def run():
            main, startup, loss = _build_net()
            server, _ = _make_pserver("127.0.0.1:0", 2, main, startup)
            shapes = {g: np.asarray(
                server.scope.find_var(p)).shape
                for g, p in server.grad_to_param.items()}
            grads = sorted(shapes)
            params = [server.grad_to_param[g] for g in grads]
            errors = []

            def trainer(tid):
                try:
                    cli = RPCClient(server.endpoint)
                    for step in range(steps):
                        for gi, g in enumerate(grads):
                            rng = np.random.RandomState(
                                10_000 + 97 * step + 13 * tid + gi)
                            cli.call("send_grad", g,
                                     rng.randn(*shapes[g]).astype(
                                         np.float32) * 0.01, aux=tid)
                        for p in params:
                            cli.call("recv_param", p, aux=step + 1)
                except Exception as e:   # surface on the main thread
                    errors.append(e)

            threads = [threading.Thread(target=trainer, args=(tid,))
                       for tid in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            alive = [t for t in threads if t.is_alive()]
            final = {p: np.asarray(server.scope.find_var(p)).copy()
                     for p in params}
            server.shutdown()
            assert not errors, f"trainer failed: {errors[0]!r}"
            assert not alive, "trainer thread deadlocked"
            return final

        pt.set_flags({"FLAGS_ps_rpc_backoff": 0.01,
                      "FLAGS_ps_rpc_timeout": 30.0})
        baseline = run()

        log = tmp_path / "chaos.jsonl"
        telemetry.configure(str(log))
        os.environ["PT_FAULT_SPEC"] = "ps.rpc.send:0.1"
        os.environ["PT_FAULT_SEED"] = "3"
        faults.reset()
        chaos = run()
        faults.configure(None)

        assert telemetry.counter_get("faults.injected") > 0, \
            "the 10% spec never fired — chaos run proved nothing"
        assert telemetry.counter_get("ps.rpc_retries") > 0
        for p in baseline:
            np.testing.assert_array_equal(
                chaos[p], baseline[p],
                err_msg=f"{p} diverged under injected faults — "
                        f"retries were not exactly-once")
        telemetry.flush_sink()   # the sink line-batches writes
        recs = [json.loads(line) for line in open(log) if line.strip()]
        assert any(r["name"] == "ps.rpc_retries" for r in recs)
        assert any(r["name"] == "faults.injected" for r in recs)


class TestDegradedBarrier:
    def test_sync_barrier_shrinks_to_survivors(self):
        """A trainer that goes silent mid-run must not stall the other
        to the barrier timeout: with FLAGS_ps_degrade_to_survivors the
        monitor's death verdict completes the barrier over the live set,
        and a revived trainer is required again at the next version."""
        import paddle_tpu as pt
        from paddle_tpu.core import telemetry
        from paddle_tpu.distributed.ps.rpc import RPCClient

        pt.set_flags({"FLAGS_ps_degrade_to_survivors": True})
        main, startup, loss = _build_net()
        server, _ = _make_pserver("127.0.0.1:0", 2, main, startup,
                                  heartbeat_timeout=0.4)
        try:
            (g,) = [g for g, p in server.grad_to_param.items()
                    if p == "ft_w0"]
            st = server.states[g]
            w0 = np.asarray(server.scope.find_var("ft_w0")).copy()
            ones = np.ones_like(w0)
            cli0, cli1 = (RPCClient(server.endpoint),
                          RPCClient(server.endpoint))

            # step 1: both trainers contribute — full barrier
            cli0.call("send_grad", g, ones, aux=0)
            cli1.call("send_grad", g, 3 * ones, aux=1)
            assert st.version == 1
            w1 = w0 - 0.1 * 2 * ones   # mean(1, 3) = 2
            np.testing.assert_allclose(
                np.asarray(server.scope.find_var("ft_w0")), w1, rtol=1e-6)

            # step 2: trainer 1 goes silent; trainer 0 must not stall
            cli0.call("send_grad", g, ones, aux=0)
            deadline = time.monotonic() + 5.0
            while st.version < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert st.version == 2, \
                "barrier never degraded to the survivor set"
            np.testing.assert_allclose(
                np.asarray(server.scope.find_var("ft_w0")),
                w1 - 0.1 * ones, rtol=1e-6,
                err_msg="degraded update must average survivors only")
            assert telemetry.counter_get("ps.barrier_degraded") >= 1
            assert telemetry.counter_get("ps.trainer_dead") >= 1
            assert 1 in server.monitor.dead

            # revival: trainer 1 pings back in and is required again
            cli1.call("heartbeat", aux=1)
            assert 1 not in server.monitor.dead
            assert telemetry.counter_get("ps.trainer_revived") >= 1
            cli0.call("send_grad", g, ones, aux=0)
            cli1.call("send_grad", g, ones, aux=1)
            assert st.version == 3, "revived trainer rejoins the barrier"
        finally:
            server.shutdown()


class TestElasticPserverRestart:
    def _feed(self, step):
        rng = np.random.RandomState(700 + step)
        return {"x": rng.randn(8, 8).astype(np.float32)}

    def _baseline(self, steps):
        import paddle_tpu as pt

        main, startup, loss = _build_net()
        server, t = _make_pserver(_free_endpoint(), 1, main, startup)
        try:
            exe = pt.Executor(pt.CPUPlace())
            scope = pt.Scope()
            exe.run(t.get_startup_program(), scope=scope,
                    use_compiled=False)
            prog = t.get_trainer_program()
            out = []
            for s in range(steps):
                r = exe.run(prog, feed=self._feed(s), fetch_list=[loss],
                            scope=scope, use_compiled=False)
                out.append(float(np.asarray(r[0]).reshape(-1)[0]))
            return out
        finally:
            server.shutdown()

    def test_kill_restart_resumes_from_checkpoint(self, tmp_path):
        """Acceptance criterion: the pserver dies mid-run; ElasticRunner
        recognises the transport error, the operator hook restarts the
        server from its snapshot, and training finishes — matching the
        uninterrupted run step-for-step."""
        import paddle_tpu as pt
        from paddle_tpu.distributed.elastic import ElasticRunner
        from paddle_tpu.distributed.ps import PServer
        from paddle_tpu.distributed.ps.rpc import RPCClient
        from paddle_tpu.ops.ps_ops import reset_recv_versions

        steps = 6
        base_losses = self._baseline(steps)

        ep = _free_endpoint()   # a fixed endpoint the restart can rebind

        main, startup, loss = _build_net()
        from paddle_tpu.distributed.ps import DistributeTranspiler

        t = DistributeTranspiler()
        t.transpile(0, program=main, startup_program=startup,
                    pservers=ep, trainers=1, sync_mode=True)
        prog, ps_startup = t.get_pserver_programs(ep)

        def start_server():
            return PServer(ep, prog, ps_startup, num_trainers=1,
                           sync_mode=True,
                           grad_to_param=prog._ps_grad_to_param,
                           grad_to_ops=prog._ps_grad_to_ops,
                           common_ops=prog._ps_common_ops)

        srv_ckpt = str(tmp_path / "srv")
        server_holder = [start_server()]
        pt.set_flags({"FLAGS_ps_rpc_timeout": 3.0,
                      "FLAGS_ps_rpc_max_retries": 2,
                      "FLAGS_ps_rpc_backoff": 0.02})
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(t.get_startup_program(), scope=scope, use_compiled=False)
        trainer_prog = t.get_trainer_program()
        losses = {}
        killed = [False]

        def step_fn(step):
            if step == 3 and not killed[0]:
                killed[0] = True
                server_holder[0].shutdown()   # the crash
            r = exe.run(trainer_prog, feed=self._feed(step),
                        fetch_list=[loss], scope=scope,
                        use_compiled=False)
            # coordinated snapshot: server state after this step's apply
            RPCClient.get(ep).call("checkpoint", f"{srv_ckpt}|srv")
            losses[step] = float(np.asarray(r[0]).reshape(-1)[0])
            return losses[step]

        def on_restart(step, exc):
            fresh = start_server()
            fresh.load_checkpoint(srv_ckpt, "srv")
            server_holder[0] = fresh
            RPCClient.reset_pool()
            reset_recv_versions()

        runner = ElasticRunner(str(tmp_path / "tr"), trainer_prog, scope,
                               save_interval_steps=1, max_restarts=2)
        try:
            runner.run(step_fn, steps, on_restart=on_restart)
        finally:
            runner.mgr.close()
            server_holder[0].shutdown()
        assert killed[0] and runner.restarts == 1
        got = [losses[s] for s in range(steps)]
        np.testing.assert_allclose(
            got, base_losses, rtol=1e-5,
            err_msg="resume from checkpoint diverged from the "
                    "uninterrupted run")


class TestChaosCheckCLI:
    def test_smoke(self):
        """Tier-1 smoke of tools/chaos_check.py (satellite: CI/tooling)."""
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, "tools", "chaos_check.py"),
             "--fault-spec", "ps.rpc.send:%5", "--seed", "3",
             "--steps", "3", "--rpc-timeout", "10"],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert out.returncode == 0, \
            f"chaos_check failed:\n{out.stdout[-3000:]}\n{out.stderr[-3000:]}"
        assert "faults.injected" in out.stdout
        assert "ps.rpc_retries" in out.stdout
        assert "CHAOS OK" in out.stdout
