"""Test config: run on a virtual 8-device CPU mesh (no TPU contention).

Mirrors the reference's test strategy (SURVEY.md §4): multi-device tests run
single-process against mesh slices of the 8 virtual devices.
MUST set env before jax is imported anywhere.
"""

import os

# NOTE: this environment pins JAX_PLATFORMS=axon via sitecustomize; the env
# var alone is not enough — use jax.config.update after import.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 runs "
                   "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: fault-injection / failure-path tests driven by "
                   "the core/faults.py harness (tools/chaos_check.py is "
                   "the CLI twin). Tier-1-safe: localhost sockets, "
                   "sub-second timeouts.")
    config.addinivalue_line(
        "markers", "serving: micro-batching serving-engine tests "
                   "(paddle_tpu/serving/). Tier-1-fast: in-process "
                   "client for engine tests, one ephemeral-port HTTP "
                   "smoke.")


@pytest.fixture
def scope():
    import paddle_tpu as pt

    return pt.Scope()


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Give every test fresh default programs + a fresh name generator,
    and clear any process-global mesh a test installed (a leaked mesh
    makes later single-device tests shard their feeds)."""
    import paddle_tpu as pt
    from paddle_tpu.core import ir, unique_name
    from paddle_tpu.parallel import mesh as mesh_mod

    old_main, old_startup = ir._main_program, ir._startup_program
    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    old_gen = unique_name.switch()
    old_mesh = mesh_mod._current_mesh
    mesh_mod._current_mesh = None
    yield
    unique_name.switch(old_gen)
    ir._main_program, ir._startup_program = old_main, old_startup
    mesh_mod._current_mesh = old_mesh


# Op-sweep modules run with the static program verifier gating every
# executor dispatch (FLAGS_verify_program, core/verify.py): the OpTest
# harness builds one program per op, so the whole registry's programs
# flow through the verifier's structure/dataflow/hazard/donation checks
# — any op whose desc wiring the verifier would mis-judge fails loudly
# here, keeping the lint trustworthy on real models.
_VERIFY_FLAG_MODULES = {
    "test_op_registry_sweep", "test_gate_smoke_execution",
    "test_ops_batch2", "test_ops_batch3", "test_ops_extended",
    "test_ops_round4", "test_ops_round5", "test_crf_ops",
    "test_pallas_serving_kernels",
}


@pytest.fixture(autouse=True)
def _verify_program_on_op_sweeps(request):
    mod = request.module.__name__.rsplit(".", 1)[-1]
    if mod not in _VERIFY_FLAG_MODULES:
        yield
        return
    from paddle_tpu.core import flags as _flags

    # the typed scoped-override API (PR 15): exact prior restored even
    # when the test body raises — no ad-hoc save/restore
    with _flags.overrides(verify_program=True):
        yield


# Concurrency-sanitizer opt-in (PT_SANITIZE_TESTS=1): the serving/
# cluster tier-1 modules — the most thread-dense surfaces — run with
# FLAGS_sanitize_locks=1, so every engine/router/cluster lock they
# construct is an instrumented core/analysis/lockdep.py lock: a
# lock-order inversion or a same-thread re-entry introduced by a new
# change raises LockOrderError inside the test instead of wedging a
# production router at 3 a.m. Off by default: the instrumented wrappers
# add per-acquire bookkeeping the rest of the suite shouldn't pay.
_SANITIZE_MODULES = {"test_serving", "test_cluster_serving"}


@pytest.fixture(autouse=True)
def _sanitize_locks_opt_in(request):
    if not os.environ.get("PT_SANITIZE_TESTS"):
        yield
        return
    mod = request.module.__name__.rsplit(".", 1)[-1]
    if mod not in _SANITIZE_MODULES:
        yield
        return
    from paddle_tpu.core import flags as _flags

    with _flags.overrides(sanitize_locks=True):
        yield


def rand(*shape, dtype=np.float32, seed=None):
    rng = np.random.RandomState(seed if seed is not None else 42)
    return rng.randn(*shape).astype(dtype)


# ---------------------------------------------------------------------------
# Execution-based op-coverage gate (round 5; VERDICT r4 weak #4)
#
# The old gate regex-searched test SOURCES, so an op named in a comment
# counted as covered. Now every process records the op types that actually
# flowed through the executors (core/executor.py EXECUTED_OP_TYPES), dumps
# them at session end, and the controller asserts
# registry ⊆ executed ∪ allowlist. Enforced only for full-suite runs (the
# sentinel below fires when the collected test count says "whole tests/
# directory"), so single-file invocations stay usable.
# ---------------------------------------------------------------------------

_COV_DIR_ENV = "PT_OP_COVERAGE_DIR"
if not os.environ.get(_COV_DIR_ENV):
    import tempfile as _tempfile

    # set BEFORE xdist spawns workers so every process shares the dir
    os.environ[_COV_DIR_ENV] = _tempfile.mkdtemp(prefix="pt_opcov_")

# Infra ops exercised through dedicated runtimes, not executor-visible ops
# (mirrors the justification list in test_op_registry_sweep.py).
_GATE_ALLOWLIST = {
    "listen_and_serv",              # PS server loop (pserver runtime)
    "distributed_lookup_table",     # io_callback body inside jit — the
    "distributed_lookup_table_grad",  # push/pull runs outside run_op
}


def pytest_sessionfinish(session, exitstatus):
    import glob as _glob
    import json as _json
    import uuid as _uuid

    covdir = os.environ.get(_COV_DIR_ENV)
    if not covdir or not os.path.isdir(covdir):
        return
    try:
        from paddle_tpu.core.executor import EXECUTED_OP_TYPES
    except Exception:
        EXECUTED_OP_TYPES = set()
    if EXECUTED_OP_TYPES:
        with open(os.path.join(covdir, f"{_uuid.uuid4().hex}.json"),
                  "w") as f:
            _json.dump(sorted(EXECUTED_OP_TYPES), f)
    # full-suite sentinel: any process that COLLECTED the whole suite
    # (workers collect everything under xdist) plants it
    if len(getattr(session, "items", []) or []) > 500 or \
            os.path.exists(os.path.join(covdir, "SENTINEL")):
        open(os.path.join(covdir, "SENTINEL"), "w").close()
    if hasattr(session.config, "workerinput"):
        return  # xdist worker: the controller does the assert
    import shutil as _shutil

    if not os.path.exists(os.path.join(covdir, "SENTINEL")):
        # partial run: no enforcement — and clean this session's dir so
        # dev loops don't accumulate /tmp/pt_opcov_* litter (workers
        # have already dumped by the time the controller gets here)
        _shutil.rmtree(covdir, ignore_errors=True)
        os.environ.pop(_COV_DIR_ENV, None)
        return
    if exitstatus not in (0,):
        _shutil.rmtree(covdir, ignore_errors=True)
        os.environ.pop(_COV_DIR_ENV, None)
        return  # failures already reported; don't stack a gate error
    executed = set()
    for path in _glob.glob(os.path.join(covdir, "*.json")):
        try:
            executed.update(_json.load(open(path)))
        except Exception:
            pass
    import paddle_tpu  # noqa: F401
    from paddle_tpu.core.registry import registered_ops

    missing = [op for op in registered_ops()
               if op not in executed and op not in _GATE_ALLOWLIST]
    _shutil.rmtree(covdir, ignore_errors=True)
    os.environ.pop(_COV_DIR_ENV, None)
    if missing:
        raise pytest.UsageError(
            f"EXECUTION coverage gate: {len(missing)} registered ops "
            f"never flowed through an executor during the suite: "
            f"{missing} — add a test that RUNS them (a textual mention "
            f"no longer counts)")
