"""Test config: run on a virtual 8-device CPU mesh (no TPU contention).

Mirrors the reference's test strategy (SURVEY.md §4): multi-device tests run
single-process against mesh slices of the 8 virtual devices.
MUST set env before jax is imported anywhere.
"""

import os

# NOTE: this environment pins JAX_PLATFORMS=axon via sitecustomize; the env
# var alone is not enough — use jax.config.update after import.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def scope():
    import paddle_tpu as pt

    return pt.Scope()


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Give every test fresh default programs + a fresh name generator,
    and clear any process-global mesh a test installed (a leaked mesh
    makes later single-device tests shard their feeds)."""
    import paddle_tpu as pt
    from paddle_tpu.core import ir, unique_name
    from paddle_tpu.parallel import mesh as mesh_mod

    old_main, old_startup = ir._main_program, ir._startup_program
    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    old_gen = unique_name.switch()
    old_mesh = mesh_mod._current_mesh
    mesh_mod._current_mesh = None
    yield
    unique_name.switch(old_gen)
    ir._main_program, ir._startup_program = old_main, old_startup
    mesh_mod._current_mesh = old_mesh


def rand(*shape, dtype=np.float32, seed=None):
    rng = np.random.RandomState(seed if seed is not None else 42)
    return rng.randn(*shape).astype(dtype)
