"""Cluster serving control plane tests (paddle_tpu/serving/router.py,
cluster.py, health.py + core/retry.py).

Contracts under test:
* core/retry.py reproduces the PS transport's schedule semantics
  (deadline beats budget, exponential+jittered backoff, capped delays) —
  the rpc.py rebase itself is pinned by tests/test_fault_tolerance.py;
* /healthz is READINESS (503 while starting/draining), /livez liveness;
* the router balances by live queue-depth score, skips not-ready
  replicas, and falls back to a SWAPPING replica only when nothing is
  READY;
* models publish atomically with COMMIT manifests; the watcher only
  reports verified versions and falls back past corrupt ones;
* a replica death mid-load loses ZERO accepted requests — retried on a
  survivor, exactly once per request id (process-mode SIGKILL included);
* a hot swap under load returns only committed-version results: every
  response is bitwise one version's output, tagged with that version,
  and the fleet converges to the new version with zero failures;
* deadlines hold across a failover hop, including the all-replicas-down
  case (bounded 503, not a hang).
"""

import json
import random
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.serving

IN_DIM, OUT_DIM = 6, 4


def _save_mlp(dirname, seed):
    import paddle_tpu as pt
    from paddle_tpu import io, layers

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [IN_DIM])
        h = layers.fc(x, 8, act="relu", param_attr=pt.ParamAttr(
            name="cs_w0", initializer=pt.initializer.Xavier(seed=seed)))
        y = layers.fc(h, OUT_DIM, param_attr=pt.ParamAttr(
            name="cs_w1", initializer=pt.initializer.Xavier(seed=seed + 1)))
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    io.save_inference_model(str(dirname), ["x"], [y],
                            main_program=main, scope=scope)
    return str(dirname)


def _predictor(model_dir):
    from paddle_tpu.inference import AnalysisConfig, create_predictor

    return create_predictor(AnalysisConfig(model_dir))


def _rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, IN_DIM).astype(np.float32)


def _post_infer(url, x, rid=None, deadline_ms=None, timeout=60):
    doc = {"inputs": {"x": x.tolist()}}
    if deadline_ms is not None:
        doc["deadline_ms"] = deadline_ms
    headers = {"Content-Type": "application/json"}
    if rid is not None:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(url + "/v1/infer",
                                 data=json.dumps(doc).encode(),
                                 headers=headers)
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, path, timeout=10):
    try:
        resp = urllib.request.urlopen(url + path, timeout=timeout)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# core/retry.py — the extracted schedule
# ---------------------------------------------------------------------------

class TestRetrySchedule:
    def test_backoff_doubles_and_caps(self):
        from paddle_tpu.core import retry

        sched = retry.RetryPolicy(max_retries=5, backoff=0.1, jitter=0.0,
                                  max_delay=0.4).start()
        delays = []
        for _ in range(5):
            outcome, delay = sched.note_failure()
            assert outcome == retry.RETRY
            delays.append(round(delay, 6))
        assert delays == [0.1, 0.2, 0.4, 0.4, 0.4]
        outcome, _ = sched.note_failure()
        assert outcome == retry.EXHAUSTED
        assert sched.attempt == 6

    def test_deadline_beats_remaining_budget(self):
        from paddle_tpu.core import retry

        sched = retry.RetryPolicy(max_retries=100, backoff=0.001,
                                  deadline=0.02).start()
        time.sleep(0.03)
        outcome, _ = sched.note_failure()
        assert outcome == retry.DEADLINE
        assert sched.expired()

    def test_delay_clipped_to_deadline(self):
        from paddle_tpu.core import retry

        sched = retry.RetryPolicy(max_retries=10, backoff=10.0, jitter=0.0,
                                  max_delay=10.0, deadline=0.2).start()
        outcome, delay = sched.note_failure()
        assert outcome == retry.RETRY
        assert delay <= 0.2

    def test_jitter_bounds(self):
        from paddle_tpu.core import retry

        policy = retry.RetryPolicy(max_retries=1, backoff=1.0, jitter=0.5,
                                   max_delay=10.0)
        for seed in range(20):
            sched = policy.start(rng=random.Random(seed))
            _, delay = sched.note_failure()
            assert 0.5 <= delay < 1.5

    def test_remaining_default_without_deadline(self):
        from paddle_tpu.core import retry

        sched = retry.RetryPolicy(deadline=None).start()
        assert sched.remaining(default=7.5) == 7.5
        assert sched.remaining() is None
        bounded = retry.RetryPolicy(deadline=5.0).start()
        assert 0 < bounded.remaining() <= 5.0


# ---------------------------------------------------------------------------
# /healthz readiness vs /livez liveness
# ---------------------------------------------------------------------------

class TestHealthEndpoints:
    def test_readiness_lifecycle(self, tmp_path):
        from paddle_tpu.serving import ServingConfig, ServingEngine
        from paddle_tpu.serving.server import ServingHTTPServer

        model_dir = _save_mlp(tmp_path / "m", seed=3)
        engine = ServingEngine(_predictor(model_dir),
                               config=ServingConfig(max_batch_size=4,
                                                    batch_timeout_ms=2.0))
        server = ServingHTTPServer(engine).start()
        try:
            code, doc = _get(server.url, "/healthz")
            assert (code, doc["status"]) == (503, "starting")
            assert doc["ready"] is False and doc["alive"] is True
            assert _get(server.url, "/livez")[0] == 200

            engine.start(warmup=True)
            code, doc = _get(server.url, "/healthz")
            assert (code, doc["status"]) == (200, "ok")
            assert doc["ready"] is True

            engine.close(drain=True, timeout=10)
            code, doc = _get(server.url, "/healthz")
            assert code == 503
            assert doc["status"] in ("draining", "stopped")
            code, doc = _get(server.url, "/livez")
            assert (code, doc["status"]) == (503, "stopped")
        finally:
            server.shutdown()

    def test_swap_gate_restores_ready_only_from_ready(self):
        from paddle_tpu.serving.health import (DRAINING, READY, SWAPPING,
                                               HealthState, ReadyGate)

        h = HealthState(READY)
        with ReadyGate(h, SWAPPING):
            assert h.state == SWAPPING
        assert h.state == READY
        h.set(DRAINING)
        with ReadyGate(h, SWAPPING):
            pass
        assert h.state == DRAINING   # a failed swap must not resurrect


# ---------------------------------------------------------------------------
# router balancing (stubbed handles, no sockets)
# ---------------------------------------------------------------------------

class TestRouterPick:
    def _router_with(self, states):
        """states: list of (ready, queue_depth[, status])"""
        from paddle_tpu.serving.router import ReplicaHandle, Router

        router = Router()
        for i, st in enumerate(states):
            handle = ReplicaHandle(f"r{i}", f"http://127.0.0.1:{40000 + i}")
            handle.ready = st[0]
            handle.queue_depth = st[1]
            if len(st) > 2:
                handle.status = st[2]
            router._handles.append(handle)
        return router

    def test_picks_lowest_queue_depth(self):
        router = self._router_with([(True, 5), (True, 1), (True, 9)])
        for _ in range(6):
            assert router.pick().name == "r1"

    def test_skips_not_ready(self):
        router = self._router_with([(False, 0), (True, 7)])
        assert router.pick().name == "r1"

    def test_inflight_counts_toward_score(self):
        router = self._router_with([(True, 2), (True, 2)])
        router._handles[0].inflight = 5
        assert router.pick().name == "r1"

    def test_round_robins_ties(self):
        router = self._router_with([(True, 0), (True, 0), (True, 0)])
        picks = {router.pick().name for _ in range(9)}
        assert picks == {"r0", "r1", "r2"}, \
            "an idle fleet must share work, not hammer one replica"

    def test_swapping_fallback_only_when_nothing_ready(self):
        router = self._router_with([(False, 0, "swapping"), (True, 50)])
        assert router.pick().name == "r1"   # READY beats swapping
        router = self._router_with([(False, 0, "swapping"),
                                    (False, 0, "down")])
        assert router.pick().name == "r0"   # swapping still serves
        router = self._router_with([(False, 0, "down"), (False, 0, "down")])
        assert router.pick() is None

    def test_exclude_honored(self):
        router = self._router_with([(True, 0), (True, 5)])
        first = router.pick()
        other = router.pick(exclude={first})
        assert other is not None and other is not first


# ---------------------------------------------------------------------------
# model publishing + watching
# ---------------------------------------------------------------------------

class TestModelPublishing:
    def test_publish_verify_watch(self, tmp_path):
        from paddle_tpu import checkpoint as ckpt

        src = _save_mlp(tmp_path / "src", seed=5)
        root = str(tmp_path / "models")
        p1 = ckpt.publish_model(root, src)
        manifest = ckpt.verify_model_dir(p1)
        assert manifest["version"] == 1 and manifest["committed"]
        assert "__model__.json" in manifest["files"]

        watcher = ckpt.ModelWatcher(root)
        assert watcher.poll() == (1, p1)
        assert watcher.poll() is None       # fires once per version
        p2 = ckpt.publish_model(root, src)
        assert watcher.poll() == (2, p2)

    def test_corrupt_version_is_skipped(self, tmp_path):
        import os

        from paddle_tpu import checkpoint as ckpt

        src = _save_mlp(tmp_path / "src", seed=6)
        root = str(tmp_path / "models")
        p1 = ckpt.publish_model(root, src)
        p2 = ckpt.publish_model(root, src)
        # corrupt v2's params: the watcher must fall back to v1
        victim = [n for n in os.listdir(p2) if n.endswith(".npy")][0]
        with open(os.path.join(p2, victim), "ab") as f:
            f.write(b"rot")
        with pytest.raises(ckpt.CheckpointCorruptError):
            ckpt.verify_model_dir(p2)
        assert ckpt.ModelWatcher(root).latest() == (1, p1)

    def test_uncommitted_dir_is_invisible(self, tmp_path):
        import os

        from paddle_tpu import checkpoint as ckpt

        src = _save_mlp(tmp_path / "src", seed=7)
        root = str(tmp_path / "models")
        ckpt.publish_model(root, src)
        # a torn publish: files but no manifest under a committed-style name
        torn = os.path.join(root, "model-000009")
        os.makedirs(torn)
        with open(os.path.join(torn, "__model__.json"), "w") as f:
            f.write("{}")
        assert ckpt.ModelWatcher(root).latest()[0] == 1
        with pytest.raises(ckpt.CheckpointCorruptError,
                           match="never committed"):
            ckpt.verify_model_dir(torn)

    def test_versions_are_immutable(self, tmp_path):
        from paddle_tpu import checkpoint as ckpt

        src = _save_mlp(tmp_path / "src", seed=8)
        root = str(tmp_path / "models")
        ckpt.publish_model(root, src, version=3)
        with pytest.raises(ckpt.CheckpointError, match="immutable"):
            ckpt.publish_model(root, src, version=3)


# ---------------------------------------------------------------------------
# in-process cluster: balance, failover, dedup, deadlines
# ---------------------------------------------------------------------------

@pytest.fixture(scope="class")
def inproc_cluster(tmp_path_factory):
    from paddle_tpu import checkpoint as ckpt
    from paddle_tpu.serving import ClusterController, ServingConfig

    tmp = tmp_path_factory.mktemp("cluster")
    model_dir = _save_mlp(tmp / "m1", seed=11)
    root = str(tmp / "models")
    ckpt.publish_model(root, model_dir, version=1)
    cluster = ClusterController(
        root, replicas=2, inprocess=True,
        serving_config=ServingConfig(max_batch_size=4,
                                     batch_timeout_ms=1.0),
        auto_swap=False).start(ready_timeout_s=120)
    yield cluster, model_dir
    cluster.close()


class TestInprocCluster:
    def test_routes_and_balances(self, inproc_cluster):
        cluster, model_dir = inproc_cluster
        reference = _predictor(model_dir)
        x = _rows(2, seed=1)
        want, = reference.run({"x": x})
        replicas_hit = set()
        for _ in range(12):
            code, doc = _post_infer(cluster.url, x)
            assert code == 200, doc
            name = next(iter(doc["outputs"]))
            got = np.asarray(doc["outputs"][name], dtype=np.float32)
            np.testing.assert_array_equal(got, want)
            assert doc["model_version"] == 1
            replicas_hit.add(doc["replica"])
        assert replicas_hit == {"replica-0", "replica-1"}, \
            "idle fleet must round-robin"

    def test_request_id_dedup_replays(self, inproc_cluster):
        from paddle_tpu.core import telemetry

        cluster, _ = inproc_cluster
        x = _rows(1, seed=2)
        before_req = telemetry.counter_get("serving.requests")
        code1, doc1 = _post_infer(cluster.url, x, rid="dedup-me")
        code2, doc2 = _post_infer(cluster.url, x, rid="dedup-me")
        assert code1 == code2 == 200
        assert doc2.get("deduped") is True
        assert doc1["outputs"] == doc2["outputs"]
        # exactly ONE backend inference for the two client attempts
        assert telemetry.counter_get("serving.requests") - before_req == 1

    def test_failover_loses_nothing(self, inproc_cluster):
        from paddle_tpu.core import telemetry

        cluster, _ = inproc_cluster
        x = _rows(1, seed=3)
        results = {}
        lock = threading.Lock()

        def worker(wid):
            for i in range(25):
                rid = f"fo-{wid}-{i}"
                code, doc = _post_infer(cluster.url, x, rid=rid)
                with lock:
                    results[rid] = (code, doc.get("request_id"))

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        cluster.replicas[0].kill()   # abrupt: socket torn, backlog failed
        for t in threads:
            t.join(60)
        assert len(results) == 100
        bad = {k: v for k, v in results.items() if v[0] != 200}
        assert not bad, f"lost requests across replica death: {bad}"
        # response ids round-trip, so exactly-once is id-verifiable
        assert all(v[1] == k for k, v in results.items())
        assert telemetry.counter_get("router.replica_deaths") >= 1
        # the dead replica is out of rotation; traffic still flows
        code, doc = _post_infer(cluster.url, x)
        assert code == 200 and doc["replica"] == "replica-1"

    def test_deadline_bounded_when_all_replicas_down(self, inproc_cluster):
        cluster, _ = inproc_cluster
        cluster.replicas[1].kill()   # [0] already dead from the test above
        t0 = time.monotonic()
        code, doc = _post_infer(cluster.url, _rows(1), deadline_ms=1500)
        waited = time.monotonic() - t0
        assert code in (503, 504), doc
        assert waited < 10.0, "dead fleet must answer within the deadline" \
            f" window, waited {waited:.1f}s"


# ---------------------------------------------------------------------------
# hot swap under load (its own cluster: the one above gets killed)
# ---------------------------------------------------------------------------

class TestHotSwapUnderLoad:
    def test_only_committed_version_results(self, tmp_path):
        from paddle_tpu import checkpoint as ckpt
        from paddle_tpu.serving import ClusterController, ServingConfig

        m1 = _save_mlp(tmp_path / "m1", seed=21)
        m2 = _save_mlp(tmp_path / "m2", seed=77)
        root = str(tmp_path / "models")
        ckpt.publish_model(root, m1, version=1)
        x = _rows(2, seed=9)
        want = {1: _predictor(m1).run({"x": x})[0],
                2: _predictor(m2).run({"x": x})[0]}
        assert not np.array_equal(want[1], want[2])

        cluster = ClusterController(
            root, replicas=2, inprocess=True,
            serving_config=ServingConfig(max_batch_size=4,
                                         batch_timeout_ms=1.0),
            model_poll_s=0.1).start(ready_timeout_s=120)
        stop = threading.Event()
        records = []
        lock = threading.Lock()

        def load():
            while not stop.is_set():
                code, doc = _post_infer(cluster.url, x)
                name = next(iter(doc["outputs"])) if code == 200 else None
                with lock:
                    records.append(
                        (code, doc.get("model_version"),
                         np.asarray(doc["outputs"][name],
                                    dtype=np.float32)
                         if code == 200 else None))

        threads = [threading.Thread(target=load) for _ in range(3)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.2)
            ckpt.publish_model(root, m2, version=2)   # triggers the roll
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                with lock:
                    seen_v2 = any(r[1] == 2 for r in records)
                if seen_v2 and cluster.current_version == 2:
                    break
                time.sleep(0.1)
            time.sleep(0.3)   # a little post-swap traffic
        finally:
            stop.set()
            for t in threads:
                t.join(30)
            cluster.close()

        assert records, "no traffic recorded"
        failures = [r for r in records if r[0] != 200]
        assert not failures, \
            f"hot swap dropped {len(failures)} requests: {failures[:3]}"
        versions = {r[1] for r in records}
        assert versions <= {1, 2}
        assert 2 in versions, "fleet never served the new version"
        for _code, version, out in records:
            # every response is BITWISE one committed version's output,
            # tagged with that version — never a mixed/cold response
            assert np.array_equal(out, want[version]), \
                "response does not match its tagged model version"


# ---------------------------------------------------------------------------
# process-mode: the real SIGKILL
# ---------------------------------------------------------------------------

class TestProcessClusterKill:
    def test_sigkill_mid_load_exactly_once(self, tmp_path):
        from paddle_tpu import checkpoint as ckpt
        from paddle_tpu.core import telemetry
        from paddle_tpu.serving import ClusterController, ServingConfig

        model_dir = _save_mlp(tmp_path / "m1", seed=31)
        root = str(tmp_path / "models")
        ckpt.publish_model(root, model_dir, version=1)
        cluster = ClusterController(
            root, replicas=2, inprocess=False,
            serving_config=ServingConfig(max_batch_size=4,
                                         batch_timeout_ms=1.0),
            auto_swap=False).start(ready_timeout_s=180)
        x = _rows(1, seed=4)
        results = {}
        lock = threading.Lock()

        def worker(wid):
            for i in range(50):
                rid = f"pk-{wid}-{i}"
                code, doc = _post_infer(cluster.url, x, rid=rid)
                with lock:
                    results[rid] = results.get(rid, 0) + (
                        1 if code == 200 else 0)

        try:
            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.25)
            victim = cluster.replicas[0]
            victim.kill()            # the real SIGKILL, mid-load
            for t in threads:
                t.join(120)
            assert victim.proc.poll() is not None
            assert len(results) == 200
            lost = {k: v for k, v in results.items() if v != 1}
            assert not lost, \
                f"SIGKILL lost/duplicated requests: {list(lost)[:5]}"
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and \
                    not telemetry.counter_get("router.replica_deaths"):
                time.sleep(0.2)
            assert telemetry.counter_get("router.replica_deaths") >= 1
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# stats surfaces
# ---------------------------------------------------------------------------

class TestStatsSurfaces:
    def test_engine_stats_carry_version_and_state(self, tmp_path):
        from paddle_tpu.serving import ServingConfig, ServingEngine

        model_dir = _save_mlp(tmp_path / "m", seed=41)
        engine = ServingEngine(_predictor(model_dir),
                               config=ServingConfig(max_batch_size=4,
                                                    batch_timeout_ms=1.0),
                               version=7)
        stats = engine.stats()
        assert stats["model_version"] == 7
        assert stats["status"] == "starting" and stats["ready"] is False
        engine.start(warmup=False)
        assert engine.stats()["ready"] is True
        engine.close(drain=True, timeout=10)
        assert engine.stats()["status"] == "stopped"

    def test_router_stats_and_perf_report_section(self):
        from paddle_tpu.core import telemetry
        from paddle_tpu.serving.router import Router

        telemetry.counter_add("router.requests", 0)
        router = Router()
        stats = router.stats()
        assert "replicas" in stats and stats["ready"] is False

        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "perf_report", os.path.join(os.path.dirname(__file__), "..",
                                        "tools", "perf_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        summary = mod._router_summary(
            {"router.requests": 10, "router.retries": 2,
             "router.failovers": 1, "router.swaps": 1}, {}, {})
        assert summary["requests"] == 10 and summary["failovers"] == 1
        assert mod._router_summary({}, {}, {}) is None
