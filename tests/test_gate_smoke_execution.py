"""Execute every op the round-5 EXECUTION coverage gate found running
nowhere (they had only textual mentions before). Each case runs through
executor.run_op — the REAL executor path (slot resolution, attr
injection, output binding) — feeding the registry-wide gate
(tests/conftest.py sessionfinish)."""

import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (registers ops)
from paddle_tpu.core import registry
from paddle_tpu.core.executor import run_op
from paddle_tpu.core.ir import OpDesc

_OUT_SLOTS = {
    "norm": ("Out", "Norm"), "fused_layer_norm": ("Y", "Mean", "Variance"),
    "beam_search_decode": ("SentenceIds", "SentenceScores"),
}


def _fwd(op, ins, attrs=None, n_out=1):
    """Build an OpDesc + env and execute through run_op (the executors'
    shared entry), returning {slot: value-or-list}."""
    import jax.numpy as jnp

    env = {}
    in_names = {}
    for slot, vals in ins.items():
        names = []
        for i, v in enumerate(vals):
            nm = f"in_{slot}_{i}"
            env[nm] = None if v is None else jnp.asarray(v)
            names.append(nm)
        in_names[slot] = names
    out_names = {s: [f"out_{s}_{j}" for j in range(n_out)]
                 for s in _OUT_SLOTS.get(op, ("Out",))}
    desc = OpDesc(op, in_names, out_names, dict(attrs or {}))
    run_op(desc, env, step=np.int32(0))
    res = {}
    for s, names in out_names.items():
        vals = [env.get(nm) for nm in names]
        res[s] = vals if len(vals) > 1 else vals[0]
    return res


X = np.linspace(-2.0, 2.0, 12).reshape(3, 4).astype(np.float32)
POS = np.abs(X) + 0.5


UNARY = {
    "ceil": (X, {}, np.ceil),
    "cos": (X, {}, np.cos),
    "sin": (X, {}, np.sin),
    "erf": (X, {}, None),
    "round": (X, {}, np.round),
    "sign": (X, {}, np.sign),
    "log1p": (POS, {}, np.log1p),
    "log2": (POS, {}, np.log2),
    "leaky_relu": (X, {"alpha": 0.1},
                   lambda x: np.where(x > 0, x, 0.1 * x)),
    "flip": (X, {"axis": [1]}, lambda x: x[:, ::-1]),
    "transpose": (X, {"axis": [1, 0]}, lambda x: x.T),
    "reshape": (X, {"shape": [4, 3]}, lambda x: x.reshape(4, 3)),
    "tile": (X, {"repeat_times": [2, 1]}, lambda x: np.tile(x, (2, 1))),
    "pad": (X, {"paddings": [1, 1, 0, 0], "pad_value": 0.0},
            lambda x: np.pad(x, [(1, 1), (0, 0)])),
    "reduce_all": ((X > -10), {"reduce_all": True}, None),
    "allreduce": (X, {}, lambda x: x),      # degrades to identity 1-rank
    "print": (X, {"message": "gate-smoke"}, lambda x: x),
    "select_output": (X, {"branch_num": 2}, None),
}


@pytest.mark.parametrize("op", sorted(UNARY))
def test_unary_family(op):
    x, attrs, ref = UNARY[op]
    ins = {"X": [x]}
    if op == "select_output":
        ins["Mask"] = [np.zeros((1,), np.int32)]
    out = _fwd(op, ins, attrs,
               n_out=2 if op == "select_output" else 1)
    key = "Out"
    val = out[key][0] if isinstance(out[key], list) else out[key]
    if ref is not None:
        np.testing.assert_allclose(np.asarray(val, np.float64),
                                   ref(x.astype(np.float64)), rtol=1e-5,
                                   atol=1e-6)
    else:
        assert np.asarray(val).size


def test_binary_and_misc():
    np.testing.assert_allclose(
        np.asarray(_fwd("maximum", {"X": [X], "Y": [-X]})["Out"]),
        np.maximum(X, -X))
    np.testing.assert_allclose(
        np.asarray(_fwd("minus", {"X": [X], "Y": [X * 0.5]})["Out"]),
        X * 0.5, rtol=1e-6)
    out = _fwd("norm", {"X": [POS]}, {"axis": 1, "epsilon": 1e-10})
    np.testing.assert_allclose(
        np.asarray(out["Out"]),
        POS / np.linalg.norm(POS, axis=1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(_fwd("diag", {"Diagonal": [np.arange(3.0)
                                              .astype(np.float32)]})["Out"]),
        np.diag(np.arange(3.0)))
    np.testing.assert_allclose(
        np.asarray(_fwd("linspace", {"Start": [np.float32(0.0)],
                                     "Stop": [np.float32(1.0)]},
                        {"num": 5})["Out"]),
        np.linspace(0, 1, 5), rtol=1e-6)
    assert int(np.asarray(_fwd("rank", {"Input": [X]})["Out"])) == 2
    assert np.asarray(_fwd("seed", {}, {"seed": 7})["Out"])[0] == 7
    got = _fwd("scatter", {"X": [np.zeros((4, 2), np.float32)],
                           "Ids": [np.array([1, 3], np.int64)],
                           "Updates": [np.ones((2, 2), np.float32)]})
    np.testing.assert_allclose(np.asarray(got["Out"]).sum(), 4.0)


def test_fused_layer_norm_runs():
    out = _fwd("fused_layer_norm",
               {"X": [X], "Scale": [np.ones(4, np.float32)],
                "Bias": [np.zeros(4, np.float32)]},
               {"begin_norm_axis": 1, "epsilon": 1e-5})
    y = np.asarray(out["Y"], np.float64)
    np.testing.assert_allclose(y.mean(axis=1), 0.0, atol=1e-5)


def test_beam_search_decode_runs():
    # 2 steps, beam 2, batch 1: lanes [0,1] then parents [1,0]
    ids = np.array([[[0, 1]], [[2, 3]]], np.int64)       # [T, B, K]
    parents = np.array([[[0, 1]], [[1, 0]]], np.int64)
    scores = np.zeros_like(ids, np.float32)
    out = _fwd("beam_search_decode",
               {"Ids": [ids], "ParentIdx": [parents], "Scores": [scores]},
               {"beam_size": 2, "end_id": 99})
    assert np.asarray(out["SentenceIds"]).size


def test_while_op_runs():
    """The legacy `while` op (reference while_op.cc form: carried vars +
    a condition var the sub-block rewrites) executed directly."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.core.ir import Program

    sub = Program().global_block()
    sub.create_var(name="i", stop_gradient=True)
    sub.create_var(name="cond", stop_gradient=True)
    sub.create_var(name="lim", stop_gradient=True)
    sub.append_op("increment", {"X": ["i"]}, {"Out": ["i"]},
                  {"step": 1.0})
    sub.append_op("less_than", {"X": ["i"], "Y": ["lim"]},
                  {"Out": ["cond"]}, {})
    out = _fwd("while",
               {"X": [jnp.zeros((), jnp.int32),
                      jnp.asarray(True),
                      jnp.asarray(10, jnp.int32)]},
               {"sub_block": sub, "carry_names": ["i", "cond", "lim"],
                "cond_name": "cond"}, n_out=3)
    assert int(np.asarray(out["Out"][0])) == 10
