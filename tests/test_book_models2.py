"""Book-fixture model zoo, part 2 — the three remaining reference book
models: fit_a_line (linear regression + inference export round trip,
reference tests/book/test_fit_a_line.py), recommender_system (dual-tower
embeddings + cos_sim regression, tests/book/test_recommender_system.py)
and label_semantic_roles (embedding windows -> LSTM stack -> CRF,
tests/book/test_label_semantic_roles.py)."""

import numpy as np
import pytest


def _run_startup():
    import paddle_tpu as pt

    exe = pt.Executor()
    scope = pt.Scope()
    return exe, scope


class TestFitALine:
    """reference: tests/book/test_fit_a_line.py:25 — y_predict = fc(x, 1),
    square_error_cost vs y, SGD, then save_inference_model /
    load_inference_model and predict."""

    def test_trains_and_roundtrips_inference(self, tmp_path):
        import paddle_tpu as pt
        from paddle_tpu import io, layers
        from paddle_tpu.core.ir import Program, program_guard

        rng = np.random.RandomState(0)
        w_true = rng.uniform(-1, 1, size=(13, 1)).astype(np.float32)

        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.static_data("x", [-1, 13], "float32")
            y = layers.static_data("y", [-1, 1], "float32")
            y_pred = layers.fc(x, 1, param_attr="fal_w", bias_attr="fal_b")
            loss = layers.mean(layers.square_error_cost(y_pred, y))
            pt.optimizer.SGD(learning_rate=0.05).minimize(loss)

        exe, scope = _run_startup()
        exe.run(startup, scope=scope, use_compiled=False)
        losses = []
        for s in range(60):
            xb = rng.uniform(-1, 1, size=(32, 13)).astype(np.float32)
            yb = xb @ w_true + 0.01 * rng.randn(32, 1).astype(np.float32)
            out = exe.run(main, feed={"x": xb, "y": yb},
                          fetch_list=[loss], scope=scope)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])

        # inference export + reload (reference: save_inference_model
        # io.py:1164 emits the pruned program; book test reloads and runs)
        d = str(tmp_path / "fit_a_line_model")
        io.save_inference_model(d, ["x"], [y_pred], exe, main_program=main,
                                scope=scope)
        scope2 = pt.Scope()
        infer_prog, feed_names, fetch_names = io.load_inference_model(
            d, exe, scope=scope2)
        xq = rng.uniform(-1, 1, size=(8, 13)).astype(np.float32)
        pred = exe.run(infer_prog, feed={feed_names[0]: xq},
                       fetch_list=fetch_names, scope=scope2)
        ref = exe.run(main, feed={"x": xq,
                                  "y": np.zeros((8, 1), np.float32)},
                      fetch_list=[y_pred], scope=scope)
        np.testing.assert_allclose(np.asarray(pred[0]), np.asarray(ref[0]),
                                   rtol=1e-5, atol=1e-5)


class TestRecommenderSystem:
    """reference: tests/book/test_recommender_system.py:33 — user tower
    (usr id/gender/age/job embeddings -> fc) x movie tower (movie id
    embedding + mean-pooled category/title embeddings -> fc), 5 *
    cos_sim as the predicted rating, square error vs the label."""

    USR, GEN, AGE, JOB = 200, 2, 7, 21
    MOV, CAT, TIT = 300, 19, 500

    def _build(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.ir import Program, program_guard
        from paddle_tpu.param_attr import ParamAttr

        main, startup = Program(), Program()
        with program_guard(main, startup):
            feeds = {}
            for name in ("usr", "gender", "age", "job", "mov"):
                feeds[name] = layers.static_data(name, [-1, 1], "int64")
            feeds["cat"] = layers.static_data("cat", [-1, 4], "int64")
            feeds["tit"] = layers.static_data("tit", [-1, 6], "int64")
            feeds["score"] = layers.static_data("score", [-1, 1], "float32")

            def emb(var, vocab, name, dim=16):
                e = layers.embedding(var, [vocab, dim],
                                     param_attr=ParamAttr(name=f"rec_{name}"))
                return layers.reshape(e, [0, int(np.prod(e.shape[1:]))]) \
                    if len(e.shape) > 2 and int(e.shape[1]) == 1 else e

            usr = layers.concat([
                layers.fc(emb(feeds["usr"], self.USR, "usr"), 32),
                layers.fc(emb(feeds["gender"], self.GEN, "gen"), 16),
                layers.fc(emb(feeds["age"], self.AGE, "age"), 16),
                layers.fc(emb(feeds["job"], self.JOB, "job"), 16)], axis=1)
            usr_feat = layers.fc(usr, 32, act="tanh",
                                 param_attr=ParamAttr(name="rec_usr_fc"))

            mov_id = layers.fc(emb(feeds["mov"], self.MOV, "mov"), 32)
            cat_e = layers.embedding(feeds["cat"], [self.CAT, 16],
                                     param_attr=ParamAttr(name="rec_cat"))
            cat_pooled = layers.reduce_mean(cat_e, dim=1)     # sequence_pool
            tit_e = layers.embedding(feeds["tit"], [self.TIT, 16],
                                     param_attr=ParamAttr(name="rec_tit"))
            tit_pooled = layers.reduce_mean(tit_e, dim=1)
            mov = layers.concat([mov_id, layers.fc(cat_pooled, 16),
                                 layers.fc(tit_pooled, 16)], axis=1)
            mov_feat = layers.fc(mov, 32, act="tanh",
                                 param_attr=ParamAttr(name="rec_mov_fc"))

            sim = layers.cos_sim(usr_feat, mov_feat)
            pred = layers.scale(sim, scale=5.0)
            loss = layers.mean(layers.square_error_cost(pred,
                                                        feeds["score"]))
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    def _feed(self, rng, bs=32):
        return {
            "usr": rng.randint(0, self.USR, (bs, 1)).astype(np.int64),
            "gender": rng.randint(0, self.GEN, (bs, 1)).astype(np.int64),
            "age": rng.randint(0, self.AGE, (bs, 1)).astype(np.int64),
            "job": rng.randint(0, self.JOB, (bs, 1)).astype(np.int64),
            "mov": rng.randint(0, self.MOV, (bs, 1)).astype(np.int64),
            "cat": rng.randint(0, self.CAT, (bs, 4)).astype(np.int64),
            "tit": rng.randint(0, self.TIT, (bs, 6)).astype(np.int64),
            "score": rng.randint(1, 6, (bs, 1)).astype(np.float32),
        }

    def test_trains(self):
        import paddle_tpu as pt

        main, startup, loss = self._build()
        exe, scope = _run_startup()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(7)
        fixed = [self._feed(rng) for _ in range(4)]  # memorisable stream
        losses = []
        for s in range(40):
            out = exe.run(main, feed=fixed[s % 4], fetch_list=[loss],
                          scope=scope)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        assert all(np.isfinite(losses))
        assert np.mean(losses[-4:]) < 0.5 * np.mean(losses[:4]), losses


class TestLabelSemanticRoles:
    """reference: tests/book/test_label_semantic_roles.py:37 — word +
    context-window + predicate + mark embeddings -> fc -> stacked
    bidirectional LSTM -> emission fc -> linear_chain_crf; decode with
    crf_decoding sharing the trained transition parameter."""

    VOCAB, PRED, MARK, TAGS = 400, 50, 2, 9
    S = 12

    def _build(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.ir import Program, program_guard
        from paddle_tpu.param_attr import ParamAttr

        main, startup = Program(), Program()
        with program_guard(main, startup):
            word = layers.static_data("word", [-1, self.S], "int64")
            pred = layers.static_data("pred", [-1, self.S], "int64")
            mark = layers.static_data("mark", [-1, self.S], "int64")
            label = layers.static_data("label", [-1, self.S], "int64")
            length = layers.static_data("length", [-1], "int64")

            we = layers.embedding(word, [self.VOCAB, 32],
                                  param_attr=ParamAttr(name="srl_wemb"))
            pe = layers.embedding(pred, [self.PRED, 32],
                                  param_attr=ParamAttr(name="srl_pemb"))
            me = layers.embedding(mark, [self.MARK, 8],
                                  param_attr=ParamAttr(name="srl_memb"))
            x = layers.concat([we, pe, me], axis=2)
            h = layers.fc(x, 64, num_flatten_dims=2, act="tanh",
                          param_attr=ParamAttr(name="srl_fc0"))
            fwd, _, _ = layers.lstm_unit_layer(
                h, 32, seq_length=length,
                param_attr=ParamAttr(name="srl_lf_wx"), name="srl_lf")
            bwd, _, _ = layers.lstm_unit_layer(
                h, 32, is_reverse=True, seq_length=length,
                param_attr=ParamAttr(name="srl_lb_wx"), name="srl_lb")
            feat = layers.concat([fwd, bwd], axis=2)
            emission = layers.fc(feat, self.TAGS, num_flatten_dims=2,
                                 param_attr=ParamAttr(name="srl_emit"))
            crf_cost = layers.linear_chain_crf(
                emission, label,
                param_attr=ParamAttr(name="srl_crf_trans"), length=length)
            loss = layers.mean(crf_cost)
            decode = layers.crf_decoding(
                emission, ParamAttr(name="srl_crf_trans"), length=length)
            pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
        return main, startup, loss, decode

    def _feed(self, rng, bs=16):
        length = rng.randint(4, self.S + 1, (bs,)).astype(np.int64)
        return {
            "word": rng.randint(0, self.VOCAB, (bs, self.S)).astype(np.int64),
            "pred": rng.randint(0, self.PRED, (bs, self.S)).astype(np.int64),
            "mark": rng.randint(0, self.MARK, (bs, self.S)).astype(np.int64),
            "label": rng.randint(0, self.TAGS, (bs, self.S)).astype(np.int64),
            "length": length,
        }

    def test_trains_and_decodes(self):
        import paddle_tpu as pt

        main, startup, loss, decode = self._build()
        exe, scope = _run_startup()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(3)
        fixed = [self._feed(rng) for _ in range(2)]
        losses = []
        for s in range(50):
            out = exe.run(main, feed=fixed[s % 2], fetch_list=[loss],
                          scope=scope)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        assert all(np.isfinite(losses))
        assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])
        # decode must emit valid tags, padded region ignored by length
        path = exe.run(main, feed=fixed[0], fetch_list=[decode],
                       scope=scope)
        path = np.asarray(path[0])
        assert path.shape == (16, self.S)
        assert path.min() >= 0 and path.max() < self.TAGS
