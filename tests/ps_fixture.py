"""Subprocess fixture for parameter-server tests (reference:
test_dist_base.py TestDistRunnerBase — a runner script started as
pserver or trainer role).

Usage:
  python ps_fixture.py pserver  <endpoint> <all_endpoints> <trainers> <sync>
  python ps_fixture.py trainer  <trainer_id> <all_endpoints> <trainers> \
      <sync> <steps>
  python ps_fixture.py local    <steps>

Prints one line per step: LOSS <step> <value>. Deterministic model +
data so trainer losses are comparable to the local run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def build_model():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu import layers

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [32], stop_gradient=True)
        label = layers.data("label", [1], dtype="int64", stop_gradient=True)
        from paddle_tpu.initializer import Xavier

        h = layers.fc(x, 64, act="relu",
                      param_attr=pt.ParamAttr(name="w0",
                                              initializer=Xavier(seed=7)),
                      bias_attr=pt.ParamAttr(name="b0"))
        h = layers.fc(h, 64, act="relu",
                      param_attr=pt.ParamAttr(name="w1",
                                              initializer=Xavier(seed=8)),
                      bias_attr=pt.ParamAttr(name="b1"))
        logits = layers.fc(h, 10,
                           param_attr=pt.ParamAttr(name="w2",
                                                   initializer=Xavier(seed=9)),
                           bias_attr=pt.ParamAttr(name="b2"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = pt.optimizer.SGDOptimizer(0.5)
        opt.minimize(loss)
    return main, startup, loss


def batch_for(step, trainer_id=None, trainers=1):
    """Full batch of 32; trainer i takes its contiguous half."""
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(32, 32).astype(np.float32)
    y = rng.randint(0, 10, (32, 1)).astype(np.int64)
    if trainer_id is None:
        return x, y
    n = 32 // trainers
    sl = slice(trainer_id * n, (trainer_id + 1) * n)
    return x[sl], y[sl]


def run_local(steps):
    import paddle_tpu as pt

    main, startup, loss = build_model()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    for s in range(steps):
        x, y = batch_for(s)
        out = exe.run(main, feed={"x": x, "label": y}, fetch_list=[loss],
                      scope=scope)
        print(f"LOSS {s} {float(np.asarray(out[0]).reshape(-1)[0]):.6f}",
              flush=True)


def run_pserver(endpoint, all_eps, trainers, sync):
    from paddle_tpu.distributed.ps import DistributeTranspiler, PServer

    main, startup, loss = build_model()
    t = DistributeTranspiler()
    t.transpile(0, program=main, startup_program=startup, pservers=all_eps,
                trainers=trainers, sync_mode=sync)
    prog, ps_startup = t.get_pserver_programs(endpoint)
    server = PServer(endpoint, prog, ps_startup, num_trainers=trainers,
                     sync_mode=sync, grad_to_param=prog._ps_grad_to_param,
                     grad_to_ops=prog._ps_grad_to_ops,
                     common_ops=prog._ps_common_ops)
    print(f"SERVING {server.endpoint}", flush=True)
    server.run()


def run_trainer(trainer_id, all_eps, trainers, sync, steps):
    import paddle_tpu as pt
    from paddle_tpu.distributed.ps import DistributeTranspiler

    main, startup, loss = build_model()
    t = DistributeTranspiler()
    t.transpile(trainer_id, program=main, startup_program=startup,
                pservers=all_eps, trainers=trainers, sync_mode=sync)
    trainer_prog = t.get_trainer_program()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    exe.run(t.get_startup_program(), scope=scope, use_compiled=False)
    for s in range(steps):
        x, y = batch_for(s, trainer_id, trainers)
        out = exe.run(trainer_prog, feed={"x": x, "label": y},
                      fetch_list=[loss], scope=scope)
        print(f"LOSS {s} {float(np.asarray(out[0]).reshape(-1)[0]):.6f}",
              flush=True)
    print("DONE", flush=True)
    # servers are stopped by the test harness once ALL trainers finish
    # (a trainer stopping them early would cut off slower peers mid-step)


if __name__ == "__main__":
    role = sys.argv[1]
    if role == "local":
        run_local(int(sys.argv[2]))
    elif role == "pserver":
        run_pserver(sys.argv[2], sys.argv[3], int(sys.argv[4]),
                    sys.argv[5] == "1")
    elif role == "trainer":
        run_trainer(int(sys.argv[2]), sys.argv[3], int(sys.argv[4]),
                    sys.argv[5] == "1", int(sys.argv[6]))
    else:
        raise SystemExit(f"unknown role {role}")
