"""Round-4 op batch (ops/extra_ops4.py + chunk_eval schemes) tests."""

import numpy as np
import pytest

from tests.test_ops_batch3 import _fwd


def _fresh():
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()


class TestMaskedSelect:
    def test_forward(self):
        x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        m = np.array([[1, 0], [1, 1]], np.int32)
        out = _fwd("masked_select", {"X": [x], "Mask": [m]})
        assert int(np.asarray(out["Count"])) == 3
        np.testing.assert_allclose(np.asarray(out["Y"]),
                                   [1.0, 3.0, 4.0, 0.0])

    def test_grad(self):
        from tests.op_test import OpTest

        class T(OpTest):
            op_type = "masked_select"

            def setup(self):
                rng = np.random.RandomState(0)
                x = rng.randn(3, 4).astype(np.float32)
                m = (rng.rand(3, 4) > 0.4).astype(np.int32)
                sel = x.reshape(-1)[np.argsort(~m.reshape(-1).astype(bool),
                                               kind="stable")]
                cnt = int(m.sum())
                y = np.where(np.arange(12) < cnt, sel, 0).astype(np.float32)
                self.inputs = {"X": x, "Mask": m}
                self.outputs = {"Y": y,
                                "Count": np.asarray(cnt, np.int32)}

        t = T()
        t.check_output(no_check_set=("Count",))
        t.check_grad(["X"], "Y")


class TestCrossEntropy2:
    def test_forward_and_grad(self):
        from tests.op_test import OpTest

        class T(OpTest):
            op_type = "cross_entropy2"

            def setup(self):
                rng = np.random.RandomState(1)
                x = rng.rand(5, 7).astype(np.float32) + 0.1
                x /= x.sum(-1, keepdims=True)
                lab = rng.randint(0, 7, (5, 1)).astype(np.int64)
                match = np.take_along_axis(x, lab.astype(np.int64), 1)
                self.inputs = {"X": x, "Label": lab}
                self.outputs = {"Y": -np.log(match),
                                "MatchX": match,
                                "XShape": np.zeros((2,), np.int64)}

        t = T()
        t.check_output(no_check_set=("XShape",))
        t.check_grad(["X"], "Y")

    def test_ignore_index(self):
        x = np.full((2, 3), 1 / 3, np.float32)
        lab = np.array([[0], [-100]], np.int64)
        out = _fwd("cross_entropy2", {"X": [x], "Label": [lab]},
                   {"ignore_index": -100})
        y = np.asarray(out["Y"]).reshape(-1)
        assert abs(y[0] - np.log(3)) < 1e-5 and y[1] == 0.0


class TestPartialOps:
    def test_partial_sum(self):
        a = np.arange(8, dtype=np.float32).reshape(2, 4)
        b = 10 * np.arange(8, dtype=np.float32).reshape(2, 4)
        out = np.asarray(_fwd("partial_sum", {"X": [a, b]},
                              {"start_index": 1, "length": 2})["Out"])
        np.testing.assert_allclose(out, (a + b)[:, 1:3])

    def test_partial_concat(self):
        a = np.arange(8, dtype=np.float32).reshape(2, 4)
        b = -a
        out = np.asarray(_fwd("partial_concat", {"X": [a, b]},
                              {"start_index": 2, "length": -1})["Out"])
        np.testing.assert_allclose(out, np.concatenate(
            [a[:, 2:], b[:, 2:]], axis=1))

    def test_partial_sum_grad(self):
        from tests.op_test import OpTest

        class T(OpTest):
            op_type = "partial_sum"

            def setup(self):
                rng = np.random.RandomState(2)
                a = rng.randn(3, 5).astype(np.float32)
                b = rng.randn(3, 5).astype(np.float32)
                self.inputs = {"X": [("a", a), ("b", b)]}
                self.attrs = {"start_index": 1, "length": 3}
                self.outputs = {"Out": (a + b)[:, 1:4]}

        t = T()
        t.check_output()
        t.check_grad(["a"], "Out")


class TestInplaceABN:
    def test_matches_bn_plus_act(self):
        rng = np.random.RandomState(3)
        x = rng.randn(4, 3, 2, 2).astype(np.float32)
        scale = rng.rand(3).astype(np.float32) + 0.5
        bias = rng.randn(3).astype(np.float32)
        mean = np.zeros(3, np.float32)
        var = np.ones(3, np.float32)
        ins = {"X": [x], "Scale": [scale], "Bias": [bias],
               "Mean": [mean], "Variance": [var]}
        bn = _fwd("batch_norm", ins, {})
        abn = _fwd("inplace_abn", ins, {"activation": "leaky_relu",
                                        "alpha": 0.2})
        ref = np.asarray(bn["Y"])
        ref = np.where(ref >= 0, ref, 0.2 * ref)
        np.testing.assert_allclose(np.asarray(abn["Y"]), ref, atol=1e-5)
        np.testing.assert_allclose(np.asarray(abn["MeanOut"]),
                                   np.asarray(bn["MeanOut"]))


class TestRankTableBridges:
    def _table(self, lengths):
        t = _fwd("lod_rank_table", {"X": [np.asarray(lengths, np.int64)]})
        return np.asarray(t["Items"]), np.asarray(t["Index"])

    def test_lod_tensor_to_array_roundtrip(self):
        rng = np.random.RandomState(4)
        lengths = [2, 4, 1]
        x = rng.randn(3, 4, 5).astype(np.float32)
        for b, ln in enumerate(lengths):
            x[b, ln:] = 0.0  # padded region
        items, index = self._table(lengths)
        arr = _fwd("lod_tensor_to_array",
                   {"X": [x], "RankTable": [items, index]})["Out"]
        arr = np.asarray(arr)
        assert arr.shape == (4, 3, 5)
        # step 0 holds all 3 sequences in rank order (lens 4,2,1)
        np.testing.assert_allclose(arr[0], x[index][:, 0])
        # step 2: only the len-4 sequence is alive
        assert np.all(arr[2, 1:] == 0)
        np.testing.assert_allclose(arr[2, 0], x[index[0], 2])
        back = _fwd("array_to_lod_tensor",
                    {"X": [arr], "RankTable": [items, index]})["Out"]
        np.testing.assert_allclose(np.asarray(back), x)

    def test_shrink_rnn_memory(self):
        items, index = self._table([2, 4, 1])
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = np.asarray(_fwd(
            "shrink_rnn_memory",
            {"X": [x], "RankTable": [items, index],
             "I": [np.asarray(1, np.int64)]})["Out"])
        # lengths sorted desc = [4,2,1]; step 1 -> 2 rows still active
        np.testing.assert_allclose(out[:2], x[:2])
        assert np.all(out[2] == 0)


class TestLstmp:
    def test_projection_semantics(self):
        rng = np.random.RandomState(5)
        b, s, h, p = 2, 3, 4, 3
        xw = rng.randn(b, s, 4 * h).astype(np.float32) * 0.3
        wh = rng.randn(p, 4 * h).astype(np.float32) * 0.3
        wp = rng.randn(h, p).astype(np.float32) * 0.3
        out = _fwd("lstmp", {"Input": [xw], "Weight": [wh],
                             "ProjWeight": [wp], "Bias": [None],
                             "H0": [None], "C0": [None],
                             "SequenceLength": [None]}, {})
        proj, cell = np.asarray(out["Projection"]), np.asarray(out["Cell"])
        assert proj.shape == (b, s, p) and cell.shape == (b, s, h)

        def sig(v):
            return 1 / (1 + np.exp(-v))

        r = np.zeros((b, p), np.float32)
        c = np.zeros((b, h), np.float32)
        for t in range(s):
            g = xw[:, t] + r @ wh
            i, f, cand, o = np.split(g, 4, axis=-1)
            c = sig(f) * c + sig(i) * np.tanh(cand)
            hh = sig(o) * np.tanh(c)
            r = hh @ wp
            np.testing.assert_allclose(proj[:, t], r, atol=1e-5)
            np.testing.assert_allclose(cell[:, t], c, atol=1e-5)

    def test_seq_length_freeze(self):
        rng = np.random.RandomState(6)
        xw = rng.randn(2, 4, 8).astype(np.float32)
        wh = rng.randn(3, 8).astype(np.float32) * 0.3
        wp = rng.randn(2, 3).astype(np.float32) * 0.3
        out = _fwd("lstmp", {"Input": [xw], "Weight": [wh],
                             "ProjWeight": [wp], "Bias": [None],
                             "H0": [None], "C0": [None],
                             "SequenceLength": [np.array([2, 4])]}, {})
        proj = np.asarray(out["Projection"])
        # row 0 frozen after step 2
        np.testing.assert_allclose(proj[0, 1], proj[0, 3])


class TestBatchFC:
    def test_forward(self):
        rng = np.random.RandomState(7)
        x = rng.randn(3, 4, 5).astype(np.float32)
        w = rng.randn(3, 5, 2).astype(np.float32)
        bias = rng.randn(3, 1, 2).astype(np.float32)
        out = np.asarray(_fwd("batch_fc", {"Input": [x], "W": [w],
                                           "Bias": [bias]})["Out"])
        np.testing.assert_allclose(out, np.einsum("sni,sio->sno", x, w) + bias,
                                   rtol=1e-5)


class TestFilterByInstag:
    def test_semantics(self):
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        tags = np.array([[1, -1], [2, 3], [4, -1], [3, -1]], np.int64)
        filt = np.array([3], np.int64)
        out = _fwd("filter_by_instag",
                   {"Ins": [x], "Ins_tag": [tags], "Filter_tag": [filt]})
        assert int(np.asarray(out["Count"])) == 2
        got = np.asarray(out["Out"])
        np.testing.assert_allclose(got[0], x[1])
        np.testing.assert_allclose(got[1], x[3])
        assert np.all(got[2:] == 0)
        np.testing.assert_allclose(np.asarray(out["IndexMap"]),
                                   [1, 3, -1, -1])
        np.testing.assert_allclose(np.asarray(out["LossWeight"]).reshape(-1),
                                   [1, 1, 0, 0])


# --------------------------------------------------------------------------
# chunk_eval: all schemes vs a direct port of chunk_eval_op.h GetSegments
# --------------------------------------------------------------------------

SCHEMES = {"IOB": (2, 0, 1, -1, -1), "IOE": (2, -1, 0, 1, -1),
           "IOBES": (4, 0, 1, 2, 3), "plain": (1, -1, -1, -1, -1)}


def _segments(seq, n_types, scheme):
    """Literal port of chunk_eval_op.h GetSegments/ChunkBegin/ChunkEnd."""
    ntag, tb, ti, te, ts = SCHEMES[scheme]
    other = n_types

    def chunk_end(pt, pty, t, ty):
        if pty == other:
            return False
        if ty == other or ty != pty:
            return True
        if pt == tb or pt == ti:
            return t == tb or t == ts
        if pt == te or pt == ts:
            return True
        return False

    def chunk_begin(pt, pty, t, ty):
        if pty == other:
            return ty != other
        if ty == other:
            return False
        if ty != pty:
            return True
        if t == tb or t == ts:
            return True
        if t == ti or t == te:
            return pt == te or pt == ts
        return False

    segs, in_chunk, start = [], False, 0
    tag, typ = -1, other
    for i, lab in enumerate(seq):
        ptag, ptyp = tag, typ
        tag, typ = lab % ntag, lab // ntag
        if in_chunk and chunk_end(ptag, ptyp, tag, typ):
            segs.append((start, i - 1, ptyp))
            in_chunk = False
        if chunk_begin(ptag, ptyp, tag, typ):
            start, in_chunk = i, True
    if in_chunk:
        segs.append((start, len(seq) - 1, typ))
    return segs


@pytest.mark.parametrize("scheme", ["IOB", "IOE", "IOBES", "plain"])
def test_chunk_eval_schemes_vs_reference_port(scheme):
    ntag = SCHEMES[scheme][0]
    n_types = 3
    rng = np.random.RandomState(hash(scheme) % 1000)
    b, s = 6, 12
    hi = n_types * ntag + 1   # includes the O label
    pred = rng.randint(0, hi, (b, s)).astype(np.int64)
    lab = rng.randint(0, hi, (b, s)).astype(np.int64)
    out = _fwd("chunk_eval", {"Inference": [pred], "Label": [lab],
                              "SeqLength": [None]},
               {"num_chunk_types": n_types, "chunk_scheme": scheme})
    n_inf = n_lab = n_cor = 0
    for r in range(b):
        ps = _segments(pred[r], n_types, scheme)
        ls = _segments(lab[r], n_types, scheme)
        n_inf += len(ps)
        n_lab += len(ls)
        n_cor += len(set(ps) & set(ls))
    assert int(np.asarray(out["NumInferChunks"])) == n_inf, scheme
    assert int(np.asarray(out["NumLabelChunks"])) == n_lab, scheme
    assert int(np.asarray(out["NumCorrectChunks"])) == n_cor, scheme


def test_chunk_eval_excluded_types():
    pred = np.array([[0, 1, 2, 3, 4, 4]], np.int64)   # IOB, 3 types
    lab = np.array([[0, 1, 2, 3, 4, 4]], np.int64)
    base = _fwd("chunk_eval", {"Inference": [pred], "Label": [lab],
                               "SeqLength": [None]},
                {"num_chunk_types": 3})
    excl = _fwd("chunk_eval", {"Inference": [pred], "Label": [lab],
                               "SeqLength": [None]},
                {"num_chunk_types": 3, "excluded_chunk_types": [1]})
    # chunks: [0,1]->t0, [2,3]->t1, [4]->t2, [5]->t2 (B after B splits)
    assert int(np.asarray(base["NumInferChunks"]).reshape(())) == 4
    assert int(np.asarray(excl["NumInferChunks"]).reshape(())) == 3


# --------------------------------------------------------------------------
# py_func: end-to-end through a program with a custom backward
# --------------------------------------------------------------------------

class TestPyFunc:
    def test_forward_and_backward(self):
        import paddle_tpu as pt
        from paddle_tpu import layers

        _fresh()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.static_data("x", [4, 3])
            w = layers.create_parameter([3, 3], "float32", name="pfw")
            h = layers.matmul(x, w)
            out = main.current_block().create_var(
                name="pyfunc_out", shape=[4, 3], dtype="float32")

            def fwd(a):
                return 2.0 * a

            def bwd(a, dy):
                return 2.0 * dy

            layers.py_func(fwd, h, out, backward_func=bwd)
            loss = layers.mean(out)
            opt = pt.optimizer.SGDOptimizer(0.1)
            pg = opt.backward(loss)
            opt.apply_gradients(pg)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(8)
        feed = {"x": rng.randn(4, 3).astype(np.float32)}
        w0 = np.array(scope.find_var("pfw"), np.float32).copy()
        out1 = exe.run(main, feed=feed, fetch_list=[loss], scope=scope,
                       use_compiled=False)
        w1 = np.asarray(scope.find_var("pfw"))
        # forward: loss == mean(2 * x @ w0)
        np.testing.assert_allclose(
            float(np.asarray(out1[0])),
            float(np.mean(2.0 * feed["x"] @ w0)), rtol=1e-5)
        # backward flowed through the custom bwd: w updated by -lr * dW
        expect_gw = feed["x"].T @ np.full((4, 3), 2.0 / 12, np.float32)
        np.testing.assert_allclose(w1, w0 - 0.1 * expect_gw, rtol=1e-4,
                                   atol=1e-6)


class TestDropoutMaskConsistency:
    """Regression (found in round 4): the __vjp_grad__ re-trace must see
    the same __step__/__axis_coords__ as the forward op, or the backward
    dropout mask silently disagrees with the forward mask."""

    def test_fwd_bwd_masks_agree(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.backward import gradients

        _fresh()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.static_data("x", [4, 16])
            x.stop_gradient = False
            y = layers.dropout(x, dropout_prob=0.5)
            loss = layers.reduce_sum(y)
            g, = gradients([loss], [x])
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.ones((4, 16), np.float32)}
        for uc in (False, True):
            out = exe.run(main, feed=feed, fetch_list=[y, g], scope=scope,
                          use_compiled=uc)
            yv, gv = np.asarray(out[0]), np.asarray(out[1])
            assert ((yv != 0) == (gv != 0)).all(), f"compiled={uc}"


class TestBoundedScanTruncationGuard:
    """ADVICE r3 (medium): a runtime trip count exceeding grad_max_iters
    must surface, not silently truncate."""

    def _run(self, n_val, bound):
        import jax.numpy as jnp

        import paddle_tpu as pt
        from paddle_tpu import layers

        _fresh()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            i = layers.static_data("i", [1])
            n = layers.static_data("n", [1])

            def cond(i_, n_):
                return layers.less_than(i_, n_)

            def body(i_, n_):
                return [i_ + 1.0, n_]

            out_i, _ = layers.while_loop(cond, body, [i, n],
                                         grad_max_iters=bound)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        out = exe.run(main, feed={"i": np.zeros(1, np.float32),
                                  "n": np.array([n_val], np.float32)},
                      fetch_list=[out_i], scope=scope, use_compiled=False)
        return float(np.asarray(out[0]).reshape(-1)[0])

    def test_within_bound_ok(self):
        assert self._run(3.0, 8) == 3.0

    def test_exceeding_bound_raises(self):
        from paddle_tpu.core.executor import ExecutionError

        with pytest.raises(ExecutionError, match="truncated"):
            self._run(20.0, 8)


class TestFusedFamilyTail:
    """fusion_squared_mat_sub + fusion_repeated_fc_relu (reference
    fused/ kernels — thin compositions here, XLA fuses the chain)."""

    def test_fusion_squared_mat_sub(self):
        from paddle_tpu.core.registry import get

        rng = np.random.RandomState(0)
        x = rng.randn(4, 5).astype(np.float32)
        y = rng.randn(5, 6).astype(np.float32)
        out = get("fusion_squared_mat_sub").forward(
            {"X": [x], "Y": [y]}, {"scalar": 0.5})
        want = ((x @ y) ** 2 - (x ** 2) @ (y ** 2)) * 0.5
        np.testing.assert_allclose(np.asarray(out["Out"]), want,
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(out["SquaredX"]), x ** 2,
                                   rtol=1e-6)

    def test_fusion_repeated_fc_relu(self):
        from paddle_tpu.core.registry import get

        rng = np.random.RandomState(1)
        x = rng.randn(3, 4).astype(np.float32)
        ws = [rng.randn(4, 5).astype(np.float32),
              rng.randn(5, 2).astype(np.float32)]
        bs = [rng.randn(5).astype(np.float32),
              rng.randn(2).astype(np.float32)]
        out = get("fusion_repeated_fc_relu").forward(
            {"X": [x], "W": ws, "Bias": bs}, {})
        h = np.maximum(x @ ws[0] + bs[0], 0)
        want = np.maximum(h @ ws[1] + bs[1], 0)
        np.testing.assert_allclose(np.asarray(out["Out"]), want,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(out["ReluOut"][0]), h,
                                   rtol=1e-5, atol=1e-6)
