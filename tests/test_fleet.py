"""Fleet strategy tests (reference pattern: test_dist_base.py loss parity +
fleet meta-optimizer unit tests under unittests/)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel import create_mesh, mesh as meshmod


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    meshmod.set_mesh(None)


def _build(strategy=None, lr=0.1, opt_factory=None, checkpoints=False):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [32])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(x, 64, act="relu")
        h2 = layers.fc(h, 64, act="relu")
        logits = layers.fc(h2, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = (opt_factory or pt.optimizer.SGDOptimizer)(lr)
        if strategy is not None:
            if checkpoints:
                strategy.recompute_configs = {"checkpoints": [h.name, h2.name]}
            fleet.distributed_optimizer(opt, strategy).minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, loss


def _feed(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(n, 32).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


def _train(main, startup, loss, steps=5, mesh=None, feed=None):
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.Scope()
    exe.run(startup, scope=sc, use_compiled=False)
    feed = feed or _feed()
    out = None
    for _ in range(steps):
        out, = exe.run(main, feed=feed, fetch_list=[loss], scope=sc, mesh=mesh)
    return float(out)


def test_fleet_dp_collective_matches_single_device():
    """c_allreduce_sum DP under shard_map == single-device numerics
    (the reference's test_dist_base.py:1007 check, minus subprocesses)."""
    mesh = create_mesh({"dp": 8})
    fleet.init(is_collective=True)
    main, startup, loss = _build(fleet.DistributedStrategy())
    ops = [op.type for op in main.global_block().ops]
    assert "c_allreduce_sum" in ops and "scale" in ops
    l_dp = _train(main, startup, loss, mesh=mesh)

    meshmod.set_mesh(None)
    main1, startup1, loss1 = _build(None)
    l_1 = _train(main1, startup1, loss1)
    assert abs(l_dp - l_1) < 1e-4


def test_fleet_amp_bf16():
    fleet.init(is_collective=True)
    strat = fleet.DistributedStrategy()
    strat.amp = True
    main, startup, loss = _build(strat)
    casts = [op for op in main.global_block().ops if op.type == "cast"]
    assert casts, "AMP inserted no bf16 casts"
    l = _train(main, startup, loss)
    assert np.isfinite(l) and l < 2.5


def test_fleet_amp_dynamic_loss_scaling():
    fleet.init(is_collective=True)
    strat = fleet.DistributedStrategy()
    strat.amp = True
    strat.amp_configs = {"init_loss_scaling": 1024.0,
                         "use_dynamic_loss_scaling": True}
    main, startup, loss = _build(strat)
    ops = [op.type for op in main.global_block().ops]
    assert "check_finite_and_unscale" in ops and "update_loss_scaling" in ops
    l = _train(main, startup, loss)
    assert np.isfinite(l) and l < 2.5


def test_fleet_gradient_merge_fires_every_k():
    fleet.init(is_collective=True)
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 4, "avg": True}
    main, startup, loss = _build(strat)
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.Scope()
    exe.run(startup, scope=sc, use_compiled=False)
    feed = _feed()
    losses = []
    for _ in range(9):
        lv, = exe.run(main, feed=feed, fetch_list=[loss], scope=sc)
        losses.append(float(lv))
    # constant within a window, drops across windows
    assert losses[0] == pytest.approx(losses[3])
    assert losses[4] == pytest.approx(losses[7])
    assert losses[4] < losses[0]
    assert losses[8] < losses[4]


def test_fleet_recompute_same_numerics():
    fleet.init(is_collective=True)
    strat = fleet.DistributedStrategy()
    strat.recompute = True
    main, startup, loss = _build(strat, checkpoints=True)
    assert any(op.type == "block_call" and op.attrs.get("remat")
               for op in main.global_block().ops)
    l_rc = _train(main, startup, loss)
    main1, startup1, loss1 = _build(None)
    l_1 = _train(main1, startup1, loss1)
    assert abs(l_rc - l_1) < 1e-4


def test_fleet_lamb_swap():
    fleet.init(is_collective=True)
    strat = fleet.DistributedStrategy()
    strat.lamb = True
    main, startup, loss = _build(
        strat, lr=0.01, opt_factory=pt.optimizer.AdamOptimizer)
    ops = [op.type for op in main.global_block().ops]
    assert "lamb" in ops and "adam" not in ops
    l = _train(main, startup, loss)
    assert np.isfinite(l)


def test_eager_collectives_single_proc():
    from paddle_tpu.distributed import all_gather, all_reduce, broadcast

    mesh = create_mesh({"dp": 8})
    x = np.ones((4,), np.float32)
    out = all_reduce(x)
    np.testing.assert_allclose(np.asarray(out), 8.0)  # replicated input
    g = all_gather(None, np.arange(3, dtype=np.float32))
    assert g.shape == (8, 3)
    b = broadcast(x, src=0)
    np.testing.assert_allclose(np.asarray(b), 1.0)


def test_strategy_serialization(tmp_path):
    strat = fleet.DistributedStrategy()
    strat.amp = True
    strat.gradient_merge_configs = {"k_steps": 7, "avg": False}
    p = tmp_path / "strategy.json"
    strat.save_to_file(str(p))
    loaded = fleet.DistributedStrategy.load_from_file(str(p))
    assert loaded.amp is True
    assert loaded.gradient_merge_configs["k_steps"] == 7


def test_fleet_localsgd_k1_matches_dp_allreduce():
    """LocalSGD with k=1 and SGD is mathematically identical to classic
    grad-allreduce DP: averaging params after one local SGD step equals
    stepping with the averaged gradient (reference:
    localsgd_optimizer.py semantics)."""
    mesh = create_mesh({"dp": 8})
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 1}
    main, startup, loss = _build(s)
    ops = [op.type for op in main.global_block().ops]
    assert "local_sgd_sync" in ops
    # grads are NOT allreduced on this path
    assert not any(o == "c_allreduce_sum" for o in ops)
    l_local = _train(main, startup, loss, mesh=mesh)

    fleet.init(is_collective=True)
    main2, startup2, loss2 = _build(fleet.DistributedStrategy())
    l_dp = _train(main2, startup2, loss2, mesh=mesh)
    meshmod.set_mesh(None)
    assert abs(l_local - l_dp) < 1e-4


def test_fleet_localsgd_k2_trains():
    mesh = create_mesh({"dp": 8})
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 2}
    main, startup, loss = _build(s)
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.Scope()
    exe.run(startup, scope=sc, use_compiled=False)
    feed = _feed()
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss], scope=sc,
                            mesh=mesh)[0]) for _ in range(6)]
    meshmod.set_mesh(None)
    assert losses[-1] < losses[0]
    assert all(np.isfinite(v) for v in losses)


def test_fleet_dgc_compressed_grads_train():
    """DGC meta-optimizer: dgc ops inserted before the allreduce, carry
    buffers created, training converges (reference:
    dgc_optimizer.py + dgc_op.cc)."""
    mesh = create_mesh({"dp": 8})
    fleet.init(is_collective=True)
    s = fleet.DistributedStrategy()
    s.dgc = True
    s.dgc_configs = {"sparsity": 0.7, "momentum": 0.9}  # drop 70%, keep top 30%
    main, startup, loss = _build(
        s, opt_factory=lambda lr: pt.optimizer.MomentumOptimizer(lr, 0.9))
    ops = [op.type for op in main.global_block().ops]
    assert "dgc" in ops and "c_allreduce_sum" in ops
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.Scope()
    exe.run(startup, scope=sc, use_compiled=False)
    feed = _feed()
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss], scope=sc,
                            mesh=mesh)[0]) for _ in range(8)]
    meshmod.set_mesh(None)
    assert losses[-1] < losses[0]
