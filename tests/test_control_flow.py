"""Control-flow layer tests: cond / while_loop / static_loop (reference:
test_cond.py, test_while_loop_op.py, StaticRNN tests)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.layers.control_flow import cond, static_loop, while_loop


class TestCond:
    def test_branches_and_grad(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=False)
            flag = layers.data("flag", [1], dtype="bool",
                               append_batch_size=False)
            out = cond(flag,
                       lambda: layers.scale(x, scale=3.0),
                       lambda: layers.scale(x, scale=0.5))
            loss = layers.mean(out)
            grads = pt.gradients([loss], [x])
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        xv = np.ones((2, 4), np.float32)
        for flag_v, scale in ((True, 3.0), (False, 0.5)):
            o, g = exe.run(main,
                           feed={"x": xv, "flag": np.array([flag_v])},
                           fetch_list=[out, grads[0]], scope=scope)
            np.testing.assert_allclose(o, scale * xv, atol=1e-6)
            np.testing.assert_allclose(g, scale / 8 * np.ones_like(xv),
                                       atol=1e-6)

    def test_mismatched_branches_rejected(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4])
            flag = layers.data("flag", [1], dtype="bool",
                               append_batch_size=False)
            with pytest.raises(ValueError, match="same number"):
                cond(flag, lambda: (layers.scale(x, scale=1.0),
                                    layers.scale(x, scale=2.0)),
                     lambda: layers.scale(x, scale=0.5))


class TestWhileLoop:
    def test_dynamic_count(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            i = layers.fill_constant([1], "int32", 0)
            acc = layers.fill_constant([1], "float32", 0.0)
            limit = layers.data("limit", [1], dtype="int32",
                                append_batch_size=False)

            def c(i, acc):
                return layers.less_than(i, limit)

            def b(i, acc):
                return layers.increment(i, 1.0), acc + 2.0

            i_out, acc_out = while_loop(c, b, [i, acc])
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        for n in (3, 7):
            iv, av = exe.run(main, feed={"limit": np.array([n], np.int32)},
                             fetch_list=[i_out, acc_out], scope=scope)
            assert int(np.asarray(iv).reshape(-1)[0]) == n
            assert float(np.asarray(av).reshape(-1)[0]) == 2.0 * n


class TestStaticLoop:
    def test_scan_loop_with_grad(self, scope):
        """x -> x * w repeated n times; d(out)/dw flows through the scan."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [3], stop_gradient=True)
            w = layers.create_parameter([1], "float32", name="w",
                                        default_initializer=pt.initializer
                                        .Constant(1.5))

            def body(i, acc):
                return layers.elementwise_mul(acc, w, axis=-1)

            (out,) = static_loop(3, body, [x])
            loss = layers.reduce_sum(out)
            grads = pt.gradients([loss], [w])
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        xv = np.ones((2, 3), np.float32)
        o, g = exe.run(main, feed={"x": xv}, fetch_list=[out, grads[0]],
                       scope=scope)
        np.testing.assert_allclose(o, 1.5 ** 3 * xv, atol=1e-5)
        # d/dw sum(x * w^3) = 3 w^2 * sum(x) = 3 * 2.25 * 6
        np.testing.assert_allclose(np.asarray(g).reshape(-1)[0],
                                   3 * 1.5 ** 2 * 6.0, rtol=1e-5)


class TestCondEdgeCases:
    def test_identity_branches(self, scope):
        """Branches that return outer vars directly (no ops traced)."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [2])
            y = layers.data("y", [2])
            flag = layers.data("flag", [1], dtype="bool",
                               append_batch_size=False)
            out = cond(flag, lambda: x, lambda: y)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        xv = np.ones((1, 2), np.float32)
        yv = 2 * np.ones((1, 2), np.float32)
        o, = exe.run(main, feed={"x": xv, "y": yv,
                                 "flag": np.array([False])},
                     fetch_list=[out], scope=scope)
        np.testing.assert_allclose(o, yv)

    def test_missing_false_fn_with_outputs_rejected(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [2])
            flag = layers.data("flag", [1], dtype="bool",
                               append_batch_size=False)
            with pytest.raises(ValueError, match="false_fn"):
                cond(flag, lambda: layers.scale(x, scale=2.0))

    def test_branch_reads_predicate(self, scope):
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [2])
            flag = layers.data("flag", [1], dtype="bool",
                               append_batch_size=False)
            out = cond(flag,
                       lambda: layers.cast(flag, "float32"),
                       lambda: layers.cast(flag, "float32") + 1.0)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        o, = exe.run(main, feed={"x": np.ones((1, 2), np.float32),
                                 "flag": np.array([True])},
                     fetch_list=[out], scope=scope)
        np.testing.assert_allclose(np.asarray(o).reshape(-1)[0], 1.0)
