"""Static program verifier + dataflow lint (core/verify.py, ISSUE 8).

Covers the seeded corruption classes the verifier must catch (dangling
input, undefined output, unregistered op, def-after-use, unordered
write-write hazard, static shape mismatch, missing required attr,
missing fetch, donation hazards), the typed ProgramVerifyError contract
(located fields, NOT swallowed by ElasticRunner), control-flow
sub-block recursion, the apply_passes post-pass gate + orphaned-desc
pruning, the registered-pass sweep over book-model programs, the
Executor's FLAGS_verify_program pre-compile gate (incl. run_steps
donation), the tools/graph_lint.py CLI, and the perf_report verifier
section.
"""

import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import telemetry
from paddle_tpu.core.ir import OpDesc
from paddle_tpu.core.passes import apply_passes, register_pass, \
    registered_passes, _PASS_REGISTRY
from paddle_tpu.core.verify import (ProgramVerifyError, VerifyContext,
                                    Violation, registered_checks,
                                    verify_program)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp_program(with_optimizer=False):
    """data -> matmul -> mean (+ optional SGD): the corruption substrate."""
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], stop_gradient=False)
        w = layers.create_parameter([4, 8], "float32", name="w")
        y = layers.matmul(x, w)
        loss = layers.mean(y)
        if with_optimizer:
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


def _checks_of(exc):
    return {v.check for v in exc.violations}


# ---------------------------------------------------------------------------
# seeded corruption classes
# ---------------------------------------------------------------------------

class TestCorruptionClasses:
    def test_clean_program_verifies(self):
        main, _, loss = _mlp_program(with_optimizer=True)
        r = verify_program(main, feed_names={"x"}, fetch_names=[loss.name],
                          infer_shapes=True)
        assert r.ok and r.violations == []
        assert set(r.checks_run) >= {"structure", "dataflow", "hazards",
                                     "donation", "shapes"}

    def test_dangling_input(self):
        main, _, _ = _mlp_program()
        main.global_block().ops[0].inputs["X"] = ["ghost"]
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main)
        assert ei.value.check == "dangling_input"
        assert ei.value.op_type == "matmul"

    def test_undefined_output(self):
        main, _, _ = _mlp_program()
        main.global_block().ops[0].outputs["Out"] = ["ghost_out"]
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main)
        assert "undefined_output" in _checks_of(ei.value)

    def test_unregistered_op(self):
        main, _, _ = _mlp_program()
        main.global_block().ops.insert(
            0, OpDesc("totally_unknown_op", {}, {}))
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main)
        assert ei.value.check == "unregistered_op"
        assert ei.value.op_type == "totally_unknown_op"

    def test_def_after_use(self):
        main, _, _ = _mlp_program()
        blk = main.global_block()
        blk.ops = [blk.ops[1], blk.ops[0]]   # mean before matmul
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main)
        assert ei.value.check == "def_after_use"
        assert ei.value.op_idx == 0

    def test_waw_hazard(self):
        main, _, _ = _mlp_program()
        blk = main.global_block()
        blk.create_var(name="t", shape=[2], dtype="float32")
        fill = {"shape": [2], "value": 1.0, "dtype": "float32"}
        blk.ops.insert(0, OpDesc("fill_constant", {}, {"Out": ["t"]},
                                 dict(fill)))
        blk.ops.insert(1, OpDesc("fill_constant", {}, {"Out": ["t"]},
                                 dict(fill, value=2.0)))
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main)
        assert ei.value.check == "waw_hazard"
        assert ei.value.var if hasattr(ei.value, "var") else True
        [v] = [v for v in ei.value.violations if v.check == "waw_hazard"]
        assert v.var == "t"

    def test_waw_with_intervening_read_is_clean(self):
        """Read-modify-write chains (param updates, increments) must NOT
        trip the hazard check."""
        main, _, _ = _mlp_program()
        blk = main.global_block()
        blk.create_var(name="t", shape=[2], dtype="float32")
        blk.create_var(name="t2", shape=[2], dtype="float32")
        blk.ops.insert(0, OpDesc("fill_constant", {}, {"Out": ["t"]},
                                 {"shape": [2], "value": 1.0,
                                  "dtype": "float32"}))
        blk.ops.insert(1, OpDesc("scale", {"X": ["t"]}, {"Out": ["t"]},
                                 {"scale": 2.0}))
        blk.ops.insert(2, OpDesc("scale", {"X": ["t"]}, {"Out": ["t2"]},
                                 {"scale": 1.0}))
        assert verify_program(main).ok

    def test_static_shape_mismatch_lowering_rejects(self):
        """Corrupt an INPUT desc: the matmul lowering fails under
        eval_shape at the declared shapes — the pjit error, caught
        statically."""
        main, _, _ = _mlp_program()
        main.global_block().vars["w"].desc.shape = (5, 8)
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main, infer_shapes=True)
        assert ei.value.check == "shape_mismatch"
        assert ei.value.op_type == "matmul"

    def test_static_shape_mismatch_declared_vs_inferred(self):
        """Corrupt an OUTPUT desc: inference disagrees with the declared
        shape."""
        main, _, loss = _mlp_program()
        blk = main.global_block()
        out_name = blk.ops[0].outputs["Out"][0]
        blk.vars[out_name].desc.shape = (-1, 16)   # really (-1, 8)
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main, infer_shapes=True)
        vs = [v for v in ei.value.violations if v.check == "shape_mismatch"]
        assert vs and vs[0].var == out_name
        assert "declared" in vs[0].message

    def test_shapes_check_is_opt_in(self):
        """Without infer_shapes the cheap checks pass the corrupt-shape
        program — the hot-path gates stay pure Python."""
        main, _, _ = _mlp_program()
        main.global_block().vars["w"].desc.shape = (5, 8)
        assert verify_program(main).ok

    def test_missing_required_attr(self):
        main, _, _ = _mlp_program()
        blk = main.global_block()
        blk.create_var(name="fa", shape=[-1, 4], dtype="float32")
        blk.create_var(name="fa_i", shape=[-1, 4], dtype="float32")
        blk.ops.append(OpDesc("fused_elemwise_activation",
                              {"X": ["x"], "Y": ["x"]},
                              {"Out": ["fa"], "IntermediateOut": ["fa_i"]},
                              {}))   # no functor_list
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main)
        assert ei.value.check == "missing_attr"
        [v] = [v for v in ei.value.violations if v.check == "missing_attr"]
        assert v.var == "functor_list"

    def test_dangling_read_with_feed_knowledge(self):
        """A non-persistable var nobody produces or feeds — the classic
        pass-removed-producer corruption — needs feed info to judge."""
        main, _, _ = _mlp_program()
        blk = main.global_block()
        blk.create_var(name="orphan", shape=[-1, 8], dtype="float32")
        blk.ops[1].inputs["X"] = ["orphan"]
        # without feed knowledge: structurally fine (could be a feed)
        assert verify_program(main).ok
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main, feed_names={"x"})
        assert ei.value.check == "dangling_read"

    def test_missing_fetch(self):
        main, _, _ = _mlp_program()
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main, feed_names={"x"},
                           fetch_names=["never_produced"])
        assert ei.value.check == "missing_fetch"

    def test_donated_feed_overlap(self):
        """Feeding a var that is also written persistable state: the feed
        shadows the donated carry — run_steps scan donation breaks."""
        main, _, _ = _mlp_program()
        blk = main.global_block()
        blk.ops.append(OpDesc("scale", {"X": ["w"]}, {"Out": ["w"]},
                              {"scale": 0.5}))
        assert verify_program(main, feed_names={"x"}).ok
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main, feed_names={"x", "w"})
        assert ei.value.check == "donated_feed_overlap"


# ---------------------------------------------------------------------------
# typed error contract
# ---------------------------------------------------------------------------

class TestTypedError:
    def test_error_carries_location_and_message(self):
        main, _, _ = _mlp_program()
        main.global_block().ops[0].inputs["X"] = ["ghost"]
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main, context="unit test")
        e = ei.value
        assert (e.block_idx, e.op_idx) == (0, 0)
        assert e.op_type == "matmul" and e.check == "dangling_input"
        assert e.context == "unit test"
        # clickable-style location in the rendered message
        assert "program:block0:op0" in str(e)
        assert "[dangling_input/error]" in str(e)
        assert isinstance(e, RuntimeError)

    def test_elastic_runner_does_not_recover_verify_errors(self, tmp_path):
        """ProgramVerifyError names a programming error — RECOVERABLE
        (typed transport errors) must re-raise it, even wrapped under an
        ExecutionError cause chain."""
        from paddle_tpu.core.executor import ExecutionError
        from paddle_tpu.distributed.elastic import RECOVERABLE, ElasticRunner

        assert not issubclass(ProgramVerifyError, RECOVERABLE)
        runner = ElasticRunner(str(tmp_path / "ckpt"))
        err = ProgramVerifyError(
            [Violation("dangling_input", "error", 0, 0, "matmul")])
        assert not runner._recoverable_exc(err)
        wrapped = ExecutionError("step failed")
        wrapped.__cause__ = err
        assert not runner._recoverable_exc(wrapped)
        # sanity: real transport errors still recover
        from paddle_tpu.distributed.errors import RpcError

        assert runner._recoverable_exc(RpcError("boom"))

    def test_warnings_do_not_raise(self):
        main, _, _ = _mlp_program()
        blk = main.global_block()
        blk.create_var(name="never_used", shape=[2], dtype="float32")
        # pre-existing unreferenced decl with feed knowledge -> dead_var
        r = verify_program(main, feed_names={"x"}, raise_on_error=False)
        assert r.ok
        assert any(v.check == "dead_var" and v.var == "never_used"
                   for v in r.warnings)
        verify_program(main, feed_names={"x"})   # errors only -> no raise


# ---------------------------------------------------------------------------
# control-flow sub-blocks
# ---------------------------------------------------------------------------

class TestControlFlowRecursion:
    def _cond_program(self):
        from paddle_tpu.layers.control_flow import cond

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4], stop_gradient=False)
            flag = layers.data("flag", [1], dtype="bool",
                               append_batch_size=False)
            out = cond(flag, lambda: layers.scale(x, scale=3.0),
                       lambda: layers.scale(x, scale=0.5))
            loss = layers.mean(out)
        return main, loss

    def test_cond_program_clean(self):
        main, loss = self._cond_program()
        r = verify_program(main, feed_names={"x", "flag"},
                           fetch_names=[loss.name], infer_shapes=True)
        assert r.ok and not r.violations

    def test_corruption_inside_sub_block_located(self):
        main, _ = self._cond_program()
        cop = [op for op in main.global_block().ops
               if op.type == "cond"][0]
        cop.attrs["true_block"].ops[0].inputs["X"] = ["ghost_inner"]
        with pytest.raises(ProgramVerifyError) as ei:
            verify_program(main, feed_names={"x", "flag"})
        assert ei.value.check == "dangling_input"
        assert ei.value.block_idx > 0   # located IN the sub-block

    def test_while_loop_program_clean(self):
        from paddle_tpu.layers.control_flow import while_loop

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            i = layers.fill_constant([1], "int32", 0)
            acc = layers.fill_constant([1], "float32", 0.0)
            limit = layers.data("limit", [1], dtype="int32",
                                append_batch_size=False)
            i_out, acc_out = while_loop(
                lambda i, a: layers.less_than(i, limit),
                lambda i, a: (layers.increment(i, 1.0), a + 2.0),
                [i, acc])
        r = verify_program(main, feed_names={"limit"},
                           fetch_names=[i_out.name, acc_out.name])
        assert r.ok and not r.violations

    def test_static_loop_with_grad_clean(self):
        from paddle_tpu.layers.control_flow import static_loop

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [3], stop_gradient=True)
            w = layers.create_parameter(
                [1], "float32", name="w",
                default_initializer=pt.initializer.Constant(1.5))
            (out,) = static_loop(
                3, lambda i, acc: layers.elementwise_mul(acc, w, axis=-1),
                [x])
            loss = layers.reduce_sum(out)
            grads = pt.gradients([loss], [w])
        r = verify_program(main, feed_names={"x"},
                           fetch_names=[out.name, grads[0].name])
        assert r.ok and not r.violations

    def test_sub_block_write_to_outer_persistable_warns(self):
        """The compiling executor's state analysis only sees block-0
        writes — a sub-block update of an outer persistable is silently
        dropped. The donation lint flags it."""
        main, _ = self._cond_program()
        cop = [op for op in main.global_block().ops
               if op.type == "cond"][0]
        tb = cop.attrs["true_block"]
        tb.ops.append(OpDesc("scale", {"X": ["x"]}, {"Out": ["p_state"]},
                             {"scale": 1.0}))
        main.global_block().create_var(name="p_state", shape=[-1, 4],
                                       dtype="float32", persistable=True)
        r = verify_program(main, feed_names={"x", "flag"},
                           raise_on_error=False)
        assert any(v.check == "sub_block_state_write" and
                   v.var == "p_state" for v in r.warnings)


# ---------------------------------------------------------------------------
# apply_passes gate + orphan pruning
# ---------------------------------------------------------------------------

class TestApplyPassesGate:
    def test_bad_pass_named_in_error(self):
        @register_pass("_test_bad_pass")
        def _bad(program):
            # fuse-gone-wrong: rewires an op to a var it then deletes
            blk = program.global_block()
            blk.ops[0].inputs["X"] = ["vanished"]
            return program

        try:
            main, _, _ = _mlp_program()
            with pytest.raises(ProgramVerifyError) as ei:
                apply_passes(main, ["_test_bad_pass"])
            assert "_test_bad_pass" in str(ei.value)
            assert ei.value.check == "dangling_input"
        finally:
            _PASS_REGISTRY.pop("_test_bad_pass", None)

    def test_fc_fuse_prunes_orphaned_intermediate(self):
        """mul+add -> fc orphans the mul's Out desc; apply_passes prunes
        it and the verifier reports the program dead-var clean."""
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [4])
            y = layers.fc(x, 8)
        blk = main.global_block()
        inter = [op for op in blk.ops if op.type == "mul"]
        assert inter, "expected an unfused mul op"
        mul_out = inter[0].outputs["Out"][0]
        assert blk.has_var(mul_out)
        telemetry.reset()
        apply_passes(main, ["fc_fuse_pass"], feed_names={"x"},
                     fetch_names=[y.name])
        assert [op.type for op in blk.ops] == ["fc"]
        assert not blk.has_var(mul_out), "orphaned desc not pruned"
        r = verify_program(main, feed_names={"x"}, fetch_names=[y.name],
                           raise_on_error=False)
        assert r.ok and not r.warnings

    def test_verify_false_skips_gate(self):
        @register_pass("_test_bad_pass2")
        def _bad(program):
            program.global_block().ops[0].inputs["X"] = ["vanished"]
            return program

        try:
            main, _, _ = _mlp_program()
            apply_passes(main, ["_test_bad_pass2"], verify=False)  # no raise
        finally:
            _PASS_REGISTRY.pop("_test_bad_pass2", None)


# ---------------------------------------------------------------------------
# registered-pass sweep over the book-model programs (ISSUE satellite)
# ---------------------------------------------------------------------------

def _book_builders():
    from paddle_tpu.models import lenet, sentiment, word2vec

    return {
        "lenet": lambda: lenet.build_lenet_program(batch_size=4),
        "word2vec": lambda: word2vec.build_word2vec_program(
            dict_size=100, batch_size=4),
        "sentiment_conv": lambda: sentiment.build_sentiment_program(
            net="conv", vocab=100, seq_len=8, batch_size=4),
    }


class TestPassSweepBookModels:
    @pytest.mark.parametrize("model", sorted(_book_builders()))
    def test_every_registered_pass_verifies_clean(self, model):
        """Each registered pass applied to the book model's eval clone
        must leave a program with zero violations — errors AND warnings
        (no dangling vars, no stale wiring) — under full verification
        including static shape propagation."""
        main, startup, feeds, fetches = _book_builders()[model]()
        feed_names = set(feeds)
        fetch_names = [v.name for v in fetches.values()]
        for pname in registered_passes():
            infer = main.clone(for_test=True)
            apply_passes(infer, [pname], feed_names=feed_names,
                         fetch_names=fetch_names)
            r = verify_program(infer, feed_names=feed_names,
                               fetch_names=fetch_names, infer_shapes=True,
                               raise_on_error=False,
                               context=f"{model}/{pname}")
            assert not r.violations, (
                f"{model} after {pname}: "
                f"{[v.format() for v in r.violations]}")

    @pytest.mark.parametrize("model", sorted(_book_builders()))
    def test_default_pipeline_verifies_clean(self, model):
        from paddle_tpu.inference.predictor import DEFAULT_PASSES

        main, startup, feeds, fetches = _book_builders()[model]()
        feed_names = set(feeds)
        fetch_names = [v.name for v in fetches.values()]
        infer = main.clone(for_test=True)
        apply_passes(infer, DEFAULT_PASSES, feed_names=feed_names,
                     fetch_names=fetch_names)
        r = verify_program(infer, feed_names=feed_names,
                           fetch_names=fetch_names, infer_shapes=True,
                           raise_on_error=False)
        assert not r.violations, [v.format() for v in r.violations]

    def test_training_programs_verify_clean(self):
        for model, build in _book_builders().items():
            main, startup, feeds, fetches = build()
            fetch_names = [v.name for v in fetches.values()]
            r = verify_program(main, feed_names=set(feeds),
                               fetch_names=fetch_names, infer_shapes=True,
                               raise_on_error=False, context=model)
            assert not r.errors, (model, [v.format() for v in r.errors])
            r2 = verify_program(startup, raise_on_error=False)
            assert not r2.errors, (model, [v.format() for v in r2.errors])


# ---------------------------------------------------------------------------
# executor FLAGS_verify_program gate
# ---------------------------------------------------------------------------

@pytest.fixture
def verify_flag():
    from paddle_tpu.core import flags as _flags

    with _flags.overrides(verify_program=True):
        yield


class TestExecutorGate:
    def test_corrupt_program_fails_before_compile(self, scope, verify_flag):
        main, startup, loss = _mlp_program_with_opt()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        main.global_block().ops[0].inputs["X"] = ["ghost"]
        with pytest.raises(ProgramVerifyError) as ei:
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss], scope=scope)
        assert ei.value.check == "dangling_input"

    def test_clean_program_runs_and_verification_is_cached(self, scope,
                                                           verify_flag):
        telemetry.reset()
        main, startup, loss = _mlp_program_with_opt()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.ones((2, 4), np.float32)}
        l1, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        l2, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        assert np.isfinite(float(np.asarray(l1).reshape(-1)[0]))
        snap = telemetry.snapshot()
        # startup + main verified once each; the second run hit the
        # (uid, version) cache
        assert snap["counters"].get("verifier.programs") == 2
        assert snap["counters"].get("verifier.checks_run", 0) >= 8

    def test_run_steps_donation_gate(self, scope, verify_flag):
        """run_steps with a feed aliasing donated state is exactly the
        silent-wrong-answer case — the gate turns it into a typed error."""
        main, startup, loss = _mlp_program_with_opt()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        k = 2
        feed = {"x": np.ones((k, 2, 4), np.float32),
                "w": np.ones((k, 4, 8), np.float32)}
        with pytest.raises(ProgramVerifyError) as ei:
            exe.run_steps(main, feed=feed, fetch_list=[loss], k=k,
                          scope=scope)
        assert ei.value.check == "donated_feed_overlap"

    def test_run_steps_clean_program_unaffected(self, scope, verify_flag):
        main, startup, loss = _mlp_program_with_opt()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        k = 3
        feed = {"x": np.stack([np.full((2, 4), i, np.float32)
                               for i in range(k)])}
        out, = exe.run_steps(main, feed=feed, fetch_list=[loss], k=k,
                             scope=scope)
        assert np.shape(out)[0] == k

    def test_flag_off_means_no_verification(self, scope):
        telemetry.reset()
        main, startup, loss = _mlp_program_with_opt()
        main.global_block().vars["w"].desc.shape = (5, 8)  # corrupt desc
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        # cheap checks don't look at shapes; flag off -> no verify at all
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss], scope=scope)
        assert "verifier.programs" not in telemetry.snapshot()["counters"]


def _mlp_program_with_opt():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], stop_gradient=False)
        w = layers.create_parameter([4, 8], "float32", name="w")
        y = layers.matmul(x, w)
        loss = layers.mean(y)
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# graph_lint CLI (tier-1 smoke, ISSUE satellite)
# ---------------------------------------------------------------------------

@pytest.fixture
def lint_main():
    sys.path.insert(0, REPO_ROOT)
    try:
        from tools.graph_lint import main as lint
        yield lint
    finally:
        sys.path.remove(REPO_ROOT)


def _save_small_model(tmp_path, scope):
    from paddle_tpu import io

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6])
        y = layers.fc(x, 4, act="relu")
        out = layers.softmax(y)
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    d = str(tmp_path / "model")
    io.save_inference_model(d, ["x"], [out], main_program=main, scope=scope)
    return d, out.name


class TestGraphLintCLI:
    def test_clean_model_exits_zero(self, tmp_path, scope, lint_main,
                                    capsys):
        d, _ = _save_small_model(tmp_path, scope)
        assert lint_main([d]) == 0
        assert "clean" in capsys.readouterr().out

    def test_corrupted_model_exits_nonzero(self, tmp_path, scope,
                                           lint_main, capsys):
        d, _ = _save_small_model(tmp_path, scope)
        mf = os.path.join(d, "__model__.json")
        doc = json.load(open(mf))
        b0 = doc["program"]["blocks"][0]
        keep = b0["ops"][0]["inputs"]
        # corrupt: first op reads a var whose desc we delete
        victim = next(n for ns in keep.values() for n in ns)
        b0["vars"] = [v for v in b0["vars"] if v["name"] != victim]
        json.dump(doc, open(mf, "w"))
        assert lint_main([d]) == 1
        assert "dangling_input" in capsys.readouterr().out

    def test_json_report(self, tmp_path, scope, lint_main, capsys):
        d, _ = _save_small_model(tmp_path, scope)
        assert lint_main([d, "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["errors"] == 0 and rep["ops"] >= 2
        assert "shapes" in rep["checks_run"]

    def test_unloadable_path_exits_two(self, lint_main):
        assert lint_main([os.path.join("/nonexistent", "dir")]) == 2

    def test_bare_program_json(self, tmp_path, lint_main, capsys):
        main, _, _ = _mlp_program()
        f = tmp_path / "prog.json"
        f.write_text(json.dumps(main.to_dict()))
        assert lint_main([str(f)]) == 0

    def test_strict_fails_on_warnings(self, tmp_path, scope, lint_main,
                                      capsys):
        d, _ = _save_small_model(tmp_path, scope)
        mf = os.path.join(d, "__model__.json")
        doc = json.load(open(mf))
        doc["program"]["blocks"][0]["vars"].append(
            {"name": "dead_decl", "shape": [2], "dtype": "float32"})
        json.dump(doc, open(mf, "w"))
        assert lint_main([d]) == 0
        assert lint_main([d, "--strict"]) == 1


# ---------------------------------------------------------------------------
# telemetry + perf_report section
# ---------------------------------------------------------------------------

class TestVerifierTelemetry:
    def test_counters_and_perf_report_section(self, tmp_path):
        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        telemetry.reset()
        try:
            main, _, loss = _mlp_program()
            verify_program(main, feed_names={"x"}, fetch_names=[loss.name],
                           infer_shapes=True)
            bad, _, _ = _mlp_program()
            bad.global_block().ops[0].inputs["X"] = ["ghost"]
            with pytest.raises(ProgramVerifyError):
                verify_program(bad)
            telemetry.flush_sink()
        finally:
            telemetry.configure(None)
        sys.path.insert(0, REPO_ROOT)
        try:
            from tools.perf_report import load_counted, summarize_log
        finally:
            sys.path.remove(REPO_ROOT)
        recs, malformed = load_counted(str(log))
        s = summarize_log(recs, malformed)
        vf = s["verifier"]
        assert vf["programs"] == 2
        assert vf["violations"] >= 1
        assert vf["checks_run"] >= 8
        assert "verify_ms" in vf

    def test_check_registry_surface(self):
        assert {"structure", "dataflow", "hazards", "donation",
                "shapes"} <= set(registered_checks())
        ctx = VerifyContext(pt.Program())
        assert ctx.blocks and ctx.referenced == set()
