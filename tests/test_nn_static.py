"""Static-mode nn 2.0 Layers (VERDICT r1 item 10).

The reference's 2.0 class layers work in both dygraph and static mode;
here a model built from nn.* classes inside program_guard must train
identically to the same model built from layers.* functions (same
initializer seeds -> identical losses step for step)."""

import numpy as np


def _train(mode, steps=4):
    import paddle_tpu as pt
    from paddle_tpu import layers, nn
    from paddle_tpu.core import ir, unique_name
    from paddle_tpu.initializer import Constant, Xavier

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16], stop_gradient=True)
        label = layers.data("label", [1], dtype="int64", stop_gradient=True)
        w0 = pt.ParamAttr(name="w0", initializer=Xavier(seed=3))
        b0 = pt.ParamAttr(name="b0", initializer=Constant(0.0))
        w1 = pt.ParamAttr(name="w1", initializer=Xavier(seed=4))
        b1 = pt.ParamAttr(name="b1", initializer=Constant(0.0))
        if mode == "nn":
            net1 = nn.Linear(16, 32, weight_attr=w0, bias_attr=b0)
            net2 = nn.Linear(32, 10, weight_attr=w1, bias_attr=b1)
            logits = net2(nn.ReLU()(net1(x)))
        else:
            h = layers.fc(x, 32, act="relu", param_attr=w0, bias_attr=b0)
            logits = layers.fc(h, 10, param_attr=w1, bias_attr=b1)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.SGDOptimizer(0.5).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    sc = pt.Scope()
    exe.run(startup, scope=sc, use_compiled=False)
    xs = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    ys = np.random.RandomState(1).randint(0, 10, (8, 1))
    return [float(exe.run(main, feed={"x": xs, "label": ys},
                          fetch_list=[loss], scope=sc)[0])
            for _ in range(steps)]


class TestStaticNN:
    def test_nn_matches_layers_static(self):
        np.testing.assert_allclose(_train("layers"), _train("nn"),
                                   rtol=1e-5)

    def test_conv_bn_static(self):
        """Conv2D + BatchNorm2D as nn classes in a static program: the
        running stats become persistable startup-initialised vars."""
        import paddle_tpu as pt
        from paddle_tpu import layers, nn
        from paddle_tpu.core import ir, unique_name

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("img", [3, 8, 8], stop_gradient=True)
            conv = nn.Conv2D(3, 4, 3, padding=1)
            bn = nn.BatchNorm2D(4)
            y = layers.mean(bn(conv(x)))
            pt.optimizer.SGDOptimizer(0.1).minimize(y)
        exe = pt.Executor(pt.CPUPlace())
        sc = pt.Scope()
        exe.run(startup, scope=sc, use_compiled=False)
        img = np.random.RandomState(0).randn(4, 3, 8, 8).astype(np.float32)
        vals = [float(exe.run(main, feed={"img": img}, fetch_list=[y],
                              scope=sc)[0]) for _ in range(2)]
        assert all(np.isfinite(v) for v in vals)
        # running stats updated in the scope across steps
        stats = [n for n in sc.keys()] if hasattr(sc, "keys") else \
            [k for k, _ in sc.items()]
        assert any("_mean" in n or "mean" in n for n in stats)
