"""paddle.vision.models zoo (reference: python/paddle/vision/models/) —
construction, forward shapes, head/pool switches, and one training step."""

import numpy as np
import pytest

import paddle_tpu as pt


def _x(b=2, c=3, hw=32, seed=0):
    return pt.to_tensor(np.random.RandomState(seed).randn(
        b, c, hw, hw).astype(np.float32))


class TestVisionModels:
    def test_lenet_shapes_and_headless(self):
        from paddle_tpu.vision import models as M

        with pt.dygraph.guard():
            x = pt.to_tensor(np.random.RandomState(0).randn(
                2, 1, 28, 28).astype(np.float32))
            assert tuple(M.LeNet()(x).shape) == (2, 10)
            feat = M.LeNet(num_classes=0)(x)
            assert tuple(feat.shape) == (2, 16, 5, 5)

    @pytest.mark.parametrize("ctor,classes", [
        ("resnet18", 7), ("resnet50", 5), ("vgg11", 4)])
    def test_backbones_forward(self, ctor, classes):
        from paddle_tpu.vision import models as M

        with pt.dygraph.guard():
            net = getattr(M, ctor)(num_classes=classes)
            out = net(_x())
            assert tuple(out.shape) == (2, classes)

    def test_mobilenets_forward(self):
        from paddle_tpu.vision import models as M

        with pt.dygraph.guard():
            assert tuple(M.mobilenet_v1(scale=0.25, num_classes=3)(
                _x()).shape) == (2, 3)
            assert tuple(M.mobilenet_v2(scale=0.25, num_classes=3)(
                _x()).shape) == (2, 3)

    def test_pretrained_raises(self):
        from paddle_tpu.vision import models as M

        with pytest.raises(ValueError, match="pretrained"):
            M.resnet18(pretrained=True)

    def test_resnet18_trains_a_step(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.vision import models as M

        with pt.dygraph.guard():
            net = M.resnet18(num_classes=4)
            opt = pt.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
            x = _x(b=4)
            y = pt.to_tensor(np.array([[0], [1], [2], [3]], np.int64))
            losses = []
            for _ in range(3):
                loss = F.cross_entropy(net(x), y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(np.asarray(loss.numpy())))
            assert all(np.isfinite(losses))
            assert losses[-1] < losses[0]


class TestAdaptivePoolSemantics:
    """Regression: adaptive pool's ksize is the OUTPUT size with
    reference cell bounds floor(i*H/oh):ceil((i+1)*H/oh) — previously it
    was treated as a fixed window (wrong off the divisible case, empty
    output when output > input, as VGG at 32x32 exposed)."""

    def test_non_divisible_and_upsample(self):
        import paddle_tpu.nn.functional as F

        with pt.dygraph.guard():
            xa = np.arange(2 * 3 * 6 * 6, dtype=np.float32).reshape(
                2, 3, 6, 6)
            x = pt.to_tensor(xa)
            y = np.asarray(F.adaptive_avg_pool2d(x, (4, 4)).numpy())
            for i in range(4):
                h0, h1 = (i * 6) // 4, -(-((i + 1) * 6) // 4)
                for j in range(4):
                    w0, w1 = (j * 6) // 4, -(-((j + 1) * 6) // 4)
                    np.testing.assert_allclose(
                        y[:, :, i, j], xa[:, :, h0:h1, w0:w1].mean((2, 3)),
                        rtol=1e-6)
            ym = np.asarray(F.adaptive_max_pool2d(x, (4, 4)).numpy())
            assert ym[0, 0, 0, 0] == xa[0, 0, :2, :2].max()
            small = pt.to_tensor(np.random.RandomState(1).randn(
                1, 2, 1, 1).astype(np.float32))
            up = np.asarray(F.adaptive_avg_pool2d(small, (7, 7)).numpy())
            np.testing.assert_allclose(
                up, np.broadcast_to(np.asarray(small.numpy()),
                                    (1, 2, 7, 7)), rtol=1e-6)
