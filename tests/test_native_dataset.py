"""Native data runtime + Dataset API + train_from_dataset tests.

Mirrors the reference's dataset/data_feed tests (test_dataset.py,
data_feed.cc CheckFile): MultiSlot parsing, shuffle determinism, batch
LoD assembly, and the executor's trainer path."""

import os

import numpy as np
import pytest


def _write_multislot(tmp_path, n_files=2, rows=6, feat=8):
    """Files with slots: feat (float, `feat` values) + label (uint, 1)."""
    files = []
    rng = np.random.RandomState(7)
    truth = []
    for fi in range(n_files):
        path = str(tmp_path / f"part-{fi}")
        with open(path, "w") as f:
            for r in range(rows):
                vals = rng.randn(feat).astype(np.float32)
                label = int(rng.randint(0, 4))
                truth.append((vals, label))
                f.write(f"{feat} " + " ".join(f"{v:.6f}" for v in vals)
                        + f" 1 {label}\n")
        files.append(path)
    return files, truth


class TestNativeEngine:
    def test_available(self):
        from paddle_tpu import native

        assert native.available(), native.build_error()

    def test_parse_matches_python_fallback(self, tmp_path):
        from paddle_tpu import native
        from paddle_tpu.dataset import _PyParserDataset

        files, truth = _write_multislot(tmp_path)
        slots = [("feat", "f"), ("label", "u")]

        nat = native.NativeDataset(slots)
        nat.set_filelist(files)
        assert nat.load_into_memory(3) == len(truth)

        py = _PyParserDataset(slots)
        py.set_filelist(files)
        py.load_into_memory()

        nb = list(nat.batches(5))
        pb = list(py.batches(5))
        assert len(nb) == len(pb)
        for b1, b2 in zip(nb, pb):
            np.testing.assert_allclose(b1["feat"][0], b2["feat"][0],
                                       atol=1e-6)
            np.testing.assert_array_equal(b1["label"][0], b2["label"][0])
            np.testing.assert_array_equal(b1["feat"][1], b2["feat"][1])

    def test_shuffle_deterministic(self, tmp_path):
        from paddle_tpu import native

        files, truth = _write_multislot(tmp_path)
        orders = []
        for _ in range(2):
            ds = native.NativeDataset([("feat", "f"), ("label", "u")])
            ds.set_filelist(files)
            ds.load_into_memory(2)
            ds.global_shuffle(seed=123)
            labels = []
            for b in ds.batches(4):
                labels.extend(b["label"][0].tolist())
            orders.append(labels)
        assert orders[0] == orders[1]
        assert sorted(orders[0]) == sorted(t[1] for t in truth)

    def test_parse_error_reported(self, tmp_path):
        from paddle_tpu import native

        bad = str(tmp_path / "bad")
        with open(bad, "w") as f:
            f.write("2 1.0 notafloat 1 0\n")
        ds = native.NativeDataset([("feat", "f"), ("label", "u")])
        ds.set_filelist([bad])
        with pytest.raises(RuntimeError, match="bad float"):
            ds.load_into_memory(1)


class TestTrainFromDataset:
    def test_mlp_trains(self, tmp_path, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers

        files, _ = _write_multislot(tmp_path, n_files=2, rows=16, feat=8)

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            feat = layers.data("feat", [8], stop_gradient=True)
            label = layers.data("label", [1], dtype="int64",
                                stop_gradient=True)
            h = layers.fc(feat, 16, act="relu")
            logits = layers.fc(h, 4)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.SGDOptimizer(0.2).minimize(loss)

        dataset = pt.DatasetFactory().create_dataset("InMemoryDataset")
        dataset.set_batch_size(8)
        dataset.set_thread(2)
        dataset.set_use_var([feat, label])
        dataset.set_filelist(files)
        dataset.load_into_memory()
        dataset.global_shuffle(seed=1)
        assert dataset.get_memory_data_size() == 32

        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        first = exe.train_from_dataset(main, dataset, scope=scope,
                                       fetch_list=[loss])
        for _ in range(12):
            last = exe.train_from_dataset(main, dataset, scope=scope,
                                          fetch_list=[loss])
        assert float(np.asarray(last[0]).reshape(-1)[0]) < \
            float(np.asarray(first[0]).reshape(-1)[0])


class TestQueueDataset:
    def test_streaming_covers_all_records(self, tmp_path):
        import paddle_tpu as pt

        files, truth = _write_multislot(tmp_path, n_files=3, rows=10)
        import paddle_tpu.layers as layers
        from paddle_tpu.core import ir

        ir._main_program = ir.Program()
        feat = layers.data("feat", [8], stop_gradient=True)
        label = layers.data("label", [1], dtype="int64", stop_gradient=True)

        ds = pt.DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(7)
        ds.set_thread(3)
        ds.set_use_var([feat, label])
        ds.set_filelist(files)
        seen = []
        for feed in ds.iter_batches():
            assert feed["feat"].shape[1] == 8
            seen.extend(feed["label"].reshape(-1).tolist())
        assert sorted(seen) == sorted(t[1] for t in truth)


class TestInferFromDataset:
    def test_does_not_update_params(self, tmp_path, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers

        files, _ = _write_multislot(tmp_path, n_files=1, rows=8)
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            feat = layers.data("feat", [8], stop_gradient=True)
            label = layers.data("label", [1], dtype="int64",
                                stop_gradient=True)
            logits = layers.fc(feat, 4, param_attr=pt.ParamAttr(name="w"))
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.SGDOptimizer(0.5).minimize(loss)

        dataset = pt.DatasetFactory().create_dataset("InMemoryDataset")
        dataset.set_batch_size(4)
        dataset.set_use_var([feat, label])
        dataset.set_filelist(files)
        dataset.load_into_memory()

        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        w0 = np.asarray(scope.find_var("w")).copy()
        exe.infer_from_dataset(main, dataset, scope=scope,
                               fetch_list=[loss])
        np.testing.assert_array_equal(np.asarray(scope.find_var("w")), w0)
        exe.train_from_dataset(main, dataset, scope=scope,
                               fetch_list=[loss])
        assert not np.array_equal(np.asarray(scope.find_var("w")), w0)

    def test_unloaded_dataset_raises(self, tmp_path, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.executor import ExecutionError

        files, _ = _write_multislot(tmp_path, n_files=1, rows=4)
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            feat = layers.data("feat", [8], stop_gradient=True)
            loss = layers.mean(layers.fc(feat, 2))
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        ds = pt.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_use_var([feat])
        ds.set_filelist(files)  # load_into_memory() NOT called
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        with pytest.raises(ExecutionError, match="load_into_memory"):
            exe.train_from_dataset(main, ds, scope=scope)

    def test_stream_parse_error_raises(self, tmp_path):
        from paddle_tpu import native

        bad = str(tmp_path / "bad")
        with open(bad, "w") as f:
            f.write("8 1 2 3 4 5 6 7 8 1 0\n")
            f.write("8 1 2 oops 4 5 6 7 8 1 0\n")
        ds = native.NativeDataset([("feat", "f"), ("label", "u")])
        ds.set_filelist([bad])
        with pytest.raises(RuntimeError, match="bad float"):
            list(ds.stream_batches(2, 1))


class TestStreamConcurrency:
    def test_restart_stream_while_workers_live(self, tmp_path):
        """Regression for the ADVICE r1 use-after-free: calling
        stream_begin while a previous stream's parser threads are mid-Put
        must join them first (native/data_feed.cc ptds_stream_begin now
        calls ptds_stream_end). Abandon iterators mid-stream repeatedly —
        with the bug this crashes/hangs; fixed it re-streams cleanly."""
        import paddle_tpu as pt
        import paddle_tpu.layers as layers
        from paddle_tpu.core import ir

        files, truth = _write_multislot(tmp_path, n_files=4, rows=50)
        ir._main_program = ir.Program()
        feat = layers.data("feat", [8], stop_gradient=True)
        label = layers.data("label", [1], dtype="int64", stop_gradient=True)

        ds = pt.DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(3)
        ds.set_thread(4)
        ds.set_use_var([feat, label])
        ds.set_filelist(files)
        for trial in range(5):
            it = ds.iter_batches()
            next(it)           # pull one batch, abandon the rest
            del it
        # final full pass still yields every record exactly once
        seen = []
        for feed in ds.iter_batches():
            seen.extend(feed["label"].reshape(-1).tolist())
        assert sorted(seen) == sorted(t[1] for t in truth)
