"""Quantization (slim) + large-scale KV tests (reference:
slim/tests/test_quantization_pass.py, test_post_training_quantization,
large_scale_kv / downpour pull-push flow)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _mlp_program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8], stop_gradient=True)
        label = layers.data("label", [1], dtype="int64", stop_gradient=True)
        h = layers.fc(x, 16, act="relu")
        logits = layers.fc(h, 4)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return main, startup, x, label, logits, loss


class TestQATPass:
    def test_insert_and_train(self, scope):
        from paddle_tpu.contrib.slim import QuantizationTransformPass

        main, startup, x, label, logits, loss = _mlp_program()
        # QAT order matters: transform BEFORE minimize so the backward is
        # built over the fake-quant ops (STE grad makers engage)
        qpass = QuantizationTransformPass()
        qpass.apply(main)
        with pt.program_guard(main, startup):
            pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        types = [op.type for op in main.global_block().ops]
        assert "fake_channel_wise_quantize_dequantize_abs_max" in types
        assert "fake_quantize_dequantize_moving_average_abs_max" in types

        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        qpass.init_scale_state(scope)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(16, 8).astype(np.float32),
                "label": rng.randint(0, 4, (16, 1)).astype(np.int64)}
        losses = []
        for _ in range(8):
            lv, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert losses[-1] < losses[0]     # STE grads train through fake-quant

    def test_quantized_close_to_fp(self, scope):
        from paddle_tpu.contrib.slim import QuantizationTransformPass

        main, startup, x, label, logits, loss = _mlp_program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(1).randn(4, 8).astype(np.float32),
                "label": np.zeros((4, 1), np.int64)}
        fp, = exe.run(main, feed=feed, fetch_list=[logits], scope=scope)
        qpass = QuantizationTransformPass(for_test=False)
        qpass.apply(main)
        qpass.init_scale_state(scope)
        q, = exe.run(main, feed=feed, fetch_list=[logits], scope=scope)
        # int8 simulation stays within ~2% of fp
        assert np.max(np.abs(np.asarray(q) - np.asarray(fp))) < \
            0.02 * (np.max(np.abs(fp)) + 1.0)


class TestPTQ:
    def test_calibrate_and_freeze(self, scope):
        from paddle_tpu.contrib.slim import (PostTrainingQuantization,
                                             quantize_weights_int8)

        main, startup, x, label, logits, loss = _mlp_program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(2)
        batches = [{"x": rng.randn(8, 8).astype(np.float32),
                    "label": np.zeros((8, 1), np.int64)} for _ in range(3)]
        feed = batches[0]
        fp, = exe.run(main, feed=feed, fetch_list=[logits], scope=scope)
        ptq = PostTrainingQuantization(exe, main, ["x", "label"],
                                       scope, batches)
        qprog = ptq.quantize()
        assert any(s > 0 for s in ptq.calibrated_scales.values())
        q, = exe.run(qprog, feed=feed, fetch_list=[logits], scope=scope)
        assert np.max(np.abs(np.asarray(q) - np.asarray(fp))) < \
            0.05 * (np.max(np.abs(fp)) + 1.0)

        packs = quantize_weights_int8(qprog, scope)
        assert packs and all(p["int8"].dtype == np.int8
                             for p in packs.values())


class TestLargeScaleKV:
    def test_pull_push_roundtrip(self):
        from paddle_tpu.distributed.large_scale_kv import LargeScaleKV

        kv = LargeScaleKV(dim=4, num_shards=3, seed=0)
        ids = np.array([5, 99, 5, 1000000007])
        rows = kv.pull(ids)
        assert rows.shape == (4, 4)
        np.testing.assert_allclose(rows[0], rows[2])   # same id, same row
        assert kv.size() == 3

        grads = np.ones((4, 4), np.float32)
        before = kv.pull(np.array([5]))[0].copy()
        kv.push(ids, grads, lr=0.5)
        after = kv.pull(np.array([5]))[0]
        # id 5 appears twice -> accumulated grad 2.0, sgd 0.5 * 2
        np.testing.assert_allclose(after, before - 1.0, atol=1e-6)

    def test_save_load(self, tmp_path):
        from paddle_tpu.distributed.large_scale_kv import LargeScaleKV

        kv = LargeScaleKV(dim=3, seed=1)
        kv.pull(np.arange(10))
        kv.save(str(tmp_path / "kv"))
        kv2 = LargeScaleKV(dim=3, seed=2)
        kv2.load(str(tmp_path / "kv"))
        np.testing.assert_allclose(kv2.pull(np.arange(10)),
                                   kv.pull(np.arange(10)))

    def test_sparse_embedding_trains(self):
        """Host-KV embedding + device loss: the downpour per-batch flow."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distributed.large_scale_kv import (LargeScaleKV,
                                                           SparseEmbedding)

        kv = LargeScaleKV(dim=4, seed=3)
        emb = SparseEmbedding(kv)
        ids = np.array([1, 2, 3, 4])
        target = jnp.ones((4, 4))
        losses = []
        for _ in range(20):
            rows = emb.pull(ids)
            loss, g = jax.value_and_grad(
                lambda r: jnp.mean((r - target) ** 2))(rows)
            emb.push(np.asarray(g), lr=1.0)
            losses.append(float(loss))
        assert losses[-1] < 0.1 * losses[0]
