"""EMA / ModelAverage / Lookahead wrapper optimizers
(reference: fluid test_ema.py, test_lookahead.py, ModelAverage tests)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def _build(wrap=None):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        loss = layers.mean(layers.fc(x, 1))
        inner = pt.optimizer.SGDOptimizer(0.1)
        if wrap is None:
            inner.minimize(loss)
            extra = None
        elif wrap == "ema":
            inner.minimize(loss)
            extra = pt.optimizer.ExponentialMovingAverage(0.5)
            extra.update()
        elif wrap == "ma":
            inner.minimize(loss)
            extra = pt.optimizer.ModelAverage(0.15)
        elif wrap == "lookahead":
            extra = pt.optimizer.LookaheadOptimizer(inner, alpha=0.5, k=2)
            extra.minimize(loss)
    w = main.all_parameters()[0].name
    return main, startup, loss, extra, w


def test_ema_apply_restore(scope):
    main, startup, loss, ema, w = _build("ema")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"x": np.ones((2, 4), np.float32)}
    for _ in range(5):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    w_now = np.array(scope.find_var(w))
    with ema.apply(exe, scope=scope):
        w_ema = np.array(scope.find_var(w))
        assert not np.allclose(w_ema, w_now)
    np.testing.assert_array_equal(np.array(scope.find_var(w)), w_now)


def test_lookahead_sync_every_k(scope):
    main, startup, loss, la, w = _build("lookahead")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    slow_name = w + "@SLOW"
    w0 = np.array(scope.find_var(w))
    np.testing.assert_array_equal(w0, np.array(scope.find_var(slow_name)))
    feed = {"x": np.ones((2, 4), np.float32)}
    for _ in range(4):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    slow = np.array(scope.find_var(slow_name))
    fast = np.array(scope.find_var(w))
    assert not np.allclose(slow, w0), "slow weights never updated"
    # step 4 is a sync step (k=2): fast == slow
    np.testing.assert_allclose(slow, fast, rtol=1e-6)


def test_model_average_apply(scope):
    main, startup, loss, ma, w = _build("ma")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"x": np.ones((2, 4), np.float32)}
    for _ in range(3):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    w_now = np.array(scope.find_var(w))
    with ma.apply(exe, scope=scope):
        w_avg = np.array(scope.find_var(w))
        assert not np.allclose(w_avg, w_now)
    np.testing.assert_array_equal(np.array(scope.find_var(w)), w_now)


def test_model_average_window_bounded(scope):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        loss = layers.mean(layers.fc(x, 1))
        pt.optimizer.SGDOptimizer(0.0).minimize(loss)  # lr 0: params frozen
        ma = pt.optimizer.ModelAverage(0.15, max_average_window=4)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"x": np.ones((2, 4), np.float32)}
    for _ in range(10):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    cnt = float(np.asarray(scope.find_var(ma._count_name)).reshape(-1)[0])
    assert cnt <= 5.5, cnt  # halved whenever it crosses 4
    # average of a constant param is that param
    w = main.all_parameters()[0].name
    w_now = np.array(scope.find_var(w))
    with ma.apply(exe, scope=scope):
        np.testing.assert_allclose(np.array(scope.find_var(w)), w_now,
                                   rtol=1e-5)
