"""EMA / ModelAverage / Lookahead wrapper optimizers
(reference: fluid test_ema.py, test_lookahead.py, ModelAverage tests),
plus the dygraph optimizer state_dict/set_state_dict restore paths the
crash-consistent checkpoint stack depends on."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _build(wrap=None):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        loss = layers.mean(layers.fc(x, 1))
        inner = pt.optimizer.SGDOptimizer(0.1)
        if wrap is None:
            inner.minimize(loss)
            extra = None
        elif wrap == "ema":
            inner.minimize(loss)
            extra = pt.optimizer.ExponentialMovingAverage(0.5)
            extra.update()
        elif wrap == "ma":
            inner.minimize(loss)
            extra = pt.optimizer.ModelAverage(0.15)
        elif wrap == "lookahead":
            extra = pt.optimizer.LookaheadOptimizer(inner, alpha=0.5, k=2)
            extra.minimize(loss)
    w = main.all_parameters()[0].name
    return main, startup, loss, extra, w


def test_ema_apply_restore(scope):
    main, startup, loss, ema, w = _build("ema")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"x": np.ones((2, 4), np.float32)}
    for _ in range(5):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    w_now = np.array(scope.find_var(w))
    with ema.apply(exe, scope=scope):
        w_ema = np.array(scope.find_var(w))
        assert not np.allclose(w_ema, w_now)
    np.testing.assert_array_equal(np.array(scope.find_var(w)), w_now)


def test_lookahead_sync_every_k(scope):
    main, startup, loss, la, w = _build("lookahead")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    slow_name = w + "@SLOW"
    w0 = np.array(scope.find_var(w))
    np.testing.assert_array_equal(w0, np.array(scope.find_var(slow_name)))
    feed = {"x": np.ones((2, 4), np.float32)}
    for _ in range(4):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    slow = np.array(scope.find_var(slow_name))
    fast = np.array(scope.find_var(w))
    assert not np.allclose(slow, w0), "slow weights never updated"
    # step 4 is a sync step (k=2): fast == slow
    np.testing.assert_allclose(slow, fast, rtol=1e-6)


def test_model_average_apply(scope):
    main, startup, loss, ma, w = _build("ma")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"x": np.ones((2, 4), np.float32)}
    for _ in range(3):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    w_now = np.array(scope.find_var(w))
    with ma.apply(exe, scope=scope):
        w_avg = np.array(scope.find_var(w))
        assert not np.allclose(w_avg, w_now)
    np.testing.assert_array_equal(np.array(scope.find_var(w)), w_now)


def test_model_average_window_bounded(scope):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4])
        loss = layers.mean(layers.fc(x, 1))
        pt.optimizer.SGDOptimizer(0.0).minimize(loss)  # lr 0: params frozen
        ma = pt.optimizer.ModelAverage(0.15, max_average_window=4)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = {"x": np.ones((2, 4), np.float32)}
    for _ in range(10):
        exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    cnt = float(np.asarray(scope.find_var(ma._count_name)).reshape(-1)[0])
    assert cnt <= 5.5, cnt  # halved whenever it crosses 4
    # average of a constant param is that param
    w = main.all_parameters()[0].name
    w_now = np.array(scope.find_var(w))
    with ma.apply(exe, scope=scope):
        np.testing.assert_allclose(np.array(scope.find_var(w)), w_now,
                                   rtol=1e-5)


# ---------------------------------------------------------------------------
# dygraph optimizer state restore (checkpoint/exact-resume dependency)
# ---------------------------------------------------------------------------

def _dy_train(net, opt, x, y, steps):
    from paddle_tpu.nn import functional as F

    for _ in range(steps):
        loss = F.cross_entropy(net(pt.dygraph.to_variable(x)),
                               pt.dygraph.to_variable(y))
        loss.backward()
        opt.minimize(loss)
        net.clear_gradients()


def test_set_state_dict_into_fresh_optimizer():
    """Restore-into-fresh-optimizer: state saved mid-run applies through
    the pending-state path (set BEFORE the first step builds the
    micro-program) and the continued run matches an uninterrupted one —
    Adam's moments must carry over, not restart cold."""
    from paddle_tpu import nn

    rng = np.random.RandomState(0)
    x = rng.rand(16, 4).astype(np.float32)
    y = (x.sum(1) > 2).astype(np.int32).reshape(16, 1)

    def make():
        net = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
        opt = pt.optimizer.AdamOptimizer(0.05,
                                         parameter_list=net.parameters())
        return net, opt

    with pt.dygraph.guard():
        net_a, opt_a = make()
        _dy_train(net_a, opt_a, x, y, 4)
        w_ref = {k: v.numpy().copy() for k, v in net_a.state_dict().items()}
        st_ref = {k: np.asarray(v).copy()
                  for k, v in opt_a.state_dict().items()}

        # run B: 2 steps, checkpoint, then a FRESH net+optimizer resumes
        net_b, opt_b = make()
        _dy_train(net_b, opt_b, x, y, 2)
        net_state = {k: v.numpy().copy() for k, v in net_b.state_dict().items()}
        opt_state = {k: np.asarray(v).copy()
                     for k, v in opt_b.state_dict().items()}
        assert any("#" in k for k in opt_state)   # positional accum keys

        net_c, opt_c = make()
        net_c.set_state_dict(net_state)
        opt_c.set_state_dict(opt_state)           # pending path: no scope yet
        assert getattr(opt_c, "_pending_state", None)
        _dy_train(net_c, opt_c, x, y, 2)
        w_c = {k: v.numpy() for k, v in net_c.state_dict().items()}
        for k in w_ref:
            np.testing.assert_allclose(w_c[k], w_ref[k], rtol=1e-6,
                                       atol=1e-7, err_msg=k)
        st_c = opt_c.state_dict()
        for k in st_ref:
            np.testing.assert_allclose(np.asarray(st_c[k]),
                                       np.asarray(st_ref[k]), rtol=1e-6,
                                       atol=1e-7, err_msg=k)


def test_set_state_dict_stale_keys_raise():
    """Stale-checkpoint keys (a different optimizer type's accumulators)
    must raise the 'restored 0 entries' error, not silently train with
    cold state."""
    from paddle_tpu import nn

    rng = np.random.RandomState(1)
    x = rng.rand(8, 4).astype(np.float32)
    y = (x.sum(1) > 2).astype(np.int32).reshape(8, 1)
    with pt.dygraph.guard():
        net = nn.Sequential(nn.Linear(4, 2))
        opt = pt.optimizer.AdamOptimizer(0.05,
                                         parameter_list=net.parameters())
        _dy_train(net, opt, x, y, 1)   # accumulators + scope now exist
        with pytest.raises(ValueError, match="restored 0 entries"):
            opt.set_state_dict({"bogus_acc#0": np.zeros((2,), np.float32),
                                "bogus_acc#1": np.zeros((2,), np.float32)})
