"""Regression tests for nn functional loss semantics (weight / ignore_index /
pos_weight / padding_mode / scalar promotion).

Mirrors the reference's test_cross_entropy_loss.py / test_nll_loss.py /
test_bce_with_logits_loss.py coverage points.
"""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.dygraph import guard, to_variable


def _softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_cross_entropy_ignore_index_mean_divides_by_valid():
    rng = np.random.RandomState(0)
    logits = rng.randn(4, 5).astype(np.float32)
    label = np.array([1, -100, 3, -100], np.int64)
    with guard():
        out = F.cross_entropy(to_variable(logits), to_variable(label))
        lp = np.log(_softmax(logits))
        expect = -(lp[0, 1] + lp[2, 3]) / 2.0  # mean over the 2 VALID entries
        np.testing.assert_allclose(out.numpy(), expect, rtol=1e-5)


def test_cross_entropy_class_weight():
    rng = np.random.RandomState(1)
    logits = rng.randn(6, 3).astype(np.float32)
    label = rng.randint(0, 3, (6,)).astype(np.int64)
    w = np.array([0.2, 1.0, 3.0], np.float32)
    with guard():
        out = F.cross_entropy(to_variable(logits), to_variable(label),
                              weight=to_variable(w))
        lp = np.log(_softmax(logits))
        per = -lp[np.arange(6), label] * w[label]
        expect = per.sum() / w[label].sum()  # weighted mean
        np.testing.assert_allclose(out.numpy(), expect, rtol=1e-5)


def test_nll_loss_weight_and_ignore_index_dygraph():
    rng = np.random.RandomState(2)
    logp = np.log(_softmax(rng.randn(5, 4).astype(np.float32)))
    label = np.array([0, 1, -100, 3, 2], np.int64)
    w = np.array([1.0, 2.0, 0.5, 4.0], np.float32)
    with guard():
        loss = nn.NLLLoss(weight=to_variable(w))(to_variable(logp),
                                                 to_variable(label))
        valid = label != -100
        per = -logp[np.arange(5), np.clip(label, 0, 3)] * w[np.clip(label, 0, 3)]
        expect = per[valid].sum() / w[label[valid]].sum()
        np.testing.assert_allclose(loss.numpy(), expect, rtol=1e-5)


def test_bce_with_logits_pos_weight():
    rng = np.random.RandomState(3)
    x = rng.randn(8, 3).astype(np.float32)
    z = (rng.rand(8, 3) > 0.5).astype(np.float32)
    pw = np.array([1.0, 2.0, 0.5], np.float32)
    with guard():
        loss = nn.BCEWithLogitsLoss(pos_weight=to_variable(pw))(
            to_variable(x), to_variable(z))
        sp = lambda v: np.logaddexp(0.0, v)  # noqa: E731
        expect = (pw * z * sp(-x) + (1 - z) * sp(x)).mean()
        np.testing.assert_allclose(loss.numpy(), expect, rtol=1e-5)


def test_int_tensor_times_float_scalar_promotes():
    with guard():
        x = to_variable(np.array([4, 6], np.int32))
        y = x * 0.5
        np.testing.assert_allclose(y.numpy(), [2.0, 3.0])


def test_interpolate_list_scale_factor():
    with guard():
        x = to_variable(np.ones((1, 1, 4, 4), np.float32))
        y = F.interpolate(x, scale_factor=[2, 3])
        assert tuple(y.shape) == (1, 1, 8, 12)


def test_conv2d_padding_mode_reflect():
    with guard():
        x = to_variable(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        conv = nn.Conv2D(1, 1, 3, padding=1, padding_mode="reflect",
                         bias_attr=False)
        conv.weight.set_value(np.ones((1, 1, 3, 3), np.float32))
        out = conv(x).numpy()
        xp = np.pad(np.arange(16, dtype=np.float32).reshape(4, 4), 1,
                    mode="reflect")
        expect = np.array([[xp[i:i + 3, j:j + 3].sum() for j in range(4)]
                           for i in range(4)])
        np.testing.assert_allclose(out[0, 0], expect, rtol=1e-5)


def test_load_vars_rank_mismatch_raises(tmp_path):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        from paddle_tpu import layers

        x = layers.data("x", [8])
        layers.fc(x, 4)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    # corrupt: overwrite the weight with a wrong-rank array, save, reload
    scope.set("fc_0.w_0", np.zeros((32,), np.float32))
    pt.io.save_params(exe, str(tmp_path), main, scope=scope)
    with pytest.raises(RuntimeError, match="shape mismatch"):
        pt.io.load_params(exe, str(tmp_path), main, scope=pt.Scope())
