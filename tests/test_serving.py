"""Serving-engine tests: dynamic micro-batching behind admission control.

Contracts under test (paddle_tpu/serving/):
* coalesced + padded batches return responses bitwise-identical to
  unbatched AnalysisPredictor.run of the same rows, across buckets;
* partial batches flush on the batch timeout;
* a saturated queue rejects with ServerOverloadedError (never stalls);
* warmup pre-compiles every bucket exactly once;
* the stdlib HTTP front end round-trips JSON on an ephemeral port;
* close(drain=True) serves the backlog before exiting;
* injected serving.handler faults produce per-request error responses
  and the queue keeps moving.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.serving

IN_DIM, OUT_DIM = 6, 4


def _save_mlp(tmp_path, name="m"):
    """Tiny fc net exported as an inference model (fast to compile)."""
    import paddle_tpu as pt
    from paddle_tpu import io, layers

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [IN_DIM])
        h = layers.fc(x, 8, act="relu")
        y = layers.fc(h, OUT_DIM)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    model_dir = str(tmp_path / name)
    io.save_inference_model(model_dir, ["x"], [y],
                            main_program=main, scope=scope)
    return model_dir


def _predictor(model_dir):
    from paddle_tpu.inference import AnalysisConfig, create_predictor

    return create_predictor(AnalysisConfig(model_dir))


def _engine(model_dir, **cfg):
    from paddle_tpu.serving import ServingConfig, ServingEngine

    cfg.setdefault("max_batch_size", 8)
    cfg.setdefault("batch_timeout_ms", 5.0)
    return ServingEngine(_predictor(model_dir), config=ServingConfig(**cfg))


def _rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, IN_DIM).astype(np.float32)


class TestBatchedEquivalence:
    def test_batched_bitwise_identical_across_buckets(self, tmp_path):
        """Requests of 1..8 rows — coalesced, padded to pow2 buckets —
        must be BITWISE equal to single-request predictor runs."""
        model_dir = _save_mlp(tmp_path)
        reference = _predictor(model_dir)
        engine = _engine(model_dir).start(warmup=True)
        try:
            sizes = [1, 2, 3, 5, 8, 4, 1, 7]
            feeds = [_rows(n, seed=i) for i, n in enumerate(sizes)]
            reqs = [engine.submit({"x": f}) for f in feeds]
            for f, req in zip(feeds, reqs):
                got, = req.result(timeout=30)
                want, = reference.run({"x": f})
                assert got.shape == (f.shape[0], OUT_DIM)
                assert np.array_equal(got, want), \
                    "batched output differs bitwise from unbatched run"
        finally:
            engine.close(drain=True, timeout=10)

    def test_concurrent_clients_coalesce(self, tmp_path):
        """8 threads x 1-row requests: all answers right, and the engine
        actually batched (fewer batches than requests)."""
        from paddle_tpu.core import telemetry

        model_dir = _save_mlp(tmp_path)
        reference = _predictor(model_dir)
        engine = _engine(model_dir, batch_timeout_ms=20.0).start(warmup=True)
        before = telemetry.counter_get("serving.batches")
        results = {}
        lock = threading.Lock()

        def client(i):
            f = _rows(1, seed=100 + i)
            got, = engine.infer({"x": f}, timeout=30)
            want, = reference.run({"x": f})
            with lock:
                results[i] = np.array_equal(got, want)

        try:
            # the 20 ms batch window is far wider than the thread-start
            # skew, so concurrent submits coalesce
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
        finally:
            engine.close(drain=True, timeout=10)
        assert len(results) == 8 and all(results.values())
        batches = telemetry.counter_get("serving.batches") - before
        assert batches < 8, f"no coalescing happened ({batches} batches)"


class TestBatchingPolicy:
    def test_timeout_flushes_partial_batch(self, tmp_path):
        from paddle_tpu.core import telemetry

        engine = _engine(_save_mlp(tmp_path),
                         batch_timeout_ms=15.0).start(warmup=True)
        before_b = telemetry.counter_get("serving.batches")
        before_p = telemetry.counter_get("serving.padded_rows")
        try:
            t0 = time.monotonic()
            out, = engine.infer({"x": _rows(3)}, timeout=30)
            waited = time.monotonic() - t0
        finally:
            engine.close(drain=True, timeout=10)
        assert out.shape == (3, OUT_DIM)
        assert waited < 5.0, "partial batch did not flush on timeout"
        assert telemetry.counter_get("serving.batches") - before_b == 1
        # 3 rows pad to the 4-bucket: exactly one padded row, sliced out
        assert telemetry.counter_get("serving.padded_rows") - before_p == 1

    def test_backpressure_rejects_when_saturated(self, tmp_path):
        from paddle_tpu.core import telemetry
        from paddle_tpu.serving import ServerOverloadedError

        # worker not started -> the queue only fills
        engine = _engine(_save_mlp(tmp_path), max_queue_depth=2)
        before = telemetry.counter_get("serving.rejects")
        r1 = engine.submit({"x": _rows(1)})
        r2 = engine.submit({"x": _rows(2)})
        with pytest.raises(ServerOverloadedError):
            engine.submit({"x": _rows(1)})
        assert telemetry.counter_get("serving.rejects") - before == 1
        engine.start(warmup=False)   # drain the two admitted requests
        try:
            assert r1.result(timeout=30)[0].shape == (1, OUT_DIM)
            assert r2.result(timeout=30)[0].shape == (2, OUT_DIM)
        finally:
            engine.close(drain=True, timeout=10)

    def test_expired_deadline_fails_at_dequeue(self, tmp_path):
        from paddle_tpu.core import telemetry
        from paddle_tpu.serving import DeadlineExceededError

        engine = _engine(_save_mlp(tmp_path))
        before = telemetry.counter_get("serving.deadline_expired")
        req = engine.submit({"x": _rows(1)}, deadline_ms=1)
        ok = engine.submit({"x": _rows(1)})         # no deadline
        time.sleep(0.05)
        engine.start(warmup=False)
        try:
            with pytest.raises(DeadlineExceededError):
                req.result(timeout=30)
            assert ok.result(timeout=30)[0].shape == (1, OUT_DIM)
        finally:
            engine.close(drain=True, timeout=10)
        assert telemetry.counter_get("serving.deadline_expired") - before == 1

    def test_graceful_drain_serves_backlog(self, tmp_path):
        from paddle_tpu.serving import EngineClosedError

        engine = _engine(_save_mlp(tmp_path))
        reqs = [engine.submit({"x": _rows(n, seed=n)}) for n in (1, 2, 3)]
        engine.start(warmup=False)
        engine.close(drain=True, timeout=30)
        for n, req in zip((1, 2, 3), reqs):
            assert req.result(timeout=1)[0].shape == (n, OUT_DIM)
        with pytest.raises(EngineClosedError):
            engine.submit({"x": _rows(1)})


class TestWarmup:
    def test_warmup_compiles_every_bucket_once(self, tmp_path):
        from paddle_tpu.core import telemetry

        engine = _engine(_save_mlp(tmp_path))
        before = telemetry.counter_get("predictor.compiles")
        fresh = engine.warmup()
        # pow2 buckets for max_batch 8: [1, 2, 4, 8]
        assert fresh == 4
        assert telemetry.counter_get("predictor.compiles") - before == 4
        engine.start(warmup=True)    # second warmup: all cache hits
        try:
            for n in (1, 2, 3, 5, 8):
                engine.infer({"x": _rows(n, seed=n)}, timeout=30)
        finally:
            engine.close(drain=True, timeout=10)
        # every request landed in a warmed bucket: zero fresh compiles
        assert telemetry.counter_get("predictor.compiles") - before == 4


class TestHTTP:
    def test_http_round_trip_and_health(self, tmp_path):
        from paddle_tpu.serving import serve

        model_dir = _save_mlp(tmp_path)
        reference = _predictor(model_dir)
        server = serve(model_dir, port=0)    # ephemeral port
        try:
            x = _rows(2, seed=7)
            body = json.dumps({"inputs": {"x": x.tolist()}}).encode()
            req = urllib.request.Request(
                server.url + "/v1/infer", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
                doc = json.loads(resp.read())
            want, = reference.run({"x": x})
            name = server.engine.fetch_names[0]
            got = np.asarray(doc["outputs"][name], dtype=np.float32)
            np.testing.assert_array_equal(got, want)
            assert doc["latency_ms"] >= 0
            with urllib.request.urlopen(server.url + "/healthz",
                                        timeout=10) as resp:
                assert resp.status == 200
                assert json.loads(resp.read())["status"] == "ok"
        finally:
            server.shutdown()
            server.engine.close(drain=True, timeout=10)


@pytest.mark.chaos
class TestHandlerFaults:
    def test_injected_fault_is_per_request_not_wedge(self, tmp_path):
        from paddle_tpu.core import faults, telemetry

        engine = _engine(_save_mlp(tmp_path)).start(warmup=True)
        before = telemetry.counter_get("serving.handler_errors")
        faults.configure("serving.handler:@1:RuntimeError")
        try:
            with pytest.raises(RuntimeError):
                engine.infer({"x": _rows(2)}, timeout=30)
            # the very next request sails through — no wedged queue
            out, = engine.infer({"x": _rows(2, seed=1)}, timeout=30)
            assert out.shape == (2, OUT_DIM)
        finally:
            faults.configure("")
            engine.close(drain=True, timeout=10)
        assert telemetry.counter_get("serving.handler_errors") - before >= 1


class TestValidation:
    def test_bad_feeds_rejected_before_queueing(self, tmp_path):
        engine = _engine(_save_mlp(tmp_path))
        with pytest.raises(ValueError, match="missing input"):
            engine.submit({})
        with pytest.raises(ValueError, match="unknown inputs"):
            engine.submit({"x": _rows(1), "bogus": _rows(1)})
        with pytest.raises(ValueError, match="leading batch dim"):
            engine.submit({"x": np.float32(1.0)})
        engine.close(drain=False)

    def test_bucket_boundaries(self):
        from paddle_tpu.serving import ServingConfig

        cfg = ServingConfig(max_batch_size=8)
        assert cfg.buckets == [1, 2, 4, 8]
        assert [cfg.bucket(n) for n in (1, 2, 3, 5, 8, 11)] == \
            [1, 2, 4, 8, 8, 11]
        cfg = ServingConfig(max_batch_size=6, buckets=[2, 6])
        assert [cfg.bucket(n) for n in (1, 2, 3, 6)] == [2, 2, 6, 6]
