"""Inference engine tests: save → load → analyze (passes) → predict.

Mirrors the reference's inference tests (inference/tests/api/,
test_inference_model_io.py): optimized predictor output must match the
unoptimized executor run of the same program."""

import numpy as np
import pytest


def _build_lenet():
    import paddle_tpu as pt
    from paddle_tpu import layers

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = layers.data("img", [1, 28, 28])
        conv = layers.conv2d(img, 6, 5, act="relu")
        pool = layers.pool2d(conv, 2, pool_stride=2)
        flat = layers.reshape(pool, [0, 6 * 12 * 12])
        h = layers.fc(flat, 64, act="relu")
        logits = layers.fc(h, 10)
    return main, startup, img, logits


class TestSaveLoadPredict:
    def test_lenet_roundtrip(self, tmp_path, scope):
        import paddle_tpu as pt

        main, startup, img, logits = _build_lenet()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.random.RandomState(0).randn(4, 1, 28, 28).astype(np.float32)
        want, = exe.run(main, feed={"img": x}, fetch_list=[logits],
                        scope=scope)

        from paddle_tpu import io
        from paddle_tpu.inference import AnalysisConfig, create_predictor

        io.save_inference_model(str(tmp_path / "model"), ["img"], [logits],
                                main_program=main, scope=scope)
        pred = create_predictor(AnalysisConfig(str(tmp_path / "model")))
        got, = pred.run({"img": x})
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_zero_copy_handles(self, tmp_path, scope):
        import paddle_tpu as pt

        main, startup, img, logits = _build_lenet()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)

        from paddle_tpu import io
        from paddle_tpu.inference import AnalysisConfig, create_predictor

        io.save_inference_model(str(tmp_path / "m"), ["img"], [logits],
                                main_program=main, scope=scope)
        pred = create_predictor(AnalysisConfig(str(tmp_path / "m")))
        assert pred.get_input_names() == ["img"]
        x = np.random.randn(2, 1, 28, 28).astype(np.float32)
        pred.get_input_handle("img").copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        assert out.shape == (2, 10)


class TestPasses:
    def _bert_inference_program(self):
        from paddle_tpu.models import bert

        cfg = bert.BertConfig(vocab_size=64, hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=2,
                              intermediate_size=64,
                              max_position_embeddings=32)
        return bert.build_pretraining_program(
            cfg, seq_len=32, with_optimizer=False, is_test=True), cfg

    def test_attention_fuse_and_dropout_delete(self, scope):
        import paddle_tpu as pt
        from paddle_tpu.core.passes import apply_passes
        from paddle_tpu.models import bert

        (main, startup, feeds, fetches), cfg = self._bert_inference_program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        batch = bert.synthetic_pretraining_batch(cfg, 2, 32)
        want, = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                        scope=scope)

        from paddle_tpu import io

        pruned = io.prune_program(main, list(batch), [fetches["loss"].name])
        n_before = len(pruned.global_block().ops)
        types_before = [o.type for o in pruned.global_block().ops]
        opt = apply_passes(pruned, ["delete_dropout_pass",
                                    "multihead_attention_fuse_pass",
                                    "fc_fuse_pass"])
        types_after = [o.type for o in opt.global_block().ops]
        assert types_after.count("flash_attention") == cfg.num_hidden_layers
        assert "dropout" not in types_after
        assert "softmax" not in [t for t in types_after
                                 if t != "softmax_with_cross_entropy"]
        assert types_after.count("fc") >= 4
        assert len(types_after) < n_before

        got, = exe.run(opt, feed=batch, fetch_list=[fetches["loss"]],
                       scope=scope)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_embedding_eltwise_layernorm_fuse(self, scope):
        """The BERT embedding stack (3 lookups + adds + layer_norm)
        collapses to one fused op with identical outputs (reference:
        ir/embedding_eltwise_layernorm_fuse_pass.cc)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            a = layers.data("a", [8], dtype="int64", stop_gradient=True)
            b = layers.data("b", [8], dtype="int64", stop_gradient=True)
            c = layers.data("c", [8], dtype="int64", stop_gradient=True)
            ea = layers.embedding(a, [32, 16])
            eb = layers.embedding(b, [4, 16])
            ec = layers.embedding(c, [8, 16])
            y = layers.layer_norm(ea + eb + ec, begin_norm_axis=2)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(0)
        feed = {"a": rng.randint(0, 32, (2, 8)).astype(np.int64),
                "b": rng.randint(0, 4, (2, 8)).astype(np.int64),
                "c": rng.randint(0, 8, (2, 8)).astype(np.int64)}
        want, = exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        apply_passes(main, ["embedding_eltwise_layernorm_fuse_pass"])
        types = [o.type for o in main.global_block().ops]
        assert "fused_embedding_eltwise_layernorm" in types
        assert "lookup_table_v2" not in types
        assert "layer_norm" not in types
        got, = exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_fuse_elewise_add_act(self, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8])
            y2 = layers.data("y2", [8])
            z = layers.relu(layers.elementwise_add(x, y2))
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(0).randn(3, 8).astype(np.float32),
                "y2": np.random.RandomState(1).randn(3, 8).astype(np.float32)}
        want, = exe.run(main, feed=feed, fetch_list=[z], scope=scope)
        apply_passes(main, ["fuse_elewise_add_act_pass"])
        types = [o.type for o in main.global_block().ops]
        assert "fused_elemwise_activation" in types and "relu" not in types
        got, = exe.run(main, feed=feed, fetch_list=[z], scope=scope)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_fuse_add_gelu_keeps_exact_form(self, scope):
        """gelu's approximate attr must survive the fuse (erf vs tanh
        forms differ ~1e-3 — the equivalence contract would break)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8])
            y2 = layers.data("y2", [8])
            z = layers.gelu(layers.elementwise_add(x, y2))  # erf default
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(0).randn(3, 8).astype(np.float32)
                * 2.0,
                "y2": np.random.RandomState(1).randn(3, 8).astype(np.float32)}
        want, = exe.run(main, feed=feed, fetch_list=[z], scope=scope)
        apply_passes(main, ["fuse_elewise_add_act_pass"])
        types = [o.type for o in main.global_block().ops]
        assert "fused_elemwise_activation" in types and "gelu" not in types
        got, = exe.run(main, feed=feed, fetch_list=[z], scope=scope)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_conv_bn_fuse(self, scope):
        """conv2d + batch_norm(is_test) folds into conv + bias add
        (reference: ir/conv_bn_fuse_pass.cc); outputs must match the
        unfused program on the same weights."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [3, 8, 8])
            h = layers.conv2d(x, 6, 3, padding=1, bias_attr=False)
            y = layers.batch_norm(h, is_test=True)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        # non-trivial running stats so the fold actually does arithmetic
        import numpy as _np

        for name, v in list(scope.items()):
            arr = _np.asarray(v)
            if "mean" in name:
                scope.set(name, _np.linspace(-0.5, 0.5,
                                             arr.size).astype(arr.dtype))
            if "var" in name.lower() or "variance" in name:
                scope.set(name, _np.linspace(0.5, 2.0,
                                             arr.size).astype(arr.dtype))
        xv = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
        want, = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
        apply_passes(main, ["conv_bn_fuse_pass"], scope=scope)
        types = [o.type for o in main.global_block().ops]
        assert "batch_norm" not in types
        assert types.count("conv2d") == 1
        got, = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_fc_fuse_simple(self, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [16])
            y = layers.fc(x, 8)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        xv = np.random.randn(3, 16).astype(np.float32)
        want, = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
        apply_passes(main, ["fc_fuse_pass"])
        types = [o.type for o in main.global_block().ops]
        assert "fc" in types and "elementwise_add" not in types
        got, = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
        np.testing.assert_allclose(got, want, atol=1e-6)
