"""Inference engine tests: save → load → analyze (passes) → predict.

Mirrors the reference's inference tests (inference/tests/api/,
test_inference_model_io.py): optimized predictor output must match the
unoptimized executor run of the same program."""

import numpy as np
import pytest


def _build_lenet():
    import paddle_tpu as pt
    from paddle_tpu import layers

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = layers.data("img", [1, 28, 28])
        conv = layers.conv2d(img, 6, 5, act="relu")
        pool = layers.pool2d(conv, 2, pool_stride=2)
        flat = layers.reshape(pool, [0, 6 * 12 * 12])
        h = layers.fc(flat, 64, act="relu")
        logits = layers.fc(h, 10)
    return main, startup, img, logits


class TestSaveLoadPredict:
    def test_lenet_roundtrip(self, tmp_path, scope):
        import paddle_tpu as pt

        main, startup, img, logits = _build_lenet()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.random.RandomState(0).randn(4, 1, 28, 28).astype(np.float32)
        want, = exe.run(main, feed={"img": x}, fetch_list=[logits],
                        scope=scope)

        from paddle_tpu import io
        from paddle_tpu.inference import AnalysisConfig, create_predictor

        io.save_inference_model(str(tmp_path / "model"), ["img"], [logits],
                                main_program=main, scope=scope)
        pred = create_predictor(AnalysisConfig(str(tmp_path / "model")))
        got, = pred.run({"img": x})
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_zero_copy_handles(self, tmp_path, scope):
        import paddle_tpu as pt

        main, startup, img, logits = _build_lenet()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)

        from paddle_tpu import io
        from paddle_tpu.inference import AnalysisConfig, create_predictor

        io.save_inference_model(str(tmp_path / "m"), ["img"], [logits],
                                main_program=main, scope=scope)
        pred = create_predictor(AnalysisConfig(str(tmp_path / "m")))
        assert pred.get_input_names() == ["img"]
        x = np.random.randn(2, 1, 28, 28).astype(np.float32)
        pred.get_input_handle("img").copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        assert out.shape == (2, 10)


class TestPredictorCacheAndHandles:
    def _mlp_predictor(self, tmp_path, scope):
        import paddle_tpu as pt
        from paddle_tpu import io, layers

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [6])
            y = layers.fc(x, 4)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        from paddle_tpu.inference import AnalysisConfig, create_predictor

        io.save_inference_model(str(tmp_path / "m"), ["x"], [y],
                                main_program=main, scope=scope)
        return create_predictor(AnalysisConfig(str(tmp_path / "m")))

    def test_cache_is_lru_bounded(self, tmp_path, scope):
        """Shape churn beyond FLAGS_predictor_cache_capacity evicts the
        coldest signature instead of growing without limit."""
        import paddle_tpu as pt
        from paddle_tpu.core import telemetry

        from paddle_tpu.core import flags as _flags

        pred = self._mlp_predictor(tmp_path, scope)
        before = telemetry.counter_get("predictor.cache_evictions")
        with _flags.overrides(predictor_cache_capacity=2):
            for rows in (1, 2, 3):      # 3 signatures > capacity 2
                pred.run({"x": np.zeros((rows, 6), np.float32)})
            assert len(pred._cache) == 2
            assert telemetry.counter_get(
                "predictor.cache_evictions") - before == 1
            # evicted signature recompiles and still answers correctly
            x = np.random.RandomState(0).randn(1, 6).astype(np.float32)
            out, = pred.run({"x": x})
            assert out.shape == (1, 4)

    def test_cache_hits_counted(self, tmp_path, scope):
        from paddle_tpu.core import telemetry

        pred = self._mlp_predictor(tmp_path, scope)
        x = np.zeros((2, 6), np.float32)
        c0 = telemetry.counter_get("predictor.compiles")
        h0 = telemetry.counter_get("predictor.cache_hits")
        pred.run({"x": x})
        pred.run({"x": x})
        assert telemetry.counter_get("predictor.compiles") - c0 == 1
        assert telemetry.counter_get("predictor.cache_hits") - h0 == 1

    def test_output_handle_shape(self, tmp_path, scope):
        """PredictorTensor.shape reads output handles too (it used to
        only see staged inputs)."""
        pred = self._mlp_predictor(tmp_path, scope)
        out_name = pred.get_output_names()[0]
        handle = pred.get_output_handle(out_name)
        assert handle.shape is None          # run() not called yet
        pred.run({"x": np.zeros((3, 6), np.float32)})
        assert handle.shape == (3, 4)

    def test_int64_downcast_follows_x64_config(self, tmp_path, scope):
        """int64 feeds narrow to int32 only because jax x64 is OFF here
        (the old code downcast unconditionally)."""
        import jax

        import paddle_tpu as pt
        from paddle_tpu import io, layers

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", [4], dtype="int64",
                              stop_gradient=True)
            emb = layers.embedding(ids, [16, 8])
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        from paddle_tpu.inference import AnalysisConfig, create_predictor

        io.save_inference_model(str(tmp_path / "emb"), ["ids"], [emb],
                                main_program=main, scope=scope)
        pred = create_predictor(AnalysisConfig(str(tmp_path / "emb")))
        out, = pred.run({"ids": np.zeros((2, 4), np.int64)})
        assert out.shape == (2, 4, 8)
        (sig,) = pred._cache.keys()
        fed_dtype = dict((n, d) for n, _s, d in sig)["ids"]
        expect = "int64" if jax.config.jax_enable_x64 else "int32"
        assert fed_dtype == expect


class TestPasses:
    def _bert_inference_program(self):
        from paddle_tpu.models import bert

        cfg = bert.BertConfig(vocab_size=64, hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=2,
                              intermediate_size=64,
                              max_position_embeddings=32)
        return bert.build_pretraining_program(
            cfg, seq_len=32, with_optimizer=False, is_test=True), cfg

    def test_attention_fuse_and_dropout_delete(self, scope):
        import paddle_tpu as pt
        from paddle_tpu.core.passes import apply_passes
        from paddle_tpu.models import bert

        (main, startup, feeds, fetches), cfg = self._bert_inference_program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        batch = bert.synthetic_pretraining_batch(cfg, 2, 32)
        want, = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                        scope=scope)

        from paddle_tpu import io

        pruned = io.prune_program(main, list(batch), [fetches["loss"].name])
        n_before = len(pruned.global_block().ops)
        types_before = [o.type for o in pruned.global_block().ops]
        opt = apply_passes(pruned, ["delete_dropout_pass",
                                    "multihead_attention_fuse_pass",
                                    "fc_fuse_pass"])
        types_after = [o.type for o in opt.global_block().ops]
        assert types_after.count("flash_attention") == cfg.num_hidden_layers
        assert "dropout" not in types_after
        assert "softmax" not in [t for t in types_after
                                 if t != "softmax_with_cross_entropy"]
        assert types_after.count("fc") >= 4
        assert len(types_after) < n_before

        got, = exe.run(opt, feed=batch, fetch_list=[fetches["loss"]],
                       scope=scope)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_embedding_eltwise_layernorm_fuse(self, scope):
        """The BERT embedding stack (3 lookups + adds + layer_norm)
        collapses to one fused op with identical outputs (reference:
        ir/embedding_eltwise_layernorm_fuse_pass.cc)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            a = layers.data("a", [8], dtype="int64", stop_gradient=True)
            b = layers.data("b", [8], dtype="int64", stop_gradient=True)
            c = layers.data("c", [8], dtype="int64", stop_gradient=True)
            ea = layers.embedding(a, [32, 16])
            eb = layers.embedding(b, [4, 16])
            ec = layers.embedding(c, [8, 16])
            y = layers.layer_norm(ea + eb + ec, begin_norm_axis=2)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(0)
        feed = {"a": rng.randint(0, 32, (2, 8)).astype(np.int64),
                "b": rng.randint(0, 4, (2, 8)).astype(np.int64),
                "c": rng.randint(0, 8, (2, 8)).astype(np.int64)}
        want, = exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        apply_passes(main, ["embedding_eltwise_layernorm_fuse_pass"])
        types = [o.type for o in main.global_block().ops]
        assert "fused_embedding_eltwise_layernorm" in types
        assert "lookup_table_v2" not in types
        assert "layer_norm" not in types
        got, = exe.run(main, feed=feed, fetch_list=[y], scope=scope)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_fuse_elewise_add_act(self, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8])
            y2 = layers.data("y2", [8])
            z = layers.relu(layers.elementwise_add(x, y2))
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(0).randn(3, 8).astype(np.float32),
                "y2": np.random.RandomState(1).randn(3, 8).astype(np.float32)}
        want, = exe.run(main, feed=feed, fetch_list=[z], scope=scope)
        apply_passes(main, ["fuse_elewise_add_act_pass"])
        types = [o.type for o in main.global_block().ops]
        assert "fused_elemwise_activation" in types and "relu" not in types
        got, = exe.run(main, feed=feed, fetch_list=[z], scope=scope)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_fuse_add_gelu_keeps_exact_form(self, scope):
        """gelu's approximate attr must survive the fuse (erf vs tanh
        forms differ ~1e-3 — the equivalence contract would break)."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8])
            y2 = layers.data("y2", [8])
            z = layers.gelu(layers.elementwise_add(x, y2))  # erf default
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(0).randn(3, 8).astype(np.float32)
                * 2.0,
                "y2": np.random.RandomState(1).randn(3, 8).astype(np.float32)}
        want, = exe.run(main, feed=feed, fetch_list=[z], scope=scope)
        apply_passes(main, ["fuse_elewise_add_act_pass"])
        types = [o.type for o in main.global_block().ops]
        assert "fused_elemwise_activation" in types and "gelu" not in types
        got, = exe.run(main, feed=feed, fetch_list=[z], scope=scope)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_conv_bn_fuse(self, scope):
        """conv2d + batch_norm(is_test) folds into conv + bias add
        (reference: ir/conv_bn_fuse_pass.cc); outputs must match the
        unfused program on the same weights."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [3, 8, 8])
            h = layers.conv2d(x, 6, 3, padding=1, bias_attr=False)
            y = layers.batch_norm(h, is_test=True)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        # non-trivial running stats so the fold actually does arithmetic
        import numpy as _np

        for name, v in list(scope.items()):
            arr = _np.asarray(v)
            if "mean" in name:
                scope.set(name, _np.linspace(-0.5, 0.5,
                                             arr.size).astype(arr.dtype))
            if "var" in name.lower() or "variance" in name:
                scope.set(name, _np.linspace(0.5, 2.0,
                                             arr.size).astype(arr.dtype))
        xv = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
        want, = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
        apply_passes(main, ["conv_bn_fuse_pass"], scope=scope)
        types = [o.type for o in main.global_block().ops]
        assert "batch_norm" not in types
        assert types.count("conv2d") == 1
        got, = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_fc_fuse_simple(self, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [16])
            y = layers.fc(x, 8)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        xv = np.random.randn(3, 16).astype(np.float32)
        want, = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
        apply_passes(main, ["fc_fuse_pass"])
        types = [o.type for o in main.global_block().ops]
        assert "fc" in types and "elementwise_add" not in types
        got, = exe.run(main, feed={"x": xv}, fetch_list=[y], scope=scope)
        np.testing.assert_allclose(got, want, atol=1e-6)


class TestFusionGroupPass:
    """fusion_group_pass packs elementwise runs into one composite op
    (reference: ir/fusion_group/ NVRTC subgraph codegen — here the win
    is one interp dispatch / jit-cache entry per run)."""

    def _build(self, with_dropout):
        import paddle_tpu as pt
        from paddle_tpu import layers

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [16])
            y = layers.data("y", [16])
            h = layers.elementwise_add(layers.tanh(x), layers.sigmoid(y))
            h = layers.scale(h, scale=1.7, bias=0.3)
            if with_dropout:
                h = layers.dropout(h, 0.4,
                                   dropout_implementation="upscale_in_train")
            out = layers.relu(h)
        return main, startup, out

    def test_pass_groups_and_matches(self, scope):
        import paddle_tpu as pt
        from paddle_tpu.core.passes import apply_passes

        main, startup, out = self._build(with_dropout=False)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(0).randn(4, 16).astype(np.float32),
                "y": np.random.RandomState(1).randn(4, 16).astype(np.float32)}
        want, = exe.run(main, feed=feed, fetch_list=[out], scope=scope)
        apply_passes(main, ["fusion_group_pass"])
        types = [o.type for o in main.global_block().ops]
        assert types.count("fusion_group") == 1, types
        assert not set(types) & {"tanh", "sigmoid", "elementwise_add",
                                 "scale", "relu"}, types
        for use_compiled in (False, True):
            got, = exe.run(main, feed=feed, fetch_list=[out], scope=scope,
                           use_compiled=use_compiled)
            np.testing.assert_allclose(got, want, atol=1e-6)

    def test_dropout_mask_survives_grouping(self, scope):
        """The composite threads __step__ into sub-ops: the grouped
        dropout must draw the SAME per-step mask as the ungrouped op."""
        import paddle_tpu as pt
        from paddle_tpu.core.passes import apply_passes

        main, startup, out = self._build(with_dropout=True)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(0).randn(4, 16).astype(np.float32),
                "y": np.random.RandomState(1).randn(4, 16).astype(np.float32)}
        base = [np.asarray(exe.run(main, feed=feed, fetch_list=[out],
                                   scope=scope)[0]) for _ in range(2)]
        # fresh scope -> same step counter sequence for the fused run
        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        apply_passes(main, ["fusion_group_pass"])
        assert "fusion_group" in [o.type for o in main.global_block().ops]
        fused = [np.asarray(exe.run(main, feed=feed, fetch_list=[out],
                                    scope=scope2)[0]) for _ in range(2)]
        for b, f in zip(base, fused):
            np.testing.assert_allclose(f, b, atol=1e-6)
        assert not np.allclose(fused[0], fused[1])  # step advances mask

    def test_grads_flow_through_group(self, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8])
            w = layers.create_parameter([8], "float32", name="fgw")
            h = layers.sigmoid(layers.elementwise_mul(x, w))
            h = layers.scale(h, scale=2.0)
            loss = layers.mean(h)
            apply_passes(main, ["fusion_group_pass"])
            assert "fusion_group" in [o.type for o in main.global_block().ops]
            pt.optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(2).randn(4, 8).astype(np.float32)}
        l0 = float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss],
                                      scope=scope)[0]))
        for _ in range(10):
            lv = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)[0]
        assert float(np.asarray(lv)) < l0  # params moved: grads flowed

    def test_intermediate_stays_fetchable(self, scope):
        """Regression (round-4 review): a var consumed only INSIDE the
        grouped run can still be a fetch target — fetch_list names are
        metadata the pass cannot see, so every produced var must stay
        materialized."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.passes import apply_passes

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8])
            h = layers.scale(layers.tanh(x), scale=2.0)   # mid-run var
            out = layers.relu(h)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(3).randn(2, 8).astype(np.float32)}
        want_h, want_out = exe.run(main, feed=feed, fetch_list=[h, out],
                                   scope=scope)
        apply_passes(main, ["fusion_group_pass"])
        assert "fusion_group" in [o.type for o in main.global_block().ops]
        got_h, got_out = exe.run(main, feed=feed, fetch_list=[h, out],
                                 scope=scope)
        np.testing.assert_allclose(got_h, want_h, atol=1e-6)
        np.testing.assert_allclose(got_out, want_out, atol=1e-6)
