"""Op tests for the round-2 surface batch (linalg, interp, vision,
metrics, sequence, beam search, fused, optimizer, collective extras).

Mirrors the reference per-op test style (test_*_op.py files): numpy
reference forward + numeric-grad checks via the OpTest harness for
differentiable ops; direct lowering checks for the rest.
"""

import numpy as np
import pytest

from op_test import OpTest


def _r(*shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(
        np.float32)


# -- linalg -------------------------------------------------------------------

class TestAddmm(OpTest):
    op_type = "addmm"

    def setup(self):
        i, x, y = _r(3, 5, seed=1), _r(3, 4, seed=2), _r(4, 5, seed=3)
        self.inputs = {"Input": i, "X": x, "Y": y}
        self.attrs = {"Alpha": 0.5, "Beta": 2.0}
        self.outputs = {"Out": 2.0 * i + 0.5 * (x @ y)}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y", "Input"], "Out")


class TestCross(OpTest):
    op_type = "cross"

    def setup(self):
        x, y = _r(4, 3, seed=1), _r(4, 3, seed=2)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"dim": 1}
        self.outputs = {"Out": np.cross(x, y, axis=1)}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestMv(OpTest):
    op_type = "mv"

    def setup(self):
        x, v = _r(5, 4, seed=1), _r(4, seed=2)
        self.inputs = {"X": x, "Vec": v}
        self.outputs = {"Out": x @ v}

    def test(self):
        self.check_output()
        self.check_grad(["X", "Vec"], "Out")


class TestTrace(OpTest):
    op_type = "trace"

    def setup(self):
        x = _r(4, 5, seed=1)
        self.inputs = {"Input": x}
        self.attrs = {"offset": 1, "axis1": 0, "axis2": 1}
        self.outputs = {"Out": np.trace(x, offset=1)}

    def test(self):
        self.check_output()
        self.check_grad(["Input"], "Out")


class TestInverse(OpTest):
    op_type = "inverse"

    def setup(self):
        x = _r(3, 3, seed=1) + 3.0 * np.eye(3, dtype=np.float32)
        self.inputs = {"Input": x}
        self.outputs = {"Output": np.linalg.inv(x)}

    def test(self):
        self.check_output(atol=1e-4)


class TestCholesky(OpTest):
    op_type = "cholesky"

    def setup(self):
        a = _r(3, 3, seed=2)
        spd = a @ a.T + 3.0 * np.eye(3, dtype=np.float32)
        self.inputs = {"X": spd}
        self.outputs = {"Out": np.linalg.cholesky(spd)}

    def test(self):
        self.check_output(atol=1e-4)


class TestLogsumexp(OpTest):
    op_type = "logsumexp"

    def setup(self):
        x = _r(3, 6, seed=1)
        from scipy.special import logsumexp as lse

        self.inputs = {"X": x}
        self.attrs = {"axis": [1], "keepdim": False}
        self.outputs = {"Out": lse(x, axis=1)}

    def test(self):
        try:
            import scipy  # noqa: F401
        except ImportError:
            pytest.skip("scipy unavailable")
        self.check_output()
        self.check_grad(["X"], "Out")


class TestFrobeniusNorm(OpTest):
    op_type = "frobenius_norm"

    def setup(self):
        x = _r(3, 4, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"dim": [0, 1], "reduce_all": True}
        self.outputs = {"Out": np.sqrt((x * x).sum())}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestMultiplex(OpTest):
    op_type = "multiplex"

    def setup(self):
        a, b = _r(4, 5, seed=1), _r(4, 5, seed=2)
        ids = np.array([[1], [0], [1], [0]], np.int32)
        self.inputs = {"X": [("x0", a), ("x1", b)], "Ids": ids}
        out = np.stack([b[0], a[1], b[2], a[3]])
        self.outputs = {"Out": out}

    def test(self):
        self.check_output()


class TestReverse(OpTest):
    op_type = "reverse"

    def setup(self):
        x = _r(3, 4, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"axis": [1]}
        self.outputs = {"Out": x[:, ::-1]}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestShardIndex(OpTest):
    op_type = "shard_index"

    def setup(self):
        x = np.array([[1], [6], [11], [15]], np.int64)
        self.inputs = {"X": x}
        self.attrs = {"index_num": 20, "nshards": 2, "shard_id": 1,
                      "ignore_value": -1}
        self.outputs = {"Out": np.array([[-1], [-1], [1], [5]], np.int64)}

    def test(self):
        self.check_output()


# -- interp / vision ----------------------------------------------------------

class TestNearestInterp(OpTest):
    op_type = "nearest_interp_v2"

    def setup(self):
        x = _r(2, 3, 4, 4, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"out_h": 8, "out_w": 8}
        self.outputs = {"Out": np.repeat(np.repeat(x, 2, 2), 2, 3)}

    def test(self):
        self.check_output(atol=1e-5)


class TestBilinearInterpShape(OpTest):
    op_type = "bilinear_interp_v2"

    def setup(self):
        x = _r(2, 3, 4, 4, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"out_h": 8, "out_w": 8}
        import jax.image

        self.outputs = {"Out": np.asarray(jax.image.resize(
            x, (2, 3, 8, 8), method="linear"))}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"], "Out")


class TestPixelShuffle(OpTest):
    op_type = "pixel_shuffle"

    def setup(self):
        x = _r(2, 8, 3, 3, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"upscale_factor": 2}
        n, c, h, w = x.shape
        r = 2
        want = x.reshape(n, c // 4, r, r, h, w).transpose(
            0, 1, 4, 2, 5, 3).reshape(n, c // 4, h * r, w * r)
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSpaceToDepth(OpTest):
    op_type = "space_to_depth"

    def setup(self):
        x = _r(2, 3, 4, 4, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"blocksize": 2}
        n, c, h, w = x.shape
        want = x.reshape(n, c, 2, 2, 2, 2).transpose(
            0, 3, 5, 1, 2, 4).reshape(n, 12, 2, 2)
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestShuffleChannel(OpTest):
    op_type = "shuffle_channel"

    def setup(self):
        x = _r(2, 6, 3, 3, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"group": 2}
        n, c, h, w = x.shape
        want = x.reshape(n, 2, 3, h, w).transpose(0, 2, 1, 3, 4) \
            .reshape(n, c, h, w)
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestAffineChannel(OpTest):
    op_type = "affine_channel"

    def setup(self):
        x = _r(2, 3, 4, 4, seed=1)
        s, b = _r(3, seed=2), _r(3, seed=3)
        self.inputs = {"X": x, "Scale": s, "Bias": b}
        self.outputs = {"Out": x * s[None, :, None, None]
                        + b[None, :, None, None]}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestUnfold(OpTest):
    op_type = "unfold"

    def setup(self):
        x = _r(1, 2, 4, 4, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"kernel_sizes": [2, 2], "strides": [2, 2],
                      "paddings": [0, 0, 0, 0], "dilations": [1, 1]}
        # reference im2col with 2x2/stride2: 4 patches
        cols = []
        for i in (0, 2):
            for j in (0, 2):
                cols.append(x[0, :, i:i + 2, j:j + 2].reshape(-1))
        want = np.stack(cols, axis=1)[None]
        self.outputs = {"Y": want}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Y")


class TestGridSampler(OpTest):
    op_type = "grid_sampler"

    def setup(self):
        x = _r(1, 1, 3, 3, seed=1)
        # identity grid samples the input exactly
        ys, xs = np.meshgrid(np.linspace(-1, 1, 3), np.linspace(-1, 1, 3),
                             indexing="ij")
        grid = np.stack([xs, ys], axis=-1)[None].astype(np.float32)
        self.inputs = {"X": x, "Grid": grid}
        self.outputs = {"Output": x}

    def test(self):
        self.check_output(atol=1e-5)


class TestMaxPoolWithIndex(OpTest):
    op_type = "max_pool2d_with_index"

    def setup(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        self.inputs = {"X": x}
        self.attrs = {"ksize": [2, 2], "strides": [2, 2]}
        self.outputs = {
            "Out": np.array([[[[5, 7], [13, 15]]]], np.float32),
            "Mask": np.array([[[[5, 7], [13, 15]]]], np.int32)}

    def test(self):
        self.check_output()


# -- metrics / losses ---------------------------------------------------------

class TestPrecisionRecall(OpTest):
    op_type = "precision_recall"

    def setup(self):
        idx = np.array([[0], [1], [1], [0]], np.int64)
        lab = np.array([[0], [1], [0], [1]], np.int64)
        self.inputs = {"Indices": idx, "Labels": lab}
        self.attrs = {"class_number": 2}
        # per class: c0: tp=1 fp=1 fn=1; c1 same -> P=R=F1=0.5 everywhere
        m = np.full((6,), 0.5, np.float32)
        states = np.array([[1, 1, 1, 1], [1, 1, 1, 1]], np.float32)
        self.outputs = {"BatchMetrics": m, "AccumMetrics": m,
                        "AccumStatesInfo": states}

    def test(self):
        self.check_output()


class TestBprLoss(OpTest):
    op_type = "bpr_loss"

    def setup(self):
        x = _r(3, 4, seed=1)
        lab = np.array([[1], [0], [3]], np.int64)
        self.inputs = {"X": x, "Label": lab}

        def sig(v):
            return 1.0 / (1.0 + np.exp(-v))

        want = np.zeros((3, 1), np.float32)
        for b in range(3):
            l = lab[b, 0]
            s = 0.0
            for j in range(4):
                if j != l:
                    s += np.log(sig(x[b, l] - x[b, j]))
            want[b, 0] = -s / 3.0
        self.outputs = {"Y": want}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"], "Y")


class TestSigmoidFocalLoss(OpTest):
    op_type = "sigmoid_focal_loss"

    def setup(self):
        x = _r(3, 4, seed=5)
        lab = np.array([[1], [0], [4]], np.int64)
        fg = np.array([2], np.int32)
        self.inputs = {"X": x, "Label": lab, "FgNum": fg}
        self.attrs = {"gamma": 2.0, "alpha": 0.25}

        def sig(v):
            return 1.0 / (1.0 + np.exp(-v))

        t = np.zeros_like(x)
        for b in range(3):
            if lab[b, 0] > 0:
                t[b, lab[b, 0] - 1] = 1.0
        p = sig(x)
        ce = -(t * np.log(p) + (1 - t) * np.log(1 - p))
        w = t * 0.25 * (1 - p) ** 2 + (1 - t) * 0.75 * p ** 2
        self.outputs = {"Out": (w * ce / 2.0).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-4)
        self.check_grad(["X"], "Out", delta=1e-3, max_relative_error=5e-2)


# -- sequence extras ----------------------------------------------------------

class TestSequenceConcat(OpTest):
    op_type = "sequence_concat"

    def setup(self):
        a, b = _r(2, 3, 4, seed=1), _r(2, 2, 4, seed=2)
        self.inputs = {"X": [("a", a), ("b", b)]}
        self.outputs = {"Out": np.concatenate([a, b], axis=1)}

    def test(self):
        self.check_output(no_check_set=("OutLod",))
        self.check_grad(["a"], "Out")


class TestSequenceReshapeOp(OpTest):
    op_type = "sequence_reshape"

    def setup(self):
        x = _r(2, 4, 6, seed=1)
        self.inputs = {"X": x}
        self.attrs = {"new_dim": 3}
        self.outputs = {"Out": x.reshape(2, 8, 3)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSequenceEnumerate(OpTest):
    op_type = "sequence_enumerate"

    def setup(self):
        x = np.array([[1, 2, 3, 4]], np.int64)
        self.inputs = {"X": x}
        self.attrs = {"win_size": 2, "pad_value": 0}
        self.outputs = {"Out": np.array(
            [[[1, 2], [2, 3], [3, 4], [4, 0]]], np.int64)}

    def test(self):
        self.check_output()


class TestSequenceConv(OpTest):
    op_type = "sequence_conv"

    def setup(self):
        x = _r(2, 5, 3, seed=1)
        w = _r(9, 4, seed=2) * 0.3     # win=3 * D=3
        self.inputs = {"X": x, "Filter": w}
        self.attrs = {"contextLength": 3, "contextStart": -1,
                      "contextStride": 1}
        b, s, d = x.shape
        ctx = np.zeros((b, s, 9), np.float32)
        for t in range(s):
            for k in range(3):
                src = t + k - 1
                if 0 <= src < s:
                    ctx[:, t, k * 3:(k + 1) * 3] = x[:, src]
        self.outputs = {"Out": ctx @ w}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X", "Filter"], "Out")


# -- beam search --------------------------------------------------------------

class TestGatherTree(OpTest):
    op_type = "gather_tree"

    def setup(self):
        # T=3, B=1, W=2
        ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
        parents = np.array([[[0, 0]], [[0, 1]], [[1, 0]]], np.int64)
        self.inputs = {"Ids": ids, "Parents": parents}
        # beam0 at t2: parent=1 -> t1 lane1 (4, parent 1->... wait
        # backtrace: lane0: t2 id 5 parent 1; t1 lane1 id 4 parent 1;
        # t0 lane1 id 2
        want = np.array([[[2, 1]], [[4, 3]], [[5, 6]]], np.int64)
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestBeamSearchDense:
    def test_step(self):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        fwd = registry.lookup("beam_search").forward
        # B=1, W=2, V=3; accumulated log-probs
        pre_ids = np.array([[1], [2]], np.int64)
        pre_scores = np.array([[0.0], [-1.0]], np.float32)
        scores = np.array([[-1.0, -2.0, -3.0],
                           [-0.1, -5.0, -6.0]], np.float32)
        out = fwd({"pre_ids": [jnp.asarray(pre_ids)],
                   "pre_scores": [jnp.asarray(pre_scores)],
                   "scores": [jnp.asarray(scores)]},
                  {"beam_size": 2, "end_id": 0, "is_accumulated": True})
        ids = np.asarray(out["selected_ids"]).reshape(-1)
        parents = np.asarray(out["parent_idx"]).reshape(-1)
        # best two candidates: lane1 token0 (-0.1), lane0 token0 (-1.0)
        assert list(ids) == [0, 0]
        assert list(parents) == [1, 0]


# -- fused --------------------------------------------------------------------

class TestFusionSquaredMatSub(OpTest):
    op_type = "fusion_squared_mat_sub"

    def setup(self):
        x, y = _r(3, 4, seed=1), _r(4, 5, seed=2)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"scalar": 0.5}
        ab = x @ y
        self.outputs = {"Out": 0.5 * (ab * ab - (x * x) @ (y * y)),
                        "SquaredXY": ab * ab}

    def test(self):
        self.check_output(atol=1e-4)


class TestFusionRepeatedFcRelu(OpTest):
    op_type = "fusion_repeated_fc_relu"

    def setup(self):
        x = _r(3, 4, seed=1)
        w1, b1 = _r(4, 5, seed=2), _r(5, seed=3)
        w2, b2 = _r(5, 2, seed=4), _r(2, seed=5)
        self.inputs = {"X": x, "W": [("w1", w1), ("w2", w2)],
                       "Bias": [("b1", b1), ("b2", b2)]}
        h = np.maximum(x @ w1 + b1, 0)
        self.outputs = {"Out": np.maximum(h @ w2 + b2, 0)}

    def test(self):
        self.check_output(atol=1e-5)


class TestFusedElemwiseActivation(OpTest):
    op_type = "fused_elemwise_activation"

    def setup(self):
        x, y = _r(3, 4, seed=1), _r(3, 4, seed=2)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"functor_list": ["elementwise_add", "relu"]}
        self.outputs = {"Out": np.maximum(x + y, 0),
                        "IntermediateOut": x + y}

    def test(self):
        self.check_output()


# -- conv3d / misc ------------------------------------------------------------

class TestConv3D(OpTest):
    op_type = "conv3d"

    def setup(self):
        import jax.lax as lax
        import jax.numpy as jnp

        x = _r(1, 2, 4, 4, 4, seed=1)
        w = _r(3, 2, 2, 2, 2, seed=2) * 0.3
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1, 1], "paddings": [0, 0, 0]}
        want = np.asarray(lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1, 1),
            [(0, 0)] * 3, dimension_numbers=("NCDHW", "OIDHW", "NCDHW")))
        self.outputs = {"Output": want}

    def test(self):
        self.check_output(atol=1e-4)
        self.check_grad(["Input", "Filter"], "Output")


class TestRowConv(OpTest):
    op_type = "row_conv"

    def setup(self):
        x = _r(2, 4, 3, seed=1)
        w = _r(2, 3, seed=2)
        self.inputs = {"X": x, "Filter": w}
        want = np.zeros_like(x)
        for t in range(4):
            for k in range(2):
                if t + k < 4:
                    want[:, t] += x[:, t + k] * w[k]
        self.outputs = {"Out": want}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X", "Filter"], "Out")


class TestWarpCTC:
    def test_loss_positive_and_differentiable(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        fwd = registry.lookup("warpctc").forward
        logits = jnp.asarray(_r(2, 6, 5, seed=1))
        labels = jnp.asarray(np.array([[1, 2, 0], [3, 1, 2]], np.int64))
        out = fwd({"Logits": [logits], "Label": [labels]}, {"blank": 0})
        loss = np.asarray(out["Loss"])
        assert loss.shape == (2, 1) and np.all(loss > 0)

        g = jax.grad(lambda l: jnp.sum(fwd(
            {"Logits": [l], "Label": [labels]}, {"blank": 0})["Loss"]))(
                logits)
        assert np.isfinite(np.asarray(g)).all()


class TestSegmentPool(OpTest):
    op_type = "segment_pool"

    def setup(self):
        x = _r(5, 3, seed=1)
        ids = np.array([0, 0, 1, 1, 1], np.int64)
        self.inputs = {"X": x, "SegmentIds": ids}
        self.attrs = {"pooltype": "MEAN", "num_segments": 2}
        want = np.stack([x[:2].mean(0), x[2:].mean(0)])
        self.outputs = {"Out": want}

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["X"], "Out")


class TestProximalGD(OpTest):
    op_type = "proximal_gd"

    def setup(self):
        p, g = _r(4, seed=1), _r(4, seed=2)
        lr = np.array([0.1], np.float32)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        self.attrs = {"l1": 0.01, "l2": 0.02}
        prox = p - 0.1 * g
        prox = np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * 0.01, 0)
        self.outputs = {"ParamOut": prox / (1 + 0.1 * 0.02)}

    def test(self):
        self.check_output(atol=1e-6)


class TestDGC:
    def test_topk_sparsify(self):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        fwd = registry.lookup("dgc").forward
        g = jnp.asarray(_r(100, seed=3))
        u = jnp.zeros_like(g)
        v = jnp.zeros_like(g)
        out = fwd({"U": [u], "V": [v], "Grad": [g],
                   "Param": [jnp.zeros_like(g)]},
                  {"m": 0.9, "ratios": 0.1, "use_nesterov": False})
        enc = np.asarray(out["EncodeGrad"])
        nz = (enc != 0).sum()
        assert 10 <= nz <= 12              # ~top-10% released (ties ok)
        # released mass leaves the carry buffers
        assert np.all(np.asarray(out["V_out"])[enc != 0] == 0)


class TestOpsBatch3:
    """Direct lowering checks for the last op batch (mode/kthvalue/
    median/searchsorted/bincount/diag/scatter_nd/size/lgamma/...)."""

    def _run(self, name, ins, attrs={}):
        from paddle_tpu.core import registry

        return registry.lookup(name).forward(ins, dict(attrs))

    def test_order_statistics(self):
        import jax.numpy as jnp

        x = jnp.asarray(np.array([[3., 1., 3., 2., 3.],
                                  [5., 5., 1., 1., 1.]], np.float32))
        np.testing.assert_array_equal(
            np.asarray(self._run("mode", {"X": [x]})["Out"]), [3., 1.])
        np.testing.assert_array_equal(
            np.asarray(self._run("kthvalue", {"X": [x]},
                                 {"k": 2, "axis": -1})["Out"]), [2., 1.])
        np.testing.assert_array_equal(
            np.asarray(self._run("median", {"X": [x]},
                                 {"axis": 1})["Out"]), [3., 1.])

    def test_search_and_counts(self):
        import jax.numpy as jnp

        out = self._run("searchsorted",
                        {"SortedSequence": [jnp.asarray([1., 3., 5., 7.])],
                         "Values": [jnp.asarray([[2., 6.]])]})
        np.testing.assert_array_equal(np.asarray(out["Out"]), [[1, 3]])
        out = self._run("bincount", {"X": [jnp.asarray([1, 2, 2, 5])]},
                        {"minlength": 7})
        np.testing.assert_array_equal(np.asarray(out["Out"]),
                                      [0, 1, 2, 0, 0, 1, 0])

    def test_scatter_diag_size(self):
        import jax.numpy as jnp

        out = self._run("scatter_nd",
                        {"Index": [jnp.asarray([[0], [2], [0]])],
                         "Updates": [jnp.asarray([1., 2., 3.])]},
                        {"shape": [4]})
        np.testing.assert_array_equal(np.asarray(out["Out"]),
                                      [4., 0., 2., 0.])
        out = self._run("diag_v2", {"X": [jnp.asarray([1., 2.])]},
                        {"offset": 0})
        np.testing.assert_array_equal(np.asarray(out["Out"]),
                                      [[1., 0.], [0., 2.]])
        out = self._run("size", {"Input": [jnp.zeros((3, 4))]})
        assert int(out["Out"]) == 12

    def test_special_functions(self):
        import jax.numpy as jnp
        from math import lgamma as ref_lgamma

        x = jnp.asarray([0.5, 2.0, 5.0])
        got = np.asarray(self._run("lgamma", {"X": [x]})["Out"])
        want = [ref_lgamma(v) for v in [0.5, 2.0, 5.0]]
        np.testing.assert_allclose(got, want, rtol=1e-5)
        got = np.asarray(self._run("frac",
                                   {"X": [jnp.asarray([1.5, -1.5])]})["Out"])
        np.testing.assert_allclose(got, [0.5, -0.5], atol=1e-6)

    def test_bilinear_tensor_product(self):
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 3).astype(np.float32))
        y = jnp.asarray(rng.randn(2, 4).astype(np.float32))
        w = jnp.asarray(rng.randn(5, 3, 4).astype(np.float32))
        out = np.asarray(self._run(
            "bilinear_tensor_product",
            {"X": [x], "Y": [y], "Weight": [w]})["Out"])
        want = np.einsum("bi,kij,bj->bk", x, w, y)
        np.testing.assert_allclose(out, want, rtol=1e-5)
