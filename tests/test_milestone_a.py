"""Milestone A: MNIST-style static-graph end-to-end (SURVEY.md §7 L5').

Mirrors the reference's book test test_recognize_digits.py:67 at smoke scale:
build program → append_backward → optimizer ops → compiled executor; loss
must decrease; interpreter and compiler must agree.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _mnist_program(conv=False, optimizer="sgd"):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        if conv:
            img = layers.data("img", [1, 28, 28])
            h = layers.conv2d(img, 6, 5, padding=2, act="relu")
            h = layers.pool2d(h, 2, "max", 2)
            h = layers.conv2d(h, 16, 5, act="relu")
            h = layers.pool2d(h, 2, "max", 2)
        else:
            img = layers.data("img", [784])
            h = layers.fc(img, 64, act="relu")
        label = layers.data("label", [1], dtype="int64")
        logits = layers.fc(h, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if optimizer == "sgd":
            opt = pt.optimizer.SGDOptimizer(0.1)
        elif optimizer == "momentum":
            opt = pt.optimizer.MomentumOptimizer(0.05, 0.9)
        else:
            opt = pt.optimizer.AdamOptimizer(1e-3)
        opt.minimize(loss)
    return main, startup, loss, acc


def _feed(conv=False, n=16, seed=0):
    rng = np.random.RandomState(seed)
    shape = (n, 1, 28, 28) if conv else (n, 784)
    return {"img": rng.randn(*shape).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


@pytest.mark.parametrize("optimizer", ["sgd", "momentum", "adam"])
def test_mlp_loss_decreases(scope, optimizer):
    main, startup, loss, acc = _mnist_program(conv=False, optimizer=optimizer)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = _feed()
    losses = []
    for _ in range(12):
        lv, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        losses.append(lv.item())
    assert losses[-1] < losses[0] * 0.5, losses


def test_lenet_conv_overfits_batch(scope):
    main, startup, loss, acc = _mnist_program(conv=True, optimizer="momentum")
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    feed = _feed(conv=True)
    for _ in range(40):
        lv, av = exe.run(main, feed=feed, fetch_list=[loss, acc], scope=scope)
    assert av.item() > 0.95
    assert lv.item() < 0.05


def test_interpreter_compiler_parity():
    main, startup, loss, _ = _mnist_program(conv=False, optimizer="momentum")
    exe = pt.Executor(pt.CPUPlace())
    s1 = pt.Scope()
    exe.run(startup, scope=s1, use_compiled=False)
    s2 = pt.Scope()
    for k, v in list(s1.items()):
        s2.set(k, np.array(v))
    feed = _feed()
    for _ in range(3):
        a, = exe.run(main, feed=feed, fetch_list=[loss], scope=s1,
                     use_compiled=False)
    for _ in range(3):
        b, = exe.run(main, feed=feed, fetch_list=[loss], scope=s2,
                     use_compiled=True)
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)


def test_clone_for_test_strips_backward(scope):
    main, startup, loss, acc = _mnist_program()
    n_train = len(main.global_block().ops)
    test_prog = main.clone(for_test=True)
    n_test = len(test_prog.global_block().ops)
    assert n_test < n_train
    assert not any(op.is_backward_op() or op.is_optimize_op()
                   for op in test_prog.global_block().ops)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    lv, = exe.run(test_prog, feed=_feed(), fetch_list=[loss], scope=scope)
    assert np.isfinite(lv).all()


def test_gradients_fan_out(scope):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], append_batch_size=False, stop_gradient=False)
        y = layers.reduce_sum(x * 3.0 + x * 2.0)
        (gx,) = pt.gradients([y], [x])
    exe = pt.Executor(pt.CPUPlace())
    g, = exe.run(main, feed={"x": np.ones(4, np.float32)}, fetch_list=[gx],
                 scope=scope)
    np.testing.assert_allclose(g, 5.0)


def test_save_scope_roundtrip(scope):
    """Params live device-side in the scope and survive across run calls."""
    main, startup, loss, _ = _mnist_program()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope, use_compiled=False)
    names = [p.name for p in main.all_parameters()]
    before = {n: np.array(scope.find_var(n)) for n in names}
    exe.run(main, feed=_feed(), fetch_list=[loss], scope=scope)
    changed = [n for n in names
               if not np.allclose(before[n], np.array(scope.find_var(n)))]
    assert changed, "no parameter changed after a training step"
