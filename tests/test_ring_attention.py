"""Ring attention / sequence parallelism tests.

Greenfield capability (SURVEY.md §5: the reference has no SP/CP). Strategy
mirrors the reference's distributed tests (test_dist_base.py): N-shard run
must match the single-device run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh, PartitionSpec as P


def _rand(*shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape).astype(np.float32))


def _sp_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


class TestRingAttentionFn:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        from paddle_tpu.parallel.api import get_shard_map
        from paddle_tpu.parallel.ring_attention import ring_attention
        from paddle_tpu.ops.pallas.flash_attention import reference_attention

        shard_map, kw = get_shard_map()
        mesh = _sp_mesh(4)
        q, k, v = (_rand(2, 2, 64, 16, seed=s) for s in range(3))
        bias = jnp.asarray(
            ((np.random.RandomState(3).rand(2, 64) < 0.2) * -10000.0)
            .astype(np.float32))
        spec = P(None, None, "sp", None)
        f = shard_map(
            lambda q, k, v, b: ring_attention(q, k, v, bias_kv=b,
                                              causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec, P(None, "sp")),
            out_specs=spec, **kw)
        out = f(q, k, v, bias)
        ref = reference_attention(q, k, v, bias_kv=bias, causal=causal)
        np.testing.assert_allclose(out, ref, atol=3e-5)

        g1 = jax.grad(lambda q, k, v: jnp.sum(f(q, k, v, bias) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: jnp.sum(
                reference_attention(q, k, v, bias_kv=bias,
                                    causal=causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_degrades_outside_spmd(self):
        from paddle_tpu.parallel.ring_attention import ring_attention
        from paddle_tpu.ops.pallas.flash_attention import reference_attention

        q, k, v = (_rand(1, 2, 64, 16, seed=s) for s in range(3))
        out = ring_attention(q, k, v)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_dropout_sharding_invariant(self):
        """Attention-probs dropout is keyed on GLOBAL positions, so the
        4-way sp-sharded result (and grads) must equal the unsharded
        reference with the same seed — sequence sharding never changes
        training numerics (VERDICT r2 #3)."""
        from paddle_tpu.parallel.api import get_shard_map
        from paddle_tpu.parallel.ring_attention import ring_attention
        from paddle_tpu.ops.pallas.flash_attention import reference_attention

        shard_map, kw = get_shard_map()
        mesh = _sp_mesh(4)
        rate, seed = 0.25, jnp.uint32(99)
        q, k, v = (_rand(2, 2, 64, 16, seed=s) for s in range(3))
        bias = jnp.asarray(
            ((np.random.RandomState(3).rand(2, 64) < 0.2) * -10000.0)
            .astype(np.float32))
        spec = P(None, None, "sp", None)
        f = shard_map(
            lambda q, k, v, b: ring_attention(q, k, v, bias_kv=b,
                                              dropout_rate=rate,
                                              dropout_seed=seed),
            mesh=mesh, in_specs=(spec, spec, spec, P(None, "sp")),
            out_specs=spec, **kw)
        out = f(q, k, v, bias)
        ref = reference_attention(q, k, v, bias_kv=bias,
                                  dropout_rate=rate, dropout_seed=seed)
        assert float(jnp.max(jnp.abs(
            ref - reference_attention(q, k, v, bias_kv=bias)))) > 1e-3
        np.testing.assert_allclose(out, ref, atol=3e-5)

        g1 = jax.grad(lambda q, k, v: jnp.sum(f(q, k, v, bias) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: jnp.sum(
                reference_attention(q, k, v, bias_kv=bias, dropout_rate=rate,
                                    dropout_seed=seed) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-5)


class TestSequenceParallelBert:
    def test_sp_training_matches_dense(self):
        """SP BERT (ring attention, dp=2 x sp=4 mesh) must track the dense
        single-device MLM run step for step — the reference's
        check_with_place loss-parity contract (test_dist_base.py:1007)."""
        import paddle_tpu as pt
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.models import bert
        from paddle_tpu.parallel import create_mesh

        B, S, steps = 4, 64, 3
        cfg_kw = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=128,
                      max_position_embeddings=64, hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)
        results = {}
        for mode in ("dense", "sp"):
            ir._main_program, ir._startup_program = ir.Program(), ir.Program()
            unique_name.switch()
            cfg = bert.BertConfig(**cfg_kw)
            sp = 4 if mode == "sp" else 0
            main, startup, feeds, fetches = bert.build_pretraining_program(
                cfg, seq_len=S, optimizer_name="adamw", with_nsp=False,
                sequence_parallel=sp, data_parallel=2 if sp else 1)
            mesh = create_mesh({"dp": 2, "sp": 4}) if sp else None
            exe = pt.Executor()
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            batch = bert.synthetic_pretraining_batch(cfg, B, S)
            losses = []
            for _ in range(steps):
                out = exe.run(main, feed=batch,
                              fetch_list=[fetches["loss"]],
                              scope=scope, mesh=mesh)
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            results[mode] = losses
        np.testing.assert_allclose(results["sp"], results["dense"],
                                   rtol=2e-4)
        assert results["sp"][-1] < results["sp"][0]
