"""Prefix-sharing content-addressed KV store + disaggregated serving
(paddle_tpu/serving/prefix_store.py, serving/disagg.py, the chunked
prefill path in serving/decode.py + models/decoder_lm.py, router
prefix affinity).

Contracts under test:
* the store's hash CHAIN keys each page-sized block by (parent hash,
  token block): full lookup hit, miss, and partial hit at the
  divergence point — and lookup matches at most floor((L-1)/P) blocks
  so the final prompt chunk is ALWAYS recomputed;
* copy-on-write forks: a second child registered under a shared parent
  is a fork (kv.cow_forks), and the diverging request's blocks are its
  own — mutating one chain never perturbs the other's tokens;
* refcounting + LRU reclaim: refcount-zero chains stay cached until
  pool pressure evicts them leaf-first in last_used order; blocks
  still referenced (or with cached children) are never evicted;
* bytes_saved accounting lands in the store stats, the kv.bytes_saved
  counter and the HBM ledger (serving_kv_prefix_saved_bytes);
* BITWISE identity: prefix-hit continuous-batched decode equals
  cold-prefill decode equals the classic one-pass prefill engine —
  greedy and seeded sampling, fp32 and int8, PT_PALLAS off and
  interpret;
* pool.audit() proves the free list + lent pages partition the pool
  (and, fed owned_pages(), that nothing leaked or was over-freed);
* disaggregated shipments: pack/unpack round-trips every page
  bit-exactly, a corrupted payload is rejected with ShipmentCRCError
  (disagg.crc_rejects) — never installed;
* router prefix affinity: equal full-page prefix chains pick the same
  ready decode-tier replica, the unified tier absorbs traffic when the
  decode tier is down (router.affinity_fallbacks), and prefill-tier
  replicas never carry generate traffic.

tools/chaos_check.py --prefix and tools/bench_serving.py
--prefix-share are the CLI twins.
"""

import contextlib
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core import telemetry

pytestmark = pytest.mark.serving

CFG_KW = dict(vocab_size=97, d_model=32, n_head=2, n_layers=2,
              d_inner=64, max_seq_len=32)
POOL_KW = dict(max_slots=4, page_size=4, kv_pages=28,
               prefill_buckets=[8, 16])


def _model_cfg(**over):
    from paddle_tpu.models.decoder_lm import DecoderLMConfig

    return DecoderLMConfig(**{**CFG_KW, **over})


def _counter(name):
    return int(telemetry.counter_get(name))


def _pool(num_pages=16, page_size=4):
    from paddle_tpu.serving.kv_cache import KVPagePool

    return KVPagePool(n_layers=2, num_pages=num_pages,
                      page_size=page_size, kv_dim=8)


def _store(num_pages=16, page_size=4):
    from paddle_tpu.serving.prefix_store import PrefixStore

    return PrefixStore(_pool(num_pages, page_size))


@contextlib.contextmanager
def _pallas(mode):
    old = os.environ.get("PT_PALLAS")
    os.environ["PT_PALLAS"] = mode
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("PT_PALLAS", None)
        else:
            os.environ["PT_PALLAS"] = old


@pytest.fixture(scope="module")
def prompts():
    """Two prompts sharing a 9-token prefix (2 full pages at P=4) plus
    divergent suffixes — the canonical shared-system-prompt workload."""
    rng = np.random.RandomState(0)
    prefix = rng.randint(3, 90, size=9)
    p1 = np.concatenate([prefix, rng.randint(3, 90, size=3)]) \
        .astype(np.int32)
    p2 = np.concatenate([prefix, rng.randint(3, 90, size=2)]) \
        .astype(np.int32)
    return p1, p2


# ---------------------------------------------------------------------------
# chain hashing
# ---------------------------------------------------------------------------

class TestChainHash:
    def test_full_page_blocks_only(self):
        from paddle_tpu.serving.prefix_store import (ROOT_HASH,
                                                     prefix_chain_hash)

        t = list(range(20, 31))          # 11 tokens, P=4 -> 2 full pages
        h8 = prefix_chain_hash(t[:8], 4)
        # the partial final page never contributes to the chain
        assert prefix_chain_hash(t[:9], 4) == h8
        assert prefix_chain_hash(t, 4) == h8
        # any full-page token flips the chain
        t2 = list(t)
        t2[7] += 1
        assert prefix_chain_hash(t2, 4) != h8
        # under one full page there is no chain at all
        assert prefix_chain_hash(t[:3], 4) == ROOT_HASH

    def test_chain_pins_whole_prefix_not_just_own_block(self):
        from paddle_tpu.serving.prefix_store import _chain_hash

        # same second block under different first blocks -> different
        # identity: block identity = (parent hash, tokens)
        a = _chain_hash(_chain_hash("root", [1, 2, 3, 4]), [9, 9, 9, 9])
        b = _chain_hash(_chain_hash("root", [5, 6, 7, 8]), [9, 9, 9, 9])
        assert a != b


class TestChunkPrefillProgram:
    def test_chunk_program_uses_chunk_cached_attention(self):
        """The chunked-prefill program lowers attention through the
        registered ``chunk_cached_attention`` op — one per layer. Its
        numerics are pinned end-to-end by the bitwise-identity engine
        tests below; this pins the lowering itself."""
        from paddle_tpu.models.decoder_lm import build_chunk_prefill_program

        cfg = _model_cfg()
        program, feeds, fetches = build_chunk_prefill_program(
            cfg, batch=1, chunk_len=4, num_pages=8, page_size=4)
        ops = [op.type for op in program.global_block().ops]
        assert ops.count("chunk_cached_attention") == cfg.n_layers
        assert feeds and fetches


# ---------------------------------------------------------------------------
# store unit: lookup / insert / COW / reclaim over a real page pool
# ---------------------------------------------------------------------------

class TestStoreUnit:
    def test_miss_insert_hit_and_final_chunk_cap(self):
        store = _store()
        toks = list(range(10, 20))       # 10 tokens -> 2 full pages
        before = {n: _counter(f"kv.{n}")
                  for n in ("prefix_hits", "prefix_misses", "bytes_saved")}
        hashes, pages = store.lookup(toks)
        assert (hashes, pages) == ([], [])
        assert _counter("kv.prefix_misses") == before["prefix_misses"] + 1

        alloc = store.pool.try_alloc(2)
        held, canon = store.insert(toks, alloc)
        assert len(held) == 2 and canon == alloc
        assert store.num_blocks() == 2
        store.release(held)

        # full hit: both resident blocks, the SAME physical pages
        hashes, pages = store.lookup(toks)
        assert len(hashes) == 2 and pages == alloc
        assert _counter("kv.prefix_hits") == before["prefix_hits"] + 1
        saved = _counter("kv.bytes_saved") - before["bytes_saved"]
        assert saved == 2 * store.pool._page_bytes
        assert store.stats()["bytes_saved"] >= saved
        store.release(hashes)

        # the match cap: an exactly-two-page prompt matches only ONE
        # block — the final chunk must be recomputed for its logits
        hashes, _pages = store.lookup(toks[:8])
        assert len(hashes) == 1
        store.release(hashes)

    def test_partial_hit_stops_at_divergence(self):
        store = _store()
        a = [1, 2, 3, 4, 5, 6, 7, 8, 9]
        b = [1, 2, 3, 4, 50, 60, 70, 80, 90]   # diverges in block 1
        pa = store.pool.try_alloc(2)
        held, _ = store.insert(a, pa)
        store.release(held)
        hashes, pages = store.lookup(b)
        assert len(hashes) == 1 and pages == [pa[0]]
        store.release(hashes)

    def test_cow_fork_counted_and_isolated(self):
        store = _store()
        before = _counter("kv.cow_forks")
        a = [1, 2, 3, 4, 10, 11, 12, 13, 0]
        b = [1, 2, 3, 4, 20, 21, 22, 23, 0]
        pa = store.pool.try_alloc(2)
        held_a, _ = store.insert(a, pa)
        assert _counter("kv.cow_forks") == before   # first child: no fork

        # request B: lookup matched the shared block 0, recomputed its
        # own block 1 into a private page, then registers the fork
        hashes, shared = store.lookup(b)
        assert shared == [pa[0]]
        pb1 = store.pool.try_alloc(1)
        held_b, canon = store.insert(b, [shared[0], pb1[0]], start_block=1)
        assert held_b != held_a[1:] and canon == pb1
        assert _counter("kv.cow_forks") == before + 1
        # both chains resolve independently to their own pages
        assert store.lookup(a)[1] == pa
        assert store.lookup(b)[1] == [pa[0], pb1[0]]

    def test_duplicate_insert_resident_block_wins(self):
        store = _store()
        toks = [7, 7, 7, 7, 8, 8, 8, 8, 0]
        pa = store.pool.try_alloc(2)
        held_a, canon_a = store.insert(toks, pa)
        free_before = store.pool.free_pages()
        pb = store.pool.try_alloc(2)
        held_b, canon_b = store.insert(toks, pb)
        # the resident pages are canonical; the redundant candidates
        # went straight back to the pool
        assert canon_b == canon_a == pa
        assert store.pool.free_pages() == free_before
        assert store.num_blocks() == 2
        for held in (held_a, held_b):
            store.release(held)

    def test_release_corruption_guards(self):
        store = _store()
        toks = [1, 2, 3, 4, 0]
        held, _ = store.insert(toks, store.pool.try_alloc(1))
        with pytest.raises(AssertionError, match="unknown"):
            store.release(["deadbeef"])
        store.release(held)
        with pytest.raises(AssertionError, match="double release"):
            store.release(held)

    def test_reclaim_lru_leaf_first_and_refcount_protected(self):
        store = _store()
        before = _counter("kv.reclaims")
        # chain A: two blocks (interior + leaf), touched FIRST (older)
        a = [1, 2, 3, 4, 5, 6, 7, 8, 0]
        held_a, _ = store.insert(a, store.pool.try_alloc(2))
        # chain B: one block, touched later (newer)
        b = [9, 9, 9, 9, 0]
        held_b, _ = store.insert(b, store.pool.try_alloc(1))
        store.release(held_a)

        # B is still referenced: only A's blocks are evictable, and the
        # interior block must outlive its leaf — so evicting 2 pages
        # walks A's chain leaf-first
        assert store.reclaim(2) == 2
        assert store.num_blocks() == 1
        assert store.lookup(a) == ([], [])
        assert store.lookup(b)[0] == held_b          # B survived
        store.release(held_b)

        # refcount dropped: now B is evictable too
        store.release(held_b)
        assert store.reclaim(5) == 1
        assert store.num_blocks() == 0
        assert store.pool.free_pages() == store.pool.capacity_pages
        assert _counter("kv.reclaims") == before + 2

    def test_lru_order_evicts_oldest_leaf(self):
        store = _store()
        a = [1, 1, 1, 1, 0]
        b = [2, 2, 2, 2, 0]
        held_a, _ = store.insert(a, store.pool.try_alloc(1))
        held_b, _ = store.insert(b, store.pool.try_alloc(1))
        store.release(held_a)
        store.release(held_b)
        # touch A -> B becomes the LRU victim
        store.release(store.lookup(a)[0])
        assert store.reclaim(1) == 1
        assert store.lookup(b) == ([], [])
        assert store.lookup(a)[0]                    # A still resident

    def test_bytes_saved_reaches_hbm_ledger(self):
        from paddle_tpu.core import costmodel

        store = _store()
        toks = [3, 1, 4, 1, 5, 9, 2, 6, 0]
        held, _ = store.insert(toks, store.pool.try_alloc(2))
        store.release(held)
        hashes, _ = store.lookup(toks)
        store.release(hashes)
        led = costmodel.ledger()
        assert led.get("serving_kv_prefix_saved_bytes", 0) >= \
            store.stats()["bytes_saved"] > 0


# ---------------------------------------------------------------------------
# pool.audit: the free list + lent pages partition the pool
# ---------------------------------------------------------------------------

class TestPoolAudit:
    def test_clean_pool_and_owned_reconciliation(self):
        pool = _pool()
        assert pool.audit() == []
        pages = pool.try_alloc(3)
        assert pool.audit() == []
        assert pool.audit(owned=pages) == []
        # a page the ledger says is lent but nobody owns is a LEAK
        viol = pool.audit(owned=pages[:2])
        assert any("leak" in v for v in viol)
        # a page owned twice is double-booked
        viol = pool.audit(owned=pages + [pages[0]])
        assert any("twice" in v for v in viol)
        pool.free(pages)
        assert pool.audit(owned=[]) == []

    def test_tampered_ledger_detected_and_counted(self):
        pool = _pool()
        pages = pool.try_alloc(2)
        before = _counter("kv.audit_failures")
        pool._lent.discard(pages[0])     # simulate an over-free
        viol = pool.audit(owned=pages)
        assert viol
        assert _counter("kv.audit_failures") == before + 1
        pool._lent.add(pages[0])
        pool.free(pages)
        assert pool.audit() == []


# ---------------------------------------------------------------------------
# engine: bitwise identity — prefix-hit == cold == classic prefill
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def classic_engine():
    from paddle_tpu.serving.decode import DecodeConfig, demo_engine

    eng = demo_engine(DecodeConfig(**POOL_KW), _model_cfg()).start()
    yield eng
    eng.close(drain=True, timeout=30)


@pytest.fixture(scope="module")
def prefix_engine():
    from paddle_tpu.serving.decode import DecodeConfig, demo_engine

    eng = demo_engine(DecodeConfig(prefix_cache=True, **POOL_KW),
                      _model_cfg()).start()
    yield eng
    eng.close(drain=True, timeout=30)


class TestBitwiseIdentity:
    def test_greedy_hit_equals_cold_equals_classic(self, classic_engine,
                                                   prefix_engine, prompts):
        p1, p2 = prompts
        want1 = classic_engine.generate(p1, max_new_tokens=6, timeout=120)
        want2 = classic_engine.generate(p2, max_new_tokens=6, timeout=120)
        hits_before = _counter("kv.prefix_hits")
        cold1 = prefix_engine.generate(p1, max_new_tokens=6, timeout=120)
        hit2 = prefix_engine.generate(p2, max_new_tokens=6, timeout=120)
        assert np.array_equal(want1, cold1), \
            "chunked cold prefill diverged from classic prefill"
        assert np.array_equal(want2, hit2), \
            "prefix-hit decode diverged from classic prefill"
        assert _counter("kv.prefix_hits") > hits_before
        assert _counter("kv.bytes_saved") > 0
        # shared pages + private pages reconcile exactly
        assert prefix_engine.pool.audit(
            owned=prefix_engine.prefix_store.owned_pages()) == []

    def test_sampled_hit_equals_cold(self, prefix_engine, prompts):
        from paddle_tpu.serving.decode import DecodeConfig, demo_engine

        _p1, p2 = prompts
        hit = prefix_engine.generate(p2, max_new_tokens=6,
                                     temperature=0.8, seed=7, timeout=120)
        cold_eng = demo_engine(DecodeConfig(prefix_cache=True, **POOL_KW),
                               _model_cfg()).start()
        try:
            cold = cold_eng.generate(p2, max_new_tokens=6,
                                     temperature=0.8, seed=7, timeout=120)
        finally:
            cold_eng.close(drain=True, timeout=30)
        assert np.array_equal(hit, cold)

    def test_int8_hit_equals_cold(self, prompts):
        from paddle_tpu.serving.decode import DecodeConfig, demo_engine

        p1, p2 = prompts
        cold_eng = demo_engine(
            DecodeConfig(weight_quant="int8", **POOL_KW),
            _model_cfg()).start()
        try:
            want = cold_eng.generate(p2, max_new_tokens=5, timeout=120)
        finally:
            cold_eng.close(drain=True, timeout=30)
        hit_eng = demo_engine(
            DecodeConfig(weight_quant="int8", prefix_cache=True,
                         **POOL_KW), _model_cfg()).start()
        try:
            hit_eng.generate(p1, max_new_tokens=5, timeout=120)
            got = hit_eng.generate(p2, max_new_tokens=5, timeout=120)
        finally:
            hit_eng.close(drain=True, timeout=30)
        assert np.array_equal(want, got), "int8 prefix-hit diverged"

    def test_interpret_mode_hit_equals_off_mode(self, prefix_engine,
                                                prompts):
        """PT_PALLAS=interpret prefix-hit output equals the off-mode
        prefix engine's (itself pinned to classic above) — the chunked
        path composes with the kernel decode step."""
        from paddle_tpu.serving.decode import DecodeConfig, demo_engine

        p1, p2 = prompts
        want1 = prefix_engine.generate(p1, max_new_tokens=6, timeout=120)
        want2 = prefix_engine.generate(p2, max_new_tokens=6, timeout=120)
        with _pallas("interpret"):
            eng = demo_engine(DecodeConfig(prefix_cache=True, **POOL_KW),
                              _model_cfg()).start()
            try:
                got1 = eng.generate(p1, max_new_tokens=6, timeout=120)
                got2 = eng.generate(p2, max_new_tokens=6, timeout=120)
            finally:
                eng.close(drain=True, timeout=30)
        assert np.array_equal(want1, got1)
        assert np.array_equal(want2, got2)


# ---------------------------------------------------------------------------
# engine: reclaim under pool pressure keeps serving
# ---------------------------------------------------------------------------

class TestReclaimUnderPressure:
    def test_idle_chains_evicted_to_seat_new_requests(self):
        from paddle_tpu.serving.decode import DecodeConfig, demo_engine

        rng = np.random.RandomState(3)
        eng = demo_engine(
            DecodeConfig(prefix_cache=True, max_slots=2, page_size=4,
                         kv_pages=10, prefill_buckets=[8]),
            _model_cfg()).start()
        before = _counter("kv.reclaims")
        try:
            # distinct 12-token prompts: each leaves 3 idle blocks
            # behind; the 9-page pool forces eviction by the third
            for _ in range(4):
                p = rng.randint(3, 90, size=12).astype(np.int32)
                out = eng.generate(p, max_new_tokens=6, timeout=120)
                assert out.size == 6
            assert _counter("kv.reclaims") > before
            assert eng.pool.audit(
                owned=eng.prefix_store.owned_pages()) == []
        finally:
            eng.close(drain=True, timeout=30)


# ---------------------------------------------------------------------------
# disaggregation: the KV shipment wire format
# ---------------------------------------------------------------------------

class TestShipment:
    def test_pack_unpack_round_trips_bit_exactly(self):
        from paddle_tpu.serving import disagg

        rng = np.random.RandomState(11)
        layer_pages = {
            f"kv_{kv}_{i}": rng.randn(3, 4, 8).astype(np.float32)
            for kv in ("k", "v") for i in range(2)}
        logits = rng.randn(97).astype(np.float32)
        toks = [5, 6, 7, 8, 9]
        blob = disagg.pack_shipment(toks, 4, layer_pages, logits)
        ship = disagg.unpack_shipment(blob)
        assert ship["tokens"] == toks
        assert ship["page_size"] == 4 and ship["n_pages"] == 3
        for name, arr in layer_pages.items():
            got = ship["layers"][name]
            assert got.dtype == arr.dtype
            assert np.array_equal(got, arr)
        assert np.array_equal(ship["logits"], logits)

    def test_corrupted_payload_rejected_with_crc_error(self):
        from paddle_tpu.serving import disagg

        layer_pages = {"kv_k_0": np.ones((2, 4, 8), np.float32),
                       "kv_v_0": np.ones((2, 4, 8), np.float32)}
        blob = disagg.pack_shipment([1, 2, 3], 4, layer_pages,
                                    np.zeros(9, np.float32))
        before = _counter("disagg.crc_rejects")
        bad = bytearray(blob)
        bad[-40] ^= 0xFF
        with pytest.raises(disagg.ShipmentCRCError):
            disagg.unpack_shipment(bytes(bad))
        assert _counter("disagg.crc_rejects") == before + 1
        with pytest.raises(disagg.ShipmentError):
            disagg.unpack_shipment(b"NOPE" + bytes(blob)[4:])

    def test_engine_ships_prefill_and_frees_pages(self, classic_engine,
                                                  prompts):
        from paddle_tpu.serving import disagg

        _p1, p2 = prompts
        baseline = classic_engine.pool.free_pages()
        before = _counter("disagg.ships")
        blob = classic_engine.submit_prefill(p2).result(timeout=120)
        ship = disagg.unpack_shipment(bytes(blob))
        assert ship["tokens"] == [int(t) for t in p2]
        assert ship["n_pages"] == \
            classic_engine.pool.pages_for_tokens(p2.size)
        assert set(ship["layers"]) == set(classic_engine._pools)
        assert _counter("disagg.ships") == before + 1
        assert classic_engine.pool.free_pages() == baseline
        assert classic_engine.stats()["role"] == "unified"


# ---------------------------------------------------------------------------
# router: prefix affinity + tier fallback
# ---------------------------------------------------------------------------

class TestRouterAffinity:
    @pytest.fixture()
    def router(self, monkeypatch):
        from paddle_tpu.serving.router import Router

        pt.set_flags({"FLAGS_decode_page_size": 4})
        # no live replicas behind these handles: readiness is driven by
        # the test through mark_probe, not the HTTP probe
        monkeypatch.setattr(Router, "probe", lambda self, handle: None)
        r = Router()
        for name, role in (("d0", "decode"), ("d1", "decode"),
                           ("u0", "unified"), ("pf0", "prefill")):
            r.add_replica(name, f"http://127.0.0.1:1/{name}", role=role)
        yield r
        pt.set_flags({"FLAGS_decode_page_size": 16})

    def _ready(self, router, *names):
        for h in router.handles():
            h.mark_probe(h.name in names)

    def test_equal_prefix_chains_stick_to_one_decode_replica(self, router):
        self._ready(router, "d0", "d1", "u0", "pf0")
        rng = np.random.RandomState(2)
        base = rng.randint(3, 90, size=9).tolist()
        picks = {router.pick_generate(base + extra).name
                 for extra in ([], [5], [5, 6], [7, 8])}
        # same 2 full-page chain -> same replica, and it is decode-tier
        assert len(picks) == 1 and picks <= {"d0", "d1"}
        # a different chain may land elsewhere but stays in-tier
        assert router.pick_generate(
            rng.randint(3, 90, size=9).tolist()).name in ("d0", "d1")

    def test_unified_fallback_when_decode_tier_down(self, router):
        self._ready(router, "u0", "pf0")
        before = _counter("router.affinity_fallbacks")
        h = router.pick_generate([1, 2, 3, 4, 5])
        assert h.name == "u0"
        assert _counter("router.affinity_fallbacks") == before + 1

    def test_prefill_tier_never_carries_generate(self, router):
        self._ready(router, "pf0")
        assert router.pick_generate([1, 2, 3, 4, 5]) is None
