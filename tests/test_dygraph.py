"""Dygraph (imperative) mode tests — mirrors the reference's
test_imperative_* suite (python/paddle/fluid/tests/unittests/
test_imperative_basic.py, test_imperative_mnist.py)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph, nn
from paddle_tpu.dygraph import VarBase, to_variable
from paddle_tpu.nn import functional as F
from paddle_tpu.optimizer import AdamOptimizer, SGDOptimizer


def test_varbase_basic():
    with dygraph.guard():
        x = to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        y = x * 2 + 1
        np.testing.assert_allclose(y.numpy(), [[3, 5], [7, 9]], rtol=1e-6)
        assert y.shape == [2, 2]
        z = x.sum()
        assert z.item() == pytest.approx(10.0)


def test_simple_backward():
    with dygraph.guard():
        x = VarBase(np.array([2.0, 3.0], np.float32), stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.gradient(), [4.0, 6.0], rtol=1e-6)


def test_grad_accumulation_across_backwards():
    with dygraph.guard():
        x = VarBase(np.array([1.0], np.float32), stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.gradient(), [5.0], rtol=1e-6)


def test_chain_rule_through_shared_input():
    with dygraph.guard():
        x = VarBase(np.array([2.0], np.float32), stop_gradient=False)
        y = x * x + x  # dy/dx = 2x + 1 = 5
        y.backward()
        np.testing.assert_allclose(x.gradient(), [5.0], rtol=1e-6)


def test_no_grad():
    with dygraph.guard():
        x = VarBase(np.array([2.0], np.float32), stop_gradient=False)
        with dygraph.no_grad():
            y = x * x
        assert y._grad_node is None
        assert y.stop_gradient


def test_paddle_grad_api():
    with dygraph.guard():
        x = VarBase(np.array([3.0], np.float32), stop_gradient=False)
        y = x * x
        (g,) = dygraph.grad([y], [x])
        np.testing.assert_allclose(g.numpy(), [6.0], rtol=1e-6)
        assert x.grad is None  # .grad untouched


def test_trace_op_matches_numpy():
    with dygraph.guard():
        a = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        x = to_variable(a)
        out = dygraph.trace_op("softmax", {"X": x}, {"axis": -1})["Out"][0]
        e = np.exp(a - a.max(-1, keepdims=True))
        np.testing.assert_allclose(out.numpy(), e / e.sum(-1, keepdims=True),
                                   rtol=1e-5)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 16)
        self.fc2 = nn.Linear(16, 2)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def test_layer_params_and_state_dict():
    with dygraph.guard():
        m = MLP()
        params = m.parameters()
        assert len(params) == 4
        sd = m.state_dict()
        assert set(sd) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
        m2 = MLP()
        m2.set_state_dict({k: v.numpy() for k, v in sd.items()})
        np.testing.assert_allclose(m2.fc1.weight.numpy(),
                                   m.fc1.weight.numpy())


def test_dygraph_mlp_training_loss_decreases():
    rng = np.random.RandomState(0)
    xs = rng.rand(64, 4).astype(np.float32)
    ys = (xs.sum(1) > 2).astype(np.int32).reshape(64, 1)
    with dygraph.guard():
        m = MLP()
        opt = AdamOptimizer(0.01, parameter_list=m.parameters())
        losses = []
        for _ in range(30):
            x, y = to_variable(xs), to_variable(ys)
            loss = F.cross_entropy(m(x), y)
            loss.backward()
            opt.minimize(loss)
            m.clear_gradients()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


def test_dygraph_numeric_gradcheck():
    """Analytic tape gradient vs central finite differences through a
    two-layer net (the reference's check_grad discipline, op_test.py:1299)."""
    rng = np.random.RandomState(1)
    w = rng.rand(3, 3).astype(np.float32)
    xv = rng.rand(2, 3).astype(np.float32)

    def loss_np(wv):
        h = np.tanh(xv @ wv)
        return (h * h).sum()

    with dygraph.guard():
        wvar = VarBase(w, stop_gradient=False)
        h = to_variable(xv).__matmul__(wvar).tanh()
        (h * h).sum().backward()
        analytic = wvar.gradient()

    eps = 1e-3
    numeric = np.zeros_like(w)
    for i in range(3):
        for j in range(3):
            wp, wm = w.copy(), w.copy()
            wp[i, j] += eps
            wm[i, j] -= eps
            numeric[i, j] = (loss_np(wp) - loss_np(wm)) / (2 * eps)
    np.testing.assert_allclose(analytic, numeric, rtol=1e-2, atol=1e-3)


def test_batchnorm_running_stats_update():
    with dygraph.guard():
        bn = nn.BatchNorm2D(3)
        x = to_variable(np.random.RandomState(0)
                        .rand(4, 3, 5, 5).astype(np.float32) * 2 + 1)
        bn.train()
        _ = bn(x)
        assert not np.allclose(bn._mean.numpy(), 0.0)
        bn.eval()
        mean_before = bn._mean.numpy().copy()
        _ = bn(x)
        np.testing.assert_allclose(bn._mean.numpy(), mean_before)


def test_dropout_train_eval():
    with dygraph.guard():
        d = nn.Dropout(0.5)
        x = to_variable(np.ones((100, 100), np.float32))
        y = d(x)
        assert (y.numpy() == 0).mean() > 0.3
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), 1.0)


def test_save_load_dygraph(tmp_path):
    with dygraph.guard():
        m = MLP()
        path = str(tmp_path / "mlp")
        dygraph.save_dygraph({k: v for k, v in m.state_dict().items()}, path)
        params, _ = dygraph.load_dygraph(path)
        m2 = MLP()
        m2.set_state_dict(params)
        np.testing.assert_allclose(m2.fc2.weight.numpy(),
                                   m.fc2.weight.numpy())


def test_sequential_and_containers():
    with dygraph.guard():
        seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = to_variable(np.random.rand(3, 4).astype(np.float32))
        assert seq(x).shape == [3, 2]
        assert len(seq) == 3
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll.parameters()) == 6


def test_sgd_step_and_clear_grad():
    with dygraph.guard():
        m = nn.Linear(2, 2)
        opt = SGDOptimizer(0.1, parameter_list=m.parameters())
        x = to_variable(np.ones((4, 2), np.float32))
        loss = (m(x) * m(x)).mean()
        loss.backward()
        w_before = m.weight.numpy().copy()
        opt.step()
        assert not np.allclose(m.weight.numpy(), w_before)
        opt.clear_grad()
        assert m.weight.grad is None


# -- regression tests from code review ---------------------------------------

def test_optimizer_param_subset_changes_between_steps():
    """Accumulators created for one param subset must survive a later step
    touching a different subset (shared micro-program scope)."""
    with dygraph.guard():
        a, b = nn.Linear(2, 2), nn.Linear(2, 2)
        opt = AdamOptimizer(0.01, parameter_list=a.parameters() + b.parameters())
        x = to_variable(np.ones((2, 2), np.float32))
        a(x).mean().backward()          # only a has grads
        opt.step()
        opt.clear_grad()
        (a(x) + b(x)).mean().backward()  # now both
        opt.step()                       # must not raise


def test_save_dygraph_model_and_opt_same_prefix(tmp_path):
    with dygraph.guard():
        m = nn.Linear(2, 2)
        opt = AdamOptimizer(0.01, parameter_list=m.parameters())
        m(to_variable(np.ones((2, 2), np.float32))).mean().backward()
        opt.step()
        path = str(tmp_path / "ckpt")
        dygraph.save_dygraph(m.state_dict(), path)
        dygraph.save_dygraph(opt.state_dict(), path)
        params, opt_state = dygraph.load_dygraph(path)
        assert "weight" in params and "bias" in params
        assert opt_state is not None and len(opt_state) > 0


def test_optimizer_set_state_dict_before_first_step():
    with dygraph.guard():
        m = nn.Linear(2, 2)
        opt = AdamOptimizer(0.01, parameter_list=m.parameters())
        m(to_variable(np.ones((2, 2), np.float32))).mean().backward()
        opt.step()
        state = {k: v.copy() for k, v in opt.state_dict().items()}
        assert state

        m2 = nn.Linear(2, 2)
        opt2 = AdamOptimizer(0.01, parameter_list=m2.parameters())
        # key names depend on param names; remap onto opt2's params
        opt2.set_state_dict(state)  # before any step: must stash, not drop
        assert getattr(opt2, "_pending_state", None)


def test_grad_wrt_intermediate():
    with dygraph.guard():
        x = VarBase(np.array([2.0], np.float32), stop_gradient=False)
        y = x * x
        z = (y * 3.0).sum()
        (g,) = dygraph.grad([z], [y])
        np.testing.assert_allclose(g.numpy(), [3.0], rtol=1e-6)


def test_double_backward_without_retain_raises():
    with dygraph.guard():
        a = VarBase(np.array([1.0], np.float32), stop_gradient=False)
        b = a * 2
        (b * 3).sum().backward()
        with pytest.raises(RuntimeError, match="retain_graph"):
            b.sum().backward()


def test_cross_entropy_ignore_index_mean():
    with dygraph.guard():
        logits = to_variable(np.zeros((4, 3), np.float32))
        label = to_variable(np.array([[0], [1], [0], [0]], np.int32))
        # uniform logits -> per-token loss = log(3); half the batch ignored
        loss = F.cross_entropy(logits, label, ignore_index=0)
        np.testing.assert_allclose(float(loss), np.log(3), rtol=1e-5)


def test_nll_loss_ignore_index_and_weight():
    with dygraph.guard():
        logp = to_variable(np.log(np.full((3, 2), 0.5, np.float32)))
        label = to_variable(np.array([0, 1, 0], np.int32))
        loss = F.nll_loss(logp, label, ignore_index=0)
        np.testing.assert_allclose(float(loss), np.log(2), rtol=1e-5)
        w = to_variable(np.array([1.0, 3.0], np.float32))
        loss_w = F.nll_loss(logp, label, weight=w)
        # weights: [1,3,1]; all losses log2 -> weighted mean still log2
        np.testing.assert_allclose(float(loss_w), np.log(2), rtol=1e-5)


def test_layer_setattr_deregisters():
    with dygraph.guard():
        m = MLP()
        n_before = len(m.parameters())
        m.fc1 = None
        assert m.fc1 is None
        assert len(m.parameters()) == n_before - 2
        assert "fc1.weight" not in m.state_dict()


def test_grad_does_not_pollute_other_leaves():
    with dygraph.guard():
        x = VarBase(np.array([1.0], np.float32), stop_gradient=False)
        w = VarBase(np.array([2.0], np.float32), stop_gradient=False)
        y = (x * w).sum()
        (g,) = dygraph.grad([y], [x])
        np.testing.assert_allclose(g.numpy(), [2.0])
        assert w.grad is None  # untouched leaf


def test_optimizer_state_restore_fresh_process_names():
    """Positional state keys restore into an optimizer whose accumulator
    var names differ (simulates a new process / fresh unique_name counters)."""
    with dygraph.guard():
        m1 = nn.Linear(2, 2)
        o1 = AdamOptimizer(0.01, parameter_list=m1.parameters())
        m1(to_variable(np.ones((2, 2), np.float32))).mean().backward()
        o1.step()
        state = {k: v.copy() for k, v in o1.state_dict().items()}

        m2 = nn.Linear(2, 2)  # different unique names
        o2 = AdamOptimizer(0.01, parameter_list=m2.parameters())
        o2.set_state_dict(state)
        m2(to_variable(np.ones((2, 2), np.float32))).mean().backward()
        o2.step()  # applies pending state during build
        restored = o2.state_dict()
        # moment1 of param 0 must match what o1 saved (restored, then one
        # more adam step was applied on top — so just check it's non-zero
        # and that restore didn't silently no-op into zeros+fresh step)
        k = [k for k in restored if k.startswith("moment2#0")][0]
        assert np.abs(restored[k]).sum() > np.abs(state[k]).sum() * 0.5


def test_bool_ambiguity_raises():
    with dygraph.guard():
        a = to_variable(np.array([1.0, 2.0], np.float32))
        with pytest.raises(ValueError, match="ambiguous"):
            bool(a == a)
        assert bool((a == a).all())
