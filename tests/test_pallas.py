"""Pallas kernel tests: interpreter-mode kernels vs jnp references.

Mirrors the reference's fused-kernel tests (test_fused_multihead_matmul_op,
test_layer_norm_op) — the kernel is validated against the unfused
composition, fwd and grad.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture
def interpret_mode(monkeypatch):
    monkeypatch.setenv("PT_PALLAS", "interpret")


def _rand(*shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape).astype(np.float32))


class TestFlashAttention:
    def test_fwd_matches_reference(self, interpret_mode):
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, reference_attention)

        q, k, v = _rand(2, 2, 128, 64, seed=0), _rand(2, 2, 128, 64, seed=1), \
            _rand(2, 2, 128, 64, seed=2)
        out = flash_attention(q, k, v)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_padding_bias(self, interpret_mode):
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, reference_attention)

        q, k, v = (_rand(2, 2, 128, 64, seed=s) for s in range(3))
        mask = (np.random.RandomState(3).rand(2, 128) < 0.25)
        bias = jnp.asarray(mask * -10000.0).astype(jnp.float32)
        out = flash_attention(q, k, v, bias=bias.reshape(2, 1, 1, 128))
        ref = reference_attention(q, k, v, bias_kv=bias)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_causal_multiblock(self, interpret_mode):
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, reference_attention)

        q, k, v = (_rand(1, 2, 256, 64, seed=s) for s in range(3))
        out = flash_attention(q, k, v, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_grads_match_reference(self, interpret_mode):
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, reference_attention)

        q, k, v = (_rand(1, 2, 128, 64, seed=s) for s in range(3))

        g1 = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=True) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(reference_attention(*a, causal=True) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_causal_cross_shape(self, interpret_mode):
        """sq != sk causal must be bottom-right aligned like the reference."""
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, reference_attention)

        q = _rand(1, 1, 128, 32, seed=0)
        k, v = _rand(1, 1, 256, 32, seed=1), _rand(1, 1, 256, 32, seed=2)
        out = flash_attention(q, k, v, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_bias_grad(self, interpret_mode):
        """A trainable additive key bias must receive a real gradient
        (ADVICE r1: dbias was silently None)."""
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, reference_attention)

        q, k, v = (_rand(2, 2, 128, 64, seed=s) for s in range(3))
        bias = _rand(2, 128, seed=7) * 0.1

        db1 = jax.grad(lambda b: jnp.sum(
            flash_attention(q, k, v, bias=b) ** 2))(bias)
        db2 = jax.grad(lambda b: jnp.sum(
            reference_attention(q, k, v, bias_kv=b) ** 2))(bias)
        assert float(jnp.max(jnp.abs(db2))) > 1e-3  # non-trivial signal
        np.testing.assert_allclose(db1, db2, atol=5e-5)

    def test_xla_recompute_path_matches_reference(self):
        """The XLA custom_vjp (recompute backward) implementation must match
        the reference for outputs and all four gradients."""
        from paddle_tpu.ops.pallas.flash_attention import (
            _xla_attention, reference_attention)

        q, k, v = (_rand(2, 2, 64, 32, seed=s) for s in range(3))
        bias = _rand(2, 64, seed=9) * 0.1
        scale = 1.0 / np.sqrt(32)

        seed = jnp.uint32(0)
        out = _xla_attention(q, k, v, bias, seed, False, scale)
        ref = reference_attention(q, k, v, bias_kv=bias, scale=scale)
        np.testing.assert_allclose(out, ref, atol=2e-5)

        g1 = jax.grad(lambda *a: jnp.sum(
            _xla_attention(*a, seed, False, scale) ** 2),
            argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(lambda *a: jnp.sum(reference_attention(
            *a[:3], bias_kv=a[3], scale=scale) ** 2),
            argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-5)

        # causal variant
        out = _xla_attention(q, k, v, None, seed, True, scale)
        ref = reference_attention(q, k, v, causal=True, scale=scale)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_xla_chunked_path_matches_reference(self, monkeypatch):
        """q-chunked XLA attention (scan over query chunks, bounded f32
        scores transients) must match the reference exactly."""
        import importlib

        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        monkeypatch.setattr(fa, "XLA_ATTN_CHUNK_TARGET_BYTES", 1 << 10)
        q, k, v = (_rand(2, 2, 256, 32, seed=s) for s in range(3))
        bias = _rand(2, 256, seed=9) * 0.1
        assert fa._q_chunk(q, k) < 256  # chunking actually engaged
        seed = jnp.uint32(0)
        for causal in (False, True):
            out = fa._xla_attention(q, k, v, bias, seed, causal, 0.17)
            ref = fa.reference_attention(q, k, v, bias_kv=bias,
                                         causal=causal, scale=0.17)
            np.testing.assert_allclose(out, ref, atol=3e-5)
            g1 = jax.grad(lambda *a: jnp.sum(
                fa._xla_attention(*a, seed, causal, 0.17) ** 2),
                argnums=(0, 1, 2, 3))(q, k, v, bias)
            g2 = jax.grad(lambda *a: jnp.sum(fa.reference_attention(
                *a[:3], bias_kv=a[3], causal=causal, scale=0.17) ** 2),
                argnums=(0, 1, 2, 3))(q, k, v, bias)
            for a, b in zip(g1, g2):
                np.testing.assert_allclose(a, b, atol=1e-4)

    def test_unsupported_shapes_fall_back(self):
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, reference_attention)

        q, k, v = (_rand(1, 1, 40, 16, seed=s) for s in range(3))
        out = flash_attention(q, k, v)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5)


class TestAttentionProbsDropout:
    """Attention-probs dropout on the fused paths (VERDICT r2 #3): the
    position-keyed stateless mask must (a) actually drop ~rate of probs,
    (b) be identical across the XLA-recompute / chunked / Pallas paths,
    (c) recompute bit-identically in the backward (grads match autodiff
    through the reference with the same mask)."""

    RATE = 0.25

    def test_mask_statistics_and_effect(self):
        from paddle_tpu.ops.pallas.flash_attention import (
            _attn_keep_scale, reference_attention)

        m = _attn_keep_scale(jnp.uint32(123), self.RATE, (2, 4, 64, 64),
                             0, 0, 4, 64, 64)
        keep_frac = float(jnp.mean(m > 0))
        assert abs(keep_frac - (1 - self.RATE)) < 0.02
        # kept entries carry the 1/(1-rate) upscale
        assert np.allclose(float(jnp.max(m)), 1.0 / (1 - self.RATE))
        # different seeds -> different masks
        m2 = _attn_keep_scale(jnp.uint32(124), self.RATE, (2, 4, 64, 64),
                              0, 0, 4, 64, 64)
        assert float(jnp.mean((m > 0) != (m2 > 0))) > 0.1

        q, k, v = (_rand(1, 2, 64, 32, seed=s) for s in range(3))
        on = reference_attention(q, k, v, dropout_rate=self.RATE,
                                 dropout_seed=jnp.uint32(5))
        off = reference_attention(q, k, v)
        assert float(jnp.max(jnp.abs(on - off))) > 1e-3

    def test_xla_recompute_dropout_matches_reference(self):
        from paddle_tpu.ops.pallas.flash_attention import (
            _xla_attention, reference_attention)

        q, k, v = (_rand(2, 2, 64, 32, seed=s) for s in range(3))
        bias = _rand(2, 64, seed=9) * 0.1
        seed = jnp.uint32(77)
        scale = 1.0 / np.sqrt(32)

        out = _xla_attention(q, k, v, bias, seed, False, scale, self.RATE)
        ref = reference_attention(q, k, v, bias_kv=bias, scale=scale,
                                  dropout_rate=self.RATE, dropout_seed=seed)
        np.testing.assert_allclose(out, ref, atol=2e-5)

        g1 = jax.grad(lambda *a: jnp.sum(
            _xla_attention(*a, seed, False, scale, self.RATE) ** 2),
            argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(lambda *a: jnp.sum(reference_attention(
            *a[:3], bias_kv=a[3], scale=scale, dropout_rate=self.RATE,
            dropout_seed=seed) ** 2), argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_chunked_dropout_matches_unchunked(self, monkeypatch):
        """q-chunking must not change the mask (global-position keying)."""
        import importlib

        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        q, k, v = (_rand(2, 2, 256, 32, seed=s) for s in range(3))
        seed = jnp.uint32(3)
        ref = fa.reference_attention(q, k, v, scale=0.17,
                                     dropout_rate=self.RATE,
                                     dropout_seed=seed)
        monkeypatch.setattr(fa, "XLA_ATTN_CHUNK_TARGET_BYTES", 1 << 10)
        assert fa._q_chunk(q, k) < 256
        out = fa._xla_attention(q, k, v, None, seed, False, 0.17, self.RATE)
        np.testing.assert_allclose(out, ref, atol=3e-5)
        g1 = jax.grad(lambda *a: jnp.sum(fa._xla_attention(
            *a, None, seed, False, 0.17, self.RATE) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(fa.reference_attention(
            *a, scale=0.17, dropout_rate=self.RATE,
            dropout_seed=seed) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_pallas_dropout_matches_reference(self, interpret_mode):
        """In-kernel dropout (interpret mode) == reference, fwd + grads,
        with a padding bias in play."""
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, reference_attention)

        q, k, v = (_rand(2, 2, 128, 64, seed=s) for s in range(3))
        mask = (np.random.RandomState(3).rand(2, 128) < 0.25)
        bias = jnp.asarray(mask * -10000.0).astype(jnp.float32)
        seed = jnp.uint32(42)
        out = flash_attention(q, k, v, bias=bias.reshape(2, 1, 1, 128),
                              dropout_rate=self.RATE, dropout_seed=seed)
        ref = reference_attention(q, k, v, bias_kv=bias,
                                  dropout_rate=self.RATE, dropout_seed=seed)
        np.testing.assert_allclose(out, ref, atol=2e-5)

        g1 = jax.grad(lambda *a: jnp.sum(flash_attention(
            *a[:3], bias=a[3].reshape(2, 1, 1, 128),
            dropout_rate=self.RATE, dropout_seed=seed) ** 2),
            argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(lambda *a: jnp.sum(reference_attention(
            *a[:3], bias_kv=a[3], dropout_rate=self.RATE,
            dropout_seed=seed) ** 2), argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_flash_attention_op_dropout_steps_vary(self):
        """Through the registered op: dropout_prob>0 changes the output,
        and different __step__ values give different masks (fresh noise
        per training step) while the same step reproduces."""
        from paddle_tpu.core.registry import get as get_op

        q, k, v = (_rand(1, 2, 64, 32, seed=s) for s in range(3))
        op = get_op("flash_attention")
        ins = {"Q": [q], "K": [k], "V": [v]}
        base = dict(dropout_prob=self.RATE, seed=11)
        o1 = op.forward(ins, {**base, "__step__": jnp.int32(0)})["Out"]
        o1b = op.forward(ins, {**base, "__step__": jnp.int32(0)})["Out"]
        o2 = op.forward(ins, {**base, "__step__": jnp.int32(1)})["Out"]
        otest = op.forward(ins, {**base, "is_test": True})["Out"]
        onone = op.forward(ins, {})["Out"]
        np.testing.assert_allclose(o1, o1b, atol=0)
        assert float(jnp.max(jnp.abs(o1 - o2))) > 1e-4
        np.testing.assert_allclose(otest, onone, atol=0)


class TestFusedLayerNorm:
    def _ref(self, x, s, b, eps=1e-5):
        mean = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mean) / jnp.sqrt(var + eps) * s + b

    def test_fwd_and_grad(self, interpret_mode):
        from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm

        x = _rand(6, 384, seed=0)
        s, b = _rand(384, seed=1), _rand(384, seed=2)
        y, mean, rstd = fused_layer_norm(x, s, b)
        np.testing.assert_allclose(y, self._ref(x, s, b), atol=2e-5)
        np.testing.assert_allclose(mean, jnp.mean(x, -1), atol=1e-5)

        g1 = jax.grad(lambda *a: jnp.sum(fused_layer_norm(*a)[0] ** 2),
                      argnums=(0, 1, 2))(x, s, b)
        g2 = jax.grad(lambda *a: jnp.sum(self._ref(*a) ** 2),
                      argnums=(0, 1, 2))(x, s, b)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(a, b_, atol=5e-5)


class TestFusedAdamW:
    def test_matches_unfused(self, interpret_mode):
        from paddle_tpu.ops.pallas.fused_adam import fused_adamw

        p, g = _rand(300, 70, seed=0), _rand(300, 70, seed=1)
        m, v = jnp.zeros_like(p), jnp.zeros_like(p)
        args = (0.001, 0.9, 0.999, 1e-8, 0.01, 0.9, 0.999)
        got = fused_adamw(p, g, m, v, *args)
        os.environ["PT_PALLAS"] = "off"
        want = fused_adamw(p, g, m, v, *args)
        for a, b in zip(got, want):
            np.testing.assert_allclose(a, b, atol=1e-6)


class TestFlashAttentionInProgram:
    def test_bert_flash_vs_unfused(self, interpret_mode):
        """Whole-program parity: tiny BERT with the flash_attention op vs the
        unfused matmul/softmax chain (dropout off)."""
        import paddle_tpu as pt
        from paddle_tpu.models import bert

        losses = {}
        for fused in (False, True):
            cfg = bert.BertConfig(
                vocab_size=128, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=2, intermediate_size=128,
                max_position_embeddings=128, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0, use_flash_attention=fused)
            from paddle_tpu.core import ir, unique_name

            ir._main_program, ir._startup_program = ir.Program(), ir.Program()
            unique_name.switch()
            main, startup, feeds, fetches = bert.build_pretraining_program(
                cfg, seq_len=128, optimizer_name="adamw")
            exe = pt.Executor()
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            batch = bert.synthetic_pretraining_batch(cfg, 2, 128)
            out = exe.run(main, feed=batch, fetch_list=[fetches["loss"]],
                          scope=scope)
            losses[fused] = float(np.asarray(out[0]))
        assert np.isfinite(losses[True])
        np.testing.assert_allclose(losses[True], losses[False], rtol=1e-4)


class TestHeadBlockedFusedKernels:
    """The g-sliced single-block kernels (_fused_g) — g consecutive
    (b,h) slices per grid cell for sequences below FUSED_MIN_SEQ."""

    def test_g_path_selected_and_matches(self, interpret_mode):
        import importlib

        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        B, H, S, D = 2, 4, 128, 64
        assert fa._fused_g(S, S, H) == 4
        q, k, v = (_rand(B, H, S, D, seed=i) for i in range(3))
        bias = (np.random.RandomState(9).rand(B, S) > 0.2).astype(
            np.float32)
        bias_kv = jnp.asarray((bias - 1.0) * 10000.0)

        def f(q, k, v, b):
            out, _lse = fa._flash(q, k, v, b, jnp.uint32(3), False,
                                  1.0 / np.sqrt(D), True, 0.1)
            return out

        def ref(q, k, v, b):
            return fa.reference_attention(
                q, k, v, b, causal=False, scale=1.0 / np.sqrt(D),
                dropout_rate=0.1, dropout_seed=jnp.uint32(3))

        out, ref_out = f(q, k, v, bias_kv), ref(q, k, v, bias_kv)
        np.testing.assert_allclose(out, ref_out, atol=5e-3)
        do = _rand(B, H, S, D, seed=7)
        _, vjp = jax.vjp(f, q, k, v, bias_kv)
        _, vjp_r = jax.vjp(ref, q, k, v, bias_kv)
        for g_, r_ in zip(vjp(do)[:4], vjp_r(do)[:4]):
            np.testing.assert_allclose(g_, r_, atol=2e-2)

    def test_g_requires_h_divisor(self):
        import importlib

        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        assert fa._fused_g(128, 128, 12) == 4   # 512//128 -> 4 | 12
        assert fa._fused_g(128, 128, 7) == 0    # no divisor <= 4 > 1
        assert fa._fused_g(64, 64, 16) == 8     # 512//64=8 | 16
        assert fa._fused_g(256, 256, 16) == 0   # plain fused regime


class TestSavedResidualGrad:
    """Round 5: the flash_attention_grad op consumes the SAVED forward
    (Out, Lse) — the program backward must contain it (not the generic
    __vjp_grad__ that re-runs the fwd kernel) and its grads must match
    the reference attention's."""

    def _build(self, rate):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.ir import Program, program_guard

        main, startup = Program(), Program()
        with program_guard(main, startup):
            q = layers.static_data("q", [2, 4, 256, 64], "float32")
            k = layers.static_data("k", [2, 4, 256, 64], "float32")
            v = layers.static_data("v", [2, 4, 256, 64], "float32")
            bias = layers.static_data("bias", [2, 1, 1, 256], "float32")
            for t in (q, k, v):
                t.stop_gradient = False
            out = layers.flash_attention(q, k, v, bias=bias,
                                         dropout_rate=rate, seed=11)
            loss = layers.reduce_sum(out * out)
            from paddle_tpu.core.backward import gradients

            gq, gk, gv = gradients([loss], [q, k, v])
        return main, startup, loss, (gq, gk, gv)

    def test_grad_op_emitted_and_matches_reference(self, interpret_mode,
                                                   scope):
        import paddle_tpu as pt
        from paddle_tpu.ops.pallas.flash_attention import (
            reference_attention)

        main, startup, loss, grads = self._build(rate=0.1)
        ops = main.global_block().ops
        assert any(op.type == "flash_attention_grad" for op in ops)
        assert not any(op.type == "__vjp_grad__" and
                       op.attrs.get("fwd_type") == "flash_attention"
                       for op in ops)

        rng = np.random.RandomState(0)
        feed = {n: rng.randn(2, 4, 256, 64).astype(np.float32) * 0.3
                for n in ("q", "k", "v")}
        feed["bias"] = np.where(rng.rand(2, 1, 1, 256) < 0.2, -10000.0,
                                0.0).astype(np.float32)
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        got = exe.run(main, feed=feed, fetch_list=[loss, *grads],
                      scope=scope)

        # reference oracle with the same position-keyed dropout mask: seed
        # attr 11 + the ACTUAL __step__ the main run used (the scope's
        # counter post-run minus one — startup bumped it too)
        from paddle_tpu.ops.attention_ops import _attn_dropout

        step_used = int(scope.find_var("@STEP_COUNTER@")) - 1
        rate, seed = _attn_dropout({"dropout_prob": 0.1, "seed": 11,
                                    "__step__": np.int32(step_used)})
        qj, kj, vj = (jnp.asarray(feed[n]) for n in ("q", "k", "v"))
        bias_kv = jnp.asarray(feed["bias"]).reshape(2, 256)

        def f(q_, k_, v_):
            o = reference_attention(q_, k_, v_, bias_kv,
                                    causal=False, scale=1.0 / np.sqrt(64),
                                    dropout_rate=rate, dropout_seed=seed)
            return jnp.sum(o * o)

        ref_loss, ref_grads = jax.value_and_grad(f, argnums=(0, 1, 2))(
            qj, kj, vj)
        np.testing.assert_allclose(got[0], ref_loss, rtol=2e-4)
        for g_, r_ in zip(got[1:], ref_grads):
            np.testing.assert_allclose(g_, r_, atol=5e-3, rtol=1e-3)

    def test_fallback_without_lse_output(self, interpret_mode, scope):
        """Descs built without the Lse output (pre-round-5 programs, the
        inference fuse pass) must fall back to the generic vjp grad."""
        import paddle_tpu as pt
        from paddle_tpu.core.backward import gradients
        from paddle_tpu import layers
        from paddle_tpu.core.ir import Program, program_guard

        main, startup = Program(), Program()
        with program_guard(main, startup):
            q = layers.static_data("q", [1, 2, 128, 64], "float32")
            q.stop_gradient = False
            k = layers.static_data("k", [1, 2, 128, 64], "float32")
            v = layers.static_data("v", [1, 2, 128, 64], "float32")
            out = layers.flash_attention(q, k, v)
            # strip the Lse output as an old serialised desc would be
            op = [o for o in main.global_block().ops
                  if o.type == "flash_attention"][0]
            op.outputs.pop("Lse")
            loss = layers.reduce_sum(out * out)
            (gq,) = gradients([loss], [q])
        types = [op.type for op in main.global_block().ops]
        assert "flash_attention_grad" not in types
        assert "__vjp_grad__" in types
        rng = np.random.RandomState(1)
        feed = {n: rng.randn(1, 2, 128, 64).astype(np.float32) * 0.3
                for n in ("q", "k", "v")}
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        got = exe.run(main, feed=feed, fetch_list=[loss, gq], scope=scope)
        assert np.isfinite(np.asarray(got[0]))
        assert np.isfinite(np.asarray(got[1])).all()

    def test_grad_op_tagged_backward_and_stripped_by_clone(self,
                                                           interpret_mode):
        """The maker must not inherit the forward's op_role: the grad op
        has to be OpRole.Backward so clone(for_test=True) strips it."""
        main, _startup, _loss, _grads = self._build(rate=0.0)
        test_prog = main.clone(for_test=True)
        types = [o.type for o in test_prog.global_block().ops]
        assert "flash_attention_grad" not in types


class TestPackedLayout:
    """Round 5: packed [B,S,n*hd] kernels must match the bnsd path
    bit-for-bit (same per-head math, same position-keyed dropout), and
    the program-level packed op must route to flash_attention_grad."""

    def test_packed_matches_bnsd(self, interpret_mode):
        import importlib

        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        B, N, S, D = 2, 4, 256, 64
        rng = np.random.RandomState(0)
        q3, k3, v3 = (jnp.asarray(
            rng.randn(B, S, N * D).astype(np.float32) * 0.3)
            for _ in range(3))
        bias = jnp.asarray(np.where(rng.rand(B, 1, 1, S) < 0.2,
                                    -10000.0, 0.0).astype(np.float32))
        assert fa._packed_fast_applies(q3, k3, bias, N)[0]
        out_p, lse_p = fa.flash_attention_fwd_lse(
            q3, k3, v3, bias=bias, dropout_rate=0.1,
            dropout_seed=jnp.uint32(5), num_heads=N)
        q4 = fa._packed_to_bnsd(q3, N)
        out_4, lse_4 = fa.flash_attention_fwd_lse(
            fa._packed_to_bnsd(q3, N), fa._packed_to_bnsd(k3, N),
            fa._packed_to_bnsd(v3, N), bias=bias, dropout_rate=0.1,
            dropout_seed=jnp.uint32(5))
        np.testing.assert_array_equal(np.asarray(out_p),
                                      np.asarray(fa._bnsd_to_packed(out_4)))
        np.testing.assert_allclose(np.asarray(lse_p), np.asarray(lse_4),
                                   atol=1e-6)

        # saved-residual packed backward vs the bnsd backward
        do3 = jnp.asarray(rng.randn(B, S, N * D).astype(np.float32))
        dq_p, dk_p, dv_p, db_p = fa.flash_attention_bwd(
            q3, k3, v3, bias, out_p, lse_p, do3, dropout_rate=0.1,
            dropout_seed=jnp.uint32(5), num_heads=N)
        dq_4, dk_4, dv_4, db_4 = fa.flash_attention_bwd(
            q4, fa._packed_to_bnsd(k3, N), fa._packed_to_bnsd(v3, N),
            bias, out_4, lse_4, fa._packed_to_bnsd(do3, N),
            dropout_rate=0.1, dropout_seed=jnp.uint32(5))
        for a, b4 in ((dq_p, dq_4), (dk_p, dk_4), (dv_p, dv_4)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(fa._bnsd_to_packed(b4)),
                atol=2e-5)
        np.testing.assert_allclose(np.asarray(db_p), np.asarray(db_4),
                                   atol=2e-5)

    def test_packed_program_grad_op(self, interpret_mode, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core.backward import gradients
        from paddle_tpu.core.ir import Program, program_guard

        main, startup = Program(), Program()
        with program_guard(main, startup):
            q = layers.static_data("q", [2, 256, 256], "float32")
            q.stop_gradient = False
            k = layers.static_data("k", [2, 256, 256], "float32")
            v = layers.static_data("v", [2, 256, 256], "float32")
            out = layers.flash_attention(q, k, v, num_heads=4)
            loss = layers.reduce_sum(out * out)
            (gq,) = gradients([loss], [q])
        assert any(op.type == "flash_attention_grad"
                   for op in main.global_block().ops)
        rng = np.random.RandomState(1)
        feed = {n: rng.randn(2, 256, 256).astype(np.float32) * 0.3
                for n in ("q", "k", "v")}
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        lv, gv = exe.run(main, feed=feed, fetch_list=[loss, gq],
                         scope=scope)
        assert np.isfinite(np.asarray(lv))
        assert np.abs(np.asarray(gv)).max() > 0

    def test_packed_fallback_shapes(self, interpret_mode):
        """Below the fused regime (S=128 -> xla route on tpu, reference
        on cpu) the packed entry transposes internally and still
        matches."""
        import importlib

        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        B, N, S, D = 2, 4, 40, 16   # odd shapes: no kernel support
        rng = np.random.RandomState(2)
        q3, k3, v3 = (jnp.asarray(
            rng.randn(B, S, N * D).astype(np.float32) * 0.3)
            for _ in range(3))
        assert not fa._packed_fast_applies(q3, k3, None, N)[0]
        out_p, _ = fa.flash_attention_fwd_lse(q3, k3, v3, num_heads=N)
        ref = fa.reference_attention(
            fa._packed_to_bnsd(q3, N), fa._packed_to_bnsd(k3, N),
            fa._packed_to_bnsd(v3, N))
        np.testing.assert_allclose(
            np.asarray(out_p), np.asarray(fa._bnsd_to_packed(ref)),
            atol=2e-5)

    def test_packed_cross_attention(self, interpret_mode):
        """sq != sk with a key bias (the transformer decoder's
        cross-attention) must dispatch on K's OWN sequence length —
        a q-shaped proxy crashed the bias broadcast here."""
        import importlib

        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        B, N, SQ, SK, D = 2, 4, 256, 128, 64
        rng = np.random.RandomState(3)
        q3 = jnp.asarray(rng.randn(B, SQ, N * D).astype(np.float32) * 0.3)
        k3 = jnp.asarray(rng.randn(B, SK, N * D).astype(np.float32) * 0.3)
        v3 = jnp.asarray(rng.randn(B, SK, N * D).astype(np.float32) * 0.3)
        bias = jnp.asarray(np.where(rng.rand(B, 1, 1, SK) < 0.2,
                                    -10000.0, 0.0).astype(np.float32))
        assert not fa._packed_fast_applies(q3, k3, bias, N)[0]
        out_p, _ = fa.flash_attention_fwd_lse(q3, k3, v3, bias=bias,
                                              num_heads=N)
        ref = fa.reference_attention(
            fa._packed_to_bnsd(q3, N), fa._packed_to_bnsd(k3, N),
            fa._packed_to_bnsd(v3, N),
            bias_kv=bias.reshape(B, SK))
        np.testing.assert_allclose(
            np.asarray(out_p), np.asarray(fa._bnsd_to_packed(ref)),
            atol=2e-5)
