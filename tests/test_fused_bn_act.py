"""Training-time fused BN(+add)+ReLU (VERDICT r2 #2; reference
fuse_bn_act_pass.cc / fused_bn_add_activation_op.cu). Contract: the
fused op + pass must train EXACTLY like the unfused chain."""

import numpy as np
import pytest


class TestFusedOpNumerics:
    def _ref(self, x, scale, bias, z, eps=1e-5):
        import jax
        import jax.numpy as jnp

        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 2, 3))
        var = jnp.var(xf, axis=(0, 2, 3))
        inv = 1.0 / jnp.sqrt(var + eps)
        y = (xf - mean[None, :, None, None]) * inv[None, :, None, None] \
            * scale[None, :, None, None] + bias[None, :, None, None]
        if z is not None:
            y = y + z.astype(jnp.float32)
        return jnp.maximum(y, 0.0)

    @pytest.mark.parametrize("with_z", [False, True])
    def test_fwd_and_grads_match_autodiff(self, with_z):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.bn_act import fused_bn_add_act

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 6, 5, 5).astype(np.float32))
        scale = jnp.asarray(rng.rand(6).astype(np.float32) + 0.5)
        bias = jnp.asarray(rng.randn(6).astype(np.float32) * 0.1)
        z = jnp.asarray(rng.randn(4, 6, 5, 5).astype(np.float32)) \
            if with_z else None

        out = fused_bn_add_act(x, scale, bias, z, 1e-5, 1, "relu")
        ref = self._ref(x, scale, bias, z)
        np.testing.assert_allclose(out, ref, atol=1e-5)

        def loss_fused(*a):
            zz = a[3] if with_z else None
            return jnp.sum(fused_bn_add_act(a[0], a[1], a[2], zz,
                                            1e-5, 1, "relu") ** 2)

        def loss_ref(*a):
            zz = a[3] if with_z else None
            return jnp.sum(self._ref(a[0], a[1], a[2], zz) ** 2)

        args = (x, scale, bias) + ((z,) if with_z else ())
        idx = tuple(range(len(args)))
        g1 = jax.grad(loss_fused, argnums=idx)(*args)
        g2 = jax.grad(loss_ref, argnums=idx)(*args)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=2e-4, rtol=1e-4)


class TestFusePassParity:
    def _train(self, fuse, steps=2):
        import paddle_tpu as pt
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.models import resnet

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        cfg = resnet.ResNetConfig(18, num_classes=4,
                                  image_shape=(3, 32, 32))
        main, startup, feeds, fetches = resnet.build_classifier_program(
            cfg, batch_size=4, lr=0.001, fuse_bn_act=fuse)
        types = [op.type for op in main.global_block().ops]
        if fuse:
            assert "fused_bn_add_act" in types
            # every relu got absorbed (resnet18: bn+relu and bn+add+relu)
            assert "relu" not in types[:types.index("pool2d")]
        else:
            assert "fused_bn_add_act" not in types
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(1)
        feed = {"img": rng.randn(4, 3, 32, 32).astype(np.float32),
                "label": rng.randint(0, 4, (4, 1)).astype(np.int64)}
        losses = []
        for _ in range(steps):
            out = exe.run(main, feed=feed, fetch_list=[fetches["loss"]],
                          scope=scope)
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        stats = np.asarray(scope.find_var("conv1_bn_mean"))
        w = np.asarray(scope.find_var("res2a_c1_w"))
        return losses, stats, w

    def test_fused_matches_unfused(self):
        lf, sf, wf = self._train(True)
        lu, su, wu = self._train(False)
        # the analytic fused backward is algebraically identical to the
        # unfused autodiff chain but reassociates f32 math (elementwise
        # grad parity pinned tight by TestFusedOpNumerics): step-0 loss
        # and the post-update params/stats must agree closely; later
        # losses only to reassociation-amplified tolerance
        np.testing.assert_allclose(lf[0], lu[0], rtol=2e-5)
        np.testing.assert_allclose(wf, wu, rtol=1e-3, atol=5e-5)
        np.testing.assert_allclose(sf, su, rtol=1e-3, atol=1e-6)
        np.testing.assert_allclose(lf, lu, rtol=2e-2)
        assert lf[-1] < lf[0]
