"""Round-3 op batch (ops/extra_ops3.py) — quick numpy-oracle checks."""

import numpy as np
import pytest


def _fwd(op, ins, attrs=None):
    import jax.numpy as jnp

    import paddle_tpu  # noqa: F401  (registers ops)
    from paddle_tpu.core import registry

    wrapped = {k: [None if v is None else
                   (v if not isinstance(v, (np.ndarray, int, float))
                    else jnp.asarray(v)) for v in vs]
               for k, vs in ins.items()}
    return registry.lookup(op).forward(wrapped, attrs or {})


class TestBatch3:
    def test_allclose_and_is_empty(self):
        x = np.ones((3,), np.float32)
        assert bool(np.asarray(_fwd("allclose", {"Input": [x],
                                                 "Other": [x + 1e-9]})["Out"]))
        assert not bool(np.asarray(_fwd(
            "allclose", {"Input": [x], "Other": [x + 1.0]})["Out"]))
        assert not bool(np.asarray(_fwd("is_empty", {"X": [x]})["Out"]))

    def test_unique_and_counts(self):
        x = np.array([5, 3, 5, 1, 3, 5], np.int64)
        out = _fwd("unique", {"X": [x]})
        cnt = int(np.asarray(out["Count"]))
        assert cnt == 3
        np.testing.assert_array_equal(np.asarray(out["Out"])[:cnt],
                                      [5, 3, 1])
        np.testing.assert_array_equal(out["Index"], [0, 1, 0, 2, 1, 0])
        wc = _fwd("unique_with_counts", {"X": [x]})
        np.testing.assert_array_equal(np.asarray(wc["Count"])[:3],
                                      [3, 2, 1])

    def test_where_index(self):
        c = np.array([[1, 0], [0, 1]], np.int32)
        out = _fwd("where_index", {"Condition": [c]})
        assert int(np.asarray(out["Count"])) == 2
        np.testing.assert_array_equal(np.asarray(out["Out"])[:2],
                                      [[0, 0], [1, 1]])

    def test_diag_embed(self):
        x = np.array([[1.0, 2.0, 3.0]], np.float32)
        out = np.asarray(_fwd("diag_embed", {"Input": [x]})["Out"])
        np.testing.assert_allclose(out[0], np.diag([1, 2, 3]))

    def test_scatter_nd_add(self):
        x = np.zeros((3, 3), np.float32)
        idx = np.array([[0, 0], [1, 2], [0, 0]], np.int64)
        upd = np.array([1.0, 2.0, 3.0], np.float32)
        out = np.asarray(_fwd("scatter_nd_add",
                              {"X": [x], "Index": [idx],
                               "Updates": [upd]})["Out"])
        assert out[0, 0] == 4.0 and out[1, 2] == 2.0

    def test_add_position_encoding(self):
        x = np.zeros((1, 4, 6), np.float32)
        out = np.asarray(_fwd("add_position_encoding", {"X": [x]},
                              {"alpha": 1.0, "beta": 1.0})["Out"])
        assert out.shape == (1, 4, 6)
        np.testing.assert_allclose(out[0, 0, :3], 0.0, atol=1e-6)  # sin(0)
        np.testing.assert_allclose(out[0, 0, 3:], 1.0, atol=1e-6)  # cos(0)

    def test_squared_l2_distance(self):
        x = np.array([[1.0, 2.0], [0.0, 0.0]], np.float32)
        y = np.array([[0.0, 0.0], [3.0, 4.0]], np.float32)
        out = np.asarray(_fwd("squared_l2_distance",
                              {"X": [x], "Y": [y]})["Out"])
        np.testing.assert_allclose(out.reshape(-1), [5.0, 25.0])

    def test_chunk_eval_exact(self):
        # reference IOB with 1 type: B=0, I=1, O=2
        pred = np.array([[0, 1, 2, 0, 1, 1]], np.int64)
        lab = np.array([[0, 1, 2, 0, 2, 2]], np.int64)
        out = _fwd("chunk_eval", {"Inference": [pred], "Label": [lab]},
                   {"num_chunk_types": 1})
        assert int(np.asarray(out["NumInferChunks"])) == 2
        assert int(np.asarray(out["NumLabelChunks"])) == 2
        # only the first chunk matches exactly (second differs in extent)
        assert int(np.asarray(out["NumCorrectChunks"])) == 1
        np.testing.assert_allclose(np.asarray(out["Precision"]), 0.5)
        np.testing.assert_allclose(np.asarray(out["Recall"]), 0.5)

    def test_chunk_eval_respects_length(self):
        pred = np.array([[0, 1, 0, 0]], np.int64)
        lab = np.array([[0, 1, 0, 1]], np.int64)
        out = _fwd("chunk_eval", {"Inference": [pred], "Label": [lab],
                                  "SeqLength": [np.array([2], np.int64)]},
                   {"num_chunk_types": 1})
        assert int(np.asarray(out["NumCorrectChunks"])) == 1
        assert int(np.asarray(out["NumInferChunks"])) == 1

    def test_spp_shapes(self):
        x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
        out = np.asarray(_fwd("spp", {"X": [x]},
                              {"pyramid_height": 2,
                               "pooling_type": "max"})["Out"])
        assert out.shape == (2, 3 * (1 + 4))
        np.testing.assert_allclose(out[:, :3],
                                   x.max(axis=(2, 3)), rtol=1e-6)

    def test_roi_pool(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
        out = np.asarray(_fwd("roi_pool", {"X": [x], "ROIs": [rois]},
                              {"pooled_height": 2, "pooled_width": 2,
                               "spatial_scale": 1.0})["Out"])
        np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])

    def test_split_ids_and_selected_rows(self):
        import jax.numpy as jnp

        from paddle_tpu.core.selected_rows import SelectedRows

        ids = np.array([0, 3, 4, 7, 2], np.int64)
        out = _fwd("split_ids", {"Ids": [ids]}, {"n_parts": 2})
        c = np.asarray(out["Counts"])
        assert c.tolist() == [3, 2]
        np.testing.assert_array_equal(np.asarray(out["Out"][0])[:3],
                                      [0, 4, 2])
        sr = SelectedRows(jnp.asarray([1, 5], jnp.int32),
                          jnp.ones((2, 3)), 8)
        parts = _fwd("split_selected_rows", {"X": [sr]},
                     {"height_sections": [4, 4]})["Out"]
        assert np.asarray(parts[0].to_dense())[1].sum() == 3.0
        assert np.asarray(parts[1].to_dense())[1].sum() == 3.0

    def test_tensor_array_to_tensor_and_length(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 2, 2)
        cat = np.asarray(_fwd("tensor_array_to_tensor", {"X": [x]},
                              {"axis": 0})["Out"])
        assert cat.shape == (6, 2)
        st = np.asarray(_fwd("tensor_array_to_tensor", {"X": [x]},
                             {"axis": 1, "use_stack": True})["Out"])
        assert st.shape == (2, 3, 2)
        ln = np.asarray(_fwd("lod_array_length", {"X": [x]})["Out"])
        assert ln[0] == 3

    def test_random_family(self):
        x = np.full((2000,), 0.3, np.float32)
        b = np.asarray(_fwd("bernoulli", {"X": [x]}, {"seed": 3})["Out"])
        assert abs(b.mean() - 0.3) < 0.05
        probs = np.array([[0.0, 1.0], [1.0, 0.0]], np.float32)
        sid = np.asarray(_fwd("sampling_id", {"X": [probs]},
                              {"seed": 1})["Out"])
        np.testing.assert_array_equal(sid, [1, 0])
        ref = np.zeros((5, 2), np.float32)
        u = np.asarray(_fwd("uniform_random_batch_size_like",
                            {"Input": [ref]},
                            {"shape": [1, 7], "seed": 2})["Out"])
        assert u.shape == (5, 7)
        sh = _fwd("shuffle_batch", {"X": [np.arange(8.0)]}, {"seed": 4})
        assert sorted(np.asarray(sh["Out"]).tolist()) == list(range(8))

    def test_average_accumulates_rolls(self):
        p = np.full((2,), 2.0, np.float32)
        s1 = np.zeros((2,), np.float32)
        s2 = np.zeros((2,), np.float32)
        s3 = np.zeros((2,), np.float32)
        na = np.zeros((1,), np.int64)
        ona = np.zeros((1,), np.int64)
        nu = np.zeros((1,), np.int64)
        for _ in range(3):
            out = _fwd("average_accumulates",
                       {"param": [p], "in_sum_1": [s1], "in_sum_2": [s2],
                        "in_sum_3": [s3], "in_num_accumulates": [na],
                        "in_old_num_accumulates": [ona],
                        "in_num_updates": [nu]},
                       {"average_window": 0.0, "max_average_window": 2,
                        "min_average_window": 2})
            s1, s2, s3 = (np.asarray(out[k]) for k in
                          ("out_sum_1", "out_sum_2", "out_sum_3"))
            na = np.asarray(out["out_num_accumulates"])
            ona = np.asarray(out["out_old_num_accumulates"])
            nu = np.asarray(out["out_num_updates"])
        # window of 2 rolled once: s3 holds 2 accumulations, s1 restarted
        assert s3.sum() == 8.0 and s1.sum() == 4.0 and int(nu[0]) == 3

    def test_misc_passthroughs(self):
        x = np.ones((2, 1, 3), np.float32)
        sq = np.asarray(_fwd("squeeze", {"X": [x]}, {"axes": [1]})["Out"])
        assert sq.shape == (2, 3)
        un = np.asarray(_fwd("unsqueeze", {"X": [sq]},
                             {"axes": [0]})["Out"])
        assert un.shape == (1, 2, 3)
        assert np.asarray(_fwd("rnn_memory_helper",
                               {"X": [x]})["Out"]).shape == x.shape
        sel = np.asarray(_fwd("select_input",
                              {"X": [x, x * 2],
                               "Mask": [np.int32(1)]})["Out"])
        np.testing.assert_allclose(sel, x * 2)
        co = _fwd("coalesce_tensor", {"Input": [x, sq]})
        assert np.asarray(co["FusedOutput"]).shape == (12,)
        with pytest.raises(AssertionError):
            _fwd("assert", {"Cond": [np.asarray(False)], "Data": [x]})
        # empty / fill / seed
        assert np.asarray(_fwd("empty", {}, {"shape": [2, 2]})["Out"]
                          ).shape == (2, 2)
        f = np.asarray(_fwd("fill", {}, {"shape": [2], "value": [3, 4],
                                         "dtype": "float32"})["Out"])
        np.testing.assert_allclose(f, [3.0, 4.0])


class TestRound3NumericGrads:
    """Central-difference grad checks for round-3 ops with non-trivial
    backward paths (the OpTest harness style, reference op-test
    contract)."""

    def _grad_check(self, fn, args, argnums, delta=1e-3, tol=2e-3):
        import jax
        import jax.numpy as jnp

        g_an = jax.grad(lambda *a: jnp.sum(fn(*a)).astype(jnp.float32),
                        argnums=argnums)(*args)
        if not isinstance(g_an, tuple):
            g_an = (g_an,)
        for ai, ga in zip(argnums, g_an):
            a = np.asarray(args[ai], np.float64)
            gn = np.zeros_like(a)
            flat, gflat = a.reshape(-1), gn.reshape(-1)
            for i in range(flat.size):
                for sgn in (1, -1):
                    pert = a.copy().reshape(-1)
                    pert[i] += sgn * delta
                    newargs = list(args)
                    newargs[ai] = jnp.asarray(
                        pert.reshape(a.shape).astype(np.float32))
                    val = float(np.sum(np.asarray(fn(*newargs),
                                                  np.float64)))
                    gflat[i] += sgn * val
                gflat[i] /= 2 * delta
            np.testing.assert_allclose(np.asarray(ga, np.float64), gn,
                                       atol=tol, rtol=tol,
                                       err_msg=f"arg {ai}")

    def test_hierarchical_sigmoid_grads(self):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        fwd = registry.lookup("hierarchical_sigmoid").forward
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3, 4).astype(np.float32))
        w = jnp.asarray(rng.randn(5, 4).astype(np.float32))
        b = jnp.asarray(rng.randn(5).astype(np.float32))
        label = jnp.asarray(rng.randint(0, 6, (3, 1)).astype(np.int64))

        def f(x_, w_, b_):
            return fwd({"X": [x_], "W": [w_], "Bias": [b_],
                        "Label": [label]}, {"num_classes": 6})["Out"]

        self._grad_check(f, (x, w, b), (0, 1, 2))

    def test_spectral_norm_grads(self):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        fwd = registry.lookup("spectral_norm").forward
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(4, 3).astype(np.float32))
        u = jnp.asarray(rng.randn(4).astype(np.float32))
        v = jnp.asarray(rng.randn(3).astype(np.float32))

        def f(w_):
            return fwd({"Weight": [w_], "U": [u], "V": [v]},
                       {"dim": 0, "power_iters": 20})["Out"] ** 2

        self._grad_check(f, (w,), (0,), tol=5e-3)

    def test_sequence_topk_avg_pooling_grads(self):
        import jax.numpy as jnp

        from paddle_tpu.core import registry

        fwd = registry.lookup("sequence_topk_avg_pooling").forward
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(2, 1, 3, 4).astype(np.float32))
        row = jnp.asarray(np.array([3, 2], np.int32))
        col = jnp.asarray(np.array([4, 3], np.int32))

        def f(x_):
            return fwd({"X": [x_], "ROW": [row], "COLUMN": [col]},
                       {"topks": [2], "channel_num": 1})["Out"]

        self._grad_check(f, (x,), (0,))


class TestHSigmoidLayer:
    def _run(self, custom_tree):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        main, startup = pt.Program(), pt.Program()
        C = 6
        with pt.program_guard(main, startup):
            x = layers.data("x", [8], stop_gradient=True)
            y = layers.data("y", [1], dtype="int64", stop_gradient=True)
            kw = {}
            if custom_tree:
                # per-row (path nodes, codes): a fixed 2-level tree
                pt_t = layers.data("ptab", [2], dtype="int64",
                                   stop_gradient=True)
                pc_t = layers.data("pcode", [2], dtype="int64",
                                   stop_gradient=True)
                kw = dict(path_table=pt_t, path_code=pc_t)
            cost = layers.hsigmoid(layers.fc(x, 12), y, num_classes=C,
                                   **kw)
            loss = layers.mean(cost)
            pt.optimizer.SGDOptimizer(0.3).minimize(loss)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(12, 8).astype(np.float32),
                "y": rng.randint(0, C, (12, 1)).astype(np.int64)}
        if custom_tree:
            feed["ptab"] = np.stack(
                [np.full(12, 0), feed["y"].reshape(-1) % 5]).T.astype(
                    np.int64)
            feed["pcode"] = np.stack(
                [feed["y"].reshape(-1) % 2,
                 (feed["y"].reshape(-1) // 2) % 2]).T.astype(np.int64)
        ls = [float(np.asarray(exe.run(main, feed=feed, fetch_list=[loss],
                                       scope=scope)[0]).reshape(-1)[0])
              for _ in range(8)]
        return ls

    def test_default_tree_trains(self):
        ls = self._run(False)
        assert ls[-1] < ls[0], ls

    def test_custom_tree_trains(self):
        ls = self._run(True)
        assert ls[-1] < ls[0], ls

    def test_table_code_must_pair(self):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name

        ir._main_program, ir._startup_program = ir.Program(), ir.Program()
        unique_name.switch()
        with pt.program_guard(pt.Program(), pt.Program()):
            x = layers.data("x", [4], stop_gradient=True)
            y = layers.data("y", [1], dtype="int64", stop_gradient=True)
            with pytest.raises(ValueError, match="together"):
                layers.hsigmoid(x, y, 4, path_table=y)
