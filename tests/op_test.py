"""OpTest harness — numpy-reference forward + numeric-gradient checks.

Capability mirror of the reference's op-test workhorse
(python/paddle/fluid/tests/unittests/op_test.py:184 OpTest,
check_output_with_place:979, check_grad_with_place:1299): a subclass
declares op_type/inputs/attrs and numpy-computed expected outputs;
check_output runs the single op through BOTH executors (interpreting
oracle and compiled) and compares; check_grad compares the analytic
gradient (program-level append_backward over the op's grad maker) against
central-difference numeric gradients.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu.core import ir, unique_name
from paddle_tpu.core.ir import Program


class OpTest:
    op_type: str = ""

    # subclasses set in setup(): inputs / attrs / outputs
    def setup(self):
        raise NotImplementedError

    # -- helpers -------------------------------------------------------------
    def _norm_io(self, io):
        """{slot: arr | [(name, arr), ...]} → {slot: [(name, arr), ...]}"""
        norm = {}
        for slot, v in io.items():
            if isinstance(v, list) and v and isinstance(v[0], tuple):
                norm[slot] = [(n, np.asarray(a)) for n, a in v]
            else:
                norm[slot] = [(f"{slot}", np.asarray(v))]
        return norm

    def _build(self):
        self.setup()
        ins = self._norm_io(self.inputs)
        outs = self._norm_io(getattr(self, "outputs", {}))
        attrs = dict(getattr(self, "attrs", {}))

        ir._main_program, ir._startup_program = Program(), Program()
        unique_name.switch()
        main = ir._main_program
        block = main.global_block()
        feed = {}
        input_names = {}
        for slot, pairs in ins.items():
            names = []
            for name, arr in pairs:
                vname = f"{self.op_type}_{name}"
                block.create_var(name=vname, shape=list(arr.shape),
                                 dtype=str(arr.dtype))
                feed[vname] = arr
                names.append(vname)
            input_names[slot] = names
        output_names = {}
        expected = {}
        for slot, pairs in outs.items():
            names = []
            for name, arr in pairs:
                vname = f"{self.op_type}_out_{name}"
                block.create_var(name=vname, shape=list(arr.shape),
                                 dtype=str(arr.dtype))
                expected[vname] = arr
                names.append(vname)
            output_names[slot] = names
        block.append_op(self.op_type, input_names, output_names, attrs)
        return main, feed, expected, input_names, output_names

    # -- checks --------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5, no_check_set=()):
        main, feed, expected, _, _ = self._build()
        fetch = [n for n in expected if not any(s in n for s in no_check_set)]
        for use_compiled in (False, True):
            exe = pt.Executor()
            got = exe.run(main, feed=dict(feed), fetch_list=fetch,
                          scope=pt.Scope(), use_compiled=use_compiled)
            for name, val in zip(fetch, got):
                want = expected[name]
                np.testing.assert_allclose(
                    np.asarray(val, dtype=want.dtype), want, atol=atol,
                    rtol=rtol,
                    err_msg=f"{self.op_type}.{name} "
                            f"(compiled={use_compiled})")

    def check_grad(self, inputs_to_check, output_name,
                   max_relative_error=0.005, delta=5e-3, atol=2e-4):
        """Analytic (grad-op) vs central-difference numeric gradient of
        sum(output) wrt each input in inputs_to_check."""
        main, feed, expected, input_names, output_names = self._build()
        out_var = None
        for slot, names in output_names.items():
            for n in names:
                if n.endswith(output_name) or slot == output_name:
                    out_var = n
        assert out_var is not None, f"no output '{output_name}'"

        block = main.global_block()
        loss = block.create_var(name="optest_loss", shape=[], dtype="float32")
        block.append_op("reduce_sum", {"X": [out_var]},
                        {"Out": ["optest_loss"]}, {"reduce_all": True})
        from paddle_tpu.core.backward import gradients

        target_names = []
        for want in inputs_to_check:
            found = None
            for slot, names in input_names.items():
                for n in names:
                    if n.endswith(want) or slot == want:
                        found = n
            assert found is not None, f"no input '{want}'"
            target_names.append(found)
        grad_vars = gradients([block.var("optest_loss")],
                              [block.var(n) for n in target_names])
        exe = pt.Executor()
        analytic = exe.run(main, feed=dict(feed),
                           fetch_list=[g.name for g in grad_vars],
                           scope=pt.Scope())

        # numeric: rerun the forward with perturbed inputs
        base_main, base_feed, _, _, _ = self._build()

        def f(feed_over):
            exe2 = pt.Executor()
            out, = exe2.run(base_main, feed=feed_over, fetch_list=[out_var],
                            scope=pt.Scope(), use_compiled=False)
            return float(np.sum(np.asarray(out, np.float64)))

        for tname, g_an in zip(target_names, analytic):
            arr = np.asarray(base_feed[tname], np.float64)
            g_num = np.zeros_like(arr)
            flat = arr.reshape(-1)
            gflat = g_num.reshape(-1)
            for i in range(flat.size):
                orig = flat[i]
                fo = dict(base_feed)
                pert = arr.copy().reshape(-1)
                pert[i] = orig + delta
                fo[tname] = pert.reshape(arr.shape).astype(
                    base_feed[tname].dtype)
                up = f(fo)
                pert[i] = orig - delta
                fo[tname] = pert.reshape(arr.shape).astype(
                    base_feed[tname].dtype)
                down = f(fo)
                gflat[i] = (up - down) / (2 * delta)
            g_an = np.asarray(g_an, np.float64).reshape(g_num.shape)
            denom = np.maximum(np.abs(g_num), 1.0)
            rel = np.abs(g_an - g_num) / denom
            assert rel.max() <= max_relative_error or \
                np.abs(g_an - g_num).max() <= atol, (
                    f"{self.op_type} grad wrt {tname}: max rel err "
                    f"{rel.max():.5f} (abs {np.abs(g_an - g_num).max():.6f})")
