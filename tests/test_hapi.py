"""hapi Model.fit/evaluate/predict/save/load + metrics.

Mirrors reference test_model.py (python/paddle/tests/test_model.py): MNIST-
style Model trained via fit() on a Dataset, metrics accumulate, checkpoint
round-trips.
"""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.hapi import EarlyStopping, Model
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.reader import TensorDataset


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _dataset(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    w = rng.randn(8, 4)
    y = np.argmax(x @ w, axis=1).astype(np.int64)
    return TensorDataset([x, y])


def test_metric_accuracy_topk():
    m = Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.7, 0.2], [0.8, 0.1, 0.1]])
    label = np.array([[1], [2]])
    m.update(pred, label)
    acc1, acc2 = m.accumulate()
    assert acc1 == 0.5 and acc2 == 0.5
    m.update(pred, np.array([[1], [0]]))
    acc1, acc2 = m.accumulate()
    assert abs(acc1 - 0.75) < 1e-9


def test_metric_precision_recall_auc():
    p, r, a = Precision(), Recall(), Auc()
    pred = np.array([0.9, 0.8, 0.2, 0.6])
    label = np.array([1, 0, 1, 1])
    p.update(pred, label)
    r.update(pred, label)
    a.update(pred.reshape(-1, 1), label)
    assert abs(p.accumulate() - 2 / 3) < 1e-9   # TP=2 FP=1
    assert abs(r.accumulate() - 2 / 3) < 1e-9   # TP=2 FN=1
    assert 0.0 <= a.accumulate() <= 1.0


def test_model_fit_overfits_and_metrics():
    with pt.dygraph.guard():
        net = MLP()
        model = Model(net)
        model.prepare(
            optimizer=pt.optimizer.AdamOptimizer(
                5e-3, parameter_list=net.parameters()),
            loss=nn.CrossEntropyLoss(),
            metrics=Accuracy())
    ds = _dataset()
    model.fit(ds, batch_size=16, epochs=25, verbose=0, shuffle=True)
    logs = model.evaluate(ds, batch_size=16, verbose=0)
    assert logs["eval_acc"] > 0.85, logs
    assert logs["eval_loss"] < 0.7


def test_model_predict_shapes():
    with pt.dygraph.guard():
        net = MLP()
        model = Model(net)
        model.prepare(loss=nn.CrossEntropyLoss())
    ds = _dataset(20)
    outs = model.predict(ds, batch_size=8, stack_outputs=True)
    assert len(outs) == 1 and outs[0].shape == (20, 4)


def test_model_save_load_roundtrip(tmp_path):
    with pt.dygraph.guard():
        net = MLP()
        model = Model(net)
        model.prepare(
            optimizer=pt.optimizer.AdamOptimizer(
                5e-3, parameter_list=net.parameters()),
            loss=nn.CrossEntropyLoss())
    ds = _dataset()
    model.fit(ds, batch_size=16, epochs=2, verbose=0)
    ref = model.predict(ds, batch_size=64, stack_outputs=True)[0]
    model.save(str(tmp_path / "ckpt" / "m"))

    with pt.dygraph.guard():
        net2 = MLP()
        model2 = Model(net2)
        model2.prepare(loss=nn.CrossEntropyLoss())
        model2.load(str(tmp_path / "ckpt" / "m"))
    out = model2.predict(ds, batch_size=64, stack_outputs=True)[0]
    np.testing.assert_allclose(ref, out, rtol=1e-5, atol=1e-6)


def test_early_stopping_stops():
    with pt.dygraph.guard():
        net = MLP()
        model = Model(net)
        model.prepare(
            optimizer=pt.optimizer.SGDOptimizer(
                0.0, parameter_list=net.parameters()),  # lr 0 → no progress
            loss=nn.CrossEntropyLoss(),
            metrics=Accuracy())
    ds = _dataset(32)
    es = EarlyStopping(monitor="eval_loss", mode="min", patience=1)
    model.fit(ds, eval_data=ds, batch_size=16, epochs=50, verbose=0,
              callbacks=[es])
    assert model.stop_training


def test_summary_reports_layerwise_params():
    """reference: hapi/model_summary.py — summary walks the Layer tree
    with forward hooks and returns the param totals."""
    import paddle_tpu as pt
    from paddle_tpu import nn

    with pt.dygraph.guard():
        net = nn.Sequential(nn.Linear(32, 16), nn.ReLU(), nn.Linear(16, 4))
        info = pt.summary(net, (1, 32))
        assert info["total_params"] == 32 * 16 + 16 + 16 * 4 + 4
        assert info["trainable_params"] == info["total_params"]
        m = pt.hapi.Model(net)
        assert m.summary(input_size=(1, 32)) == info
        # frozen params drop out of trainable
        for p in net[0].parameters():
            p.stop_gradient = True
        info2 = pt.summary(net, (1, 32))
        assert info2["total_params"] == info["total_params"]
        assert info2["trainable_params"] == 16 * 4 + 4


def test_summary_preserves_training_mode():
    """Regression (round-4 review): summary must not flip a training
    net into eval as a side effect."""
    import paddle_tpu as pt
    from paddle_tpu import nn

    with pt.dygraph.guard():
        net = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5))
        net.train()
        pt.summary(net, (1, 8))
        assert all(lyr.training for lyr in net.sublayers(include_self=True))
        import pytest

        with pytest.raises(ValueError, match="dtypes length"):
            pt.summary(net, [(1, 8), (1, 8)], dtypes=["float32"])
