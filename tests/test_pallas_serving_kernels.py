"""Pallas serving kernels (tier-1 gate): int8 weight-only MXU GEMM +
paged cached-KV decode attention.

Contracts under test (ops/pallas/int8_gemm.py + paged_attention.py and
the wiring behind the ``int8_matmul`` / ``cached_kv_attention`` op
contracts):

* numpy-oracle OpTests for both kernels run in ``PT_PALLAS=interpret``
  (per-channel scales, bias/act epilogue variants, ragged K/N vs the
  tile shape; partially-filled pages, page-0 scratch masking,
  single-token vs multi-slot batches) — this module is in the conftest
  op-sweep set, so the programs also flow through the static verifier;
* ``PT_PALLAS=off`` takes the counted stock lowering
  (``pallas.*_fallbacks``) bitwise-identically to the pre-kernel path;
* jitted interpret-kernel output is BITWISE-identical to the jitted
  stock lowering in the single-block/single-chunk regime, and the
  multi-chunk online-softmax path matches within float tolerance with
  stale positions contributing exactly zero;
* DECODE ENGINE identity (the PR acceptance pin): generations under
  ``PT_PALLAS=interpret`` equal ``PT_PALLAS=off`` token for token —
  greedy + seeded sampling, fp32 + int8;
* fault injection at decode.step composes with the kernel path
  (per-request errors, zero leaked pages — tools/chaos_check.py
  --decode runs the CLI twin);
* the executor/decode compile caches key on kernels_fingerprint()
  (a PT_PALLAS flip RECOMPILES with cause "pallas_kernels"), and
  /v1-stats-visible dispatch counters land in the decode stats payload.
"""

import contextlib
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import telemetry
from paddle_tpu.core.flags import flag as _flag, set_flags

from op_test import OpTest


@contextlib.contextmanager
def _pallas(mode):
    old = os.environ.get("PT_PALLAS")
    os.environ["PT_PALLAS"] = mode
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("PT_PALLAS", None)
        else:
            os.environ["PT_PALLAS"] = old


def _counter(name):
    return int(telemetry.counter_get(name))


# ---------------------------------------------------------------------------
# numpy oracles
# ---------------------------------------------------------------------------

def _gemm_oracle(x, w8, scale, bias=None, act=None):
    out = (x.astype(np.float64) @ w8.astype(np.float64)) \
        * scale.astype(np.float64)
    if bias is not None:
        out = out + bias.astype(np.float64)
    if act == "relu":
        out = np.maximum(out, 0.0)
    return out.astype(np.float32)


def _paged_attn_oracle(q, k, v, pool_k, pool_v, table, pos, n, hd, scale):
    """cached_kv_attention in numpy: write the step K/V, then per-row
    masked softmax attention over the row's gathered pages."""
    pool_k, pool_v = pool_k.copy(), pool_v.copy()
    b, page = q.shape[0], pool_k.shape[1]
    mp = table.shape[1]
    for i in range(b):
        pool_k[table[i, pos[i] // page], pos[i] % page] = k[i]
        pool_v[table[i, pos[i] // page], pos[i] % page] = v[i]
    out = np.zeros((b, n * hd), np.float32)
    for i in range(b):
        ctx_k = pool_k[table[i]].reshape(mp * page, n, hd)
        ctx_v = pool_v[table[i]].reshape(mp * page, n, hd)
        qh = q[i].reshape(n, hd)
        s = np.einsum("nh,snh->ns", qh, ctx_k).astype(np.float64) * scale
        s[:, np.arange(mp * page) > pos[i]] = -1e9
        p = np.exp(s - s.max(axis=-1, keepdims=True))
        p /= p.sum(axis=-1, keepdims=True)
        out[i] = np.einsum("ns,snh->nh", p, ctx_v).reshape(-1)
    return out, pool_k, pool_v


def _mk_paged_case(rng, b, n, hd, page, mp, npages, pos):
    kvdim = n * hd
    pool_k = rng.randn(npages, page, kvdim).astype(np.float32)
    pool_v = rng.randn(npages, page, kvdim).astype(np.float32)
    table = np.zeros((b, mp), np.int32)
    nxt = 1
    for i in range(b):
        need = pos[i] // page + 1
        table[i, :need] = np.arange(nxt, nxt + need)
        nxt += need
    assert nxt <= npages
    q = rng.randn(b, kvdim).astype(np.float32)
    k = rng.randn(b, kvdim).astype(np.float32)
    v = rng.randn(b, kvdim).astype(np.float32)
    return q, k, v, pool_k, pool_v, table, np.asarray(pos, np.int32)


# ---------------------------------------------------------------------------
# OpTests — interpret mode, under the conftest op-sweep (verifier on)
# ---------------------------------------------------------------------------

class _Int8MatmulCase(OpTest):
    op_type = "int8_matmul"
    shape = (6, 64, 128)          # (M, K, N)
    with_bias = False
    act = None
    lead = ()                     # extra leading dims on x

    def setup(self):
        rng = np.random.RandomState(
            sum(map(ord, type(self).__name__)) % 10000)
        m, k, n = self.shape
        x = rng.randn(*self.lead, m, k).astype(np.float32)
        w8 = rng.randint(-127, 128, (k, n)).astype(np.int8)
        scale = ((rng.rand(n) + 0.5) / 127.0).astype(np.float32)
        self.inputs = {"X": x, "Y": w8, "YScale": scale}
        self.attrs = {}
        bias = None
        if self.with_bias:
            bias = rng.randn(n).astype(np.float32)
            self.inputs["Bias"] = bias
        if self.act:
            self.attrs["act"] = self.act
        self.outputs = {"Out": _gemm_oracle(
            x.reshape(-1, k), w8, scale, bias, self.act).reshape(
                *self.lead, m, n)}

    def test_interpret_oracle(self):
        with _pallas("interpret"):
            before = _counter("pallas.int8_gemm_dispatches")
            self.check_output(atol=2e-4, rtol=2e-4)
            assert _counter("pallas.int8_gemm_dispatches") > before


class TestInt8MatmulPerChannel(_Int8MatmulCase):
    pass


class TestInt8MatmulBiasRelu(_Int8MatmulCase):
    # epilogue variants compose: bias-only and act-only are the same
    # _epilogue branches with the other leg skipped
    with_bias = True
    act = "relu"


class TestInt8MatmulRaggedTiledKN(_Int8MatmulCase):
    """Ragged M and K vs the tile shape, N ragged AND spanning two
    output tiles (200 → padded 256, sliced back), bias riding along."""
    shape = (5, 33, 200)
    with_bias = True


class TestInt8Matmul3D(_Int8MatmulCase):
    """The prefill programs feed [B, S, d] activations."""
    shape = (7, 16, 24)
    lead = (2,)


class TestInt8MatmulStaticQuantPreserved(OpTest):
    """The PTQ static-quant mode (act_scale attr) is untouched by the
    weight-only kernel wiring."""
    op_type = "int8_matmul"

    def setup(self):
        rng = np.random.RandomState(11)
        x = rng.randn(4, 32).astype(np.float32)
        w8 = rng.randint(-127, 128, (32, 16)).astype(np.int8)
        scale = ((rng.rand(16) + 0.5) / 127.0).astype(np.float32)
        act_scale = float(np.abs(x).max())
        sx = act_scale / 127.0
        xq = np.clip(np.round(x / sx), -127, 127).astype(np.int8)
        out = (xq.astype(np.int64) @ w8.astype(np.int64)).astype(
            np.float32) * sx * scale
        self.inputs = {"X": x, "Y": w8, "YScale": scale}
        self.attrs = {"act_scale": act_scale}
        self.outputs = {"Out": out}

    def test_interpret_oracle(self):
        with _pallas("interpret"):
            self.check_output(atol=1e-4, rtol=1e-4)


class _PagedAttnCase(OpTest):
    op_type = "cached_kv_attention"
    n, hd, page, mp, npages = 4, 8, 8, 4, 16
    b = 3
    pos = (0, 11, 27)              # page-partial fills on purpose

    def setup(self):
        rng = np.random.RandomState(23)
        n, hd = self.n, self.hd
        q, k, v, pool_k, pool_v, table, pos = _mk_paged_case(
            rng, self.b, n, hd, self.page, self.mp, self.npages,
            list(self.pos))
        scale = hd ** -0.5
        out, pk, pv = _paged_attn_oracle(q, k, v, pool_k, pool_v, table,
                                         pos, n, hd, scale)
        self.inputs = {"Q": q, "K": k, "V": v, "PoolK": pool_k,
                       "PoolV": pool_v, "PageTable": table,
                       "Positions": pos}
        self.attrs = {"num_heads": n, "head_dim": hd, "scale": scale}
        self.outputs = {"Out": out, "PoolKOut": pk, "PoolVOut": pv}

    def test_interpret_oracle(self):
        with _pallas("interpret"):
            before = _counter("pallas.paged_attn_dispatches")
            self.check_output(atol=2e-5, rtol=2e-5)
            assert _counter("pallas.paged_attn_dispatches") > before


class TestPagedAttnPartialPages(_PagedAttnCase):
    pass


class TestPagedAttnSingleToken(_PagedAttnCase):
    """B=1 at position 0 — the first decode step after a 1-token
    prompt."""
    b, pos = 1, (0,)


class TestPagedAttnScratchPageMasked(_PagedAttnCase):
    """An empty slot (all-zero page table) writes to the reserved
    scratch page 0 and attends only over it — the oracle covers that
    row too, proving the write can't corrupt live pages and the row's
    output ignores every stale pool value."""

    def setup(self):
        super().setup()
        # row 0 becomes an empty slot: zero table, position 0
        self.inputs["PageTable"][0] = 0
        self.inputs["Positions"][0] = 0
        # poison every unused pool slot: masked positions must not leak
        q, k, v = (self.inputs[s] for s in ("Q", "K", "V"))
        pool_k = self.inputs["PoolK"]
        pool_v = self.inputs["PoolV"]
        pool_k[8:] = 1e6
        pool_v[8:] = 1e6
        out, pk, pv = _paged_attn_oracle(
            q, k, v, pool_k, pool_v, self.inputs["PageTable"],
            self.inputs["Positions"], self.n, self.hd,
            self.attrs["scale"])
        self.outputs = {"Out": out, "PoolKOut": pk, "PoolVOut": pv}


class TestPagedAttnChunkedOnlineSoftmax(_PagedAttnCase):
    """FLAGS_pallas_kv_chunk_tokens forced below the context length:
    the online-softmax accumulation path, oracle-checked — with every
    stale position poisoned, so a single non-zero masked contribution
    in ANY chunk would blow the comparison (exact-zero masking)."""
    n, hd, page, mp, npages = 2, 8, 8, 4, 12
    b, pos = 2, (20, 30)

    def setup(self):
        super().setup()
        table = self.inputs["PageTable"]
        pos = self.inputs["Positions"]
        pool_v = self.inputs["PoolV"]
        for i in range(self.b):
            for s in range(int(pos[i]) + 1, self.mp * self.page):
                pool_v[table[i, s // self.page], s % self.page] = 1e6
        out, pk, pv = _paged_attn_oracle(
            self.inputs["Q"], self.inputs["K"], self.inputs["V"],
            self.inputs["PoolK"], pool_v, table, pos, self.n, self.hd,
            self.attrs["scale"])
        self.outputs = {"Out": out, "PoolKOut": pk, "PoolVOut": pv}

    def test_interpret_oracle(self):
        from paddle_tpu.core import flags as _flags

        # 2 pages/chunk (typed scoped override, exact restore)
        with _flags.overrides(pallas_kv_chunk_tokens=16):
            with _pallas("interpret"):
                self.check_output(atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# off-mode fallback counters + bitwise stock identity
# ---------------------------------------------------------------------------

class TestCountedFallbacks:
    def test_int8_gemm_off_is_counted_stock_bitwise(self):
        from paddle_tpu.ops.pallas.int8_gemm import (int8_weight_only_gemm,
                                                     stock_int8_gemm)
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        x = rng.randn(6, 48).astype(np.float32)
        w8 = rng.randint(-127, 128, (48, 64)).astype(np.int8)
        sc = ((rng.rand(64) + 0.5) / 127.0).astype(np.float32)
        b = rng.randn(64).astype(np.float32)
        with _pallas("off"):
            before = _counter("pallas.int8_gemm_fallbacks")
            got = np.asarray(int8_weight_only_gemm(x, w8, sc, bias=b,
                                                   act="relu"))
            assert _counter("pallas.int8_gemm_fallbacks") == before + 1
        want = np.asarray(stock_int8_gemm(
            jnp.asarray(x), jnp.asarray(w8), jnp.asarray(sc),
            jnp.asarray(b), "relu"))
        assert np.array_equal(got, want)

    def test_paged_attn_off_is_counted_stock_bitwise(self):
        """PT_PALLAS=off must produce byte-identical results to the
        pre-kernel einsum lowering (inlined here as the frozen
        reference)."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.paged_attention import \
            paged_decode_attention

        rng = np.random.RandomState(1)
        n, hd, page, mp = 4, 8, 8, 4
        q, k, v, pool_k, pool_v, table, pos = _mk_paged_case(
            rng, 3, n, hd, page, mp, 16, [3, 14, 30])
        scale = hd ** -0.5
        # the step write, shared by every route
        phys = table[np.arange(3), pos // page]
        pool_k[phys, pos % page] = k
        pool_v[phys, pos % page] = v

        def legacy(q, pool_k, pool_v, table, pos):
            b = q.shape[0]
            ctx_k = pool_k[table].reshape(b, mp * page, -1)
            ctx_v = pool_v[table].reshape(b, mp * page, -1)
            qh = q.reshape(b, n, hd)
            kh = ctx_k.reshape(b, mp * page, n, hd)
            vh = ctx_v.reshape(b, mp * page, n, hd)
            scores = jnp.einsum("bnh,bsnh->bns", qh, kh) * scale
            mask = jnp.arange(mp * page, dtype=jnp.int32)[None, None, :] \
                <= pos[:, None, None]
            scores = jnp.where(mask, scores, -1e9)
            probs = jax.nn.softmax(scores, axis=-1)
            return jnp.einsum("bns,bsnh->bnh", probs, vh).reshape(
                b, n * hd)

        with _pallas("off"):
            before = _counter("pallas.paged_attn_fallbacks")
            got = np.asarray(jax.jit(
                lambda *a: paged_decode_attention(
                    *a, num_heads=n, head_dim=hd, scale=scale))(
                        q, pool_k, pool_v, table, pos))
            assert _counter("pallas.paged_attn_fallbacks") == before + 1
        want = np.asarray(jax.jit(legacy)(q, pool_k, pool_v, table, pos))
        assert np.array_equal(got, want)


class TestInterpretBitwise:
    """Jitted interpret kernel == jitted stock lowering, bit for bit,
    in the single-block / single-chunk regime (the decode engine's)."""

    def test_int8_gemm_interpret_bitwise_vs_off(self):
        import functools

        import jax

        from paddle_tpu.ops.pallas.int8_gemm import int8_weight_only_gemm

        rng = np.random.RandomState(2)
        x = rng.randn(8, 64).astype(np.float32)
        w8 = rng.randint(-127, 128, (64, 128)).astype(np.int8)
        sc = ((rng.rand(128) + 0.5) / 127.0).astype(np.float32)
        b = rng.randn(128).astype(np.float32)
        with _pallas("off"):
            off = np.asarray(jax.jit(functools.partial(
                int8_weight_only_gemm, act="relu"))(x, w8, sc, b))
        with _pallas("interpret"):
            it = np.asarray(jax.jit(functools.partial(
                int8_weight_only_gemm, act="relu"))(x, w8, sc, b))
        assert np.array_equal(off, it)

    def test_paged_attn_interpret_bitwise_vs_off(self):
        import jax

        from paddle_tpu.ops.pallas.paged_attention import \
            paged_decode_attention

        rng = np.random.RandomState(3)
        n, hd, page, mp = 4, 16, 16, 8
        q, k, v, pool_k, pool_v, table, pos = _mk_paged_case(
            rng, 4, n, hd, page, mp, 24, [0, 17, 63, 99])
        scale = hd ** -0.5
        phys = table[np.arange(4), pos // page]
        pool_k[phys, pos % page] = k
        pool_v[phys, pos % page] = v

        def run(mode):
            with _pallas(mode):
                # fresh closure per mode: jax shares trace caches across
                # jit wrappers of one function object, which would hand
                # the second mode the first mode's lowering
                return np.asarray(jax.jit(
                    lambda *a: paged_decode_attention(
                        *a, num_heads=n, head_dim=hd, scale=scale))(
                            q, pool_k, pool_v, table, pos))

        off, it = run("off"), run("interpret")
        assert np.array_equal(off, it)

# ---------------------------------------------------------------------------
# decode-engine identity: the PR acceptance gate
# ---------------------------------------------------------------------------

def _gen_all(mode, quant, prompts, seed=0):
    """One engine per (mode, quant): greedy AND seeded-sampled
    generations through the same engine (one compile pays for both
    sampling disciplines)."""
    from paddle_tpu.models.decoder_lm import DecoderLMConfig
    from paddle_tpu.serving.decode import DecodeConfig, demo_engine

    with _pallas(mode):
        cfg = DecodeConfig(max_slots=4, page_size=16, kv_pages=24,
                           weight_quant=quant, prefill_buckets=[32])
        # small vocab/short max_seq/one layer keep the per-mode compiles
        # cheap; d_model/n_head stay at the kernel-relevant defaults and
        # the multi-layer kernel path is covered by the 2-layer chaos
        # engine below
        eng = demo_engine(cfg, model_cfg=DecoderLMConfig(
            vocab_size=128, max_seq_len=64, n_layers=1), seed=seed)
        eng.start()
        try:
            # all requests in flight at once (continuous batching):
            # continuous == sequential is already tier-1-pinned by
            # PR 12, so the interpret-vs-off comparison is unaffected
            # and the engine finishes in ~max_steps instead of Σsteps
            reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
            reqs += [eng.submit(p, max_new_tokens=8, temperature=0.8,
                                seed=100 + i)
                     for i, p in enumerate(prompts)]
            return [np.asarray(r.result(timeout=120)) for r in reqs]
        finally:
            eng.close(drain=True, timeout=10)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(7)
    return [rng.randint(3, 200, rng.randint(3, 20)).astype(np.int32)
            for _ in range(3)]


@pytest.mark.serving
class TestDecodeInterpretIdentity:
    """The acceptance pin: PT_PALLAS=interpret decode output is
    bitwise-identical to PT_PALLAS=off — greedy + seeded sampling,
    fp32 + int8."""

    def test_fp32_greedy_and_sampled(self, prompts):
        off = _gen_all("off", "none", prompts)
        it = _gen_all("interpret", "none", prompts)
        assert all(np.array_equal(a, b) for a, b in zip(off, it))

    def test_int8_greedy_and_sampled(self, prompts):
        off = _gen_all("off", "int8", prompts)
        it = _gen_all("interpret", "int8", prompts)
        assert all(np.array_equal(a, b) for a, b in zip(off, it))


# ---------------------------------------------------------------------------
# chaos composition + cache keys + stats surfaces
# ---------------------------------------------------------------------------

@pytest.mark.serving
@pytest.mark.chaos
def test_step_fault_stats_and_capture_on_kernel_path(scope):
    """One interpret-mode engine session proving three contracts:
    decode.step fault injection composes with the kernel path (typed
    per-request errors, pages back to baseline, engine stays live);
    the /v1/stats decode payload exposes the pallas dispatch counters +
    kernels fingerprint; and the cost capture keys on the kernel
    variant (a second off-mode engine lands under NEW keys)."""
    from paddle_tpu.core import costmodel, faults
    from paddle_tpu.models.decoder_lm import DecoderLMConfig
    from paddle_tpu.serving.decode import DecodeConfig, demo_engine

    set_flags({"cost_capture": "cost"})
    costmodel.reset()
    cfg = DecoderLMConfig(vocab_size=128, d_model=32, n_head=2,
                          n_layers=2, max_seq_len=32, d_inner=64)
    dcfg = dict(max_slots=4, page_size=8, kv_pages=20,
                prefill_buckets=[16])
    try:
        with _pallas("interpret"):
            eng = demo_engine(DecodeConfig(**dcfg), model_cfg=cfg)
            eng.start(warmup=True)
            baseline = eng.pool.free_pages()
            faults.configure("decode.step:@2")
            try:
                rng = np.random.RandomState(5)
                reqs = [eng.submit(
                    rng.randint(3, 120, 5).astype(np.int32),
                    max_new_tokens=6) for _ in range(6)]
                errors = 0
                for r in reqs:
                    try:
                        r.result(timeout=60)
                    except Exception:
                        errors += 1
                assert errors >= 1   # the injected step fault surfaced
                faults.configure("")
                # engine still live on the kernel path after the fault
                out = eng.generate(np.asarray([5, 6, 7], np.int32),
                                   max_new_tokens=4, timeout=60)
                assert np.asarray(out).size == 4
                assert eng.pool.free_pages() == baseline
                stats = eng.stats()
            finally:
                faults.configure("")
                eng.close(drain=True, timeout=10)
        assert stats["pallas"]["kernels"].startswith("interpret")
        assert stats["pallas"].get("paged_attn_dispatches", 0) > 0
        kern_keys = {r.key_id for r in costmodel.programs()
                     if r.kind == "decode"}
        assert kern_keys
        # an off-mode engine's captures land under NEW keys: the pallas
        # fingerprint is part of the capture identity
        with _pallas("off"):
            eng = demo_engine(DecodeConfig(**dcfg), model_cfg=cfg)
            eng.start()
            eng.generate(np.asarray([3, 4], np.int32), max_new_tokens=2,
                         timeout=60)
            off_stats = eng.stats()
            eng.close(drain=True, timeout=10)
        assert off_stats["pallas"]["kernels"].startswith("off")
        off_keys = {r.key_id for r in costmodel.programs()
                    if r.kind == "decode"} - kern_keys
        assert off_keys
    finally:
        set_flags({"cost_capture": "auto"})
        costmodel.reset()


def test_executor_recompiles_on_kernel_mode_flip(scope, tmp_path):
    """kernels_fingerprint() is a compile-cache key component: flipping
    PT_PALLAS between runs of one program RECOMPILES with the cause
    named — reusing the other mode's lowering would silently serve
    stale kernels (and blur per-variant cost capture)."""
    import json

    log = tmp_path / "run.jsonl"
    telemetry.configure(str(log))
    try:
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.static_data("x", [4, 8], "float32")
            y = layers.relu(x)
        exe = pt.Executor()
        feed = {"x": np.ones((4, 8), np.float32)}
        before = _counter("executor.compiles")
        with _pallas("off"):
            exe.run(main, feed=feed, fetch_list=[y.name], scope=scope)
        with _pallas("interpret"):
            exe.run(main, feed=feed, fetch_list=[y.name], scope=scope)
        assert _counter("executor.compiles") == before + 2
        telemetry.flush_sink()
        with open(log) as f:
            recs = [json.loads(line) for line in f if line.strip()]
        compiles = [r for r in recs if r.get("kind") == "compile"
                    and r.get("name") == "executor"]
        assert len(compiles) == 2
        assert compiles[1]["attrs"]["cause"] == "pallas_kernels"
        assert compiles[1]["attrs"]["pallas_kernels"].startswith(
            "interpret|")
    finally:
        telemetry.configure(None)


