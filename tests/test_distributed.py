"""Distributed/sharding tests on the 8-virtual-device CPU mesh.

Mirrors the reference's dist-test strategy (test_dist_base.py:1007 loss
parity 1→N workers) — here single-process over mesh slices (SURVEY.md §4).
"""

import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import create_mesh, mesh
from paddle_tpu.parallel.api import shard_tensor


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    mesh.set_mesh(None)


def _mlp_program(lr=0.1):
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        img = layers.data("img", [32])
        label = layers.data("label", [1], dtype="int64")
        h = layers.fc(img, 64, act="relu")
        logits = layers.fc(h, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.SGDOptimizer(lr).minimize(loss)
    return main, startup, loss


def _feed(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {"img": rng.randn(n, 32).astype(np.float32),
            "label": rng.randint(0, 10, (n, 1)).astype(np.int64)}


def test_mesh_creation():
    import jax

    m = create_mesh({"dp": 2, "mp": 4})
    assert m.shape["dp"] == 2 and m.shape["mp"] == 4
    m2 = create_mesh({"dp": -1, "mp": 2})
    assert m2.shape["dp"] == len(jax.devices()) // 2


def test_dp_loss_matches_single_device():
    """1-device vs 8-device data-parallel loss parity (the reference's
    parallel_executor_test_base.py pattern)."""
    feed = _feed(16)
    # single device
    main, startup, loss = _mlp_program()
    exe = pt.Executor(pt.CPUPlace())
    s1 = pt.Scope()
    exe.run(startup, scope=s1, use_compiled=False)
    params = {k: np.array(v) for k, v in s1.items()}
    l1, = exe.run(main, feed=feed, fetch_list=[loss], scope=s1)
    # 8-device dp over same params: same global batch → same loss & update
    m = create_mesh({"dp": 8})
    s2 = pt.Scope()
    for k, v in params.items():
        s2.set(k, v)
    l2, = exe.run(main, feed=feed, fetch_list=[loss], scope=s2, mesh=m)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    # params after one step must match too
    for pname in [p.name for p in main.all_parameters()]:
        np.testing.assert_allclose(np.array(s1.find_var(pname)),
                                   np.array(s2.find_var(pname)),
                                   rtol=1e-4, atol=1e-6)


def test_tp_sharded_weight_matches_replicated():
    feed = _feed(16)
    main, startup, loss = _mlp_program()
    # annotate first fc weight column-parallel over mp
    w = next(p for p in main.all_parameters() if p.shape == (32, 64))
    shard_tensor(w, (None, "mp"))
    exe = pt.Executor(pt.CPUPlace())
    s1 = pt.Scope()
    exe.run(startup, scope=s1, use_compiled=False)
    params = {k: np.array(v) for k, v in s1.items()}
    l1, = exe.run(main, feed=feed, fetch_list=[loss], scope=s1)

    m = create_mesh({"dp": 2, "mp": 4})
    s2 = pt.Scope()
    for k, v in params.items():
        s2.set(k, v)
    l2, = exe.run(main, feed=feed, fetch_list=[loss], scope=s2, mesh=m)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    # the weight really is sharded over mp
    sharded = s2.find_var(w.name)
    assert "mp" in str(sharded.sharding.spec)


def test_graft_dryrun_multichip():
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


@pytest.mark.skipif(os.environ.get("PT_SKIP_MULTIPROC") == "1",
                    reason="multiprocess rendezvous disabled")
def test_spawn_launches_cluster(tmp_path):
    """distributed.spawn (reference: distributed/spawn.py) must start
    nprocs fresh processes with the per-rank PADDLE_* env and a shared
    coordination service that jax.distributed joins."""
    import json

    import paddle_tpu.distributed as dist
    from tests.spawn_fixture import write_env_info

    dist.spawn(write_env_info, args=(str(tmp_path),), nprocs=2)
    infos = []
    for r in range(2):
        with open(tmp_path / f"rank{r}.json") as f:
            infos.append(json.load(f))
    assert [i["rank"] for i in infos] == [0, 1]
    assert all(i["world_size"] == 2 for i in infos)
    assert all(i["initialized"] for i in infos)
    assert all(i["process_count"] == 2 for i in infos)
    assert sorted(i["process_index"] for i in infos) == [0, 1]
    for i in infos:
        assert len(i["endpoints"]) == 2
        assert i["current_endpoint"] == i["endpoints"][i["rank"]]


def test_parallel_env_reads_cluster_vars(monkeypatch):
    import paddle_tpu.distributed as dist

    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "8")
    monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                       "10.0.0.1:6170,10.0.0.2:6170")
    monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "10.0.0.2:6170")
    env = dist.ParallelEnv()
    assert env.rank == 3 and env.world_size == 8
    assert env.trainer_endpoints == ["10.0.0.1:6170", "10.0.0.2:6170"]
    assert env.current_endpoint == "10.0.0.2:6170"


@pytest.mark.skipif(os.environ.get("PT_SKIP_MULTIPROC") == "1",
                    reason="multiprocess rendezvous disabled")
def test_spawn_terminates_survivors_on_failure(tmp_path):
    """A crashed rank must not hang the launcher: the surviving rank
    (blocked in the collective rendezvous) is terminated and spawn
    raises promptly (reference mp.spawn semantics)."""
    import time

    import paddle_tpu.distributed as dist
    from tests.spawn_fixture import crash_on_rank1

    t0 = time.time()
    with pytest.raises(RuntimeError, match="1 of 2 processes failed"):
        dist.spawn(crash_on_rank1, args=(str(tmp_path),), nprocs=2)
    assert time.time() - t0 < 60  # far below the rendezvous timeout
