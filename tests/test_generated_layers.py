"""Round-5 generated fluid.layers surface (layers/generated.py — the
layer_function_generator mirror) + namespace aliases."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _run(build, feeds, fetch_n=1):
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        outs = build()
    outs = outs if isinstance(outs, (list, tuple)) else [outs]
    exe = pt.Executor()
    sc = pt.Scope()
    exe.run(startup, scope=sc, use_compiled=False)
    got = exe.run(main, feed=feeds, fetch_list=list(outs), scope=sc)
    return [np.asarray(g) for g in got]


class TestGeneratedTable:
    def test_unary_binary_family(self):
        x = np.array([[-2.0, -0.5, 0.5, 2.0]], np.float32)
        y = np.array([[1.0, -0.5, 0.25, 3.0]], np.float32)

        def build():
            xv = layers.static_data("x", [1, 4])
            yv = layers.static_data("y", [1, 4])
            return [layers.brelu(xv, t_min=-1.0, t_max=1.0),
                    layers.hard_shrink(xv, threshold=0.6),
                    layers.logical_or(layers.less_equal(xv, yv),
                                      layers.greater_equal(xv, yv)),
                    layers.elementwise_floordiv(
                        layers.cast(xv, "int64") + 4,
                        layers.cast(yv, "int64") * 0 + 2)]

        b, h, lo, fd = _run(build, {"x": x, "y": y})
        np.testing.assert_allclose(b, np.clip(x, -1, 1))
        np.testing.assert_allclose(h, np.where(np.abs(x) > 0.6, x, 0))
        assert lo.dtype == np.bool_ and lo.all()
        np.testing.assert_array_equal(fd, (x.astype(np.int64) + 4) // 2)

    def test_gather_scatter_shape(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)

        def build():
            xv = layers.static_data("x", [3, 4])
            idx = layers.cast(layers.fill_constant([2, 1], "int64", 1.0),
                              "int64")
            return [layers.gather_nd(xv, idx), layers.shape(xv),
                    layers.size(xv)]

        g, sh, sz = _run(build, {"x": x})
        np.testing.assert_allclose(g, np.stack([x[1], x[1]]))
        np.testing.assert_array_equal(sh, [3, 4])
        assert int(sz) == 12

    def test_compositions(self):
        x = np.array([[1.0, np.inf], [np.nan, 2.0]], np.float32)

        def build():
            xv = layers.static_data("x", [2, 2])
            fin = layers.static_data("f", [2, 2])
            return [layers.has_nan(xv), layers.has_inf(xv),
                    layers.has_nan(fin), layers.has_inf(fin),
                    layers.smooth_l1(fin, fin * 0.5)]

        hn, hi, fn_, fi, sl1 = _run(
            build, {"x": x, "f": np.ones((2, 2), np.float32)})
        assert bool(hn) and bool(hi)
        assert not bool(fn_) and not bool(fi)
        # smooth_l1 of d=0.5: 0.5*0.25 = 0.125 per element, 2 per row
        np.testing.assert_allclose(sl1, [[0.25], [0.25]], atol=1e-6)

    def test_losses_and_rnn_wrappers(self):
        B, S, H4 = 2, 3, 8

        def build():
            pre = layers.static_data("pre", [B, S, H4])
            out, last_c = layers.dynamic_lstm(pre, H4)
            gout = layers.dynamic_gru(
                layers.static_data("pre3", [B, S, 6]), 2)
            hub = layers.huber_loss(
                layers.static_data("a", [2, 2]),
                layers.static_data("b", [2, 2]), delta=1.0)
            return [out, gout, hub]

        rng = np.random.RandomState(0)
        o, g, h = _run(build, {
            "pre": rng.randn(B, S, H4).astype(np.float32),
            "pre3": rng.randn(B, S, 6).astype(np.float32),
            "a": rng.randn(2, 2).astype(np.float32),
            "b": rng.randn(2, 2).astype(np.float32)})
        assert o.shape == (B, S, 2) and g.shape == (B, S, 2)
        assert np.isfinite(h).all()

    def test_multi_output_unique(self):
        x = np.array([3, 1, 3, 2, 1], np.int64)

        def build():
            xv = layers.static_data("x", [5], "int64")
            out, idx = layers.unique(xv)
            return [out, idx]

        out, idx = _run(build, {"x": x})
        assert set(out[:3].tolist()) >= {1, 2, 3} or len(out) >= 3

    def test_case_switch_case(self):
        def build():
            one = layers.fill_constant([1], "float32", 1.0)
            p1 = layers.less_than(one, one)           # False
            p2 = layers.less_than(one, one * 2)       # True
            r = layers.case([(p1, lambda: one * 10),
                             (p2, lambda: one * 20)],
                            default=lambda: one * 30)
            idx = layers.cast(layers.fill_constant([1], "int64", 1.0),
                              "int64")
            s = layers.switch_case(idx, {0: lambda: one * 5,
                                         1: lambda: one * 7},
                                   default=lambda: one * 9)
            return [r, s]

        r, s = _run(build, {})
        assert float(r) == 20.0 and float(s) == 7.0

    def test_namespace_aliases(self):
        import paddle_tpu.dygraph as D
        import paddle_tpu.static.nn as SN

        assert D.BatchNorm is not None and D.Linear is not None
        assert callable(SN.conv3d) and callable(SN.case)
        assert callable(layers.GRUCell) and callable(layers.LSTMCell)
