"""Crash-consistent checkpoint tests: save/restore roundtrip, async save,
manager retention + auto-resume (the checkpoint-restart failure-recovery
path — SURVEY.md §5), and the atomic-commit/verify/quarantine protocol
(torn writes, injected save/restore faults, RNG capture, fallback to the
newest valid checkpoint)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], stop_gradient=True)
        y = layers.fc(x, 8, act="relu")
        loss = layers.mean(y)
        pt.optimizer.AdamOptimizer(0.05).minimize(loss)
    return main, startup, loss


class TestCheckpoint:
    def test_roundtrip_resumes_training_state(self, tmp_path, scope):
        from paddle_tpu.checkpoint import load_checkpoint, save_checkpoint

        main, startup, loss = _program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        for _ in range(3):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        step_at_save = int(np.asarray(scope.find_var("@STEP_COUNTER@")))
        save_checkpoint(str(tmp_path / "ck"), main, scope)
        want, = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)

        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        step = load_checkpoint(str(tmp_path / "ck"), main, scope2)
        assert step == step_at_save   # optimizer state + step restored
        got, = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope2)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_async_save(self, tmp_path, scope):
        from paddle_tpu.checkpoint import (load_checkpoint, save_checkpoint,
                                           wait_for_checkpoint)

        main, startup, loss = _program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        save_checkpoint(str(tmp_path / "a"), main, scope, async_save=True)
        wait_for_checkpoint()
        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        load_checkpoint(str(tmp_path / "a"), main, scope2)

    def test_manager_retention_and_resume(self, tmp_path, scope):
        from paddle_tpu.checkpoint import CheckpointManager

        main, startup, loss = _program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.ones((4, 4), np.float32)
        mgr = CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2,
                                async_save=False)
        for step in range(1, 5):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
            mgr.save(step, main, scope)
        mgr.wait_until_finished()
        assert mgr.latest_step() == 4
        assert len(mgr.all_steps()) == 2   # retention

        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        mgr2 = CheckpointManager(str(tmp_path / "mgr"), async_save=False)
        resumed = mgr2.restore_latest(main, scope2)
        assert resumed == 4
        w1, = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        w2, = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope2)
        np.testing.assert_allclose(w2, w1, atol=1e-6)
        mgr.close()
        mgr2.close()


def _corrupt(path):
    """Flip one byte in the middle of a file."""
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(raw))


def _trained(tmp_path, scope, steps=2):
    main, startup, loss = _program()
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    x = np.ones((4, 4), np.float32)
    for _ in range(steps):
        exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
    return main, startup, loss, exe


class TestCrashConsistency:
    """The atomic-commit + manifest-verification protocol."""

    def test_commit_manifest_contents(self, tmp_path, scope):
        from paddle_tpu.checkpoint import (DATA_NAME, FORMAT, MANIFEST_NAME,
                                           save_checkpoint)

        main, startup, loss, exe = _trained(tmp_path, scope)
        p = save_checkpoint(str(tmp_path / "ck"), main, scope)
        with open(os.path.join(p, MANIFEST_NAME)) as f:
            man = json.load(f)
        assert man["format"] == FORMAT and man["committed"] is True
        assert man["seq"] >= 1 and man["data_file"] == DATA_NAME
        assert os.path.getsize(os.path.join(p, DATA_NAME)) == \
            man["data_nbytes"]
        w_name = next(n for n in man["arrays"] if "w" in n.lower()
                      or "fc" in n.lower())
        spec = man["arrays"][w_name]
        assert set(spec) == {"shape", "dtype", "crc32", "nbytes"}
        assert "rng" in man["extras"]   # exact-resume RNG capture

    def test_load_rejects_corrupt_data(self, tmp_path, scope):
        from paddle_tpu.checkpoint import (CheckpointCorruptError, DATA_NAME,
                                           load_checkpoint, save_checkpoint)

        main, startup, loss, exe = _trained(tmp_path, scope)
        p = save_checkpoint(str(tmp_path / "ck"), main, scope)
        _corrupt(os.path.join(p, DATA_NAME))
        with pytest.raises(CheckpointCorruptError):
            load_checkpoint(p, main, pt.Scope())

    def test_load_rejects_uncommitted(self, tmp_path, scope):
        from paddle_tpu.checkpoint import (CheckpointCorruptError,
                                           MANIFEST_NAME, load_checkpoint,
                                           save_checkpoint)

        main, startup, loss, exe = _trained(tmp_path, scope)
        p = save_checkpoint(str(tmp_path / "ck"), main, scope)
        os.unlink(os.path.join(p, MANIFEST_NAME))
        with pytest.raises(CheckpointCorruptError):
            load_checkpoint(p, main, pt.Scope())

    def test_restore_latest_falls_back_and_quarantines(self, tmp_path,
                                                       scope):
        from paddle_tpu.checkpoint import (DATA_NAME, QUARANTINE_DIRNAME,
                                           CheckpointManager)
        from paddle_tpu.core import telemetry

        main, startup, loss, exe = _trained(tmp_path, scope)
        x = np.ones((4, 4), np.float32)
        mgr = CheckpointManager(str(tmp_path / "m"), async_save=False)
        for s in (1, 2, 3):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
            mgr.save(s, main, scope)
        _corrupt(os.path.join(mgr.directory, "ckpt-%010d" % 3, DATA_NAME))
        v0 = telemetry.counter_get("ckpt.verify_failures")
        f0 = telemetry.counter_get("ckpt.fallbacks")
        q0 = telemetry.counter_get("ckpt.quarantined")
        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        mgr2 = CheckpointManager(str(tmp_path / "m"), async_save=False)
        assert mgr2.restore_latest(main, scope2) == 2
        assert telemetry.counter_get("ckpt.verify_failures") - v0 == 1
        assert telemetry.counter_get("ckpt.fallbacks") - f0 == 1
        assert telemetry.counter_get("ckpt.quarantined") - q0 == 1
        assert os.path.isdir(os.path.join(mgr.directory,
                                          QUARANTINE_DIRNAME))
        # the rejected step is gone from the candidate set
        assert mgr2.all_steps() == [1, 2]

    def test_stale_staging_dir_is_quarantined(self, tmp_path, scope):
        """A dir a SIGKILLed save left behind is uncommitted garbage:
        never restored from, swept into quarantine."""
        from paddle_tpu.checkpoint import CheckpointManager

        main, startup, loss, exe = _trained(tmp_path, scope)
        mgr = CheckpointManager(str(tmp_path / "m"), async_save=False)
        mgr.save(1, main, scope)
        torn = os.path.join(mgr.directory, ".tmp-ckpt-0000000002-123-9")
        os.makedirs(torn)
        with open(os.path.join(torn, "state.npz"), "wb") as f:
            f.write(b"half a checkpoint")
        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        assert mgr.restore_latest(main, scope2) == 1
        assert not os.path.exists(torn)

    @pytest.mark.chaos
    @pytest.mark.parametrize("site", ["ckpt.save.write", "ckpt.save.commit"])
    def test_injected_save_fault_keeps_previous_checkpoint(
            self, tmp_path, scope, site):
        """A save that dies at either fault site must leave the previous
        checkpoint fully restorable and no torn dir under a final name."""
        from paddle_tpu.checkpoint import CheckpointManager
        from paddle_tpu.core import faults

        main, startup, loss, exe = _trained(tmp_path, scope)
        x = np.ones((4, 4), np.float32)
        mgr = CheckpointManager(str(tmp_path / "m"), async_save=False)
        mgr.save(1, main, scope)
        w1 = np.asarray(scope.find_var(
            main.all_parameters()[0].name)).copy()
        exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        faults.configure(f"{site}:@1:OSError")
        try:
            with pytest.raises(OSError):
                mgr.save(2, main, scope)
        finally:
            faults.configure("")
        assert mgr.all_steps() == [1]   # no torn final dir
        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        mgr2 = CheckpointManager(str(tmp_path / "m"), async_save=False)
        assert mgr2.restore_latest(main, scope2) == 1
        np.testing.assert_array_equal(
            np.asarray(scope2.find_var(main.all_parameters()[0].name)), w1)

    @pytest.mark.chaos
    def test_injected_restore_fault_falls_back(self, tmp_path, scope):
        from paddle_tpu.checkpoint import CheckpointManager
        from paddle_tpu.core import faults, telemetry

        main, startup, loss, exe = _trained(tmp_path, scope)
        x = np.ones((4, 4), np.float32)
        mgr = CheckpointManager(str(tmp_path / "m"), async_save=False)
        for s in (1, 2):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
            mgr.save(s, main, scope)
        f0 = telemetry.counter_get("ckpt.fallbacks")
        faults.configure("ckpt.restore.read:@1:OSError")
        try:
            scope2 = pt.Scope()
            exe.run(startup, scope=scope2, use_compiled=False)
            assert mgr.restore_latest(main, scope2) == 1
        finally:
            faults.configure("")
        assert telemetry.counter_get("ckpt.fallbacks") - f0 == 1

    def test_rng_state_roundtrips(self, tmp_path, scope):
        from paddle_tpu import generator
        from paddle_tpu.checkpoint import (load_checkpoint, save_checkpoint)

        main, startup, loss, exe = _trained(tmp_path, scope)
        generator.default_generator().set_state((777, 5))
        want = generator.get_rng_state()
        p = save_checkpoint(str(tmp_path / "ck"), main, scope)
        generator.default_generator().set_state((1, 0))
        load_checkpoint(p, main, pt.Scope())
        got = generator.get_rng_state()
        assert tuple(got[0]) == tuple(want[0])

    def test_save_sequence_is_monotonic(self, tmp_path, scope):
        from paddle_tpu.checkpoint import MANIFEST_NAME, CheckpointManager

        main, startup, loss, exe = _trained(tmp_path, scope)
        mgr = CheckpointManager(str(tmp_path / "m"), max_to_keep=10,
                                async_save=False)
        mgr.save(1, main, scope)
        mgr.save(2, main, scope)
        # a new manager over the same dir resumes the sequence, never
        # reuses a number (the manifest's total order survives restarts)
        mgr2 = CheckpointManager(str(tmp_path / "m"), max_to_keep=10,
                                 async_save=False)
        mgr2.save(3, main, scope)
        seqs = []
        for s in (1, 2, 3):
            with open(os.path.join(mgr.directory, "ckpt-%010d" % s,
                                   MANIFEST_NAME)) as f:
                seqs.append(json.load(f)["seq"])
        assert seqs == sorted(seqs) and len(set(seqs)) == 3

    def test_async_save_failure_surfaces_on_wait(self, tmp_path, scope):
        from paddle_tpu.checkpoint import (CheckpointManager,
                                           wait_for_checkpoint)
        from paddle_tpu.core import faults

        main, startup, loss, exe = _trained(tmp_path, scope)
        mgr = CheckpointManager(str(tmp_path / "m"), async_save=True)
        faults.configure("ckpt.save.write:@1:OSError")
        try:
            mgr.save(1, main, scope)
            with pytest.raises(OSError):
                mgr.wait_until_finished()
        finally:
            faults.configure("")
        # the writer survives a failed job: the next save commits
        mgr.save(2, main, scope, force=True)
        wait_for_checkpoint()
        assert mgr.latest_step() == 2

    def test_telemetry_save_accounting(self, tmp_path, scope):
        from paddle_tpu.checkpoint import save_checkpoint
        from paddle_tpu.core import telemetry

        main, startup, loss, exe = _trained(tmp_path, scope)
        s0 = telemetry.counter_get("ckpt.saves")
        b0 = telemetry.counter_get("ckpt.bytes")
        save_checkpoint(str(tmp_path / "ck"), main, scope)
        assert telemetry.counter_get("ckpt.saves") - s0 == 1
        assert telemetry.counter_get("ckpt.bytes") > b0


class TestElasticRunner:
    def test_recovers_from_injected_failure(self, tmp_path):
        """Fault injection (SURVEY §5 failure detection): a step that
        raises mid-training must resume from the last checkpoint and
        converge to the same weights as an uninterrupted run."""
        import numpy as np

        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.distributed.elastic import ElasticRunner
        from paddle_tpu.distributed.errors import RpcError

        def build():
            ir._main_program, ir._startup_program = (ir.Program(),
                                                     ir.Program())
            unique_name.switch()
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = layers.data("x", [8], stop_gradient=True)
                y = layers.fc(x, 1, param_attr=pt.ParamAttr(name="w"),
                              bias_attr=False)
                loss = layers.mean(y * y)
                pt.optimizer.SGDOptimizer(0.1).minimize(loss)
            return main, startup, loss

        feed = {"x": np.random.RandomState(0).randn(4, 8).astype(np.float32)}

        def train(inject_fail, ckpt):
            main, startup, loss = build()
            exe = pt.Executor(pt.CPUPlace())
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            runner = ElasticRunner(str(ckpt), main, scope,
                                   save_interval_steps=1, max_restarts=2)
            failed = [False]

            def step_fn(step):
                if inject_fail and step == 5 and not failed[0]:
                    failed[0] = True
                    # transport-typed: plain RuntimeError is no longer
                    # recoverable (it swallowed programming errors)
                    raise RpcError("injected transport failure")
                out, = exe.run(main, feed=feed, fetch_list=[loss],
                               scope=scope)
                return float(out)

            runner.run(step_fn, 8)
            runner.mgr.close()
            return np.asarray(scope.find_var("w")).copy(), runner.restarts

        w_fail, restarts = train(True, tmp_path / "a")
        w_ok, _ = train(False, tmp_path / "b")
        assert restarts == 1
        np.testing.assert_allclose(w_fail, w_ok, rtol=1e-5)

    def test_unrecoverable_raises_immediately(self, tmp_path):
        import paddle_tpu as pt
        import pytest

        from paddle_tpu.distributed.elastic import ElasticRunner

        runner = ElasticRunner(str(tmp_path / "c"), pt.Program(),
                               pt.Scope(), max_restarts=5)

        def bad(step):
            raise TypeError("programming error")

        with pytest.raises(TypeError):
            runner.run(bad, 3)
        assert runner.restarts == 0
        runner.mgr.close()
