"""Orbax-backed checkpoint tests: save/restore roundtrip, async save,
manager retention + auto-resume (the checkpoint-restart failure-recovery
path — SURVEY.md §5)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def _program():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [4], stop_gradient=True)
        y = layers.fc(x, 8, act="relu")
        loss = layers.mean(y)
        pt.optimizer.AdamOptimizer(0.05).minimize(loss)
    return main, startup, loss


class TestCheckpoint:
    def test_roundtrip_resumes_training_state(self, tmp_path, scope):
        from paddle_tpu.checkpoint import load_checkpoint, save_checkpoint

        main, startup, loss = _program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        for _ in range(3):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        step_at_save = int(np.asarray(scope.find_var("@STEP_COUNTER@")))
        save_checkpoint(str(tmp_path / "ck"), main, scope)
        want, = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)

        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        step = load_checkpoint(str(tmp_path / "ck"), main, scope2)
        assert step == step_at_save   # optimizer state + step restored
        got, = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope2)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_async_save(self, tmp_path, scope):
        from paddle_tpu.checkpoint import (load_checkpoint, save_checkpoint,
                                           wait_for_checkpoint)

        main, startup, loss = _program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        save_checkpoint(str(tmp_path / "a"), main, scope, async_save=True)
        wait_for_checkpoint()
        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        load_checkpoint(str(tmp_path / "a"), main, scope2)

    def test_manager_retention_and_resume(self, tmp_path, scope):
        from paddle_tpu.checkpoint import CheckpointManager

        main, startup, loss = _program()
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        x = np.ones((4, 4), np.float32)
        mgr = CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2,
                                async_save=False)
        for step in range(1, 5):
            exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
            mgr.save(step, main, scope)
        mgr.wait_until_finished()
        assert mgr._mgr.latest_step() == 4
        assert len(list(mgr._mgr.all_steps())) == 2   # retention

        scope2 = pt.Scope()
        exe.run(startup, scope=scope2, use_compiled=False)
        mgr2 = CheckpointManager(str(tmp_path / "mgr"), async_save=False)
        resumed = mgr2.restore_latest(main, scope2)
        assert resumed == 4
        w1, = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope)
        w2, = exe.run(main, feed={"x": x}, fetch_list=[loss], scope=scope2)
        np.testing.assert_allclose(w2, w1, atol=1e-6)
        mgr.close()
        mgr2.close()


class TestElasticRunner:
    def test_recovers_from_injected_failure(self, tmp_path):
        """Fault injection (SURVEY §5 failure detection): a step that
        raises mid-training must resume from the last checkpoint and
        converge to the same weights as an uninterrupted run."""
        import numpy as np

        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.core import ir, unique_name
        from paddle_tpu.distributed.elastic import ElasticRunner
        from paddle_tpu.distributed.errors import RpcError

        def build():
            ir._main_program, ir._startup_program = (ir.Program(),
                                                     ir.Program())
            unique_name.switch()
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = layers.data("x", [8], stop_gradient=True)
                y = layers.fc(x, 1, param_attr=pt.ParamAttr(name="w"),
                              bias_attr=False)
                loss = layers.mean(y * y)
                pt.optimizer.SGDOptimizer(0.1).minimize(loss)
            return main, startup, loss

        feed = {"x": np.random.RandomState(0).randn(4, 8).astype(np.float32)}

        def train(inject_fail, ckpt):
            main, startup, loss = build()
            exe = pt.Executor(pt.CPUPlace())
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            runner = ElasticRunner(str(ckpt), main, scope,
                                   save_interval_steps=1, max_restarts=2)
            failed = [False]

            def step_fn(step):
                if inject_fail and step == 5 and not failed[0]:
                    failed[0] = True
                    # transport-typed: plain RuntimeError is no longer
                    # recoverable (it swallowed programming errors)
                    raise RpcError("injected transport failure")
                out, = exe.run(main, feed=feed, fetch_list=[loss],
                               scope=scope)
                return float(out)

            runner.run(step_fn, 8)
            runner.mgr.close()
            return np.asarray(scope.find_var("w")).copy(), runner.restarts

        w_fail, restarts = train(True, tmp_path / "a")
        w_ok, _ = train(False, tmp_path / "b")
        assert restarts == 1
        np.testing.assert_allclose(w_fail, w_ok, rtol=1e-5)

    def test_unrecoverable_raises_immediately(self, tmp_path):
        import paddle_tpu as pt
        import pytest

        from paddle_tpu.distributed.elastic import ElasticRunner

        runner = ElasticRunner(str(tmp_path / "c"), pt.Program(),
                               pt.Scope(), max_restarts=5)

        def bad(step):
            raise TypeError("programming error")

        with pytest.raises(TypeError):
            runner.run(bad, 3)
        assert runner.restarts == 0
        runner.mgr.close()
