"""Fleet observatory + goodput ledger tests (PR 16).

Covers the mergeable-histogram exposition (cumulative pt_*_bucket
series over fixed log-spaced bounds), the exact cross-registry
percentile merge (the acceptance pin: fleet-merged p99 equals the
pooled-sample p99 within one bucket boundary across >= 3 adversarially
skewed member registries), the FleetAggregator scrape/staleness/
straggler machinery with its fleet SLO rules, the live inprocess
cluster serving /fleet/status, the goodput wall-clock attribution of a
real train_from_dataset run (phase fractions sum within 5% of wall),
the router satellite (staleness-aware handle stats + straggler-avoiding
pick), and the fleet_report CLI.
"""

import io
import json
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import fleetobs, incidents, telemetry
from paddle_tpu.core.telemetry import (HIST_BUCKET_BOUNDS, TelemetryRegistry,
                                       bucket_index, bucket_quantile,
                                       merge_bucket_counts)

IN_DIM = 16


@pytest.fixture(autouse=True)
def _clean_planes():
    telemetry.reset()
    incidents.reset()
    fleetobs.reset()
    yield
    fleetobs.reset()
    incidents.reset()
    telemetry.reset()


def _parse_bucket_lines(text, metric):
    """[(le_str, cum_count)] of pt_<metric>_bucket lines in exposition
    order."""
    out = []
    for line in text.splitlines():
        if line.startswith(f"{metric}_bucket{{le="):
            le = line.split('le="', 1)[1].split('"', 1)[0]
            out.append((le, int(float(line.rsplit(" ", 1)[1]))))
    return out


# ---------------------------------------------------------------------------
# exposition format (ISSUE satellite)
# ---------------------------------------------------------------------------

class TestBucketExposition:
    def test_cumulative_le_ordered_inf_terminated(self):
        """pt_*_bucket series are cumulative, le-ascending, and end with
        le="+Inf" equal to _count."""
        reg = TelemetryRegistry()
        vals = [0.02, 0.5, 3.0, 3.1, 40.0, 900.0, 2.5e6, 1e9]
        for v in vals:
            reg.observe("x.ms", v, kind="timer")
        text = reg.prometheus_text()
        rows = _parse_bucket_lines(text, "pt_x_ms")
        assert rows, "no bucket series emitted"
        assert rows[-1][0] == "+Inf"
        assert rows[-1][1] == len(vals)
        finite = [float(le) for le, _ in rows[:-1]]
        assert finite == sorted(finite), "le bounds not ascending"
        counts = [c for _, c in rows]
        assert counts == sorted(counts), "bucket counts not cumulative"
        # the finite bounds are exactly the shared fixed scheme
        assert finite == [float(f"{b}") for b in HIST_BUCKET_BOUNDS]
        assert f"pt_x_ms_count {len(vals)}" in text

    def test_overflow_and_nonfinite_land_in_inf(self):
        reg = TelemetryRegistry()
        reg.observe("y.ms", 1e12, kind="timer")       # past the last bound
        reg.observe("y.ms", float("inf"), kind="timer")
        rows = _parse_bucket_lines(reg.prometheus_text(), "pt_y_ms")
        assert rows[-1] == ("+Inf", 2)
        assert rows[-2][1] == 0, "overflow leaked into a finite bucket"


# ---------------------------------------------------------------------------
# exact merge property (acceptance pin)
# ---------------------------------------------------------------------------

def _rank_quantile(sorted_vals, q):
    """The same rank rule bucket_quantile uses, on raw samples."""
    rank = min(len(sorted_vals) - 1,
               int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[rank]


class TestMergedQuantileProperty:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5, 6, 7])
    def test_merged_p99_matches_pooled_within_one_bucket(self, seed):
        """Fleet-merged bucket p99 == pooled-sample p99 within one
        bucket boundary, across >= 3 member registries under
        adversarial skew (members live on wildly different latency
        scales and contribute wildly different volumes)."""
        rng = np.random.RandomState(seed)
        n_members = 3 + seed % 3
        regs = [TelemetryRegistry() for _ in range(n_members)]
        pooled = []
        for i, reg in enumerate(regs):
            scale = 10.0 ** rng.uniform(-2, 5)        # 0.01ms .. 100s
            n = int(rng.choice([3, 40, 500, 2000]))
            vals = np.abs(rng.lognormal(mean=np.log(scale), sigma=1.5,
                                        size=n))
            for v in vals:
                reg.observe("m.ms", float(v), kind="timer")
            pooled.extend(float(v) for v in vals)
        merged = merge_bucket_counts(
            [reg.hist_buckets()["m.ms"] for reg in regs])
        assert sum(merged) == len(pooled)
        for q in (0.5, 0.9, 0.99):
            est = bucket_quantile(merged, q)
            true = _rank_quantile(sorted(pooled), q)
            true_idx = min(bucket_index(true), len(HIST_BUCKET_BOUNDS) - 1)
            est_idx = min(bucket_index(est), len(HIST_BUCKET_BOUNDS) - 1)
            assert abs(est_idx - true_idx) <= 1, (
                f"q={q}: merged estimate {est} (bucket {est_idx}) vs "
                f"pooled truth {true} (bucket {true_idx})")

    def test_merge_is_exact_count_addition(self):
        regs = [TelemetryRegistry() for _ in range(3)]
        for i, reg in enumerate(regs):
            for v in [0.5 * (i + 1)] * (10 * (i + 1)):
                reg.observe("m.ms", v, kind="timer")
        merged = merge_bucket_counts(
            [reg.hist_buckets()["m.ms"] for reg in regs])
        assert sum(merged) == 10 + 20 + 30
        one = TelemetryRegistry()
        for i in range(3):
            for v in [0.5 * (i + 1)] * (10 * (i + 1)):
                one.observe("m.ms", v, kind="timer")
        assert merged == one.hist_buckets()["m.ms"], \
            "merging members must equal observing into one registry"


# ---------------------------------------------------------------------------
# prometheus text parsing (the scrape side)
# ---------------------------------------------------------------------------

class TestPrometheusParsing:
    def test_roundtrip_from_prometheus_text(self):
        telemetry.counter_add("par.events", 7)
        telemetry.gauge_set("par.depth", 3.5)
        for v in (1.0, 2.0, 300.0):
            telemetry.observe("par.ms", v, kind="timer")
        doc = fleetobs.parse_prometheus(telemetry.prometheus_text())
        assert doc["counters"]["pt_par_events_total"] == 7
        assert doc["gauges"]["pt_par_depth"] == 3.5
        h = doc["hists"]["pt_par_ms"]
        assert h["count"] == 3
        assert h["sum"] == pytest.approx(303.0)
        counts = fleetobs.counts_from_cumulative(h["buckets"])
        assert counts == telemetry.hist_buckets()["par.ms"]

    def test_garbage_lines_are_skipped(self):
        doc = fleetobs.parse_prometheus(
            "# HELP x\nnot a metric line!!\n"
            'pt_ok_total 3\npt_bad{le=}"x" 4\n')
        assert doc["counters"] == {"pt_ok_total": 3.0}


# ---------------------------------------------------------------------------
# the aggregator: staleness, stragglers, rules
# ---------------------------------------------------------------------------

class TestFleetAggregator:
    def test_scrape_marks_stale_without_wedging(self):
        for v in (1.0, 2.0, 5.0):
            telemetry.observe("serving.request_ms", v, kind="timer")
        srv = telemetry.start_metrics_server(port=0)
        try:
            agg = fleetobs.FleetAggregator(interval_s=0.2,
                                           stale_after_s=0.0)
            agg.register("live", srv.url, kind="trainer", stats_url=None)
            agg.register("dead", "http://127.0.0.1:1", kind="trainer",
                         stats_url=None)
            s = agg.scrape_once()
            assert s["ok"] == 1 and s["stale"] == 1
            members = {m["name"]: m for m in agg.members()}
            assert members["live"]["state"] == "OK"
            assert members["dead"]["state"] == "STALE"
            # the dead member never zeroes the fleet view: the merged
            # histogram still carries the live member's data and more
            # passes keep completing (loop not wedged)
            assert agg.fleet_quantile("serving.request_ms", 0.5) > 0
            s2 = agg.scrape_once()
            assert s2["ok"] == 1
            assert members["live"]["consecutive_failures"] == 0
        finally:
            srv.shutdown()

    def test_stale_member_retains_last_known_metrics(self):
        telemetry.observe("serving.request_ms", 7.0, kind="timer")
        srv = telemetry.start_metrics_server(port=0)
        agg = fleetobs.FleetAggregator(interval_s=0.2, stale_after_s=0.0)
        agg.register("m", srv.url, kind="trainer", stats_url=None)
        agg.scrape_once()
        srv.shutdown()
        agg.scrape_once()   # now unreachable -> STALE
        m = {x["name"]: x for x in agg.members()}["m"]
        assert m["state"] == "STALE"
        assert m["consecutive_failures"] >= 1
        # last good scrape retained: the merged view still sees it
        assert agg.fleet_quantile("serving.request_ms", 0.5) > 0

    def test_straggler_detection(self):
        flagged = fleetobs.detect_stragglers(
            {"a": 10.0, "b": 11.0, "c": 9.0, "d": 10.5, "e": 500.0},
            zscore=1.5, min_members=3)
        assert flagged == ["e"]
        # below the member floor: never flag
        assert fleetobs.detect_stragglers(
            {"a": 1.0, "b": 99.0}, zscore=1.0, min_members=3) == []
        # zero spread: never flag
        assert fleetobs.detect_stragglers(
            {"a": 5.0, "b": 5.0, "c": 5.0}, zscore=1.0,
            min_members=3) == []

    def test_member_stale_rule_trips_exactly_once(self):
        agg = fleetobs.FleetAggregator(interval_s=0.2, stale_after_s=0.0)
        agg.register("dead", "http://127.0.0.1:1", kind="trainer",
                     stats_url=None)
        for _ in range(5):
            agg.scrape_once()
        h = agg.watchdog().health()
        rule = h["rules"]["fleet_member_stale"]
        assert rule["trips"] == 1, \
            "one persistent STALE episode must trip exactly once"
        assert "fleet_member_stale" in h["firing"]

    def test_announce_registers_with_default_aggregator(self):
        agg = fleetobs.FleetAggregator(interval_s=1.0)
        fleetobs.set_aggregator(agg)
        fleetobs.announce("trainer-3", "http://127.0.0.1:9999/")
        fleetobs.announce("trainer-3", "http://127.0.0.1:9999")  # idempotent
        members = agg.members()
        assert [m["name"] for m in members] == ["trainer-3"]
        assert members[0]["kind"] == "trainer"
        # re-announce at a NEW url re-points the slot
        fleetobs.announce("trainer-3", "http://127.0.0.1:9998")
        assert {m["url"] for m in agg.members()} == \
            {"http://127.0.0.1:9998"}
        # no aggregator: announce is a no-op, never raises
        fleetobs.set_aggregator(None)
        fleetobs.announce("trainer-4", "http://127.0.0.1:9999")


# ---------------------------------------------------------------------------
# live cluster acceptance: /fleet/status on the router front end
# ---------------------------------------------------------------------------

def _save_mlp(dirname, seed):
    from paddle_tpu import io as pio

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [IN_DIM])
        y = layers.fc(x, 4, param_attr=pt.ParamAttr(
            name="fo_w0", initializer=pt.initializer.Xavier(seed=seed)))
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    pio.save_inference_model(str(dirname), ["x"], [y],
                             main_program=main, scope=scope)
    return str(dirname)


class TestLiveClusterFleet:
    def test_fleet_status_shows_every_member_fresh(self, tmp_path):
        from paddle_tpu import checkpoint as ckpt
        from paddle_tpu.serving import ClusterController, ServingConfig

        model_dir = _save_mlp(tmp_path / "m1", seed=11)
        root = str(tmp_path / "models")
        ckpt.publish_model(root, model_dir, version=1)
        pt.set_flags({"FLAGS_fleet_scrape_interval_s": 0.2})
        cluster = ClusterController(
            root, replicas=2, inprocess=True,
            serving_config=ServingConfig(max_batch_size=4,
                                         batch_timeout_ms=1.0),
            auto_swap=False, fleet=True).start(ready_timeout_s=120)
        try:
            # a little traffic so scraped histograms are non-empty
            x = np.random.RandomState(1).randn(1, IN_DIM).astype(
                np.float32)
            body = json.dumps({"inputs": {"x": x.tolist()}}).encode()
            for _ in range(4):
                urllib.request.urlopen(urllib.request.Request(
                    cluster.url + "/v1/infer", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=30).read()
            deadline = time.monotonic() + 20
            doc = None
            while time.monotonic() < deadline:
                doc = json.loads(urllib.request.urlopen(
                    cluster.url + "/fleet/status", timeout=10).read())
                if doc["passes"] >= 2 and all(
                        m["state"] == "OK" for m in doc["members"]):
                    break
                time.sleep(0.2)
            names = sorted(m["name"] for m in doc["members"])
            assert names == ["replica-0", "replica-1", "router"]
            for m in doc["members"]:
                assert m["state"] == "OK", m
                assert m["scrape_age_s"] is not None
                assert m["scrape_age_s"] < 5.0, \
                    f"stale scrape age on {m['name']}: {m}"
            assert doc["rules"]["trips"] == 0, \
                f"healthy fleet tripped rules: {doc['rules']['firing']}"
            assert "goodput" in doc
            # the merged-bucket surface is live too
            text = urllib.request.urlopen(
                cluster.url + "/fleet/metrics", timeout=10).read().decode()
            assert "pt_fleet_" in text and 'le="+Inf"' in text
            assert "pt_fleet_members " in text.replace("pt_fleet_members_",
                                                       "SKIP")
            # controller stats carry the fleet section
            assert "fleet" in cluster.stats()
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# goodput ledger on a real training run
# ---------------------------------------------------------------------------

class _StubDataset:
    def __init__(self, n, delay_s=0.0):
        self.n, self.delay_s = n, delay_s

    def iter_batches(self):
        for i in range(self.n):
            if self.delay_s:
                time.sleep(self.delay_s)
            yield {"x": np.random.RandomState(800 + i)
                   .randn(4, 8).astype(np.float32)}


def _train_net():
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8], stop_gradient=True)
        y = layers.fc(x, 1, param_attr=pt.ParamAttr(name="gp_w"),
                      bias_attr=False)
        loss = layers.mean(y * y)
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup, loss


class TestGoodputLedger:
    def test_train_from_dataset_breakdown_sums_to_wall(self):
        """Acceptance: an instrumented train_from_dataset run yields a
        goodput breakdown whose phase fractions (productive + badput
        incl. "other") sum within 5% of the measured wall time, with
        goodput.ratio live on /metrics."""
        from paddle_tpu.core import goodput

        goodput.reset()
        main, startup, _loss = _train_net()
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        goodput.start_run()
        exe.train_from_dataset(main, _StubDataset(8, delay_s=0.005),
                               scope=scope)
        b = goodput.breakdown()
        assert b["window"] == "run"
        total = b["productive_ms"] + sum(b["phases"].values())
        assert total == pytest.approx(b["wall_ms"], rel=0.05), \
            f"phases {b['phases']} + productive {b['productive_ms']} " \
            f"!= wall {b['wall_ms']}"
        assert b["productive_ms"] > 0, "device compute never attributed"
        assert b["phases"]["data_wait"] > 0, \
            "reader.data_wait_ms never attributed (5ms/batch injected)"
        assert 0.0 <= b["ratio"] <= 1.0
        # the publish path: goodput.* counters + the live gauge
        goodput.publish()
        c = telemetry.counters()
        assert c.get("goodput.productive_ms") == b["productive_ms"] \
            or c.get("goodput.productive_ms") > 0
        assert "pt_goodput_ratio" in telemetry.prometheus_text()
        for phase in goodput.PHASES:
            assert f"goodput.badput_{phase}_ms" in c

    def test_process_window_fallback(self):
        from paddle_tpu.core import goodput

        goodput.reset()
        b = goodput.breakdown()
        assert b["window"] == "process"
        assert b["wall_ms"] > 0

    def test_incident_dumps_carry_goodput(self):
        from paddle_tpu.core import goodput

        goodput.start_run()
        telemetry.configure("")   # in-memory only
        rec = incidents.report_incident("test", "test.fleet_goodput")
        assert rec is None or True   # report path must not raise
        # the flight-recorder attrs carry the breakdown (read back via
        # the incident index when a sink exists; here just the API)
        assert goodput.breakdown()["window"] == "run"


# ---------------------------------------------------------------------------
# router satellite: staleness-aware stats + straggler-avoiding pick
# ---------------------------------------------------------------------------

class TestRouterSatellite:
    def test_snapshot_exposes_probe_staleness(self):
        from paddle_tpu.serving.router import ReplicaHandle

        h = ReplicaHandle("r0", "http://127.0.0.1:1")
        snap = h.snapshot()
        assert snap["last_probe_age_s"] is None   # never probed
        assert snap["probe_failures"] == 0 and snap["stale"] is False
        h.mark_probe(True, {"queue_depth": 4})
        h.mark_down("boom")
        h.mark_down("boom")
        snap = h.snapshot()
        assert snap["queue_depth"] == 4, \
            "a failed probe must not zero the last-known queue depth"
        assert snap["probe_failures"] == 2 and snap["stale"] is True
        assert snap["last_probe_age_s"] is not None

    def test_score_penalises_stale_handles(self):
        from paddle_tpu.serving.router import ReplicaHandle

        fresh = ReplicaHandle("fresh", "http://127.0.0.1:1")
        fresh.mark_probe(True, {"queue_depth": 2})
        stale = ReplicaHandle("stale", "http://127.0.0.1:2")
        stale.mark_probe(True, {"queue_depth": 0})
        for _ in range(3):
            stale.mark_down("probe failed")
        assert stale.score() > fresh.score(), \
            "queue_depth=0 from a failing probe must not win least-loaded"

    def test_pick_avoids_fleet_stragglers(self):
        from paddle_tpu.serving.router import ReplicaHandle, Router

        class FakeFleet:
            def __init__(self, names):
                self.names = names

            def straggler_names(self):
                return self.names

        r = Router()
        a = r.add_replica("replica-0", "http://127.0.0.1:1")
        b = r.add_replica("replica-1", "http://127.0.0.1:2")
        a.mark_probe(True, {"queue_depth": 0})
        b.mark_probe(True, {"queue_depth": 5})
        # without fleet evidence the idle straggler wins on load
        assert r.pick().name == "replica-0"
        r.attach_fleet(FakeFleet(["replica-0"]))
        for _ in range(4):
            assert r.pick().name == "replica-1", \
                "flagged straggler must lose the first pass"
        # the straggler is still the last resort
        b.mark_down("gone")
        assert r.pick().name == "replica-0"


# ---------------------------------------------------------------------------
# fleet_report CLI
# ---------------------------------------------------------------------------

class TestFleetReportCLI:
    def test_smoke_self_check(self):
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "fleet_report.py"),
             "--smoke"],
            capture_output=True, text=True, cwd=repo, timeout=120)
        assert proc.returncode == 0, proc.stderr
        assert "ok" in proc.stdout

    def test_dark_plane_exits_2(self):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, repo)
        from tools import fleet_report

        assert fleet_report.main(["--url", "http://127.0.0.1:1",
                                  "--timeout", "0.5"]) == 2

    def test_renders_a_live_status_document(self):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, repo)
        from tools import fleet_report

        agg = fleetobs.FleetAggregator(interval_s=0.5)
        srv_reg = telemetry.start_metrics_server(port=0)
        try:
            agg.register("m0", srv_reg.url, kind="trainer",
                         stats_url=None)
            agg.scrape_once()
            buf = io.StringIO()
            live = fleet_report.render(agg.status(), out=buf)
            assert live == 1
            text = buf.getvalue()
            for section in fleet_report.REQUIRED_SECTIONS:
                assert section in text
        finally:
            srv_reg.shutdown()
