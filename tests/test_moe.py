"""Mixture-of-Experts tests: function-level EP parity + program-level
training (greenfield capability — SURVEY.md §2.7 has no EP in the
reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import Mesh, PartitionSpec as P


class TestSwitchMoeFn:
    def test_ep_matches_dense(self):
        from paddle_tpu.parallel.api import get_shard_map
        from paddle_tpu.parallel.moe import switch_moe

        shard_map, kw = get_shard_map()
        rng = np.random.RandomState(0)
        T, H, F, E, EP = 32, 8, 16, 4, 4
        x = jnp.asarray(rng.randn(T, H).astype(np.float32))
        gw = jnp.asarray(rng.randn(H, E).astype(np.float32))
        w1 = jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.1)
        b1 = jnp.asarray(rng.randn(E, F).astype(np.float32) * 0.1)
        w2 = jnp.asarray(rng.randn(E, F, H).astype(np.float32) * 0.1)
        b2 = jnp.asarray(rng.randn(E, H).astype(np.float32) * 0.1)
        out1, aux1 = switch_moe(x, gw, w1, b1, w2, b2)
        mesh = Mesh(np.array(jax.devices()[:EP]), ("ep",))
        f = shard_map(lambda *a: switch_moe(*a), mesh=mesh,
                      in_specs=(P(), P(), P("ep"), P("ep"), P("ep"), P("ep")),
                      out_specs=(P(), P()), **kw)
        out2, aux2 = f(x, gw, w1, b1, w2, b2)
        np.testing.assert_allclose(out1, out2, atol=1e-6)
        np.testing.assert_allclose(aux1, aux2, atol=1e-6)
        g1 = jax.grad(lambda w: jnp.sum(
            switch_moe(x, gw, w, b1, w2, b2)[0] ** 2))(w1)
        g2 = jax.grad(lambda w: jnp.sum(f(x, gw, w, b1, w2, b2)[0] ** 2))(w1)
        np.testing.assert_allclose(g1, g2, atol=1e-6)

    def test_tokens_sharded_all_to_all_matches_dense(self):
        """dp x ep composition (VERDICT r1 item 5): tokens data-parallel
        over the 'ep' axis, slots exchanged via tiled lax.all_to_all.
        With capacity high enough that nothing drops, output rows and
        expert grads must equal the dense single-device run."""
        from paddle_tpu.parallel.api import get_shard_map
        from paddle_tpu.parallel.moe import switch_moe

        shard_map, kw = get_shard_map()
        rng = np.random.RandomState(0)
        T, H, F, E, EP = 32, 16, 8, 4, 4
        x = jnp.asarray(rng.randn(T, H).astype(np.float32))
        gw = jnp.asarray(rng.randn(H, E).astype(np.float32))
        w1 = jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.1)
        b1 = jnp.asarray(rng.randn(E, F).astype(np.float32) * 0.1)
        w2 = jnp.asarray(rng.randn(E, F, H).astype(np.float32) * 0.1)
        b2 = jnp.asarray(rng.randn(E, H).astype(np.float32) * 0.1)
        cf = float(E)           # nothing drops at either sharding
        out_d, _ = switch_moe(x, gw, w1, b1, w2, b2, capacity_factor=cf)
        mesh = Mesh(np.array(jax.devices()[:EP]), ("ep",))
        f = shard_map(
            lambda *a: switch_moe(*a, capacity_factor=cf,
                                  tokens_sharded=True),
            mesh=mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
            out_specs=(P("ep"), P()), **kw)
        out_s, _ = jax.jit(f)(x, gw, w1, b1, w2, b2)
        np.testing.assert_allclose(out_s, out_d, atol=2e-5)

        g_d = jax.grad(lambda w: jnp.sum(switch_moe(
            x, gw, w, b1, w2, b2, capacity_factor=cf)[0] ** 2))(w1)
        g_s = jax.grad(lambda w: jnp.sum(
            f(x, gw, w, b1, w2, b2)[0] ** 2))(w1)
        np.testing.assert_allclose(g_s, g_d, atol=1e-4)

    def test_capacity_drops_overflow(self):
        from paddle_tpu.parallel.moe import switch_moe

        rng = np.random.RandomState(1)
        T, H, F, E = 16, 4, 8, 2
        x = jnp.asarray(rng.randn(T, H).astype(np.float32))
        # zero router: softmax ties, argmax picks expert 0 for EVERY token
        gw = jnp.zeros((H, E))
        w1 = jnp.asarray(rng.randn(E, H, F).astype(np.float32) * 0.1)
        b1 = jnp.zeros((E, F))
        w2 = jnp.asarray(rng.randn(E, F, H).astype(np.float32) * 0.1)
        b2 = jnp.zeros((E, H))
        out, aux = switch_moe(x, gw, w1, b1, w2, b2, capacity_factor=0.5)
        # capacity = ceil(16/2*0.5)=4 → only 4 tokens produce output
        nonzero_rows = int(jnp.sum(jnp.any(out != 0, axis=-1)))
        assert nonzero_rows == 4


class TestMoeProgram:
    def test_moe_mlp_trains_on_ep_mesh(self, scope):
        import paddle_tpu as pt
        from paddle_tpu import layers
        from paddle_tpu.parallel import create_mesh

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8], stop_gradient=True)
            label = layers.data("label", [1], dtype="int64",
                                stop_gradient=True)
            h = layers.fc(x, 16, act="relu")
            moe_out, aux = layers.switch_moe(h, num_experts=4, d_ff=32,
                                             ep_size=4)
            logits = layers.fc(moe_out, 4)
            ce = layers.mean(layers.softmax_with_cross_entropy(logits, label))
            loss = ce + layers.scale(aux, scale=0.01)
            pt.optimizer.AdamOptimizer(5e-3).minimize(loss)

        mesh = create_mesh({"ep": 4})
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(32, 8).astype(np.float32),
                "label": rng.randint(0, 4, (32, 1)).astype(np.int64)}
        losses = []
        for _ in range(10):
            lv, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope,
                          mesh=mesh)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses[-1])


class TestAuxLossGradient:
    def test_router_receives_aux_gradient(self, scope):
        """The load-balancing loss must push gradients into the router
        (a stop-gradient aux output would silently disable balancing)."""
        import paddle_tpu as pt
        from paddle_tpu import layers

        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            x = layers.data("x", [8], stop_gradient=True)
            moe_out, aux = layers.switch_moe(x, num_experts=4, d_ff=16)
            gate = main.global_block().ops[0].inputs["GateW"][0] \
                if "GateW" in main.global_block().ops[0].inputs else None
            gate_var = [v for v in main.global_block().vars.values()
                        if "_gate" in v.name][0]
            grads = pt.gradients([layers.scale(aux, scale=1.0)], [gate_var])
        exe = pt.Executor()
        exe.run(startup, scope=scope, use_compiled=False)
        g, = exe.run(main,
                     feed={"x": np.random.RandomState(0)
                           .randn(16, 8).astype(np.float32)},
                     fetch_list=[grads[0]], scope=scope)
        assert float(np.abs(np.asarray(g)).sum()) > 0
