"""Elastic resize: world-size-changing resume + signal-driven autoscaling
(distributed/scaler.py, distributed/elastic.py, parallel/zero_regroup.py,
the reader's global-cursor re-split, large_scale_kv re-sharding and the
pserver barrier-regrow path).

Contracts under test:
* the reader cursor is GLOBAL: a checkpoint saved at one world size
  restores into any other — each trainer takes its `index % W` residue
  class past the same cursor (reader.cursor_resplits counted);
* large_scale_kv restores into a different shard count (layout is never
  trusted at load) and KVTables rebalances across a changed SERVER
  count with zero leaked / zero duplicated rows;
* ZeRO stage-1/2 optimizer shards regroup across a dp-degree change
  (padded length is a function of the degree) — resume at a different
  degree continues the loss trajectory of the uninterrupted run;
* a degraded-to-survivors sync barrier REGROWS: a revived trainer is
  required again and a brand-new trainer id is admitted (elastic
  admission), with ps.barrier_degraded / ps.barrier_regrown pinned;
* ScalerPolicy: rule order, cooldown suppression, min/max clamping,
  exactly-once decision counters;
* ElasticRunner: windowed restart budget with progress refunds, a
  kind:"scale" ring record per restart, and execute_scale's checkpoint
  -> drain -> relaunch-at-new-world protocol (loss-transparent);
* ClusterController autoscaling: ScaleUp and ScaleDown each fire
  exactly once off the live signals with zero dropped in-flight
  requests through the drain.

tools/chaos_check.py --resize is the CLI twin of the end-to-end story.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import telemetry

_FLAG_DEFAULTS = {
    "FLAGS_ps_degrade_to_survivors": False,
    "FLAGS_ps_elastic_admission": True,
    "FLAGS_elastic_restart_window_s": 0.0,
    "FLAGS_scaler_min_world": 1,
    "FLAGS_scaler_max_world": 8,
    "FLAGS_scaler_cooldown_s": 30.0,
    "FLAGS_scaler_window_s": 30.0,
    "FLAGS_scaler_queue_high_frac": 0.85,
    "FLAGS_scaler_queue_low_frac": 0.10,
    "FLAGS_scaler_step_p99_high_ms": 0.0,
}


@pytest.fixture(autouse=True)
def _clean_state():
    from paddle_tpu.distributed.ps.rpc import RPCClient

    def scrub():
        pt.set_flags(_FLAG_DEFAULTS)
        telemetry.configure(None)
        RPCClient.reset_pool()

    scrub()
    yield
    scrub()


def _delta(before, name):
    return int(telemetry.counters().get(name, 0)) - int(before.get(name, 0))


# ---------------------------------------------------------------------------
# reader: the global cursor re-splits across world changes
# ---------------------------------------------------------------------------

def _stream(n):
    def gen():
        for i in range(n):
            yield np.full((2, 3), i, np.float32)
    return gen


def _loader(n):
    from paddle_tpu.reader import DataLoader

    loader = DataLoader.from_generator(capacity=2, return_list=True,
                                       use_double_buffer=False)
    loader.set_batch_generator(_stream(n))
    return loader


def _values(batches):
    return [int(np.asarray(b[0])[0, 0]) for b in batches]


class TestReaderCursorResplit:
    def test_residue_class_partition_covers_stream(self):
        """Trainer t of W delivers exactly the global indices ≡ t (mod W);
        the union over trainers is the whole stream, disjoint."""
        per_trainer = {}
        for tid in range(3):
            loader = _loader(12).set_world(3, tid)
            per_trainer[tid] = _values(loader)
            # the cursor is the GLOBAL stream position, not the count of
            # delivered batches
            assert loader.state_dict()["batches"] == 12
        for tid, vals in per_trainer.items():
            assert vals == [i for i in range(12) if i % 3 == tid]

    def test_world1_state_dict_stays_legacy(self):
        loader = _loader(4)
        list(loader)
        assert loader.state_dict() == {"batches": 4}

    def test_world_keys_recorded_beyond_world1(self):
        loader = _loader(6).set_world(2, 1)
        list(loader)
        assert loader.state_dict() == {"batches": 6, "world_size": 2,
                                       "trainer_id": 1}

    def test_cursor_restores_into_different_world(self):
        """A cursor saved by a world-2 member restores into a world-4
        member: the new trainer fast-forwards the same global stream and
        takes its own residue class (reader.cursor_resplits counted)."""
        saver = _loader(12).set_world(2, 0)
        it = iter(saver)
        assert _values([next(it), next(it)]) == [0, 2]
        state = saver.state_dict()      # global cursor: items 0..2 drawn
        assert state["batches"] == 3

        before = dict(telemetry.counters())
        resumed = _loader(12).set_world(4, 1)
        resumed.set_state(state)
        assert _delta(before, "reader.cursor_resplits") == 1
        assert _values(resumed) == [i for i in range(3, 12) if i % 4 == 1]

    def test_same_world_restore_counts_no_resplit(self):
        saver = _loader(6).set_world(2, 0)
        list(saver)
        before = dict(telemetry.counters())
        resumed = _loader(6).set_world(2, 1)
        resumed.set_state(saver.state_dict())
        assert _delta(before, "reader.cursor_resplits") == 0

    def test_set_world_validates(self):
        loader = _loader(2)
        with pytest.raises(ValueError, match="trainer_id"):
            loader.set_world(2, 2)
        with pytest.raises(ValueError, match="trainer_id"):
            loader.set_world(0, 0)


# ---------------------------------------------------------------------------
# large_scale_kv: shard-count-independent restore + cross-server rebalance
# ---------------------------------------------------------------------------

class TestKVReshard:
    def _train_rows(self, kv, ids, dim, seed=3):
        kv.pull(ids)
        kv.push(ids, np.random.RandomState(seed).randn(len(ids), dim)
                .astype(np.float32), lr=0.5)
        return kv.pull(ids)

    def test_restore_into_different_num_shards(self, tmp_path):
        """The in-process shard layout is never trusted at load: a table
        saved at 8 shards restores into 3 with identical rows."""
        from paddle_tpu.distributed.large_scale_kv import LargeScaleKV

        ids = np.arange(40, dtype=np.int64) * 7 + 2
        kv8 = LargeScaleKV(dim=4, num_shards=8, seed=9)
        want = self._train_rows(kv8, ids, 4)
        path = str(tmp_path / "kv8.npz")
        kv8.save(path)

        kv3 = LargeScaleKV(dim=4, num_shards=3, seed=9)
        assert kv3.load(path) == len(ids)
        assert kv3.size() == len(ids)
        np.testing.assert_array_equal(np.sort(ids), kv3.ids())
        np.testing.assert_array_equal(want, kv3.pull(ids))

    def test_load_keep_filter(self, tmp_path):
        from paddle_tpu.distributed.large_scale_kv import LargeScaleKV

        ids = np.arange(10, dtype=np.int64)
        kv = LargeScaleKV(dim=2, num_shards=4, seed=1)
        self._train_rows(kv, ids, 2)
        path = str(tmp_path / "kv.npz")
        kv.save(path)
        half = LargeScaleKV(dim=2, num_shards=4, seed=1)
        assert half.load(path, keep=lambda i: i % 2 == 0) == 5
        np.testing.assert_array_equal(half.ids(),
                                      np.arange(0, 10, 2, dtype=np.int64))

    def test_cross_server_rebalance_conserves_rows(self, tmp_path):
        """2-server snapshots restore into 3 servers: every server reads
        EVERY tag's files and keeps its `id % 3` class — the union is
        exactly the saved set (zero leaked, zero duplicated) and pulls
        match the pre-resize values."""
        from paddle_tpu.distributed.ps.kv_service import KVTables

        dim, ids = 4, np.arange(60, dtype=np.int64) * 5 + 1
        grads = np.random.RandomState(2).randn(len(ids), dim) \
            .astype(np.float32)
        old = [KVTables() for _ in range(2)]
        want = {}
        for j, tab in enumerate(old):
            kv = tab.ensure("emb", dim, seed=7)
            mine = ids[ids % 2 == j]
            kv.pull(mine)
            kv.push(mine, grads[ids % 2 == j], lr=0.5)
            for i in mine:
                want[int(i)] = kv.pull([i])[0].copy()
            tab.save_all(str(tmp_path), str(j))

        before = dict(telemetry.counters())
        new = [KVTables() for _ in range(3)]
        ingested = sum(tab.load_all(str(tmp_path), f"n{j}", num_servers=3,
                                    server_index=j)
                       for j, tab in enumerate(new))
        assert ingested == len(ids)
        assert _delta(before, "ps.kv_rebalanced_rows") == len(ids)
        got = np.concatenate([tab.tables["emb"].ids() for tab in new])
        assert got.size == len(ids), "leaked or duplicated rows"
        np.testing.assert_array_equal(np.sort(got), np.sort(ids))
        for j, tab in enumerate(new):
            mine = tab.tables["emb"].ids()
            assert np.all(mine % 3 == j), "row outside its residue class"
            for i in mine:
                np.testing.assert_array_equal(
                    want[int(i)], tab.tables["emb"].pull([i])[0])

    def test_conflicting_specs_across_servers_raise(self, tmp_path):
        from paddle_tpu.distributed.ps.kv_service import KVTables

        a, b = KVTables(), KVTables()
        a.ensure("emb", 4, seed=1).pull([0])
        b.ensure("emb", 8, seed=1).pull([1])
        a.save_all(str(tmp_path), "0")
        b.save_all(str(tmp_path), "1")
        with pytest.raises(ValueError, match="conflicting"):
            KVTables().load_all(str(tmp_path), "n0", num_servers=2,
                                server_index=0)


# ---------------------------------------------------------------------------
# ZeRO optimizer-shard regrouping across a dp-degree change
# ---------------------------------------------------------------------------

class TestZeroRegroupUnit:
    def test_repad_preserves_logical_prefix_and_tail(self):
        """Saved [numel..padded(old)] state re-pads to the new geometry:
        the logical prefix is copied, the tail comes from the startup
        array in the scope (or replicates the saved pad fill)."""
        from paddle_tpu.parallel import regroup_state

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            v = layers.create_global_var([8], 0.0, "float32",
                                         persistable=True, name="zr_acc")
        prog._zero_state_numel = {"zr_acc": 6}
        prog._zero_degree = 4

        before = dict(telemetry.counters())
        arrays = {"zr_acc": np.arange(10, dtype=np.float32)}  # degree-5 pad
        assert regroup_state(arrays, prog, scope=None) == 1
        np.testing.assert_array_equal(
            arrays["zr_acc"],
            np.array([0, 1, 2, 3, 4, 5, 6, 6], np.float32))
        assert _delta(before, "sharding.zero_regroup_events") == 1

        scope = pt.Scope()
        scope.set("zr_acc", np.full(8, 0.5, np.float32))
        arrays = {"zr_acc": np.arange(10, dtype=np.float32)}
        assert regroup_state(arrays, prog, scope=scope) == 1
        np.testing.assert_array_equal(
            arrays["zr_acc"],
            np.array([0, 1, 2, 3, 4, 5, 0.5, 0.5], np.float32))
        assert v.name == "zr_acc"

    def test_matching_geometry_is_untouched(self):
        from paddle_tpu.parallel import regroup_state

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            layers.create_global_var([8], 0.0, "float32",
                                     persistable=True, name="zr_same")
        prog._zero_state_numel = {"zr_same": 6}
        saved = np.arange(8, dtype=np.float32)
        arrays = {"zr_same": saved}
        assert regroup_state(arrays, prog, scope=None) == 0
        assert arrays["zr_same"] is saved


DP = 8


@pytest.fixture
def _dp_mesh():
    import jax

    from paddle_tpu.parallel import create_mesh
    from paddle_tpu.parallel import mesh as meshmod

    if len(jax.devices()) < DP:
        pytest.skip(f"needs {DP} virtual devices")
    mesh = create_mesh({"dp": DP})
    yield mesh
    meshmod.set_mesh(None)


def _fresh_names():
    from paddle_tpu.core import unique_name

    unique_name.switch()


def _zero_build(stage, lr=0.1):
    """Momentum net with dims chosen so padded shard lengths DIFFER
    between dp=8 and dp=4 (33 → pad 40 vs 36, 330 → 336 vs 332,
    10 → 16 vs 12) — the regroup path must actually fire."""
    from paddle_tpu.distributed import fleet

    _fresh_names()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [16])
        h = layers.fc(x, 33, act="relu", param_attr=pt.ParamAttr(
            name="zr_w0", initializer=pt.initializer.Xavier(seed=31)),
            bias_attr=pt.ParamAttr(name="zr_b0"))
        y = layers.fc(h, 10, param_attr=pt.ParamAttr(
            name="zr_w1", initializer=pt.initializer.Xavier(seed=32)),
            bias_attr=pt.ParamAttr(name="zr_b1"))
        loss = layers.mean(y * y)
        strategy = fleet.DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"stage": stage}
        dopt = fleet.distributed_optimizer(
            pt.optimizer.MomentumOptimizer(lr, 0.9), strategy)
        dopt.minimize(loss)
    return main, startup, loss


def _zero_feed(seed):
    return {"x": np.random.RandomState(seed).randn(16, 16)
            .astype(np.float32)}


class TestZeroWorldChangeResume:
    @pytest.mark.parametrize("stage", [1, 2])
    def test_dp8_checkpoint_resumes_at_dp4(self, _dp_mesh, tmp_path, stage):
        """The tentpole gate: train at dp=8, checkpoint, restore into a
        dp=4 program (different shard padding) and continue — the loss
        trajectory and final params match the uninterrupted dp=8 run at
        the preserved global batch, with the regroup events counted and
        the saved degree recorded in the manifest."""
        from paddle_tpu import checkpoint as ckpt
        from paddle_tpu.distributed import fleet
        from paddle_tpu.parallel import create_mesh
        from paddle_tpu.parallel import mesh as meshmod

        fleet.init(is_collective=True)
        exe = pt.Executor(pt.CPUPlace())

        def train(main, startup, loss, mesh, steps, scope=None,
                  start_seed=0):
            sc = scope or pt.Scope()
            if scope is None:
                exe.run(startup, scope=sc, use_compiled=False)
            out = []
            for s in range(steps):
                r = exe.run(main, feed=_zero_feed(start_seed + s),
                            fetch_list=[loss], scope=sc, mesh=mesh)
                out.append(float(np.asarray(r[0]).reshape(-1)[0]))
            return sc, out

        # uninterrupted dp=8 reference
        main8, start8, loss8 = _zero_build(stage)
        assert main8._zero_degree == DP
        sc_full, full_losses = train(main8, start8, loss8, _dp_mesh, 4)
        want = {p.name: np.asarray(sc_full.find_var(p.name))
                for p in main8.all_parameters()}

        # interrupted: 2 steps at dp=8, checkpoint
        sc_a, _ = train(main8, start8, loss8, _dp_mesh, 2)
        path = str(tmp_path / f"zero-resize-{stage}")
        ckpt.save_checkpoint(path, program=main8, scope=sc_a)
        manifest = json.load(open(f"{path}/MANIFEST.json"))
        assert manifest["extras"]["sharding"]["zero_degree"] == DP

        # resume into dp=4: same net, rebuilt at the new degree
        meshmod.set_mesh(None)
        mesh4 = create_mesh({"dp": 4})
        try:
            main4, start4, loss4 = _zero_build(stage)
            assert main4._zero_degree == 4
            sc_b = pt.Scope()
            exe.run(start4, scope=sc_b, use_compiled=False)
            before = dict(telemetry.counters())
            ckpt.load_checkpoint(path, program=main4, scope=sc_b)
            # zr_b0 (33), zr_w1 (330) and zr_b1 (10) velocity shards all
            # change padded length between degree 8 and 4; zr_w0 (528)
            # pads identically at both
            assert _delta(before, "sharding.zero_regroup_events") == 3
            _, resumed_losses = train(main4, start4, loss4, mesh4, 2,
                                      scope=sc_b, start_seed=2)
        finally:
            meshmod.set_mesh(None)
            create_mesh({"dp": DP})

        np.testing.assert_allclose(resumed_losses, full_losses[2:],
                                   rtol=2e-5, atol=1e-6)
        for p in main4.all_parameters():
            np.testing.assert_allclose(
                np.asarray(sc_b.find_var(p.name)), want[p.name],
                rtol=2e-5, atol=1e-6,
                err_msg=f"{p.name} diverged across the dp 8 -> 4 resume")


# ---------------------------------------------------------------------------
# pserver barrier regrow (revival + elastic admission)
# ---------------------------------------------------------------------------

def _ps_net():
    from paddle_tpu.core import ir

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    _fresh_names()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [8], stop_gradient=True)
        h = layers.fc(x, 8, act="relu", param_attr=pt.ParamAttr(
            name="er_w0", initializer=pt.initializer.Xavier(seed=41)),
            bias_attr=pt.ParamAttr(name="er_b0"))
        y = layers.fc(h, 2, param_attr=pt.ParamAttr(
            name="er_w1", initializer=pt.initializer.Xavier(seed=42)),
            bias_attr=pt.ParamAttr(name="er_b1"))
        loss = layers.mean(y * y)
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
    return main, startup


def _ps_server(trainers, **kw):
    from paddle_tpu.distributed.ps import DistributeTranspiler, PServer

    main, startup = _ps_net()
    t = DistributeTranspiler()
    t.transpile(0, program=main, startup_program=startup,
                pservers="127.0.0.1:0", trainers=trainers, sync_mode=True)
    prog, ps_startup = t.get_pserver_programs("127.0.0.1:0")
    return PServer("127.0.0.1:0", prog, ps_startup, num_trainers=trainers,
                   sync_mode=True, grad_to_param=prog._ps_grad_to_param,
                   grad_to_ops=prog._ps_grad_to_ops,
                   common_ops=prog._ps_common_ops, **kw)


class TestBarrierRegrow:
    def test_degrade_then_regrow_revived_and_new_trainer(self):
        """Satellite gate: a degraded-to-survivors barrier re-admits the
        revived trainer AND accepts a brand-new trainer id — the next
        barrier needs all three. Counter deltas pinned."""
        from paddle_tpu.distributed.ps.rpc import RPCClient

        pt.set_flags({"FLAGS_ps_degrade_to_survivors": True})
        server = _ps_server(2, heartbeat_timeout=0.8)
        before = dict(telemetry.counters())
        try:
            (g,) = [g for g, p in server.grad_to_param.items()
                    if p == "er_w0"]
            st = server.states[g]
            shape = np.asarray(server.scope.find_var("er_w0")).shape
            ones = np.ones(shape, np.float32)
            clis = [RPCClient(server.endpoint) for _ in range(3)]

            # full barrier at world 2
            clis[0].call("send_grad", g, ones, aux=0)
            clis[1].call("send_grad", g, ones, aux=1)
            assert st.version == 1

            # trainer 1 goes silent -> the survivors complete the step
            # (trainer 0 keeps heartbeating so only 1 draws the verdict)
            clis[0].call("send_grad", g, ones, aux=0)
            deadline = time.monotonic() + 10.0
            while st.version < 2 and time.monotonic() < deadline:
                clis[0].call("heartbeat", "", None, aux=0)
                time.sleep(0.05)
            assert st.version == 2, "barrier never degraded to survivors"
            assert _delta(before, "ps.barrier_degraded") == 1
            assert _delta(before, "ps.trainer_dead") == 1

            # revival: trainer 1 is required again...
            clis[1].call("heartbeat", "", None, aux=1)
            assert 1 not in server.monitor.dead
            assert _delta(before, "ps.trainer_revived") == 1
            assert _delta(before, "ps.barrier_regrown") == 1
            # ...and a brand-new trainer id GROWS the barrier (elastic
            # admission): world 2 -> 3
            clis[2].call("heartbeat", "", None, aux=2)
            assert server.num_trainers == 3
            assert 2 in server.monitor.last_seen
            assert _delta(before, "ps.barrier_regrown") == 2

            # the next step's barrier needs all three members
            clis[0].call("send_grad", g, ones, aux=0)
            clis[1].call("send_grad", g, ones, aux=1)
            assert st.version == 2, "barrier completed without the admitted"
            clis[2].call("send_grad", g, ones, aux=2)
            assert st.version == 3
            assert _delta(before, "ps.trainer_dead") == 1
            assert _delta(before, "ps.trainer_revived") == 1
        finally:
            server.shutdown()

    def test_admission_gated_by_flag(self):
        from paddle_tpu.distributed.ps.rpc import RPCClient

        pt.set_flags({"FLAGS_ps_elastic_admission": False})
        server = _ps_server(2, heartbeat_timeout=30.0)
        before = dict(telemetry.counters())
        try:
            cli = RPCClient(server.endpoint)
            cli.call("heartbeat", "", None, aux=5)
            assert server.num_trainers == 2
            assert _delta(before, "ps.barrier_regrown") == 0
        finally:
            server.shutdown()

    def test_admission_is_idempotent(self):
        from paddle_tpu.distributed.ps.rpc import RPCClient

        server = _ps_server(2, heartbeat_timeout=30.0)
        before = dict(telemetry.counters())
        try:
            cli = RPCClient(server.endpoint)
            cli.call("heartbeat", "", None, aux=3)
            cli.call("heartbeat", "", None, aux=3)
            assert server.num_trainers == 4
            assert _delta(before, "ps.barrier_regrown") == 1
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# ScalerPolicy: rules, cooldown, clamping
# ---------------------------------------------------------------------------

class TestScalerPolicy:
    def _policy(self, **kw):
        from paddle_tpu.distributed.scaler import ScalerPolicy

        kw.setdefault("min_world", 1)
        kw.setdefault("max_world", 8)
        kw.setdefault("cooldown_s", 0.0)
        return ScalerPolicy(**kw)

    def test_rule_order_and_directions(self):
        from paddle_tpu.distributed.scaler import (SCALE_DOWN, SCALE_UP,
                                                   ScaleSignals)

        p = self._policy()
        cases = [
            (ScaleSignals(dead_workers=2), SCALE_DOWN, 2, "heartbeat_dead"),
            (ScaleSignals(joined_workers=1), SCALE_UP, 5, "worker_rejoined"),
            (ScaleSignals(queue_frac=0.9, queue_evidence=True),
             SCALE_UP, 5, "queue_saturation"),
            (ScaleSignals(queue_frac=0.05, queue_evidence=True),
             SCALE_DOWN, 3, "underutilized"),
        ]
        for sig, direction, target, reason in cases:
            d = p.decide(4, signals=sig, now=100.0)
            p.reset_cooldown()
            assert (d.direction, d.target, d.reason) == \
                (direction, target, reason)
        # dead beats joined beats queue (first hit wins)
        d = p.decide(4, signals=ScaleSignals(dead_workers=1,
                                             joined_workers=1,
                                             queue_frac=0.99,
                                             queue_evidence=True),
                     now=200.0)
        assert d.reason == "heartbeat_dead" and d.target == 3

    def test_no_queue_evidence_means_no_queue_rules(self):
        from paddle_tpu.distributed.scaler import ScaleSignals

        p = self._policy()
        assert p.decide(4, signals=ScaleSignals(queue_frac=0.0),
                        now=1.0) is None

    def test_step_p99_rule_when_bound_set(self):
        from paddle_tpu.distributed.scaler import ScaleSignals

        p = self._policy(step_p99_high_ms=50.0)
        d = p.decide(2, signals=ScaleSignals(step_p99_ms=80.0), now=1.0)
        assert d.reason == "step_time_p99" and d.target == 3
        p2 = self._policy()          # bound 0 -> rule disabled
        assert p2.decide(2, signals=ScaleSignals(step_p99_ms=1e9),
                         now=1.0) is None

    def test_cooldown_suppresses_thrash(self):
        from paddle_tpu.distributed.scaler import ScaleSignals

        p = self._policy(cooldown_s=10.0)
        sig = ScaleSignals(queue_frac=0.95, queue_evidence=True)
        before = dict(telemetry.counters())
        assert p.decide(2, signals=sig, now=100.0) is not None
        assert p.decide(3, signals=sig, now=105.0) is None
        assert _delta(before, "scaler.suppressed_cooldown") == 1
        assert p.decide(3, signals=sig, now=111.0) is not None

    def test_clamp_to_bounds_and_to_current(self):
        from paddle_tpu.distributed.scaler import ScaleSignals

        p = self._policy(min_world=2, max_world=4)
        before = dict(telemetry.counters())
        d = p.decide(4, signals=ScaleSignals(joined_workers=3), now=1.0)
        assert d is None              # clamped back onto the current world
        assert _delta(before, "scaler.clamped") == 1
        d = p.decide(3, signals=ScaleSignals(joined_workers=3), now=2.0)
        assert d.target == 4          # clamped to max, still a move
        p.reset_cooldown()
        d = p.decide(3, signals=ScaleSignals(dead_workers=2), now=3.0)
        assert d.target == 2          # clamped to min

    def test_decision_counters_exactly_once(self):
        from paddle_tpu.distributed.scaler import ScaleSignals

        p = self._policy()
        before = dict(telemetry.counters())
        p.decide(2, signals=ScaleSignals(joined_workers=1), now=1.0)
        p.decide(3, signals=ScaleSignals(dead_workers=1), now=2.0)
        p.decide(2, signals=ScaleSignals(), now=3.0)     # no rule fires
        assert _delta(before, "scaler.evaluations") == 3
        assert _delta(before, "scaler.decisions") == 2
        assert _delta(before, "scaler.scale_up") == 1
        assert _delta(before, "scaler.scale_down") == 1

    def test_bounds_validated(self):
        with pytest.raises(ValueError, match="min_world"):
            self._policy(min_world=0)
        with pytest.raises(ValueError, match="min_world"):
            self._policy(min_world=4, max_world=2)

    def test_gather_signals_from_window(self):
        from paddle_tpu.distributed.scaler import gather_signals

        window = {
            "counters": {"ps.trainer_dead": {"delta": 2},
                         "ps.trainer_revived": {"delta": 1},
                         "ps.barrier_regrown": {"delta": 3}},
            "gauges": {"fleet.queue_frac": 0.5},
            "hists": {"executor.run_ms": {"count": 10, "p99": 42.0}},
        }
        sig = gather_signals(window=window)
        assert sig.dead_workers == 1          # dead net of revived
        assert sig.joined_workers == 3        # max(revived, regrown)
        assert sig.queue_frac == 0.5 and sig.queue_evidence
        assert sig.step_p99_ms == 42.0
        assert sig.extra["step_metric"] == "executor.run_ms"


# ---------------------------------------------------------------------------
# ScalerPolicy.from_slo_rules: firing SLO gauges as scale evidence
# ---------------------------------------------------------------------------

class TestSLOScalerPolicy:
    def _policy(self, **kw):
        from paddle_tpu.distributed.scaler import ScalerPolicy

        kw.setdefault("min_world", 1)
        kw.setdefault("max_world", 8)
        kw.setdefault("cooldown_s", 0.0)
        return ScalerPolicy.from_slo_rules(**kw)

    def test_one_saturation_trip_one_cooldown_gated_scale_up(self):
        """A decode queue-saturation episode tripped by the PR 18
        watchdog (slo.decode_queue_saturation_firing=1) yields exactly
        ONE ScaleUp while the cooldown runs, and none once the episode
        clears — the scaler consumes the watchdog's latched verdict, not
        the raw queue gauge."""
        from paddle_tpu.core import incidents

        rule = incidents.Rule(
            "decode_queue_saturation", "decode.queue_depth",
            kind="gauge", threshold=9.0, direction="above",
            cooldown_s=0.0)
        wd = incidents.Watchdog([rule])
        p = self._policy(cooldown_s=60.0)
        before = dict(telemetry.counters())
        try:
            telemetry.gauge_set("decode.queue_depth", 12)
            assert wd.evaluate(now=100.0) == ["decode_queue_saturation"]

            d = p.decide(2, now=100.0)
            assert d is not None
            assert (d.direction, d.target) == ("up", 3)
            assert d.reason == "decode_queue_saturation"
            assert "decode_queue_saturation" in \
                d.signals.get("slo_firing", [])
            # still firing inside the cooldown: suppressed, not repeated
            assert p.decide(3, now=130.0) is None
            assert _delta(before, "scaler.suppressed_cooldown") == 1
            assert _delta(before, "scaler.scale_up") == 1
            # episode clears -> gauge drops to 0 -> no decision even
            # after the cooldown expires
            telemetry.gauge_set("decode.queue_depth", 1)
            wd.evaluate(now=200.0)
            assert p.decide(3, now=300.0) is None
            assert _delta(before, "scaler.scale_up") == 1
        finally:
            telemetry.gauge_set("decode.queue_depth", 0)
            telemetry.gauge_set("slo.decode_queue_saturation_firing", 0)

    def test_down_rule_and_injected_firing_set(self):
        from paddle_tpu.distributed.scaler import ScaleSignals

        p = self._policy()
        sig = ScaleSignals(extra={"slo_firing": ["live_mfu_drop"]})
        d = p.decide(4, signals=sig, now=1.0)
        assert (d.direction, d.target, d.reason) == \
            ("down", 3, "live_mfu_drop")
        # up-rules outrank down-rules when both fire
        p.reset_cooldown()
        sig = ScaleSignals(extra={"slo_firing": [
            "live_mfu_drop", "decode_queue_saturation"]})
        d = p.decide(4, signals=sig, now=2.0)
        assert (d.direction, d.reason) == ("up", "decode_queue_saturation")

    def test_quiet_gauges_mean_no_decision(self):
        p = self._policy()
        assert p.decide(4, now=1.0) is None


# ---------------------------------------------------------------------------
# ElasticRunner: windowed restart budget + the scale-event protocol
# ---------------------------------------------------------------------------

def _local_net(lr=0.1):
    _fresh_names()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [6], stop_gradient=True)
        y = layers.fc(x, 3, param_attr=pt.ParamAttr(
            name="el_w0", initializer=pt.initializer.Xavier(seed=51)),
            bias_attr=pt.ParamAttr(name="el_b0"))
        loss = layers.mean(y * y)
        pt.optimizer.SGDOptimizer(lr).minimize(loss)
    return main, startup, loss


class TestElasticRestartBudget:
    def test_window_refunds_expired_restarts(self, tmp_path):
        from paddle_tpu.distributed.elastic import ElasticRunner

        runner = ElasticRunner(str(tmp_path), restart_window_s=10.0)
        runner.restarts = 3
        runner._restart_times.extend([100.0, 101.0, 108.0])
        before = dict(telemetry.counters())
        # at t=111.5 the first two restarts are older than the window
        assert runner.budget_used(now=111.5) == 1
        assert _delta(before, "elastic.restart_budget_refunds") == 2
        # lifetime total is untouched (observability)
        assert runner.restarts == 3

    def test_legacy_lifetime_budget_without_window(self, tmp_path):
        from paddle_tpu.distributed.elastic import ElasticRunner

        runner = ElasticRunner(str(tmp_path), restart_window_s=0.0)
        runner.restarts = 2
        assert runner.budget_used(now=1e9) == 2

    def test_restart_lands_scale_ring_record(self, tmp_path):
        """Every restart is a scale-plane event: one kind:"scale" record
        (source elastic, event restart) + incidents.scale_events."""
        from paddle_tpu.distributed.elastic import ElasticRunner

        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        main, startup, loss = _local_net()
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(0).randn(4, 6)
                .astype(np.float32)}
        runner = ElasticRunner(str(tmp_path / "ckpt"), main, scope,
                               save_interval_steps=1, max_restarts=3,
                               async_save=False)
        state = {"raised": False}

        def step_fn(step):
            if step == 1 and not state["raised"]:
                state["raised"] = True
                raise ConnectionError("injected")
            out = exe.run(main, feed=feed, fetch_list=[loss], scope=scope,
                          use_compiled=False)
            return float(np.asarray(out[0]).reshape(-1)[0])

        before = dict(telemetry.counters())
        runner.run(step_fn, 3)
        runner.close()
        assert _delta(before, "elastic.restarts") == 1
        assert _delta(before, "incidents.scale_events") == 1
        telemetry.flush_sink()
        recs = [json.loads(line) for line in open(log) if line.strip()]
        scale = [r for r in recs if r.get("kind") == "scale"]
        assert len(scale) == 1
        assert scale[0]["name"] == "elastic.restart"
        assert scale[0]["attrs"]["reason"] == "ConnectionError"
        assert scale[0]["attrs"]["old_world"] == \
            scale[0]["attrs"]["new_world"] == 1


class _ScriptedScaler:
    """decide() plays back a fixed decision list — the policy is pinned
    by TestScalerPolicy; here the EXECUTION protocol is under test."""

    def __init__(self, decisions):
        self.decisions = list(decisions)

    def decide(self, world, now=None, fleet=None, signals=None):
        return self.decisions.pop(0) if self.decisions else None


class TestElasticExecuteScale:
    def test_resize_is_loss_transparent(self, tmp_path):
        """execute_scale: checkpoint -> drain -> on_scale swap -> restore
        into the new world. With every trainer carrying the full global
        batch the resized run's losses are BITWISE the uninterrupted
        run's."""
        from paddle_tpu.distributed.elastic import ElasticRunner
        from paddle_tpu.distributed.scaler import SCALE_DOWN, ScaleDecision

        exe = pt.Executor(pt.CPUPlace())
        feed = {"x": np.random.RandomState(5).randn(4, 6)
                .astype(np.float32)}

        def leg(scaler, on_scale, steps=4):
            main, startup, loss = _local_net()
            scope = pt.Scope()
            exe.run(startup, scope=scope, use_compiled=False)
            runner = ElasticRunner(
                str(tmp_path / f"ckpt-{id(scaler)}"), main, scope,
                save_interval_steps=1, async_save=False, world_size=2,
                scaler=scaler, on_scale=on_scale)
            losses = []

            def step_fn(step):
                out = exe.run(main, feed=feed, fetch_list=[loss],
                              scope=scope, use_compiled=False)
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
                return losses[-1]

            runner.run(step_fn, steps)
            runner.close()
            return runner, losses

        _, base = leg(None, None)

        decision = ScaleDecision(direction=SCALE_DOWN, current=2, target=1,
                                 reason="heartbeat_dead", ts=1.0)
        before = dict(telemetry.counters())
        runner, got = leg(_ScriptedScaler([decision]),
                          lambda d: {"world_size": d.target})
        assert runner.world_size == 1
        assert runner.scale_events == 1
        assert got == base, "resize must be loss-transparent"
        assert _delta(before, "elastic.scale_events") == 1
        assert _delta(before, "incidents.scale_events") == 1

    def test_on_scale_veto_keeps_world(self, tmp_path):
        from paddle_tpu.distributed.elastic import ElasticRunner
        from paddle_tpu.distributed.scaler import SCALE_UP, ScaleDecision

        main, startup, loss = _local_net()
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope, use_compiled=False)
        feed = {"x": np.random.RandomState(5).randn(4, 6)
                .astype(np.float32)}
        decision = ScaleDecision(direction=SCALE_UP, current=2, target=4,
                                 reason="worker_rejoined", ts=1.0)
        runner = ElasticRunner(str(tmp_path / "ckpt"), main, scope,
                               save_interval_steps=1, async_save=False,
                               world_size=2,
                               scaler=_ScriptedScaler([decision]),
                               on_scale=lambda d: None)
        before = dict(telemetry.counters())

        def step_fn(step):
            out = exe.run(main, feed=feed, fetch_list=[loss], scope=scope,
                          use_compiled=False)
            return float(np.asarray(out[0]).reshape(-1)[0])

        runner.run(step_fn, 3)
        runner.close()
        assert runner.world_size == 2 and runner.scale_events == 0
        assert _delta(before, "elastic.scale_events") == 0


# ---------------------------------------------------------------------------
# serving: signal-driven replica autoscaling through the drain machinery
# ---------------------------------------------------------------------------

IN_DIM = 6


def _save_mlp(dirname, seed):
    from paddle_tpu import io

    _fresh_names()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", [IN_DIM])
        h = layers.fc(x, 8, act="relu", param_attr=pt.ParamAttr(
            name="as_w0", initializer=pt.initializer.Xavier(seed=seed)))
        y = layers.fc(h, 4, param_attr=pt.ParamAttr(
            name="as_w1", initializer=pt.initializer.Xavier(seed=seed + 1)))
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope, use_compiled=False)
    io.save_inference_model(str(dirname), ["x"], [y],
                            main_program=main, scope=scope)
    return str(dirname)


def _post_infer(url, x, rid=None, timeout=60):
    import urllib.error

    doc = {"inputs": {"x": x.tolist()}}
    headers = {"Content-Type": "application/json"}
    if rid is not None:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(url + "/v1/infer",
                                 data=json.dumps(doc).encode(),
                                 headers=headers)
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.mark.serving
class TestClusterAutoscale:
    def test_scale_up_and_down_exactly_once_no_dropped_requests(
            self, tmp_path):
        """Acceptance gate: the REAL ScalerPolicy over live signals —
        queue saturation scales the serving fleet 1 -> 2, the
        underutilized rule scales it back 2 -> 1 through the drain while
        closed-loop clients keep posting; ScaleUp and ScaleDown each
        fire exactly once and no request is dropped."""
        from paddle_tpu import checkpoint as ckpt
        from paddle_tpu.distributed.scaler import ScalerPolicy
        from paddle_tpu.serving import ClusterController, ServingConfig

        # counter history from earlier tests must not leak into the
        # policy's rolling window (dead-trainer verdicts would win)
        telemetry.reset()
        model = _save_mlp(tmp_path / "m1", seed=61)
        root = str(tmp_path / "models")
        ckpt.publish_model(root, model, version=1)
        cluster = ClusterController(
            root, replicas=1, inprocess=True,
            serving_config=ServingConfig(max_batch_size=4,
                                         batch_timeout_ms=1.0),
            auto_swap=False).start(ready_timeout_s=120)
        cluster.attach_scaler(ScalerPolicy(min_world=1, max_world=2,
                                           cooldown_s=0.0,
                                           source="serving"))
        before = dict(telemetry.counters())
        x = np.random.RandomState(1).randn(2, IN_DIM).astype(np.float32)
        try:
            # saturation signal -> ScaleUp 1 -> 2
            telemetry.gauge_set("fleet.queue_frac", 0.95)
            d = cluster.autoscale_tick()
            assert d is not None and d.reason == "queue_saturation"
            assert len(cluster.replicas) == 2
            names = {h for h in
                     (doc["replica"] for _, doc in
                      (_post_infer(cluster.url, x) for _ in range(8)))}
            assert len(names) == 2, "new replica never took traffic"

            # drain 2 -> 1 WHILE closed-loop clients post — zero drops
            results = {}
            lock = threading.Lock()

            def worker(wid):
                for i in range(20):
                    rid = f"as-{wid}-{i}"
                    code, doc = _post_infer(cluster.url, x, rid=rid)
                    with lock:
                        results[rid] = (code, doc.get("request_id"))

            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.05)
            telemetry.gauge_set("fleet.queue_frac", 0.02)
            d = cluster.autoscale_tick()
            assert d is not None and d.reason == "underutilized"
            assert len(cluster.replicas) == 1
            for t in threads:
                t.join(60)
            assert len(results) == 60
            bad = {k: v for k, v in results.items() if v[0] != 200}
            assert not bad, f"dropped requests across the drain: {bad}"
            assert all(v[1] == k for k, v in results.items())

            # steady state: further ticks clamp away, nothing fires
            assert cluster.autoscale_tick() is None
            assert cluster.autoscale_tick() is None
        finally:
            cluster.close()
        assert _delta(before, "scaler.scale_up") == 1
        assert _delta(before, "scaler.scale_down") == 1
        assert _delta(before, "router.scale_events") == 2
        assert _delta(before, "incidents.scale_events") == 2

    def test_scale_to_bounds(self, tmp_path):
        from paddle_tpu import checkpoint as ckpt
        from paddle_tpu.serving import (ClusterController, ClusterError,
                                        ServingConfig)

        model = _save_mlp(tmp_path / "m1", seed=71)
        root = str(tmp_path / "models")
        ckpt.publish_model(root, model, version=1)
        cluster = ClusterController(
            root, replicas=1, inprocess=True,
            serving_config=ServingConfig(max_batch_size=4,
                                         batch_timeout_ms=1.0),
            auto_swap=False).start(ready_timeout_s=120)
        try:
            with pytest.raises(ClusterError, match="at least 1"):
                cluster.scale_to(0)
            assert cluster.scale_to(1) == 1   # no-op resize is fine
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# perf_report: the elastic & autoscaling section renders from the log
# ---------------------------------------------------------------------------

def _perf_report():
    import importlib.util as ilu
    import os

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    spec = ilu.spec_from_file_location(
        "perf_report", os.path.join(tools, "perf_report.py"))
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestPerfReportScalerSection:
    def test_scale_events_render(self, tmp_path):
        from paddle_tpu.core import incidents
        from paddle_tpu.distributed.scaler import ScaleSignals, ScalerPolicy

        log = tmp_path / "run.jsonl"
        telemetry.configure(str(log))
        p = ScalerPolicy(min_world=1, max_world=4, cooldown_s=0.0)
        d = p.decide(2, signals=ScaleSignals(dead_workers=1), now=10.0)
        incidents.report_scale_event("elastic", "resize", d.current,
                                     d.target, reason=d.reason)
        telemetry.flush_sink()

        mod = _perf_report()
        recs, malformed = mod.load_counted(str(log))
        summary = mod.summarize_log(recs, malformed=malformed)
        assert summary["scaler"] is not None
        assert summary["scaler"]["decisions"] >= 1
        assert summary["scaler"]["scale_down"] >= 1
        assert any(e["name"] == "elastic.resize"
                   for e in summary["scaler"]["events"])
        import io

        buf = io.StringIO()
        mod.render(summary, out=buf)
        text = buf.getvalue()
        assert "elastic & autoscaling" in text
        assert "elastic.resize: world 2 -> 1" in text
