"""@to_static / TracedLayer / jit.save tests.

Mirrors the reference's dygraph_to_static suite
(unittests/dygraph_to_static/): parity with eager, retrace per signature,
training through the static trace, and export."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import dygraph, nn
from paddle_tpu.dygraph import VarBase, jit, to_static, to_variable
from paddle_tpu.optimizer import SGDOptimizer


class MLP(dygraph.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(8, 16)
        self.l2 = nn.Linear(16, 4)

    @to_static
    def forward(self, x):
        h = self.l1(x)
        h = nn.functional.relu(h)
        return self.l2(h)


class TestToStatic:
    def test_parity_with_eager(self):
        with dygraph.guard():
            m = MLP()
            x = to_variable(np.random.RandomState(0)
                            .randn(4, 8).astype(np.float32))
            static_out = m(x)
            jit.ProgramTranslator.get_instance().enable(False)
            try:
                eager_out = m(x)
            finally:
                jit.ProgramTranslator.get_instance().enable(True)
            np.testing.assert_allclose(static_out.numpy(), eager_out.numpy(),
                                       atol=1e-5)

    def test_trace_cached_per_signature(self):
        calls = {"n": 0}

        @to_static
        def f(x):
            calls["n"] += 1
            return x * 2.0 + 1.0

        with dygraph.guard():
            a = to_variable(np.ones((2, 3), np.float32))
            f(a)
            f(a)
            assert calls["n"] == 1          # second call hits the cache
            b = to_variable(np.ones((5, 3), np.float32))
            f(b)
            assert calls["n"] == 2          # new shape -> retrace

    def test_python_branch_frozen_per_trace(self):
        @to_static
        def f(x):
            if x.shape[0] > 3:
                return x * 10.0
            return x * 2.0

        with dygraph.guard():
            small = to_variable(np.ones((2, 2), np.float32))
            big = to_variable(np.ones((4, 2), np.float32))
            np.testing.assert_allclose(f(small).numpy(), 2 * np.ones((2, 2)))
            np.testing.assert_allclose(f(big).numpy(), 10 * np.ones((4, 2)))

    def test_training_through_static(self):
        """Grads must flow through the jitted block to the Layer params."""
        with dygraph.guard():
            rng = np.random.RandomState(0)
            m = MLP()
            opt = SGDOptimizer(0.1, parameter_list=m.parameters())
            x = to_variable(rng.randn(8, 8).astype(np.float32))
            y = to_variable(rng.randint(0, 4, (8, 1)).astype(np.int64))
            losses = []
            for _ in range(5):
                logits = m(x)
                loss = nn.functional.softmax_with_cross_entropy(
                    logits, y).mean()
                loss.backward()
                opt.minimize(loss)
                m.clear_gradients()
                losses.append(float(loss.numpy().reshape(-1)[0]))
            assert losses[-1] < losses[0]

    def test_jit_save_and_predict(self, tmp_path):
        with dygraph.guard():
            m = MLP()
            x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
            want = m(to_variable(x)).numpy()
            jit.save(m, str(tmp_path / "m"))
        loaded = jit.load(str(tmp_path / "m"))
        got = loaded(x)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_closure_ops_block_export(self, tmp_path):
        @to_static
        def f(x):
            return (x * 2.0).sum()      # .sum() -> ad-hoc closure op

        with dygraph.guard():
            f(to_variable(np.ones((2, 2), np.float32)))
            with pytest.raises(RuntimeError, match="closure"):
                jit.save(f, str(tmp_path / "f"))

    def test_traced_layer(self, tmp_path):
        class Net(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                return self.fc(x)

        with dygraph.guard():
            net = Net()
            x = to_variable(np.ones((2, 4), np.float32))
            out, traced = dygraph.TracedLayer.trace(net, [x])
            again = traced(x)
            np.testing.assert_allclose(out.numpy(), again.numpy(), atol=1e-6)
            types = [op.type for op in traced.program.global_block().ops]
            assert "matmul_v2" in types or "mul" in types
            traced.save_inference_model(str(tmp_path / "net"))
        loaded = jit.load(str(tmp_path / "net"))
        np.testing.assert_allclose(loaded(np.ones((2, 4), np.float32)),
                                   out.numpy(), atol=1e-5)

    def test_instances_do_not_share_trace(self):
        """Two instances of the same Layer class must not share a cached
        ConcreteProgram (each has its own parameters)."""
        with dygraph.guard():
            x = to_variable(np.ones((2, 8), np.float32))
            m1, m2 = MLP(), MLP()
            o1 = m1(x).numpy()
            # make m2's params very different, then call through to_static
            for p in m2.parameters():
                p._array = p._array * 0.0 + 1.0
            o2 = m2(x).numpy()
            jit.ProgramTranslator.get_instance().enable(False)
            try:
                e2 = m2(x).numpy()
            finally:
                jit.ProgramTranslator.get_instance().enable(True)
            np.testing.assert_allclose(o2, e2, atol=1e-5)
            assert not np.allclose(o1, o2)

    def test_traced_layer_on_to_static_forward(self, tmp_path):
        """TracedLayer.trace of an @to_static model must reuse the inner
        trace (exportable), not wrap it as one opaque closure op."""
        with dygraph.guard():
            m = MLP()
            x = to_variable(np.ones((2, 8), np.float32))
            out, traced = dygraph.TracedLayer.trace(m, [x])
            types = [op.type for op in traced.program.global_block().ops]
            assert "__jax_fn__" not in types
            traced.save_inference_model(str(tmp_path / "m2"))
        loaded = jit.load(str(tmp_path / "m2"))
        np.testing.assert_allclose(loaded(np.ones((2, 8), np.float32)),
                                   out.numpy(), atol=1e-5)
