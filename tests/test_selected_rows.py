"""SelectedRows sparse gradients (reference: framework/selected_rows.h,
lookup_table_op.cc sparse grad kernel, sum_op.cc / sgd_op.cc
SelectedRows branches): embedding(is_sparse=True) must train EXACTLY
like the dense-gradient path while never materialising the [V, D]
gradient."""

import numpy as np
import pytest


def _build(is_sparse, optimizer="sgd", two_lookups=False):
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        ids = layers.data("ids", [6], dtype="int64", stop_gradient=True)
        emb = layers.embedding(
            ids, [50, 8], is_sparse=is_sparse,
            param_attr=pt.ParamAttr(
                name="emb_w", initializer=pt.initializer.Xavier(seed=5)))
        if two_lookups:
            ids2 = layers.data("ids2", [6], dtype="int64",
                               stop_gradient=True)
            emb = emb + layers.embedding(
                ids2, [50, 8], is_sparse=is_sparse,
                param_attr=pt.ParamAttr(name="emb_w"))
        h = layers.reduce_mean(emb, dim=1)
        loss = layers.mean(
            layers.reduce_sum(h * h, dim=1, keep_dim=True))
        if optimizer == "sgd":
            pt.optimizer.SGDOptimizer(0.5).minimize(loss)
        elif optimizer == "adam_lazy":
            pt.optimizer.AdamOptimizer(0.01, lazy_mode=True).minimize(loss)
        elif optimizer == "momentum":
            pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
        else:
            pt.optimizer.AdamOptimizer(0.01).minimize(loss)
    return main, startup, loss


def _train(is_sparse, steps=4, use_compiled=True, optimizer="sgd",
           two_lookups=False, dup_ids=False):
    import paddle_tpu as pt

    main, startup, loss = _build(is_sparse, optimizer, two_lookups)
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    exe.run(startup, scope=scope, use_compiled=False)
    rng = np.random.RandomState(0)
    feed = {"ids": np.array([[1, 7, 7, 3, 49, 7]] * 2, np.int64)
            if dup_ids else
            rng.randint(0, 50, (2, 6)).astype(np.int64)}
    if two_lookups:
        feed["ids2"] = rng.randint(0, 50, (2, 6)).astype(np.int64)
    losses = []
    for _ in range(steps):
        out, = exe.run(main, feed=feed, fetch_list=[loss], scope=scope,
                       use_compiled=use_compiled)
        losses.append(float(np.asarray(out).reshape(-1)[0]))
    return losses, np.asarray(scope.find_var("emb_w"))


class TestSelectedRowsGrad:
    def test_sparse_matches_dense_sgd(self):
        for compiled in (False, True):
            ld, wd = _train(False, use_compiled=compiled)
            ls, ws = _train(True, use_compiled=compiled)
            np.testing.assert_allclose(ls, ld, rtol=1e-5)
            np.testing.assert_allclose(ws, wd, rtol=1e-5)

    def test_duplicate_ids_accumulate(self):
        """Duplicate ids in one batch must scatter-ADD (the reference's
        SelectedRows merge) — exact parity with the dense grad."""
        ld, wd = _train(False, dup_ids=True)
        ls, ws = _train(True, dup_ids=True)
        np.testing.assert_allclose(ws, wd, rtol=1e-5)

    def test_two_lookups_sum_accumulation(self):
        """Two lookups of ONE table: backward sums the two sparse grads
        (sum op's SelectedRows concat branch)."""
        ld, wd = _train(False, two_lookups=True)
        ls, ws = _train(True, two_lookups=True)
        np.testing.assert_allclose(ls, ld, rtol=1e-5)
        np.testing.assert_allclose(ws, wd, rtol=1e-5)

    def test_non_sparse_optimizer_densifies(self):
        """Optimizers without a sparse kernel (adam) densify and still
        match the dense run."""
        ld, wd = _train(False, optimizer="adam")
        ls, ws = _train(True, optimizer="adam")
        np.testing.assert_allclose(ls, ld, rtol=1e-5)
        np.testing.assert_allclose(ws, wd, rtol=1e-5)

    def test_sparse_adam_lazy_matches_dense(self):
        """lazy_mode Adam consumes SelectedRows row-wise (reference
        SparseAdamFunctor, adam_op.h:404). With a fixed id set the
        row-wise update is EXACTLY the dense update (untouched rows have
        zero moments, so dense moves them by 0), including duplicate-id
        merge."""
        for dup in (False, True):
            ld, wd = _train(False, optimizer="adam", dup_ids=dup)
            ls, ws = _train(True, optimizer="adam_lazy", dup_ids=dup)
            np.testing.assert_allclose(ls, ld, rtol=1e-5)
            np.testing.assert_allclose(ws, wd, rtol=1e-5)

    def test_sparse_momentum_matches_dense(self):
        """Momentum's SelectedRows branch (reference momentum_op.h sparse
        kernel): touched-rows-only velocity update == dense result for a
        fixed id set (untouched velocities are zero either way)."""
        for dup in (False, True):
            ld, wd = _train(False, optimizer="momentum", dup_ids=dup)
            ls, ws = _train(True, optimizer="momentum", dup_ids=dup)
            np.testing.assert_allclose(ls, ld, rtol=1e-5)
            np.testing.assert_allclose(ws, wd, rtol=1e-5)

    def test_lazy_adam_never_materialises_dense_grad(self):
        """Trace assert (VERDICT r2 #5): the lazy-mode sparse Adam jaxpr
        must contain NO [V, D]-shaped value outside the three scatter
        writes to param/moments — i.e. no densified gradient buffer and
        no full-table moment pass."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.core import registry
        from paddle_tpu.core.selected_rows import SelectedRows

        V, D, N = 4096, 8, 12
        fwd = registry.lookup("adam").forward

        def step(p, rows, vals, m1, m2, b1p, b2p, lr):
            outs = fwd({"Param": [p],
                        "Grad": [SelectedRows(rows, vals, V)],
                        "LearningRate": [lr], "Moment1": [m1],
                        "Moment2": [m2], "Beta1Pow": [b1p],
                        "Beta2Pow": [b2p]}, {"lazy_mode": True})
            return (outs["ParamOut"], outs["Moment1Out"],
                    outs["Moment2Out"])

        args = (jnp.zeros((V, D)), jnp.zeros((N,), jnp.int32),
                jnp.ones((N, D)), jnp.zeros((V, D)), jnp.zeros((V, D)),
                jnp.full((1,), 0.9), jnp.full((1,), 0.999),
                jnp.full((1,), 0.01))
        jaxpr = jax.make_jaxpr(step)(*args)

        offenders = []

        def scan(jp):
            for eqn in jp.eqns:
                for sub in eqn.params.values():
                    if hasattr(sub, "jaxpr"):
                        scan(sub.jaxpr)
                if "scatter" in eqn.primitive.name:
                    continue
                for out in eqn.outvars:
                    shape = getattr(out.aval, "shape", ())
                    if tuple(shape) == (V, D):
                        offenders.append(eqn.primitive.name)

        scan(jaxpr.jaxpr)
        assert not offenders, \
            f"dense [V,D] intermediates materialised by: {offenders}"

    def test_sparse_grad_object(self):
        """The grad reaching sgd really is SelectedRows (not a silently
        densified tensor)."""
        import jax.numpy as jnp

        from paddle_tpu.core import registry
        from paddle_tpu.core.selected_rows import SelectedRows

        fwd = registry.lookup("lookup_table_sparse_grad").forward
        ids = jnp.asarray(np.array([[1, 2, 2]], np.int64))
        w = jnp.zeros((10, 4), jnp.float32)
        og = jnp.ones((1, 3, 4), jnp.float32)
        out = fwd({"Ids": [ids], "W": [w], "OutGrad": [og]},
                  {"padding_idx": -1})["WGrad"]
        assert isinstance(out, SelectedRows)
        assert out.height == 10 and out.values.shape == (3, 4)
        dense = np.asarray(out.to_dense())
        assert dense[2].sum() == 8.0      # duplicate row accumulated
