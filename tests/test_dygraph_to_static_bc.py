"""@to_static break/continue transformer (VERDICT r4 #7).

Reference: dygraph_to_static/break_continue_transformer.py:86 — break/
continue in tensor-dependent loops become flag variables + guarded
statements, composed with the loop transformer's single while_loop op."""

import numpy as np
import pytest


def _fresh():
    from paddle_tpu.core import ir, unique_name

    ir._main_program, ir._startup_program = ir.Program(), ir.Program()
    unique_name.switch()


def _run(fn, *args, **to_static_kw):
    """Trace fn with to_static and also run it eagerly; both results."""
    import paddle_tpu as pt
    from paddle_tpu.dygraph.jit import to_static

    _fresh()
    with pt.dygraph.guard():
        eager = fn(*[pt.to_tensor(a) for a in args])
        eager = float(np.asarray(eager).reshape(-1)[0])
    _fresh()
    with pt.dygraph.guard():
        sfn = to_static(fn, **to_static_kw)
        out = sfn(*[pt.to_tensor(a) for a in args])
        static = float(np.asarray(out).reshape(-1)[0])
    return eager, static


def f_break(x, n):
    i = np.float32(0.0)            # python state: promoted at trace
    s = x * 0.0
    while i < n:                   # tensor-dependent trip count
        s = s + x * (i + 1.0)
        if s.sum() > 50.0:         # tensor-dependent break
            break
        i = i + 1.0
    return s.sum()


def f_continue(x, n):
    i = x.sum() * 0.0
    s = x.sum() * 0.0
    while i < n:
        i = i + 1.0
        if i < 3.5:                # tensor condition: skip first 3
            continue
        s = s + i
    return s


def f_for_break(x):
    s = x.sum() * 0.0
    for i in range(10):
        s = s + x.sum()
        if s > 7.5:
            break
    return s + i                    # i frozen at the break step


class TestBreakContinue:
    def test_while_tensor_break_matches_eager(self):
        x = np.ones((2, 2), np.float32)
        for n in (3.0, 20.0):
            eager, static = _run(f_break, x, np.float32(n),
                                 loop_max_iters=32)
            assert eager == static, (n, eager, static)

    def test_while_tensor_continue_matches_eager(self):
        x = np.ones((3,), np.float32)
        eager, static = _run(f_continue, x, np.float32(7.0),
                             loop_max_iters=16)
        # i in 4..7 accumulate: 4+5+6+7 = 22
        assert eager == static == 22.0

    def test_for_break_matches_eager(self):
        x = np.full((2,), 1.5, np.float32)   # s: 3,6,9 -> break at i=2
        eager, static = _run(f_for_break, x, loop_max_iters=16)
        assert eager == static == 11.0       # 9 + i(=2)

    def test_no_retrace_on_trip_count_change(self):
        import paddle_tpu as pt
        from paddle_tpu.dygraph import jit as jit_mod
        from paddle_tpu.dygraph.jit import to_static

        _fresh()
        with pt.dygraph.guard():
            sfn = to_static(f_break, loop_max_iters=32)
            a = sfn(pt.to_tensor(np.ones((2, 2), np.float32)),
                    pt.to_tensor(np.float32(3.0)))
            n_progs = len(sfn._cache) if hasattr(sfn, "_cache") else None
            b = sfn(pt.to_tensor(np.ones((2, 2), np.float32)),
                    pt.to_tensor(np.float32(6.0)))
            if n_progs is not None:
                assert len(sfn._cache) == n_progs, "retraced on new n"
        # different trip counts give different results through ONE trace
        assert float(np.asarray(a).reshape(-1)[0]) != \
            float(np.asarray(b).reshape(-1)[0])

    def test_break_with_grads(self):
        """Gradients flow through the active iterations only."""
        import paddle_tpu as pt
        from paddle_tpu.dygraph.jit import to_static

        def g(x, n):
            i = x.sum() * 0.0
            s = x.sum() * 0.0
            while i < n:
                s = s + x.sum() * (i + 1.0)
                if i > 1.5:
                    break
                i = i + 1.0
            return s

        _fresh()
        with pt.dygraph.guard():
            x = pt.to_tensor(np.ones((2,), np.float32),
                             stop_gradient=False)
            n = pt.to_tensor(np.float32(10.0))
            sfn = to_static(g, loop_max_iters=16)
            out = sfn(x, n)
            out.backward()
            gx = np.asarray(x.grad)
        # iterations i=0,1,2 run (break after i=2 body): s = x*(1+2+3)
        np.testing.assert_allclose(gx, np.full((2,), 6.0), rtol=1e-6)


def test_break_in_with_falls_back_to_python_semantics():
    """break inside `with` (unreachable for the rewriter) must keep
    Python semantics — not recurse forever at transform time."""
    import contextlib

    import paddle_tpu as pt
    from paddle_tpu.dygraph.jit import to_static

    def f(x):
        s = x.sum() * 0.0
        i = 0
        while i < 5:
            with contextlib.nullcontext():
                if i == 3:
                    break
            s = s + x.sum()
            i += 1
        return s

    _fresh()
    with pt.dygraph.guard():
        out = to_static(f)(pt.to_tensor(np.ones((2,), np.float32)))
        assert float(np.asarray(out).reshape(-1)[0]) == 6.0  # 3 iterations


def test_break_not_hit_at_trace_still_fires_at_runtime():
    """Review repro: trace with an input that never breaks (n=3), then
    run with one that must (n=6) — the flag has to be a carried tensor
    even though the probe never flipped it."""
    import paddle_tpu as pt
    from paddle_tpu.dygraph.jit import to_static

    _fresh()
    with pt.dygraph.guard():
        sfn = to_static(f_break, loop_max_iters=32)
        a = sfn(pt.to_tensor(np.ones((2, 2), np.float32)),
                pt.to_tensor(np.float32(3.0)))     # break never taken
        b = sfn(pt.to_tensor(np.ones((2, 2), np.float32)),
                pt.to_tensor(np.float32(20.0)))    # must break at s=60
    assert float(np.asarray(a).reshape(-1)[0]) == 24.0
    assert float(np.asarray(b).reshape(-1)[0]) == 60.0
